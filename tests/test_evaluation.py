"""Evaluation tests with sklearn as the external oracle (the reference's
equivalent role is played by spark.mllib BinaryClassificationMetrics)."""

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn import metrics as skm

from photon_ml_tpu.evaluation import metrics
from photon_ml_tpu.evaluation.suite import (
    EvaluationSuite,
    EvaluatorType,
    better_than,
    build_grouped_index,
    default_evaluator_for_task,
)
from photon_ml_tpu.types import TaskType


def test_auc_matches_sklearn(rng):
    n = 500
    scores = rng.normal(size=n).astype(np.float32)
    labels = (rng.uniform(size=n) > 0.4).astype(np.float32)
    ours = metrics.area_under_roc_curve(jnp.asarray(scores), jnp.asarray(labels))
    ref = skm.roc_auc_score(labels, scores)
    np.testing.assert_allclose(float(ours), ref, rtol=1e-5)


def test_auc_with_ties_and_weights(rng):
    n = 300
    scores = rng.integers(0, 5, size=n).astype(np.float32)  # heavy ties
    labels = (rng.uniform(size=n) > 0.5).astype(np.float32)
    weights = rng.uniform(0.5, 3.0, size=n).astype(np.float32)
    ours = metrics.area_under_roc_curve(
        jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(weights)
    )
    ref = skm.roc_auc_score(labels, scores, sample_weight=weights)
    np.testing.assert_allclose(float(ours), ref, rtol=1e-5)


def test_auc_padding_mask(rng):
    n = 100
    scores = rng.normal(size=n).astype(np.float32)
    labels = (rng.uniform(size=n) > 0.5).astype(np.float32)
    base = metrics.area_under_roc_curve(jnp.asarray(scores), jnp.asarray(labels))
    # Add garbage rows with zero weight.
    s2 = np.concatenate([scores, rng.normal(size=20).astype(np.float32)])
    l2 = np.concatenate([labels, np.ones(20, np.float32)])
    w2 = np.concatenate([np.ones(n, np.float32), np.zeros(20, np.float32)])
    padded = metrics.area_under_roc_curve(jnp.asarray(s2), jnp.asarray(l2), jnp.asarray(w2))
    np.testing.assert_allclose(float(padded), float(base), rtol=1e-5)


def test_aupr_close_to_sklearn(rng):
    n = 400
    scores = rng.normal(size=n).astype(np.float32)
    labels = (rng.uniform(size=n) > 0.6).astype(np.float32)
    ours = metrics.area_under_pr_curve(jnp.asarray(scores), jnp.asarray(labels))
    # sklearn's average_precision is the step-function integral; our trapezoid
    # matches spark mllib. They agree loosely on smooth data.
    ref = skm.average_precision_score(labels, scores)
    assert abs(float(ours) - ref) < 0.02


def test_rmse_and_losses(rng):
    n = 200
    scores = rng.normal(size=n).astype(np.float32)
    labels = rng.normal(size=n).astype(np.float32)
    np.testing.assert_allclose(
        float(metrics.rmse(jnp.asarray(scores), jnp.asarray(labels))),
        np.sqrt(np.mean((scores - labels) ** 2)),
        rtol=1e-5,
    )
    y = (labels > 0).astype(np.float32)
    ll = float(metrics.logistic_loss(jnp.asarray(scores), jnp.asarray(y)))
    ref_ll = np.mean(np.log1p(np.exp(-(2 * y - 1) * scores)))
    np.testing.assert_allclose(ll, ref_ll, rtol=1e-4)


def test_precision_at_k():
    scores = jnp.asarray([5.0, 4.0, 3.0, 2.0, 1.0])
    labels = jnp.asarray([1.0, 0.0, 1.0, 1.0, 0.0])
    np.testing.assert_allclose(float(metrics.precision_at_k(2, scores, labels)), 0.5)
    np.testing.assert_allclose(float(metrics.precision_at_k(4, scores, labels)), 0.75)


def test_evaluator_type_parsing():
    assert EvaluatorType.parse("AUC") == EvaluatorType("AUC")
    assert EvaluatorType.parse("rmse").name == "RMSE"
    g = EvaluatorType.parse("AUC:queryId")
    assert g.is_grouped and g.id_tag == "queryId"
    p = EvaluatorType.parse("PRECISION@5:documentId")
    assert p.k == 5 and p.id_tag == "documentId"
    assert str(p) == "PRECISION@5:documentId"
    with pytest.raises(ValueError):
        EvaluatorType.parse("NOT_A_METRIC")


def test_better_than_directions():
    auc = EvaluatorType("AUC")
    rmse_t = EvaluatorType("RMSE")
    assert better_than(auc, 0.9, 0.8) and not better_than(auc, 0.7, 0.8)
    assert better_than(rmse_t, 0.1, 0.2) and not better_than(rmse_t, 0.3, 0.2)
    assert better_than(auc, 0.1, None)


def test_grouped_auc_equals_per_group_mean(rng):
    n, g = 300, 7
    gids = rng.integers(0, g, size=n)
    scores = rng.normal(size=n).astype(np.float32)
    labels = (rng.uniform(size=n) > 0.5).astype(np.float32)
    suite = EvaluationSuite(
        [EvaluatorType.parse("AUC:q")],
        jnp.asarray(labels),
        id_tag_values={"q": gids},
    )
    res = suite.evaluate(jnp.asarray(scores))
    per_group = []
    for gid in np.unique(gids):
        m = gids == gid
        if len(np.unique(labels[m])) < 2:
            per_group.append(0.5)
        else:
            per_group.append(skm.roc_auc_score(labels[m], scores[m]))
    np.testing.assert_allclose(res.primary_value, np.mean(per_group), rtol=1e-4)


def test_suite_multiple_metrics(rng):
    n = 100
    labels = (rng.uniform(size=n) > 0.5).astype(np.float32)
    scores = rng.normal(size=n).astype(np.float32)
    suite = EvaluationSuite(
        [EvaluatorType("AUC"), EvaluatorType("LOGISTIC_LOSS")], jnp.asarray(labels)
    )
    res = suite.evaluate(jnp.asarray(scores))
    assert set(res.results) == {"AUC", "LOGISTIC_LOSS"}
    assert res.primary == EvaluatorType("AUC")


def test_default_evaluators():
    assert default_evaluator_for_task(TaskType.LOGISTIC_REGRESSION).name == "AUC"
    assert default_evaluator_for_task(TaskType.LINEAR_REGRESSION).name == "RMSE"
    assert default_evaluator_for_task(TaskType.POISSON_REGRESSION).name == "POISSON_LOSS"


def test_build_grouped_index_shapes(rng):
    gids = np.array([3, 1, 3, 3, 2, 1])
    idx = build_grouped_index(gids)
    assert idx.gather.shape == (3, 3)
    assert float(idx.mask.sum()) == 6.0


class TestLegacyMetrics:
    """R^2 / peak-F1 and the legacy Evaluation.evaluate metric map
    (photon-client evaluation/Evaluation.scala:31), cross-checked vs sklearn."""

    def test_r_squared_vs_sklearn(self, rng):
        from sklearn.metrics import r2_score

        from photon_ml_tpu.evaluation.metrics import r_squared

        y = rng.normal(size=200).astype(np.float32)
        pred = (y + rng.normal(size=200) * 0.5).astype(np.float32)
        ours = float(r_squared(jnp.asarray(pred), jnp.asarray(y)))
        assert ours == pytest.approx(r2_score(y, pred), abs=1e-5)
        # Weighted form vs sklearn sample_weight.
        w = rng.uniform(0.5, 2.0, size=200).astype(np.float32)
        ours_w = float(r_squared(jnp.asarray(pred), jnp.asarray(y), jnp.asarray(w)))
        assert ours_w == pytest.approx(r2_score(y, pred, sample_weight=w), abs=1e-5)

    def test_peak_f1_vs_sklearn(self, rng):
        from sklearn.metrics import precision_recall_curve

        from photon_ml_tpu.evaluation.metrics import peak_f1

        y = (rng.uniform(size=300) > 0.6).astype(np.float32)
        s = (y + rng.normal(size=300)).astype(np.float32)
        p, r, _ = precision_recall_curve(y, s)
        f1 = 2 * p * r / np.maximum(p + r, 1e-12)
        expected = float(np.max(f1))
        ours = float(peak_f1(jnp.asarray(s), jnp.asarray(y)))
        assert ours == pytest.approx(expected, abs=1e-5)

    def test_peak_f1_tied_scores_and_padding(self):
        from photon_ml_tpu.evaluation.metrics import peak_f1

        # Ties: scores [1, 1, 0]; labels [1, 0, 1]. Realizable cuts are
        # {>=1} (P=0.5, R=0.5, F1=0.5) and {>=0} (P=2/3, R=1, F1=0.8).
        s = jnp.asarray([1.0, 1.0, 0.0])
        y = jnp.asarray([1.0, 0.0, 1.0])
        assert float(peak_f1(s, y)) == pytest.approx(0.8, abs=1e-6)
        # Padding rows (weight 0) must not contribute.
        s2 = jnp.asarray([1.0, 1.0, 0.0, 9.0])
        y2 = jnp.asarray([1.0, 0.0, 1.0, 0.0])
        w2 = jnp.asarray([1.0, 1.0, 1.0, 0.0])
        assert float(peak_f1(s2, y2, w2)) == pytest.approx(0.8, abs=1e-6)

    def test_evaluate_glm_map(self, rng):
        from photon_ml_tpu.data.containers import dense_data
        from photon_ml_tpu.evaluation import legacy
        from photon_ml_tpu.models.glm import create_model
        from photon_ml_tpu.types import TaskType

        n, d = 150, 5
        X = rng.normal(size=(n, d)).astype(np.float32)
        w = rng.normal(size=d).astype(np.float32)
        ybin = (X @ w + rng.normal(size=n) * 0.3 > 0).astype(np.float32)
        ylin = (X @ w + rng.normal(size=n) * 0.3).astype(np.float32)

        logit = create_model(TaskType.LOGISTIC_REGRESSION, jnp.asarray(w))
        m = legacy.evaluate_glm(logit, dense_data(X, ybin))
        assert {
            legacy.AREA_UNDER_ROC,
            legacy.AREA_UNDER_PRECISION_RECALL,
            legacy.PEAK_F1_SCORE,
            legacy.DATA_LOG_LIKELIHOOD,
            legacy.AKAIKE_INFORMATION_CRITERION,
        } <= set(m)
        assert 0.8 < m[legacy.AREA_UNDER_ROC] <= 1.0
        assert m[legacy.DATA_LOG_LIKELIHOOD] < 0.0

        lin = create_model(TaskType.LINEAR_REGRESSION, jnp.asarray(w))
        m2 = legacy.evaluate_glm(lin, dense_data(X, ylin))
        from sklearn.metrics import mean_squared_error

        pred = np.asarray(X @ w)
        assert m2[legacy.MEAN_SQUARE_ERROR] == pytest.approx(
            mean_squared_error(ylin, pred), rel=1e-5
        )
        assert m2[legacy.R_SQUARED] > 0.8
        assert legacy.PEAK_F1_SCORE not in m2

"""Objective tests: explicit gradient/Hessian forms vs autodiff, sparse vs
dense equivalence, and normalization-as-algebra correctness.

Counterpart of the reference's aggregator + DistributedGLMLossFunction integ
tests, with jax.grad as the oracle instead of hand-computed expectations.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.containers import (
    LabeledData,
    SparseFeatures,
    dense_data,
    pack_csr_to_ell,
)
from photon_ml_tpu.ops import losses, objective
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.types import NormalizationType
from photon_ml_tpu.ops import normalization as norm_mod


def _make_data(rng, n=40, d=7, loss_name="logistic"):
    X = rng.normal(size=(n, d)).astype(np.float32)
    X[:, -1] = 1.0  # intercept column
    if loss_name == "poisson":
        y = rng.poisson(1.0, size=n).astype(np.float32)
    elif loss_name == "squared":
        y = rng.normal(size=n).astype(np.float32)
    else:
        y = (rng.uniform(size=n) > 0.5).astype(np.float32)
    offs = rng.normal(size=n).astype(np.float32) * 0.1
    wts = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    return dense_data(X, y, offsets=offs, weights=wts)


def _make_norm(rng, d, with_shift=True):
    factors = jnp.asarray(rng.uniform(0.5, 2.0, size=d).astype(np.float32))
    factors = factors.at[d - 1].set(1.0)
    shifts = None
    if with_shift:
        shifts = jnp.asarray(rng.normal(size=d).astype(np.float32) * 0.3)
        shifts = shifts.at[d - 1].set(0.0)
    return NormalizationContext(factors, shifts, d - 1)


LOSS_CASES = [
    (losses.LOGISTIC, "logistic"),
    (losses.SQUARED, "squared"),
    (losses.POISSON, "poisson"),
]


@pytest.mark.parametrize("loss,name", LOSS_CASES, ids=[c[1] for c in LOSS_CASES])
@pytest.mark.parametrize("with_norm", [False, True], ids=["raw", "normalized"])
def test_gradient_matches_autodiff(rng, loss, name, with_norm):
    data = _make_data(rng, loss_name=name)
    d = data.feature_dim
    norm = _make_norm(rng, d) if with_norm else None
    w = jnp.asarray(rng.normal(size=d).astype(np.float32) * 0.2)
    l2 = 0.7

    val, grad = objective.value_and_gradient(loss, w, data, norm, l2)
    auto_val, auto_grad = jax.value_and_grad(
        lambda ww: objective.value(loss, ww, data, norm, l2)
    )(w)
    np.testing.assert_allclose(val, auto_val, rtol=1e-5)
    np.testing.assert_allclose(grad, auto_grad, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("loss,name", LOSS_CASES, ids=[c[1] for c in LOSS_CASES])
@pytest.mark.parametrize("with_norm", [False, True], ids=["raw", "normalized"])
def test_hessian_products_match_autodiff(rng, loss, name, with_norm):
    data = _make_data(rng, loss_name=name)
    d = data.feature_dim
    norm = _make_norm(rng, d) if with_norm else None
    w = jnp.asarray(rng.normal(size=d).astype(np.float32) * 0.2)
    v = jnp.asarray(rng.normal(size=d).astype(np.float32))
    l2 = 0.3

    f = lambda ww: objective.value(loss, ww, data, norm, l2)
    hv = objective.hessian_vector(loss, w, v, data, norm, l2)
    auto_hv = jax.jvp(jax.grad(f), (w,), (v,))[1]
    np.testing.assert_allclose(hv, auto_hv, rtol=1e-3, atol=1e-3)

    H = objective.hessian_matrix(loss, w, data, norm, l2)
    auto_H = jax.hessian(f)(w)
    np.testing.assert_allclose(H, auto_H, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(
        objective.hessian_diagonal(loss, w, data, norm, l2),
        jnp.diagonal(auto_H),
        rtol=1e-3,
        atol=1e-3,
    )


def test_sparse_dense_equivalence(rng):
    n, d = 30, 12
    dense = rng.normal(size=(n, d)).astype(np.float32)
    mask = rng.uniform(size=(n, d)) < 0.4
    dense = dense * mask
    # CSR of the masked matrix
    indptr = [0]
    idxs, vals = [], []
    for r in range(n):
        nz = np.nonzero(dense[r])[0]
        idxs.extend(nz)
        vals.extend(dense[r, nz])
        indptr.append(len(idxs))
    sp = pack_csr_to_ell(
        np.asarray(indptr), np.asarray(idxs), np.asarray(vals, np.float32), d
    )
    y = (rng.uniform(size=n) > 0.5).astype(np.float32)
    d_data = dense_data(dense, y)
    s_data = LabeledData(sp, d_data.labels, d_data.offsets, d_data.weights)
    w = jnp.asarray(rng.normal(size=d).astype(np.float32))
    v = jnp.asarray(rng.normal(size=d).astype(np.float32))
    norm = _make_norm(np.random.default_rng(3), d)

    np.testing.assert_allclose(sp.to_dense(), dense, rtol=1e-6)
    for nm in (None, norm):
        vd, gd = objective.value_and_gradient(losses.LOGISTIC, w, d_data, nm, 0.1)
        vs, gs = objective.value_and_gradient(losses.LOGISTIC, w, s_data, nm, 0.1)
        np.testing.assert_allclose(vd, vs, rtol=1e-5)
        np.testing.assert_allclose(gd, gs, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            objective.hessian_vector(losses.LOGISTIC, w, v, d_data, nm, 0.1),
            objective.hessian_vector(losses.LOGISTIC, w, v, s_data, nm, 0.1),
            rtol=1e-4,
            atol=1e-5,
        )
        np.testing.assert_allclose(
            objective.hessian_diagonal(losses.LOGISTIC, w, d_data, nm, 0.1),
            objective.hessian_diagonal(losses.LOGISTIC, w, s_data, nm, 0.1),
            rtol=1e-4,
            atol=1e-5,
        )


def test_normalization_equals_materialized_transform(rng):
    """Objective with folded-in normalization == objective on transformed data.

    This is the invariant behind ValueAndGradientAggregator.scala:36-80.
    """
    data = _make_data(rng)
    d = data.feature_dim
    norm = _make_norm(rng, d)
    w = jnp.asarray(rng.normal(size=d).astype(np.float32))

    X_t = (data.features - norm.shifts) * norm.factors
    data_t = LabeledData(X_t, data.labels, data.offsets, data.weights)
    v_folded = objective.value(losses.LOGISTIC, w, data, norm, 0.0)
    v_materialized = objective.value(losses.LOGISTIC, w, data_t, None, 0.0)
    np.testing.assert_allclose(v_folded, v_materialized, rtol=1e-5)


def test_model_space_round_trip(rng):
    d = 6
    norm = _make_norm(rng, d)
    w = jnp.asarray(rng.normal(size=d).astype(np.float32))
    back = norm.model_to_transformed_space(norm.model_to_original_space(w))
    np.testing.assert_allclose(back, w, rtol=1e-5, atol=1e-6)

    # Scoring with original-space coefficients on raw data == normalized margin.
    data = _make_data(rng, d=d)
    z_norm = objective.compute_margins(w, data, norm)
    w_orig = norm.model_to_original_space(w)
    z_orig = objective.compute_margins(w_orig, data, None)
    np.testing.assert_allclose(z_norm, z_orig, rtol=1e-4, atol=1e-4)


def test_from_feature_stats_types(rng):
    d = 5
    mean = jnp.asarray(rng.normal(size=d).astype(np.float32))
    var = jnp.asarray(rng.uniform(0.0, 2.0, size=d).astype(np.float32))
    var = var.at[2].set(0.0)  # constant feature: factor must fall back to 1
    max_abs = jnp.asarray(rng.uniform(0.1, 3.0, size=d).astype(np.float32))

    ctx = norm_mod.from_feature_stats(
        NormalizationType.STANDARDIZATION,
        mean=mean, variance=var, max_abs=max_abs, intercept_index=d - 1,
    )
    assert ctx.factors[2] == 1.0
    assert ctx.factors[d - 1] == 1.0 and ctx.shifts[d - 1] == 0.0
    ctx2 = norm_mod.from_feature_stats(
        NormalizationType.SCALE_WITH_MAX_MAGNITUDE,
        mean=mean, variance=var, max_abs=max_abs, intercept_index=d - 1,
    )
    assert ctx2.shifts is None
    np.testing.assert_allclose(ctx2.factors[0], 1.0 / max_abs[0], rtol=1e-6)
    assert norm_mod.from_feature_stats(
        NormalizationType.NONE, mean=mean, variance=var, max_abs=max_abs
    ).is_identity


def test_padding_rows_are_inert(rng):
    """weight-0 rows must not affect value/grad/hvp — the masking invariant."""
    data = _make_data(rng, n=20)
    d = data.feature_dim
    w = jnp.asarray(rng.normal(size=d).astype(np.float32))
    # Append garbage rows with weight 0.
    Xp = jnp.concatenate([data.features, jnp.full((5, d), 1e3, jnp.float32)])
    yp = jnp.concatenate([data.labels, jnp.ones(5, jnp.float32)])
    op = jnp.concatenate([data.offsets, jnp.zeros(5, jnp.float32)])
    wp = jnp.concatenate([data.weights, jnp.zeros(5, jnp.float32)])
    padded = LabeledData(Xp, yp, op, wp)
    for fn in (
        lambda dd: objective.value(losses.SQUARED, w, dd, None, 0.2),
        lambda dd: objective.value_and_gradient(losses.SQUARED, w, dd, None, 0.2)[1],
        lambda dd: objective.hessian_diagonal(losses.SQUARED, w, dd, None, 0.2),
    ):
        np.testing.assert_allclose(fn(padded), fn(data), rtol=1e-5, atol=1e-5)

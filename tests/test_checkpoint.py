"""Coordinate-descent checkpoint-restart (SURVEY §5.3: the TPU replacement
for Spark lineage recovery). Kill-and-resume must reproduce the
uninterrupted result exactly."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.game_dataset import (
    GameDataset,
    RandomEffectDataConfig,
    build_random_effect_dataset,
)
from photon_ml_tpu.game.coordinate import FixedEffectCoordinate, RandomEffectCoordinate
from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent
from photon_ml_tpu.optimize.config import (
    L2,
    CoordinateOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.types import TaskType


def _dataset(rng, n=240, d=5, n_entities=6, d_re=3):
    Xf = rng.normal(size=(n, d)).astype(np.float32)
    Xf[:, -1] = 1.0
    Xe = rng.normal(size=(n, d_re)).astype(np.float32)
    entity = rng.integers(0, n_entities, size=n)
    w = rng.normal(size=d)
    u = rng.normal(size=(n_entities, d_re))
    m = Xf @ w + np.einsum("nd,nd->n", Xe, u[entity])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-m))).astype(np.float32)
    return GameDataset.build(
        {"global": jnp.asarray(Xf), "per_entity": jnp.asarray(Xe)},
        y,
        id_tags={"entityId": entity},
    )


def _coords(ds, down_sampling=1.0):
    cfg_f = CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=25, tolerance=1e-8),
        regularization=L2,
        reg_weight=0.5,
        down_sampling_rate=down_sampling,
    )
    cfg_r = CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=20, tolerance=1e-8),
        regularization=L2,
        reg_weight=1.0,
    )
    red = build_random_effect_dataset(
        ds, RandomEffectDataConfig("entityId", "per_entity", min_bucket=4)
    )
    return {
        "fixed": FixedEffectCoordinate(ds, "global", cfg_f, TaskType.LOGISTIC_REGRESSION),
        "per-entity": RandomEffectCoordinate(ds, red, cfg_r, TaskType.LOGISTIC_REGRESSION),
    }


class _KillSwitch:
    """Wraps a coordinate so train() raises after `allowed` calls — a
    deterministic stand-in for a mid-run preemption."""

    def __init__(self, inner, allowed: int):
        self.inner = inner
        self.allowed = allowed
        self.calls = 0

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def train(self, *args, **kwargs):
        if self.calls >= self.allowed:
            raise RuntimeError("simulated preemption")
        self.calls += 1
        return self.inner.train(*args, **kwargs)


def _model_arrays(result):
    out = {}
    for cid, m in result.model.models.items():
        if hasattr(m, "coefficients_matrix"):
            out[cid] = np.asarray(m.coefficients_matrix)
        else:
            out[cid] = np.asarray(m.coefficients.means)
    return out


class TestCheckpointRestart:
    def test_resume_between_iterations(self, rng, tmp_path):
        ds = _dataset(rng)
        straight = run_coordinate_descent(_coords(ds), 2, seed=3)

        ck = str(tmp_path / "ck")
        run_coordinate_descent(_coords(ds), 1, seed=3, checkpoint_dir=ck)
        resumed = run_coordinate_descent(_coords(ds), 2, seed=3, checkpoint_dir=ck)

        a, b = _model_arrays(straight), _model_arrays(resumed)
        for cid in a:
            np.testing.assert_allclose(a[cid], b[cid], rtol=1e-6, atol=1e-7)

    def test_kill_mid_pass_and_resume(self, rng, tmp_path):
        """Preempt after the first coordinate of pass 2; the resumed run
        must land exactly where the uninterrupted run does — including the
        down-sampling subsample draws keyed on (seed, step)."""
        ds = _dataset(rng)
        straight = run_coordinate_descent(_coords(ds, down_sampling=0.7), 2, seed=7)

        ck = str(tmp_path / "ck")
        coords = _coords(ds, down_sampling=0.7)
        coords["fixed"] = _KillSwitch(coords["fixed"], allowed=1)  # dies in pass 2
        with pytest.raises(RuntimeError, match="simulated preemption"):
            run_coordinate_descent(coords, 2, seed=7, checkpoint_dir=ck)
        # Pass 1 (fixed, per-entity) completed before the preemption.
        assert os.path.isfile(os.path.join(ck, "state.json"))

        resumed = run_coordinate_descent(
            _coords(ds, down_sampling=0.7), 2, seed=7, checkpoint_dir=ck
        )
        a, b = _model_arrays(straight), _model_arrays(resumed)
        for cid in a:
            np.testing.assert_allclose(a[cid], b[cid], rtol=1e-6, atol=1e-7)

    def test_seed_mismatch_refuses_resume(self, rng, tmp_path):
        ds = _dataset(rng)
        ck = str(tmp_path / "ck")
        run_coordinate_descent(_coords(ds), 1, seed=1, checkpoint_dir=ck)
        with pytest.raises(ValueError, match="seed"):
            run_coordinate_descent(_coords(ds), 1, seed=2, checkpoint_dir=ck)

    def test_validation_and_best_model_survive_resume(self, rng, tmp_path):
        from photon_ml_tpu.evaluation.suite import EvaluationSuite, EvaluatorType

        ds = _dataset(rng)
        val = _dataset(np.random.default_rng(99))
        suite = EvaluationSuite(
            [EvaluatorType("AUC")], val.labels, val.weights
        )

        def make_scorer(coords):
            def scorer(cid, model):
                if cid == "fixed":
                    return val.shards["global"] @ model.coefficients.means
                from photon_ml_tpu.game.model import random_effect_margins

                red = coords["per-entity"].re_dataset
                # Unseen entities pin to the zero row; reuse training rows
                # for simplicity (same dataset shapes).
                return random_effect_margins(
                    val.shards["per_entity"],
                    red.sample_entity_rows,
                    model.coefficients_matrix,
                    None,
                )

            return scorer

        ck = str(tmp_path / "ck")
        c1 = _coords(ds)
        run_coordinate_descent(
            c1, 1, seed=5, checkpoint_dir=ck,
            validation_scorer=make_scorer(c1), validation_suite=suite,
            validation_offsets=val.offsets,
        )
        c2 = _coords(ds)
        resumed = run_coordinate_descent(
            c2, 2, seed=5, checkpoint_dir=ck,
            validation_scorer=make_scorer(c2), validation_suite=suite,
            validation_offsets=val.offsets,
        )
        c3 = _coords(ds)
        straight = run_coordinate_descent(
            c3, 2, seed=5,
            validation_scorer=make_scorer(c3), validation_suite=suite,
            validation_offsets=val.offsets,
        )
        # History spans both runs; values match the uninterrupted run's.
        assert len(resumed.validation_history) == len(straight.validation_history)
        for (it_a, cid_a, ra), (it_b, cid_b, rb) in zip(
            resumed.validation_history, straight.validation_history
        ):
            assert (it_a, cid_a) == (it_b, cid_b)
            assert ra.primary_value == pytest.approx(rb.primary_value, abs=1e-6)
        np.testing.assert_allclose(
            _model_arrays(resumed)["fixed"], _model_arrays(straight)["fixed"], rtol=1e-6
        )

    def test_config_change_refuses_resume(self, rng, tmp_path):
        ds = _dataset(rng)
        ck = str(tmp_path / "ck")
        run_coordinate_descent(_coords(ds), 1, seed=1, checkpoint_dir=ck)
        changed = _coords(ds)
        import dataclasses
        changed["fixed"].config = dataclasses.replace(
            changed["fixed"].config, reg_weight=123.0
        )
        # reg_weights overrides are part of the fingerprint.
        with pytest.raises(ValueError, match="different run configuration"):
            run_coordinate_descent(
                changed, 1, seed=1, checkpoint_dir=ck,
                reg_weights={"fixed": 123.0},
            )

"""Streaming-vs-monolithic ingest bitwise parity (r09 streaming data plane).

The chunked/streamed ingest paths move only WHEN decode and assembly run —
never what they compute: dataset arrays, index maps, and id-tag codes must
be identical across chunk sizes, file orderings, with the threaded
decode→assemble overlap forced on or off, and with corrupt-block
quarantine active.
"""

import os

import numpy as np
import pytest

import photon_ml_tpu.io.avro_data as ad
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import avro_fast, schemas
from photon_ml_tpu.native.build import load_native
from photon_ml_tpu.utils.contracts import INGEST_TIMING_REQUIRED_KEYS

needs_native = pytest.mark.skipif(
    load_native() is None, reason="native library unavailable"
)

CFGS = {"g": ad.FeatureShardConfig(("features",), True)}


def _write_file(path, n, seed, n_entities=20, d=50):
    rng = np.random.default_rng(seed)
    feats = [
        [
            (f"f{j}", float(rng.normal()))
            for j in rng.choice(d, size=5, replace=False)
        ]
        for _ in range(n)
    ]
    labels = (rng.uniform(size=n) > 0.5).astype(float)
    ad.write_training_examples(
        path,
        feats,
        labels,
        offsets=rng.normal(size=n),
        weights=rng.uniform(0.5, 2.0, size=n),
        uids=[f"u{seed}-{i}" for i in range(n)],
        id_tags={"entityId": rng.integers(0, n_entities, size=n).astype(str)},
    )


def _read(paths, **env):
    old = {k: os.environ.get(k) for k in env}
    os.environ.update({k: str(v) for k, v in env.items()})
    try:
        return ad.read_game_dataset(
            paths, CFGS, id_tag_fields=["entityId"]
        )
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _assert_datasets_equal(a, b):
    ds_a, maps_a = a
    ds_b, maps_b = b
    assert ds_a.num_samples == ds_b.num_samples
    for k in ("labels", "offsets", "weights"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ds_a, k)),
            np.asarray(getattr(ds_b, k)),
            err_msg=k,
        )
    assert set(ds_a.id_tags) == set(ds_b.id_tags)
    for t in ds_a.id_tags:
        assert np.array_equal(ds_a.id_tags[t], ds_b.id_tags[t]), t
    # Factorized tag codes (when present on both) must agree too — entity
    # grouping consumes them directly.
    for t in set(ds_a.tag_codes) & set(ds_b.tag_codes):
        np.testing.assert_array_equal(ds_a.tag_codes[t][0], ds_b.tag_codes[t][0])
        np.testing.assert_array_equal(ds_a.tag_codes[t][1], ds_b.tag_codes[t][1])
    for shard in maps_a:
        assert maps_a[shard].size == maps_b[shard].size
        sa, sb = ds_a.shards[shard], ds_b.shards[shard]
        np.testing.assert_array_equal(
            np.asarray(sa.indices), np.asarray(sb.indices)
        )
        np.testing.assert_array_equal(
            np.asarray(sa.values), np.asarray(sb.values)
        )


@pytest.fixture
def three_files(tmp_path):
    paths = []
    for i, n in enumerate([120, 80, 150]):
        p = str(tmp_path / f"part-{i:05d}.avro")
        _write_file(p, n, seed=10 + i)
        paths.append(p)
    return paths


class TestPythonChunkedParity:
    """The pure-Python codec path streams PHOTON_STREAM_CHUNK_ROWS-row
    column chunks; chunk boundaries cannot change anything."""

    @pytest.mark.parametrize("chunk", [1, 7, 64, 10_000])
    def test_chunk_sizes_bitwise(self, three_files, chunk):
        base = _read(three_files, PHOTON_DISABLE_NATIVE="1")
        chunked = _read(
            three_files,
            PHOTON_DISABLE_NATIVE="1",
            PHOTON_STREAM_CHUNK_ROWS=chunk,
        )
        _assert_datasets_equal(base, chunked)
        ds, _ = chunked
        expect = -(-350 // chunk)
        assert ds.ingest_timing["chunks"] == expect

    def test_ingest_timing_contract(self, three_files):
        ds, _ = _read(three_files, PHOTON_DISABLE_NATIVE="1")
        missing = [
            k for k in INGEST_TIMING_REQUIRED_KEYS if k not in ds.ingest_timing
        ]
        assert not missing, missing
        assert ds.ingest_timing["ingest_path"] == "python"


@needs_native
class TestNativeStreamingParity:
    """The native path's bounded-window decode→assemble overlap consumes
    files strictly in order; streaming on/off is bitwise-identical."""

    def test_streaming_vs_monolithic(self, three_files):
        mono = _read(
            three_files, PHOTON_STREAM_INGEST="0", PHOTON_HOST_THREADS="4"
        )
        stream = _read(
            three_files, PHOTON_STREAM_INGEST="1", PHOTON_HOST_THREADS="4"
        )
        assert mono[0].ingest_timing["streaming"] is False
        assert stream[0].ingest_timing["streaming"] is True
        assert stream[0].ingest_timing["ingest_path"] == "native-stream"
        assert stream[0].ingest_timing["chunks"] == 3
        _assert_datasets_equal(mono, stream)

    def test_streaming_auto_off_on_one_core(self, three_files):
        """The 1-core auto-off gate every host-parallel knob carries: an
        unset PHOTON_STREAM_INGEST with one effective core must stay on
        the monolithic path (a producer thread would steal the core)."""
        ds, _ = _read(three_files, PHOTON_HOST_THREADS="1")
        assert ds.ingest_timing["streaming"] is False

    def test_file_ordering(self, three_files):
        """Path order is data order (the reference's readMerged `paths`
        contract): a permuted path list must produce the permuted rows and
        the identical per-row features, and the SAME feature index maps
        (map construction sorts keys, so file order cannot leak in)."""
        fwd_ds, fwd_maps = _read(
            three_files, PHOTON_STREAM_INGEST="1", PHOTON_HOST_THREADS="4"
        )
        perm = [three_files[2], three_files[0], three_files[1]]
        rev_ds, rev_maps = _read(
            perm, PHOTON_STREAM_INGEST="1", PHOTON_HOST_THREADS="4"
        )
        assert fwd_maps["g"].size == rev_maps["g"].size
        sizes = [120, 80, 150]
        starts = np.cumsum([0] + sizes)
        order = np.concatenate(
            [np.arange(starts[i], starts[i + 1]) for i in (2, 0, 1)]
        )
        np.testing.assert_array_equal(
            np.asarray(rev_ds.labels), np.asarray(fwd_ds.labels)[order]
        )
        assert np.array_equal(
            rev_ds.id_tags["entityId"], fwd_ds.id_tags["entityId"][order]
        )
        # Same index map -> per-row dense feature vectors identical.
        fi, fv = np.asarray(fwd_ds.shards["g"].indices), np.asarray(
            fwd_ds.shards["g"].values
        )
        ri, rv = np.asarray(rev_ds.shards["g"].indices), np.asarray(
            rev_ds.shards["g"].values
        )
        d = fwd_maps["g"].size
        dense_f = np.zeros((len(order), d), np.float64)
        dense_r = np.zeros((len(order), d), np.float64)
        rows = np.repeat(np.arange(len(order)), fi.shape[1])
        np.add.at(dense_f, (rows, fi.ravel()), fv.ravel())
        rows_r = np.repeat(np.arange(len(order)), ri.shape[1])
        np.add.at(dense_r, (rows_r, ri.ravel()), rv.ravel())
        np.testing.assert_array_equal(dense_r, dense_f[order])

    def test_native_vs_python_after_restructure(self, three_files):
        """The streaming restructure keeps the native/python parity the
        fixture suite pins: both paths, same arrays."""
        nat = _read(three_files, PHOTON_STREAM_INGEST="1", PHOTON_HOST_THREADS="4")
        py = _read(three_files, PHOTON_DISABLE_NATIVE="1")
        _assert_datasets_equal(nat, py)


class TestQuarantinedIngestParity:
    """Chunked ingest with quarantine=True corrupt-block handling: the
    surviving rows are identical across chunk sizes, and the quarantine
    counter fires exactly once for the one smashed block."""

    def _corrupt_middle_block(self, tmp_path):
        rows = []
        rng = np.random.default_rng(3)
        for i in range(30):
            rows.append(
                {
                    "uid": f"u{i}",
                    "label": float(i % 2),
                    "features": [
                        {"name": f"f{int(j)}", "term": "", "value": 1.0 + i}
                        for j in rng.choice(20, size=3, replace=False)
                    ],
                    "weight": 1.0,
                    "offset": 0.0,
                    "metadataMap": {"entityId": str(i % 5)},
                }
            )
        p = str(tmp_path / "q.avro")
        avro_io.write_container(
            p, schemas.TRAINING_EXAMPLE, rows, block_records=10
        )
        data = bytearray(open(p, "rb").read())
        _, _, sync, _ = avro_io.read_header(bytes(data), p)
        marks, start = [], 0
        while True:
            i = bytes(data).find(sync, start)
            if i < 0:
                break
            marks.append(i)
            start = i + 1
        # marks[0] ends the header; smash block 2 (between marks[1] and
        # marks[2]).
        lo, hi = marks[1] + len(sync), marks[2]
        data[lo:hi] = b"\xff" * (hi - lo)
        open(p, "wb").write(bytes(data))
        return p

    @pytest.mark.parametrize("chunk", [4, 1000])
    def test_quarantine_parity_across_chunks(self, tmp_path, chunk):
        from photon_ml_tpu.utils import faults

        p = self._corrupt_middle_block(tmp_path)
        ds, maps = _read(
            [p],
            PHOTON_DISABLE_NATIVE="1",
            PHOTON_STREAM_CHUNK_ROWS=chunk,
        )
        # Rows 10..19 (the smashed block) are gone; the rest survive.
        assert ds.num_samples == 20
        assert faults.COUNTERS.get("quarantined_blocks") >= 1
        labels = np.asarray(ds.labels)
        expect = np.asarray(
            [float(i % 2) for i in list(range(10)) + list(range(20, 30))],
            np.float32,
        )
        np.testing.assert_array_equal(labels, expect)
        assert list(ds.id_tags["entityId"][:3]) == ["0", "1", "2"]

"""Incremental refresh (ISSUE 16): warm-start delta fits + delta-bundle
swaps close the data->served freshness gap.

The contracts:

* fingerprint diffs localize change exactly: per coordinate, per ENTITY
  for random effects; append/update only (entity removal is loud);
* an incremental fit carries unchanged coordinates BITWISE and — on the
  entity fast path — carries unchanged ENTITIES bitwise, re-solving only
  the churned/new rows (characterized `max_rel_diff` journaled);
* model growth moves carried rows by KEY through an index re-sort;
* a delta bundle is the bitwise model diff (changed rows + changed FE
  planes only), and applying it to a live engine is an in-place
  generation flip through the reshard stage -> pre-warm -> commit ->
  rollback primitive: scores land bitwise-equal to a cold engine on the
  new model, zero requests fail during the swap, and an injected
  `shard_upload` / `reshard_commit` fault mid-apply leaves the OLD
  generation serving bitwise with zero failed requests;
* per-tenant refresh touches exactly one tenant's generation.
"""

from __future__ import annotations

import json
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.fingerprints import (
    diff_fingerprints,
    fingerprint_dataset,
)
from photon_ml_tpu.data.game_dataset import (
    FixedEffectDataConfig,
    GameDataset,
    RandomEffectDataConfig,
    concat_datasets,
    take_rows,
)
from photon_ml_tpu.game import incremental
from photon_ml_tpu.game.checkpoint import read_delta_records
from photon_ml_tpu.game.model import RandomEffectModel
from photon_ml_tpu.optimize.config import (
    L2,
    CoordinateOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.serving import ScoreRequest, ServingBundle, ServingEngine
from photon_ml_tpu.serving.delta import (
    apply_delta,
    apply_delta_for_tenant,
    build_delta_bundle,
)
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import faults, telemetry

pytestmark = pytest.mark.serving

TASK = TaskType.LOGISTIC_REGRESSION
D_FE, D_RE, E = 6, 4, 10

DATA_CONFIGS = {
    "fixed": FixedEffectDataConfig("g"),
    "per-e": RandomEffectDataConfig("eid", "re", min_bucket=4),
}
_OC = CoordinateOptimizationConfig(
    optimizer=OptimizerConfig(max_iterations=25),
    regularization=L2,
    reg_weight=1.0,
)
OPT_CONFIGS = {"fixed": _OC, "per-e": _OC}


def _dataset(rng, n, ent):
    return GameDataset.build(
        {
            "g": jnp.asarray(rng.normal(size=(n, D_FE)).astype(np.float32)),
            "re": jnp.asarray(rng.normal(size=(n, D_RE)).astype(np.float32)),
        },
        (rng.uniform(size=n) < 0.5).astype(np.float32),
        id_tags={"eid": np.asarray(ent, np.int64)},
    )


def _base(rng, n=64):
    return _dataset(rng, n, rng.integers(0, E, size=n))


def _fit(dataset, **kw):
    return incremental.full_fit(
        dataset, DATA_CONFIGS, OPT_CONFIGS, TASK, **kw
    )


def _refit(merged, prev, **kw):
    return incremental.incremental_fit(
        merged, DATA_CONFIGS, OPT_CONFIGS, TASK, prev=prev, **kw
    )


def _delta_batch(rng, n=12, ent=(2, 5, E)):
    """n delta rows over the given entity pool (E = one brand-new id)."""
    return _dataset(rng, n, np.resize(np.asarray(ent), n))


def _requests(n=14):
    return [
        ScoreRequest(
            features={
                "g": np.full(D_FE, 0.25 * (i + 1), np.float32),
                "re": np.full(D_RE, 0.1 * (i + 1), np.float32),
            },
            entity_ids={"eid": i % (E + 3)},
            uid=str(i),
        )
        for i in range(n)
    ]

# ------------------------------------------------------------ fingerprints


class TestFingerprints:
    def test_diff_localizes_churned_and_new_entities(self, rng):
        base = _base(rng)
        prev = fingerprint_dataset(base, DATA_CONFIGS)
        merged = concat_datasets(base, _delta_batch(rng, ent=(2, 5, E)))
        new = fingerprint_dataset(merged, DATA_CONFIGS)
        diffs = diff_fingerprints(prev, new)
        # FE covers every row, so appended rows change it.
        assert diffs["fixed"].changed
        d = diffs["per-e"]
        assert set(d.changed_entities) == {2, 5, E}
        assert set(d.new_entities) == {E}
        # delta_rows counts the NEW dataset's rows of changed entities.
        tags = np.asarray(merged.id_tags["eid"])
        assert d.delta_rows == int(np.isin(tags, [2, 5, E]).sum())

    def test_identical_snapshot_diffs_clean(self, rng):
        base = _base(rng)
        a = fingerprint_dataset(base, DATA_CONFIGS)
        b = fingerprint_dataset(base, DATA_CONFIGS)
        assert all(not d.changed for d in diff_fingerprints(a, b).values())

    def test_entity_removal_is_loud(self, rng):
        base = _base(rng)
        prev = fingerprint_dataset(base, DATA_CONFIGS)
        tags = np.asarray(base.id_tags["eid"])
        keep = np.nonzero(tags != int(tags[0]))[0]
        shrunk = fingerprint_dataset(take_rows(base, keep), DATA_CONFIGS)
        with pytest.raises(ValueError, match="append/update-only"):
            diff_fingerprints(prev, shrunk)

    def test_in_place_re_edit_localizes_to_one_entity(self, rng):
        base = _base(rng)
        prev = fingerprint_dataset(base, DATA_CONFIGS)
        tags = np.asarray(base.id_tags["eid"])
        target = int(tags[0])
        re_plane = np.array(np.asarray(base.peek_shard("re")))
        re_plane[tags == target] += 1.0
        edited = GameDataset.build(
            {"g": base.peek_shard("g"), "re": jnp.asarray(re_plane)},
            np.asarray(base.labels),
            id_tags={"eid": tags},
        )
        diffs = diff_fingerprints(
            prev, fingerprint_dataset(edited, DATA_CONFIGS)
        )
        # The FE shard/labels/offsets/weights are untouched bytes.
        assert not diffs["fixed"].changed
        assert diffs["per-e"].changed_entities == (target,)
        assert diffs["per-e"].new_entities == ()


class TestDeltaPlan:
    def test_modes(self, rng):
        base = _base(rng)
        prev = fingerprint_dataset(base, DATA_CONFIGS)
        same = incremental.plan_delta_fit(
            prev, fingerprint_dataset(base, DATA_CONFIGS)
        )
        assert same.mode == "none" and same.changed_coordinates == ()
        merged = concat_datasets(base, _delta_batch(rng))
        new = fingerprint_dataset(merged, DATA_CONFIGS)
        assert (
            incremental.plan_delta_fit(prev, new, max_delta_fraction=0.9).mode
            == "delta"
        )
        # The escape hatch: churn past the fraction forces a full refit.
        assert (
            incremental.plan_delta_fit(
                prev, new, max_delta_fraction=0.01
            ).mode
            == "full"
        )

    def test_fraction_knob_default_routes_through_planner(
        self, rng, monkeypatch
    ):
        monkeypatch.setenv("PHOTON_REFRESH_MAX_DELTA_FRACTION", "0.0001")
        base = _base(rng)
        prev = fingerprint_dataset(base, DATA_CONFIGS)
        merged = concat_datasets(base, _delta_batch(rng))
        plan = incremental.plan_delta_fit(
            prev, fingerprint_dataset(merged, DATA_CONFIGS)
        )
        assert plan.mode == "full"


# ------------------------------------------------------------ model growth


class TestModelGrowth:
    def test_grow_moves_rows_by_key_through_a_resort(self, rng):
        mat = rng.normal(size=(4, D_RE)).astype(np.float32)
        mat[3] = 0.0
        model = RandomEffectModel(jnp.asarray(mat), None, TASK)
        prev_idx = {2: 0, 5: 1, 9: 2}
        # Key -1 sorts FIRST: every carried row moves position.
        new_idx = {-1: 0, 2: 1, 5: 2, 7: 3, 9: 4}
        grown = incremental.grow_random_effect_model(model, prev_idx, new_idx)
        g = np.asarray(grown.coefficients_matrix)
        assert g.shape == (6, D_RE)
        for k, old_row in prev_idx.items():
            assert np.array_equal(g[new_idx[k]], mat[old_row])
        assert not g[0].any() and not g[3].any() and not g[5].any()

    def test_grow_carries_variances(self, rng):
        mat = rng.normal(size=(3, D_RE)).astype(np.float32)
        var = rng.uniform(size=(3, D_RE)).astype(np.float32)
        model = RandomEffectModel(jnp.asarray(mat), jnp.asarray(var), TASK)
        grown = incremental.grow_random_effect_model(
            model, {1: 0, 4: 1}, {1: 0, 2: 1, 4: 2}
        )
        v = np.asarray(grown.variances_matrix)
        assert np.array_equal(v[0], var[0]) and np.array_equal(v[2], var[1])
        assert not v[1].any()


# --------------------------------------------------------- incremental fit


class TestIncrementalFit:
    def test_nothing_changed_carries_the_model_object(self, rng):
        base = _base(rng)
        st = _fit(base)
        res = _refit(base, st)
        assert res.plan.mode == "none"
        assert res.state.model is st.model
        assert res.max_rel_diff == 0.0

    def test_unchanged_coordinate_carried_bitwise(self, rng):
        """An RE-only in-place edit: the fixed effect's data is untouched,
        so its model is carried BITWISE (the ISSUE 16 parity contract on
        unchanged coordinates)."""
        base = _base(rng)
        st = _fit(base)
        tags = np.asarray(base.id_tags["eid"])
        target = int(tags[0])
        re_plane = np.array(np.asarray(base.peek_shard("re")))
        re_plane[tags == target] *= 1.5
        edited = GameDataset.build(
            {"g": base.peek_shard("g"), "re": jnp.asarray(re_plane)},
            np.asarray(base.labels),
            id_tags={"eid": tags},
        )
        res = _refit(edited, st)
        assert res.plan.mode == "delta"
        assert res.plan.changed_coordinates == ("per-e",)
        assert "fixed" in res.carried_coordinates
        assert np.array_equal(
            np.asarray(res.state.model["fixed"].coefficients.means),
            np.asarray(st.model["fixed"].coefficients.means),
        )
        # And within the RE coordinate, every OTHER entity is bitwise.
        pm = np.asarray(st.model["per-e"].coefficients_matrix)
        nm = np.asarray(res.state.model["per-e"].coefficients_matrix)
        for k, row in st.entity_indices["per-e"].items():
            if k != target:
                assert np.array_equal(pm[row], nm[row]), k
        assert not np.array_equal(pm[st.entity_indices["per-e"][target]],
                                  nm[st.entity_indices["per-e"][target]])
        assert res.max_rel_diff > 0.0

    def test_unchanged_entities_bitwise_on_append(self, rng):
        """Appended rows for a few entities (+ one brand-new): unchanged
        entities' coefficient rows are bitwise-equal to the previous
        from-scratch fit, through the index re-map."""
        base = _base(rng)
        st = _fit(base)
        merged = concat_datasets(base, _delta_batch(rng, ent=(2, 5, E)))
        res = _refit(merged, st)
        assert res.plan.mode == "delta"
        changed = set(res.plan.changed_entities["per-e"])
        assert E in set(res.plan.new_entities["per-e"])
        pm = np.asarray(st.model["per-e"].coefficients_matrix)
        nm = np.asarray(res.state.model["per-e"].coefficients_matrix)
        prev_idx = st.entity_indices["per-e"]
        new_idx = res.state.entity_indices["per-e"]
        unchanged = [k for k in prev_idx if k not in changed]
        assert unchanged
        for k in unchanged:
            assert np.array_equal(pm[prev_idx[k]], nm[new_idx[k]]), k
        # The new entity actually learned something.
        assert np.asarray(nm[new_idx[E]]).any()

    def test_full_mode_grows_then_refits_everything(self, rng):
        base = _base(rng)
        st = _fit(base)
        merged = concat_datasets(base, _delta_batch(rng))
        res = _refit(merged, st, max_delta_fraction=0.01)
        assert res.plan.mode == "full"
        assert set(res.state.entity_indices["per-e"]) == set(
            np.unique(np.asarray(merged.id_tags["eid"])).tolist()
        )

    def test_full_mode_discards_stale_checkpoint_from_prior_round(
        self, rng, tmp_path
    ):
        """Two consecutive full-mode rounds sharing one checkpoint_dir
        (the refresh-loop shape): round 2's merged dataset has a new
        config fingerprint, so round 1's leftover checkpoint is stale by
        construction — the full refit must discard it and start fresh
        instead of refusing to resume."""
        ckpt_dir = str(tmp_path / "ckpt")
        base = _base(rng)
        st = _fit(base)
        merged1 = concat_datasets(base, _delta_batch(rng))
        res1 = _refit(
            merged1, st, max_delta_fraction=0.01, checkpoint_dir=ckpt_dir
        )
        assert res1.plan.mode == "full"
        merged2 = concat_datasets(merged1, _delta_batch(rng))
        res2 = _refit(
            merged2, res1.state,
            max_delta_fraction=0.01, checkpoint_dir=ckpt_dir,
        )
        assert res2.plan.mode == "full"
        # The second round refit everything over the bigger index —
        # stale state from round 1 neither resumed nor blocked it.
        assert set(res2.state.entity_indices["per-e"]) == set(
            np.unique(np.asarray(merged2.id_tags["eid"])).tolist()
        )

    def test_delta_records_and_journal(self, rng, tmp_path):
        base = _base(rng)
        st = _fit(base)
        merged = concat_datasets(base, _delta_batch(rng))
        path = str(tmp_path / "journal.jsonl")
        journal = telemetry.RunJournal(path)
        telemetry.install_journal(journal)
        try:
            res = _refit(merged, st, checkpoint_dir=str(tmp_path))
        finally:
            telemetry.uninstall_journal()
            journal.close()
        n_ok, errors = telemetry.validate_journal(path)
        assert not errors and n_ok > 0
        types = [
            json.loads(line)["type"] for line in open(path) if line.strip()
        ]
        assert "delta_fit_start" in types and "delta_fit_finish" in types
        (rec,) = read_delta_records(str(tmp_path))
        assert rec["mode"] == "delta"
        assert rec["max_rel_diff"] == res.max_rel_diff
        assert rec["total_rows"] == merged.num_samples


# ------------------------------------------------------------ delta bundle


def _serving_state(rng):
    base = _base(rng)
    st = _fit(base)
    merged = concat_datasets(base, _delta_batch(rng, ent=(2, 5, E)))
    res = _refit(merged, st)
    delta = build_delta_bundle(
        st, res.state, source="test", mode=res.plan.mode,
        delta_rows=res.plan.delta_rows, total_rows=res.plan.total_rows,
    )
    return base, st, res, delta


class TestDeltaBundle:
    def test_bundle_is_the_bitwise_model_diff(self, rng):
        _, st, res, delta = _serving_state(rng)
        d = delta.coordinates["per-e"]
        changed = set(res.plan.changed_entities["per-e"])
        new_idx = res.state.entity_indices["per-e"]
        # Exactly the churned + new entities' rows ride the wire...
        assert set(d.rows.tolist()) == {new_idx[k] for k in changed}
        nm = np.asarray(res.state.model["per-e"].coefficients_matrix)
        assert np.array_equal(d.values, nm[d.rows])
        # ...and the FE plane ships whole iff it changed.
        assert ("fixed" in delta.coordinates) == (
            "fixed" in res.plan.changed_coordinates
        )
        assert d.logical_rows == len(new_idx) + 1

    def test_manifest_matches_contract_keys(self, rng):
        from photon_ml_tpu.utils.contracts import DELTA_BUNDLE_KEYS

        _, _, _, delta = _serving_state(rng)
        assert tuple(delta.manifest()) == DELTA_BUNDLE_KEYS

    def test_identical_states_make_an_empty_bundle(self, rng):
        base = _base(rng)
        st = _fit(base)
        delta = build_delta_bundle(st, st, source="noop", mode="none")
        assert delta.is_empty and delta.nbytes == 0

    def test_resort_rides_the_carry_map_not_the_wire(self, rng):
        """A new entity that sorts FIRST (-1) moves every carried row: the
        moved-but-unchanged rows go in the carry map, not the payload."""
        base = _base(rng)
        st = _fit(base)
        merged = concat_datasets(base, _delta_batch(rng, ent=(-1,)))
        res = _refit(merged, st)
        delta = build_delta_bundle(st, res.state, source="resort")
        d = delta.coordinates["per-e"]
        assert d.carry_old is not None
        # Carried rows moved by exactly one position (the -1 prepend).
        assert np.array_equal(d.carry_new, d.carry_old + 1)
        new_idx = res.state.entity_indices["per-e"]
        assert set(d.rows.tolist()) == {new_idx[-1]}


# ------------------------------------------------------- live delta apply


def _live_engine(model, indices, **kw):
    specs = incremental.scoring_specs(DATA_CONFIGS, indices)
    return ServingEngine(
        ServingBundle.from_model(model, specs, TASK, **kw), max_batch=16
    )


def _scores(results):
    return [r.score for r in results]


class TestApplyDelta:
    def test_apply_matches_cold_engine_bitwise(self, rng):
        _, st, res, delta = _serving_state(rng)
        reqs = _requests()
        with _live_engine(res.state.model, res.state.entity_indices) as cold:
            want = _scores(cold.score_batch(reqs))
        eng = _live_engine(st.model, st.entity_indices)
        try:
            info = apply_delta(eng, delta)
            assert info["committed"] and info["version"] == 1
            assert info["delta_rows_staged"] == len(
                delta.coordinates["per-e"].rows
            )
            got = _scores(eng.score_batch(reqs))
            assert got == want
            prov = eng.bundle.provenance
            assert prov["origin"] == "incremental"
            assert prov["deltas_applied"] == 1
            assert prov["last_delta_source"] == "test"
            assert prov["generation"] == 1
            assert eng.metrics()["bundle_deltas"] == 1
            assert faults.counters()["delta_applies"] == 1
            assert faults.counters()["delta_rows_staged"] == info[
                "delta_rows_staged"
            ]
        finally:
            eng.close()
            eng.bundle.release()

    def test_empty_bundle_is_a_noop(self, rng):
        base = _base(rng)
        st = _fit(base)
        delta = build_delta_bundle(st, st, source="noop")
        with _live_engine(st.model, st.entity_indices) as eng:
            info = apply_delta(eng, delta)
            assert not info["committed"]
            assert eng.bundle_version == 0
            assert eng.bundle.provenance["deltas_applied"] == 0

    def test_two_tier_delta_rebuilds_the_cold_store(self, rng):
        _, st, res, delta = _serving_state(rng)
        reqs = _requests()
        with _live_engine(res.state.model, res.state.entity_indices) as cold:
            want = _scores(cold.score_batch(reqs))
        eng = _live_engine(st.model, st.entity_indices, hot_rows={"per-e": 4})
        try:
            info = apply_delta(eng, delta)
            assert info["committed"]
            assert _scores(eng.score_batch(reqs)) == want
        finally:
            eng.close()
            eng.bundle.release()

    def test_upload_fault_mid_apply_rolls_back_under_traffic(
        self, rng, monkeypatch
    ):
        """The ISSUE 16 rollback drill: an injected `shard_upload` fault
        mid-delta-apply leaves the OLD generation serving bitwise with
        zero failed requests, and journals the rollback."""
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        _, st, res, delta = _serving_state(rng)
        reqs = _requests()
        eng = _live_engine(st.model, st.entity_indices)
        eng.warmup()
        ref = _scores(eng.score_batch(reqs))
        stop = threading.Event()
        failures: list = []
        answered = [0]

        def _traffic(b):
            j = 0
            while not stop.is_set():
                try:
                    r = b.score(reqs[j % len(reqs)])
                    if r.score != ref[j % len(reqs)]:
                        failures.append(f"drift at {j}")
                    answered[0] += 1
                except Exception as exc:  # noqa: BLE001 - recorded
                    failures.append(repr(exc))
                j += 1

        try:
            with eng, eng.batcher(max_wait_ms=0.5) as batcher:
                th = threading.Thread(
                    target=_traffic,
                    args=(batcher,),
                    name="photon-refresh-traffic",
                )
                th.start()
                time.sleep(0.05)
                with faults.inject("shard_upload:9999"):
                    with pytest.raises(faults.InjectedFault):
                        apply_delta(eng, delta)
                time.sleep(0.05)
                stop.set()
                th.join(timeout=60)
                assert not th.is_alive()
            assert not failures, failures[:3]
            assert answered[0] > 0
            assert eng.bundle_version == 0
            assert _scores(eng.score_batch(reqs)) == ref
            assert faults.counters()["delta_rollbacks"] == 1
            assert "delta_applies" not in faults.counters()
            prov = eng.bundle.provenance
            assert prov["deltas_applied"] == 0 and prov["generation"] == 0
        finally:
            eng.close()
            eng.bundle.release()

    def test_commit_fault_rolls_back_and_journals(
        self, rng, monkeypatch, tmp_path
    ):
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        _, st, _, delta = _serving_state(rng)
        reqs = _requests()
        path = str(tmp_path / "journal.jsonl")
        journal = telemetry.RunJournal(path)
        telemetry.install_journal(journal)
        eng = _live_engine(st.model, st.entity_indices)
        try:
            ref = _scores(eng.score_batch(reqs))
            with faults.inject("reshard_commit:1"):
                with pytest.raises(faults.InjectedFault):
                    apply_delta(eng, delta)
            assert eng.bundle_version == 0
            assert _scores(eng.score_batch(reqs)) == ref
            # Second attempt (fault spent) commits the SAME delta.
            info = apply_delta(eng, delta)
            assert info["committed"] and eng.bundle_version == 1
        finally:
            eng.close()
            eng.bundle.release()
            telemetry.uninstall_journal()
            journal.close()
        n_ok, errors = telemetry.validate_journal(path)
        assert not errors and n_ok > 0
        types = [
            json.loads(line)["type"] for line in open(path) if line.strip()
        ]
        assert "delta_rollback" in types and "delta_apply" in types


class TestTenantRefresh:
    def test_per_tenant_delta_touches_one_generation(self, rng):
        from photon_ml_tpu.serving.tenancy import TenantRegistry

        _, st, res, delta = _serving_state(rng)
        specs = incremental.scoring_specs(DATA_CONFIGS, st.entity_indices)
        reqs = _requests(6)
        with _live_engine(res.state.model, res.state.entity_indices) as cold:
            want = _scores(cold.score_batch(reqs))
        with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
            reg.admit(
                "fresh", ServingBundle.from_model(st.model, specs, TASK)
            )
            reg.admit(
                "stale", ServingBundle.from_model(st.model, specs, TASK)
            )
            before = [reg.score("stale", r).score for r in reqs]
            info = apply_delta_for_tenant(reg, "fresh", delta)
            assert info["committed"]
            got = [reg.score("fresh", r).score for r in reqs]
            assert got == want
            # The OTHER tenant's generation and lineage are untouched.
            assert [reg.score("stale", r).score for r in reqs] == before
            assert reg.tenant("stale").engine.bundle_version == 0
            assert reg.tenant("stale").bundle.provenance["deltas_applied"] == 0
            assert reg.tenant("fresh").bundle.provenance["deltas_applied"] == 1

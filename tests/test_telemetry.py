"""photon-trace telemetry (utils/telemetry.py, ISSUE 11).

Four contracts:
  * spans from the named worker fleet land under the correct parent via
    the span_handoff/adopt_span discipline, with no orphans;
  * histogram merges are associative and order-independent, across
    threads and across subprocesses (the bench child merge path);
  * every journal event type round-trips its contracts.py schema;
  * with no tracer installed (PHOTON_TRACE=0), span() emits nothing and
    costs one global read — no measurable overhead on a tier-1 fit.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.game_dataset import (
    FixedEffectDataConfig,
    GameDataset,
    RandomEffectDataConfig,
)
from photon_ml_tpu.estimators.game_estimator import GameEstimator
from photon_ml_tpu.optimize.config import (
    CoordinateOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import telemetry
from photon_ml_tpu.utils.contracts import (
    JOURNAL_EVENT_SCHEMAS,
    JOURNAL_LINE_KEYS,
    PROFILE_FIT_KEYS,
)
from photon_ml_tpu.utils.observability import EventEmitter, journal_listener

# One geometric bucket width: the histogram quantile accuracy bound.
_BUCKET_RATIO = 10.0 ** (1.0 / 16.0)


def _assert_snapshots_equal(a, b):
    """Snapshot equality modulo float-summation order: buckets, count,
    min and max are exactly associative; `sum` is a float accumulation,
    equal only to rounding."""
    assert {k: v for k, v in a.items() if k != "sum"} == {
        k: v for k, v in b.items() if k != "sum"
    }
    assert a["sum"] == pytest.approx(b["sum"])


@pytest.fixture
def tracer():
    t = telemetry.install_tracer(telemetry.Tracer())
    yield t
    telemetry.uninstall_tracer()


def _game_fixture(rng, n=192, n_entities=8):
    X = rng.normal(size=(n, 6)).astype(np.float32)
    y = (rng.uniform(size=n) > 0.5).astype(np.float32)
    ents = rng.integers(0, n_entities, size=n).astype(str)
    return GameDataset.build(
        {"g": X}, y, id_tags={"e1": ents, "e2": ents[::-1].copy()}
    )


def _fit_estimator(ds, tmp_path=None, emitter=None, pipeline=None):
    est = GameEstimator(
        TaskType.LOGISTIC_REGRESSION,
        {
            "global": FixedEffectDataConfig("g"),
            "per-e1": RandomEffectDataConfig("e1", "g"),
            "per-e2": RandomEffectDataConfig("e2", "g"),
        },
        event_emitter=emitter,
        pipeline=pipeline,
        checkpoint_dir=None if tmp_path is None else str(tmp_path / "ckpt"),
    )
    cfg = {
        cid: CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(max_iterations=3)
        )
        for cid in ("global", "per-e1", "per-e2")
    }
    return est, est.fit(ds, None, [cfg])


# ------------------------------------------------------------------- spans


class TestSpans:
    def test_handoff_parents_worker_spans(self, tracer):
        """The AsyncUploader pattern: a worker thread adopting the
        submitter's handoff parents its spans under the submitter's span."""
        results = []

        def worker(handoff):
            with telemetry.adopt_span(handoff), telemetry.span("child"):
                pass
            results.append(True)

        with telemetry.span("parent"):
            h = telemetry.span_handoff()
            t = threading.Thread(target=worker, args=(h,), name="photon-test")
            t.start()
            t.join()
        spans = {s["args"]["span_id"]: s for s in tracer.spans()}
        child = next(s for s in tracer.spans() if s["name"] == "child")
        parent = next(s for s in tracer.spans() if s["name"] == "parent")
        assert child["args"]["parent_id"] == parent["args"]["span_id"]
        assert child["tid"] != parent["tid"]
        assert all(
            s["args"].get("parent_id") is None
            or s["args"]["parent_id"] in spans
            for s in tracer.spans()
        )

    def test_fit_worker_fleet_spans_parent_correctly(self, rng, tracer):
        """A pipelined fit fans work onto the photon-prepare pool and the
        async upload/pack workers; every span from a named worker thread
        must resolve to an in-trace parent — no orphans."""
        ds = _game_fixture(rng)
        _fit_estimator(ds, pipeline=True)
        spans = tracer.spans()
        by_id = {s["args"]["span_id"]: s for s in spans}
        assert any(s["name"] == "fit" for s in spans)
        assert any(s["name"] == "re_build" for s in spans)
        # No orphans anywhere: every parent reference resolves.
        for s in spans:
            pid = s["args"].get("parent_id")
            assert pid is None or pid in by_id, f"orphan span {s['name']}"
        # Spans recorded OFF the main thread (the worker fleet) must have
        # adopted a parent — a parentless worker span is a lost handoff.
        trace = tracer.to_chrome_trace()
        names = {
            e["tid"]: e["args"]["name"]
            for e in trace["traceEvents"]
            if e.get("ph") == "M"
        }
        main_tid = threading.get_ident()
        worker_spans = [s for s in spans if s["tid"] != main_tid]
        assert worker_spans, "pipelined fit recorded no worker-thread spans"
        for s in worker_spans:
            assert s["args"].get("parent_id") is not None, (
                f"span {s['name']} on thread {names.get(s['tid'])} "
                "has no parent"
            )
            assert names.get(s["tid"], "").startswith("photon-")

    @pytest.mark.serving
    def test_serving_batch_spans(self, rng, tracer):
        """The batcher's flush thread records serving_batch spans with
        queue-wait attribution; the engine's pack/lookup/score stage
        spans nest under them on the same thread."""
        from tests.test_serving import TASK, _fixture

        from photon_ml_tpu.serving import ServingBundle, ServingEngine

        model, specs, _, reqs = _fixture(rng)
        engine = ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=8
        )
        with engine, engine.batcher(max_wait_ms=1.0) as batcher:
            batcher.score_all(reqs)
        spans = tracer.spans()
        batches = [s for s in spans if s["name"] == "serving_batch"]
        assert batches
        assert all("queue_wait_ms_max" in b["args"] for b in batches)
        batch_ids = {b["args"]["span_id"] for b in batches}
        packs = [s for s in spans if s["name"] == "serve_pack"]
        assert packs and all(
            p["args"]["parent_id"] in batch_ids for p in packs
        )

    def test_export_is_chrome_loadable_json(self, tracer, tmp_path):
        with telemetry.span("a", tag="x"):
            with telemetry.span("b"):
                pass
        path = tracer.export(str(tmp_path / "trace.json"))
        doc = json.load(open(path))
        assert isinstance(doc["traceEvents"], list)
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in xs} == {"a", "b"}
        for e in xs:  # Perfetto-required fields
            assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(e)
        b = next(e for e in xs if e["name"] == "b")
        a = next(e for e in xs if e["name"] == "a")
        assert b["args"]["parent_id"] == a["args"]["span_id"]


# --------------------------------------------------------------- histograms


class TestHistogramMerge:
    def test_quantiles_within_one_bucket(self, rng):
        vals = np.exp(rng.normal(size=20_000) * 2.0)
        h = telemetry.Histogram()
        for v in vals:
            h.record(float(v))
        for q in (0.5, 0.95, 0.99):
            exact = float(np.quantile(vals, q))
            est = h.quantile(q)
            assert est / exact < _BUCKET_RATIO * 1.01
            assert exact / est < _BUCKET_RATIO * 1.01

    def test_merge_associative_and_order_independent(self, rng):
        vals = [float(v) for v in np.exp(rng.normal(size=3000))]
        parts = [telemetry.Histogram() for _ in range(4)]
        for i, v in enumerate(vals):
            parts[i % 4].record(v)
        snaps = [p.snapshot() for p in parts]
        m = telemetry.merge_histogram_snapshots
        left = m(m(m(snaps[0], snaps[1]), snaps[2]), snaps[3])
        right = m(snaps[0], m(snaps[1], m(snaps[2], snaps[3])))
        shuffled = m(snaps[3], snaps[1], snaps[0], snaps[2])
        _assert_snapshots_equal(left, right)
        _assert_snapshots_equal(left, shuffled)
        whole = telemetry.Histogram()
        for v in vals:
            whole.record(v)
        _assert_snapshots_equal(left, whole.snapshot())

    def test_thread_level_merge(self, rng):
        """Concurrent recorders into ONE histogram lose nothing, and
        per-thread histograms merge to the same snapshot — the two ways
        threads share the registry."""
        vals = [float(v) for v in np.exp(rng.normal(size=2000))]
        shared = telemetry.Histogram()
        locals_ = [telemetry.Histogram() for _ in range(4)]

        def work(k):
            for v in vals[k::4]:
                shared.record(v)
                locals_[k].record(v)

        threads = [
            threading.Thread(target=work, args=(k,), name=f"photon-test-{k}")
            for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        merged = telemetry.merge_histogram_snapshots(
            *[h.snapshot() for h in locals_]
        )
        _assert_snapshots_equal(merged, shared.snapshot())
        assert merged["count"] == len(vals)

    @pytest.mark.slow
    def test_subprocess_merge(self, tmp_path):
        """The bench-child path: a snapshot serialized from another
        process merges with a local one exactly (fixed shared bounds)."""
        code = (
            "from photon_ml_tpu.utils import telemetry\n"
            "import json\n"
            "h = telemetry.Histogram()\n"
            "for i in range(1, 1001):\n"
            "    h.record(i * 0.5)\n"
            "print(json.dumps(h.snapshot()))\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=120,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert out.returncode == 0, out.stderr
        remote = json.loads(out.stdout.strip().splitlines()[-1])
        local = telemetry.Histogram()
        for i in range(1, 1001):
            local.record(i * 0.5)
        assert remote == local.snapshot()
        merged = telemetry.merge_histogram_snapshots(remote, local.snapshot())
        assert merged["count"] == 2000
        assert merged["min"] == 0.5 and merged["max"] == 500.0


class TestLatencyStats:
    def test_small_run_exact(self, rng):
        stats = telemetry.LatencyStats(reservoir=256)
        vals = [float(v) for v in np.exp(rng.normal(size=100))]
        for v in vals:
            stats.record(v)
        for q in (50.0, 95.0, 99.0):
            assert stats.percentile(q) == pytest.approx(
                float(np.percentile(vals, q))
            )

    def test_sustained_traffic_bounded_and_close(self, rng):
        stats = telemetry.LatencyStats(reservoir=128)
        vals = [float(v) for v in np.exp(rng.normal(size=10_000))]
        for v in vals:
            stats.record(v)
        # Memory bound: reservoir never grows past its cap.
        assert len(stats._reservoir) == 128
        for q in (50.0, 95.0, 99.0):
            exact = float(np.percentile(vals, q))
            est = stats.percentile(q)
            assert est / exact < _BUCKET_RATIO * 1.01
            assert exact / est < _BUCKET_RATIO * 1.01


# ----------------------------------------------------------- labeled metrics


class TestLabeledMetrics:
    """ISSUE 19: gauges and histograms carry the same per-label
    attribution counters grew in ISSUE 15 — aggregates intact, labeled
    sub-series over the SAME fixed bucket bounds (so they merge exactly
    as order-independently as the aggregates)."""

    def test_labeled_observe_keeps_aggregate_intact(self, rng):
        vals_a = [float(v) for v in np.exp(rng.normal(size=400))]
        vals_b = [float(v) for v in np.exp(rng.normal(size=300) + 1.0)]
        for v in vals_a:
            telemetry.METRICS.observe(
                "serving_latency_ms", v, labels=(("tenant", "a"),)
            )
        for v in vals_b:
            telemetry.METRICS.observe(
                "serving_latency_ms", v, labels=(("tenant", "b"),)
            )
        telemetry.METRICS.observe("serving_latency_ms", 1.0)  # unlabeled
        agg = telemetry.METRICS.histogram("serving_latency_ms")
        assert agg.snapshot()["count"] == len(vals_a) + len(vals_b) + 1
        labeled = telemetry.METRICS.labeled_histograms("serving_latency_ms")
        assert set(labeled) == {"tenant=a", "tenant=b"}
        assert labeled["tenant=a"]["count"] == len(vals_a)
        assert labeled["tenant=b"]["count"] == len(vals_b)
        # Per-label quantiles differ the way the data does.
        qa = telemetry.snapshot_quantile(labeled["tenant=a"], 0.95)
        qb = telemetry.snapshot_quantile(labeled["tenant=b"], 0.95)
        assert qb > qa
        # The live per-label handle agrees with the snapshot.
        h = telemetry.METRICS.labeled_histogram(
            "serving_latency_ms", (("tenant", "a"),)
        )
        assert h is not None and h.snapshot()["count"] == len(vals_a)

    def test_labeled_merge_is_order_independent(self, rng):
        """Labeled sub-snapshots share the aggregate's fixed bucket
        bounds: merging them in ANY order reproduces the aggregate
        (when every observe was labeled)."""
        vals = [float(v) for v in np.exp(rng.normal(size=2000))]
        tenants = ("a", "b", "c", "d")
        for i, v in enumerate(vals):
            telemetry.METRICS.observe(
                "serving_queue_wait_ms",
                v,
                labels=(("tenant", tenants[i % 4]),),
            )
        labeled = telemetry.METRICS.labeled_histograms(
            "serving_queue_wait_ms"
        )
        snaps = [labeled[f"tenant={t}"] for t in tenants]
        m = telemetry.merge_histogram_snapshots
        fwd = m(snaps[0], snaps[1], snaps[2], snaps[3])
        rev = m(snaps[3], snaps[2], snaps[1], snaps[0])
        nested = m(m(snaps[2], snaps[0]), m(snaps[1], snaps[3]))
        _assert_snapshots_equal(fwd, rev)
        _assert_snapshots_equal(fwd, nested)
        agg = telemetry.METRICS.histogram("serving_queue_wait_ms")
        _assert_snapshots_equal(fwd, agg.snapshot())

    def test_label_scope_routes_gauges_and_histograms(self):
        with telemetry.metric_label_scope(tenant="a"):
            telemetry.METRICS.set_gauge("serving_pending_depth", 3.0)
            telemetry.METRICS.observe("serving_batch_size", 8.0)
        telemetry.METRICS.set_gauge("serving_pending_depth", 5.0)
        gauges = telemetry.METRICS.labeled_gauges("serving_pending_depth")
        assert gauges == {"tenant=a": 3.0}
        labeled = telemetry.METRICS.labeled_histograms("serving_batch_size")
        assert labeled["tenant=a"]["count"] == 1
        snap = telemetry.METRICS.snapshot()
        assert snap["gauges"]["serving_pending_depth"] == 5.0
        assert (
            snap["labeled_gauges"]["serving_pending_depth"]["tenant=a"]
            == 3.0
        )
        assert (
            snap["labeled_histograms"]["serving_batch_size"]["tenant=a"][
                "count"
            ]
            == 1
        )

    def test_undeclared_names_refused_and_reset_clears_labels(self):
        with pytest.raises(KeyError):
            telemetry.METRICS.observe("no_such_metric", 1.0)
        with pytest.raises(KeyError):
            telemetry.METRICS.set_gauge("no_such_metric", 1.0)
        telemetry.METRICS.observe(
            "serving_batch_size", 4.0, labels=(("tenant", "a"),)
        )
        telemetry.METRICS.reset_counters()  # counters only: labels stay
        assert telemetry.METRICS.labeled_histograms("serving_batch_size")
        telemetry.METRICS.reset()
        assert (
            telemetry.METRICS.labeled_histograms("serving_batch_size") == {}
        )
        assert telemetry.METRICS.labeled_gauges("serving_pending_depth") == {}


# ------------------------------------------------------------------ journal


class TestJournal:
    _SAMPLE = {
        "args": "ns",
        "num_samples": 7,
        "index": 0,
        "total": 2,
        "iteration": 1,
        "coordinate": "per-e1",
        "seconds": 0.25,
        "accepted": True,
        "step": 3,
        "num_configs": 2,
        "best_metric": 0.91,
        "error": "RuntimeError('x')",
        "from_state": "READY",
        "to_state": "DEGRADED",
        "reasons": ["circuit_open"],
        "version": 2,
        "outcome": "committed",
        "label": "serving dispatch",
        "counter": "retries",
        "attempt": 1,
        "site": "decode",
        "invocation": 4,
        "shard_index": 1,
        "bytes": 4096,
        # -- hyperparameter sweep lifecycle (ISSUE 12) --
        "round": 0,
        "trial": 5,
        "mode": "stacked",
        "value": 0.72,
        "diverged_steps": 0,
        # -- live mesh elasticity (ISSUE 13) --
        "old_shards": 8,
        "new_shards": 4,
        "moved_rows": 167,
        "moved_bytes": 5344,
        "restaged_bytes": 5344,
        "reason": "InjectedFault('reshard_stage')",
        "surviving_devices": 4,
        "source": "memory",
        # -- adaptive runtime planner (ISSUE 14) --
        "decision": "prefetch_depth",
        "fallback": 1,
        # -- multi-tenant serving (ISSUE 15) --
        "tenant": "t-a",
        "device_bytes": 4096,
        "demoted_tenants": ["t-cold"],
        "freed_bytes": 2048,
        "hot_rows": 0,
        # -- continuous refresh (ISSUE 16) --
        "changed_coordinates": ["per-e1"],
        "carried_coordinates": ["fixed"],
        "delta_rows": 96,
        "total_rows": 512,
        "max_rel_diff": 0.31,
        "coordinates": ["per-e1"],
        "rows": 96,
        # -- multi-host production mode (ISSUE 17) --
        "host": 1,
        "missed_beats": 20,
        "name": "ckpt-commit",
        "num_hosts": 2,
        "restaged_rows": 11,
        # -- shadow deployment & online evaluation (ISSUE 18) --
        "champion": "live",
        "challenger": "cand",
        "window_size": 64,
        "min_windows": 3,
        "mirror_fraction": 1.0,
        "window": 2,
        "champion_metric": 0.93,
        "challenger_metric": 0.88,
        "evaluator": "AUC",
        "healthy": False,
        "windows": 3,
        # -- closed-loop autoscaling (ISSUE 19) --
        "rule": "hbm-demote",
        "action": {"kind": "demote", "tenant": "t-cold", "params": {}},
        "evidence": {"signal": 0.91, "fire_above": 0.85},
        "rollbacks": 1,
        # -- precision ladder (ISSUE 20) --
        "from_tier": "f32",
        "to_tier": "bf16",
        "repinned_bytes": 2048,
    }

    def test_every_event_type_round_trips_its_schema(self, tmp_path):
        path = str(tmp_path / "journal.jsonl")
        with telemetry.RunJournal(path) as journal:
            for etype, schema in JOURNAL_EVENT_SCHEMAS.items():
                journal.emit(etype, **{k: self._SAMPLE[k] for k in schema})
        n_ok, errors = telemetry.validate_journal(path)
        assert errors == []
        assert n_ok == len(JOURNAL_EVENT_SCHEMAS)
        for raw in open(path):
            doc = json.loads(raw)
            schema = JOURNAL_EVENT_SCHEMAS[doc["type"]]
            body = {k for k in doc if k not in JOURNAL_LINE_KEYS}
            assert body == set(schema)
            for k in schema:  # values survive the trip
                assert doc[k] == self._SAMPLE[k]

    def test_schema_violations_raise_and_never_write(self, tmp_path):
        path = str(tmp_path / "journal.jsonl")
        with telemetry.RunJournal(path) as journal:
            with pytest.raises(KeyError):
                journal.emit("not_a_type", x=1)
            with pytest.raises(ValueError):
                journal.emit("watchdog_trip")  # missing `label`
            with pytest.raises(ValueError):
                journal.emit("watchdog_trip", label="x", extra=1)
        assert open(path).read() == ""

    def test_validate_flags_bad_lines(self, tmp_path):
        path = str(tmp_path / "journal.jsonl")
        with open(path, "w") as f:
            f.write('{"ts": 1.0, "type": "watchdog_trip", "label": "ok"}\n')
            f.write("not json\n")
            f.write('{"ts": 1.0, "type": "mystery"}\n')
            f.write('{"ts": 1.0, "type": "watchdog_trip"}\n')
        n_ok, errors = telemetry.validate_journal(path)
        assert n_ok == 1 and len(errors) == 3

    def test_estimator_lifecycle_lands_in_journal(self, rng, tmp_path):
        """The ISSUE 11 satellite: a LIBRARY fit (no CLI) with an emitter
        produces the same typed journal record as cli/train jobs —
        start, sweep, per-coordinate updates, checkpoints, finish."""
        path = str(tmp_path / "journal.jsonl")
        journal = telemetry.RunJournal(path)
        emitter = EventEmitter()
        emitter.register(journal_listener(journal))
        ds = _game_fixture(rng)
        _fit_estimator(ds, tmp_path=tmp_path, emitter=emitter)
        journal.close()
        n_ok, errors = telemetry.validate_journal(path)
        assert errors == []
        types = [json.loads(l)["type"] for l in open(path) if l.strip()]
        assert types[0] == "fit_start" and types[-1] == "fit_finish"
        assert types.count("sweep_config") == 1
        assert types.count("coordinate_update") == 3  # one per coordinate
        assert types.count("checkpoint") == 3  # checkpoint_dir was set
        updates = [
            json.loads(l)
            for l in open(path)
            if json.loads(l)["type"] == "coordinate_update"
        ]
        assert [u["coordinate"] for u in updates] == [
            "global",
            "per-e1",
            "per-e2",
        ]
        assert all(u["accepted"] for u in updates)


# ------------------------------------------------------------------ profile


class TestProfile:
    def test_fit_profile_round_trip_and_loud_contract(self, rng, tmp_path):
        ds = _game_fixture(rng)
        est, _ = _fit_estimator(ds)
        profile = est.run_profile()
        path = telemetry.write_profile(str(tmp_path / "profile.json"), profile)
        back = telemetry.read_profile(path, kind="fit")
        for key in PROFILE_FIT_KEYS:
            assert key in back
        assert back["dispatch"]["re_path"] in ("host", "device")
        assert back["bucket_shapes"]["per-e1"]
        # Loud contract: a dropped section must refuse to load.
        del back["dispatch"]
        broken = str(tmp_path / "broken.json")
        with open(broken, "w") as f:
            json.dump(back, f)
        with pytest.raises(ValueError, match="dispatch"):
            telemetry.read_profile(broken)
        with pytest.raises(ValueError, match="kind"):
            telemetry.read_profile(path, kind="serve")


# ------------------------------------------------------- tracing-off no-ops


class TestTracingOff:
    def test_span_is_shared_noop_and_records_nothing(self):
        assert telemetry.current_tracer() is None
        s1 = telemetry.span("anything", x=1)
        s2 = telemetry.span("else")
        assert s1 is s2  # the shared singleton: no allocation per call
        with s1:
            pass
        assert telemetry.span_handoff() is None

    def test_untraced_fit_records_nothing_and_costs_nothing(self, rng):
        """PHOTON_TRACE=0 contract: no tracer -> a tier-1-sized fit emits
        zero spans, and the span() fast path is orders of magnitude below
        anything a fit could measure."""
        assert telemetry.current_tracer() is None
        ds = _game_fixture(rng)
        _fit_estimator(ds)
        assert telemetry.current_tracer() is None  # nothing installed
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            with telemetry.span("x"):
                pass
        per_call_us = (time.perf_counter() - t0) / n * 1e6
        assert per_call_us < 25.0  # generous CI bound; typically ~0.3us

"""Failure-domain hardening tests (the chaos harness).

The contracts, mirroring the reference's free recovery from Spark lineage
re-computation + driver retries (CoordinateDescent.scala:325-341):

* a training run under injected TRANSIENT faults (decode, upload, one
  diverged solve) completes and produces a model BITWISE-identical to the
  fault-free run — retries/fallbacks move when work happens, never what it
  computes;
* a SIGKILLed training process, resumed from its checkpoint, lands exactly
  where the uninterrupted run does;
* the async data plane degrades instead of dying: failed uploader jobs are
  evicted (retryable), failed prefetches fall back to synchronous uploads,
  failed background packs/builds fall back to in-thread rebuilds;
* a non-finite coordinate update is rejected, counted, and NEVER written to
  the durable checkpoint;
* a checkpoint with a truncated/missing model file is refused with an
  actionable integrity error, not loaded as garbage.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data import pipeline as pl
from photon_ml_tpu.data.containers import SparseFeatures
from photon_ml_tpu.data.game_dataset import (
    GameDataset,
    RandomEffectDataConfig,
    ShardDict,
    build_random_effect_dataset,
)
from photon_ml_tpu.game.checkpoint import (
    CheckpointIntegrityError,
    CoordinateDescentCheckpoint,
)
from photon_ml_tpu.game.coordinate import (
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent
from photon_ml_tpu.game.model import Coefficients, FixedEffectModel
from photon_ml_tpu.optimize.config import (
    L2,
    CoordinateOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------- fixtures


def _chaos_dataset(n=180, d=4, n_entities=5, d_re=3, seed=0):
    rng = np.random.default_rng(seed)
    Xf = rng.normal(size=(n, d)).astype(np.float32)
    Xf[:, -1] = 1.0
    Xe = rng.normal(size=(n, d_re)).astype(np.float32)
    entity = rng.integers(0, n_entities, size=n)
    w = rng.normal(size=d)
    u = rng.normal(size=(n_entities, d_re))
    m = Xf @ w + np.einsum("nd,nd->n", Xe, u[entity])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-m))).astype(np.float32)
    return GameDataset.build(
        {"global": jnp.asarray(Xf), "per_entity": jnp.asarray(Xe)},
        y,
        id_tags={"entityId": entity},
    )


def _chaos_coords(ds):
    cfg_f = CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=25, tolerance=1e-8),
        regularization=L2,
        reg_weight=0.5,
    )
    cfg_r = CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=20, tolerance=1e-8),
        regularization=L2,
        reg_weight=1.0,
    )
    red = build_random_effect_dataset(
        ds, RandomEffectDataConfig("entityId", "per_entity", min_bucket=4)
    )
    return {
        "fixed": FixedEffectCoordinate(
            ds, "global", cfg_f, TaskType.LOGISTIC_REGRESSION
        ),
        "per-entity": RandomEffectCoordinate(
            ds, red, cfg_r, TaskType.LOGISTIC_REGRESSION
        ),
    }


def _model_arrays(result):
    out = {}
    for cid, m in result.model.models.items():
        if hasattr(m, "coefficients_matrix"):
            out[cid] = np.asarray(m.coefficients_matrix)
        else:
            out[cid] = np.asarray(m.coefficients.means)
    return out


def _assert_bitwise_equal(a, b):
    assert set(a) == set(b)
    for cid in a:
        assert np.array_equal(a[cid], b[cid]), (
            f"coordinate {cid} diverged bitwise"
        )


# --------------------------------------------------------- fault primitives


class TestFaultPlan:
    def test_parse_forms(self):
        plan = faults.FaultPlan.parse("decode:2,upload@3+5,solve:p0.5", seed=9)
        assert plan.sites["decode"].first_n == 2
        assert plan.sites["upload"].indices == frozenset({3, 5})
        assert plan.sites["solve"].probability == 0.5
        bare = faults.FaultPlan.parse("pack")
        assert bare.sites["pack"].first_n == 1

    def test_unknown_site_rejected(self):
        with pytest.raises(ValueError, match="unknown fault site"):
            faults.FaultPlan.parse("uplaod:1")

    def test_deterministic_schedule(self):
        """The probabilistic schedule replays exactly for a given seed and
        differs across seeds (so chaos runs are reproducible)."""

        def schedule(seed):
            spec = faults.SiteSpec(probability=0.3)
            return [
                spec.should_fail("solve", i, seed) for i in range(1, 200)
            ]

        assert schedule(1) == schedule(1)
        assert any(schedule(1))
        assert not all(schedule(1))
        assert schedule(1) != schedule(2)

    def test_fault_point_counts_and_raises(self):
        with faults.inject("upload:2") as inj:
            with pytest.raises(faults.InjectedFault):
                faults.fault_point("upload")
            with pytest.raises(faults.InjectedFault):
                faults.fault_point("upload")
            faults.fault_point("upload")  # 3rd invocation passes
            faults.fault_point("decode")  # unarmed site: free
            assert inj.injected == {"upload": 2}
            assert inj.invocations == {"upload": 3, "decode": 1}
        faults.fault_point("upload")  # disarmed after the scope

    def test_env_plan(self, monkeypatch):
        monkeypatch.setenv("PHOTON_FAULTS", "decode:1")
        faults.clear()  # force env re-read
        with pytest.raises(faults.InjectedFault):
            faults.fault_point("decode")
        faults.fault_point("decode")


class TestRetry:
    def _policy(self, attempts=3):
        return faults.RetryPolicy(max_attempts=attempts, base_delay_s=0.0)

    def test_retries_transient_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        assert faults.retry(flaky, self._policy()) == "ok"
        assert len(calls) == 3
        assert faults.counters()["retries"] == 2

    def test_exhaustion_reraises(self):
        def dead():
            raise TimeoutError("always")

        with pytest.raises(TimeoutError):
            faults.retry(dead, self._policy(attempts=2))
        assert faults.counters()["retries"] == 1

    def test_non_transient_raises_immediately(self):
        calls = []

        def buggy():
            calls.append(1)
            raise ValueError("a bug, not weather")

        with pytest.raises(ValueError):
            faults.retry(buggy, self._policy())
        assert len(calls) == 1
        assert faults.counters().get("retries", 0) == 0

    def test_backoff_is_bounded(self):
        p = faults.RetryPolicy(
            max_attempts=10, base_delay_s=0.5, max_delay_s=1.5, backoff=2.0
        )
        assert p.delay(1) == 0.5
        assert p.delay(2) == 1.0
        assert p.delay(5) == 1.5  # capped


# ------------------------------------------------------------ async uploads


class TestUploaderFailureDomain:
    def test_transient_job_failures_retry_in_worker(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("blip")
            return 42

        up = pl.AsyncUploader(
            retry_policy=faults.RetryPolicy(max_attempts=3, base_delay_s=0.0)
        )
        assert up.submit("k", flaky).result(timeout=30) == 42
        assert faults.counters()["retries"] == 2

    def test_failed_job_evicted_so_resubmit_works(self):
        """Satellite: a job whose fn raised must not pin a dead future under
        its key forever — after the failure surfaces, a fresh submit on the
        same key runs a fresh attempt."""

        def dead():
            raise ValueError("permanent")

        up = pl.AsyncUploader()
        fut = up.submit("k", dead)
        with pytest.raises(ValueError):
            fut.result(timeout=30)
        deadline = time.monotonic() + 10
        while up.peek("k") is not None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert up.peek("k") is None, "failed job was not evicted"
        assert up.submit("k", lambda: "second try").result(timeout=30) == (
            "second try"
        )

    def _host_sparse(self):
        rng = np.random.default_rng(3)
        return SparseFeatures(
            rng.integers(0, 40, size=(30, 4)).astype(np.int32),
            rng.normal(size=(30, 4)).astype(np.float32),
            40,
        )

    def test_prefetch_degrades_to_sync_upload(self, monkeypatch):
        """Async attempts all fail -> the consumer degrades to a bounded-
        retry synchronous upload and still gets the device arrays."""
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        sp = self._host_sparse()
        ref = ShardDict({"s": SparseFeatures(sp.indices, sp.values, sp.dim)})[
            "s"
        ]
        d = ShardDict({"s": sp})
        # Default policy = 3 attempts in the worker; arm 4 failures so the
        # async job dies, then the sync fallback burns #4 and succeeds at #5.
        with faults.inject("upload:4"):
            d.prefetch("s")
            got = d["s"]
        assert faults.counters()["fallback_sync_uploads"] == 1
        assert np.array_equal(np.asarray(got.indices), np.asarray(ref.indices))
        assert np.array_equal(np.asarray(got.values), np.asarray(ref.values))

    def test_sync_upload_retries_transient_fault(self, monkeypatch):
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        sp = self._host_sparse()
        with faults.inject("upload:1"):
            got = ShardDict({"s": sp})["s"]
        assert faults.counters()["retries"] == 1
        import jax

        assert isinstance(got.indices, jax.Array)


# -------------------------------------------------------- divergence guard


class _NaNPoison:
    """Wraps a coordinate so selected train() calls return a NaN model —
    a deterministic stand-in for a diverged solve."""

    def __init__(self, inner, poison_calls):
        self.inner = inner
        self.poison_calls = set(poison_calls)
        self.calls = 0

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def train(self, *args, **kwargs):
        self.calls += 1
        model, stats = self.inner.train(*args, **kwargs)
        if self.calls in self.poison_calls:
            bad = jnp.full_like(model.coefficients.means, jnp.nan)
            model = FixedEffectModel(
                Coefficients(bad, model.coefficients.variances), model.task
            )
        return model, stats


class TestDivergenceGuard:
    def test_transient_nan_retried_to_bitwise_parity(self, rng):
        ds = _chaos_dataset()
        clean = run_coordinate_descent(_chaos_coords(ds), 2, seed=4)

        coords = _chaos_coords(ds)
        coords["fixed"] = _NaNPoison(coords["fixed"], poison_calls={2})
        guarded = run_coordinate_descent(coords, 2, seed=4)
        assert guarded.diverged_steps == 1
        _assert_bitwise_equal(_model_arrays(clean), _model_arrays(guarded))

    def test_injected_solve_fault_retried_to_bitwise_parity(self):
        ds = _chaos_dataset()
        clean = run_coordinate_descent(_chaos_coords(ds), 2, seed=4)
        with faults.inject("solve@2"):
            faulted = run_coordinate_descent(_chaos_coords(ds), 2, seed=4)
        assert faulted.diverged_steps == 1
        _assert_bitwise_equal(_model_arrays(clean), _model_arrays(faulted))

    def test_persistent_divergence_keeps_last_good_and_counts(self, tmp_path):
        ds = _chaos_dataset()
        ck = str(tmp_path / "ck")
        coords = _chaos_coords(ds)
        # Every fixed-effect solve diverges: 1 attempt + 1 retry per step,
        # 2 passes -> 4 rejections; the coordinate never gets a model.
        coords["fixed"] = _NaNPoison(coords["fixed"], poison_calls=range(1, 99))
        result = run_coordinate_descent(coords, 2, seed=4, checkpoint_dir=ck)
        assert result.diverged_steps == 4
        assert "fixed" not in result.model.models
        re_mat = np.asarray(result.model.models["per-entity"].coefficients_matrix)
        assert np.isfinite(re_mat).all()

        # The rejected updates were NEVER checkpointed: the durable state
        # reloads finite and has no fixed-effect file.
        state = CoordinateDescentCheckpoint(ck).load(
            TaskType.LOGISTIC_REGRESSION
        )
        assert state.completed_steps == 4  # cursor still advanced
        assert "fixed" not in state.models
        loaded = np.asarray(state.models["per-entity"].coefficients_matrix)
        np.testing.assert_array_equal(loaded, re_mat)

    def test_data_plane_fault_inside_train_surfaces(self):
        """An InjectedFault raised INSIDE train/score (e.g. an upload whose
        retries exhausted) is a data-plane failure, not a divergence: the
        guard must let it surface instead of shipping an untrained model
        behind a diverged counter."""
        ds = _chaos_dataset()
        coords = _chaos_coords(ds)

        class _DeadDataPlane:
            def __init__(self, inner):
                self.inner = inner

            def __getattr__(self, name):
                return getattr(self.inner, name)

            def train(self, *args, **kwargs):
                raise faults.InjectedFault("upload retries exhausted")

        coords["fixed"] = _DeadDataPlane(coords["fixed"])
        with pytest.raises(faults.InjectedFault, match="upload retries"):
            run_coordinate_descent(coords, 1, seed=4)

    def test_rejection_lands_in_stage_registry(self):
        from photon_ml_tpu.utils.observability import TimingRegistry, stage_scope

        ds = _chaos_dataset()
        coords = _chaos_coords(ds)
        coords["fixed"] = _NaNPoison(coords["fixed"], poison_calls={1})
        reg = TimingRegistry()
        with stage_scope(reg):
            run_coordinate_descent(coords, 1, seed=4)
        assert reg.get("diverged") == 1.0


class TestBestModelResumeParity:
    def test_rejected_pass_final_update_keeps_best_selection_on_resume(
        self, tmp_path
    ):
        """Interrupt after the pass's FIRST coordinate, then resume into a
        pass-final coordinate whose update is rejected: best-model
        selection must compare against the persisted validation results
        (reconstructed pass_results), exactly as the uninterrupted run
        compared against its in-memory ones."""
        import dataclasses

        from photon_ml_tpu.evaluation.suite import EvaluationSuite, EvaluatorType
        from photon_ml_tpu.game.model import random_effect_margins

        ds = _chaos_dataset()
        val = _chaos_dataset(seed=99)
        suite = EvaluationSuite([EvaluatorType("AUC")], val.labels, val.weights)

        class _REPoison:
            """Every per-entity solve returns a NaN matrix (persistent
            divergence of the pass-final coordinate)."""

            def __init__(self, inner):
                self.inner = inner

            def __getattr__(self, name):
                return getattr(self.inner, name)

            def train(self, *args, **kwargs):
                model, stats = self.inner.train(*args, **kwargs)
                return (
                    dataclasses.replace(
                        model,
                        coefficients_matrix=jnp.full_like(
                            model.coefficients_matrix, jnp.nan
                        ),
                    ),
                    stats,
                )

        class _Preempt:
            def __init__(self, inner, allowed):
                self.inner = inner
                self.allowed = allowed
                self.calls = 0

            def __getattr__(self, name):
                return getattr(self.inner, name)

            def train(self, *args, **kwargs):
                if self.calls >= self.allowed:
                    raise RuntimeError("simulated preemption")
                self.calls += 1
                return self.inner.train(*args, **kwargs)

        def make():
            coords = _chaos_coords(ds)
            coords["per-entity"] = _REPoison(coords["per-entity"])

            def scorer(cid, model):
                if cid == "fixed":
                    return val.shards["global"] @ model.coefficients.means
                red = coords["per-entity"].re_dataset
                return random_effect_margins(
                    val.shards["per_entity"],
                    red.sample_entity_rows,
                    model.coefficients_matrix,
                    None,
                )

            return coords, scorer

        kwargs = dict(
            validation_suite=suite, validation_offsets=val.offsets, seed=5
        )
        c, s = make()
        straight = run_coordinate_descent(c, 1, validation_scorer=s, **kwargs)

        # Interrupted run: fixed trains + commits (with its validation
        # entry), then the per-entity step is preempted before solving.
        ck = str(tmp_path / "ck")
        c, s = make()
        c["per-entity"] = _Preempt(c["per-entity"], 0)
        with pytest.raises(RuntimeError, match="simulated preemption"):
            run_coordinate_descent(
                c, 1, validation_scorer=s, checkpoint_dir=ck, **kwargs
            )
        c, s = make()
        resumed = run_coordinate_descent(
            c, 1, validation_scorer=s, checkpoint_dir=ck, **kwargs
        )

        def arrays(model):
            return {
                cid: np.asarray(m.coefficients_matrix)
                if hasattr(m, "coefficients_matrix")
                else np.asarray(m.coefficients.means)
                for cid, m in model.models.items()
            }

        # The rejected per-entity update means best was selected against
        # fixed's pass results in BOTH runs (per-entity has no model at all).
        assert "per-entity" not in straight.best_model.models
        _assert_bitwise_equal(
            arrays(straight.best_model), arrays(resumed.best_model)
        )


# ----------------------------------------------------- checkpoint integrity


class TestCheckpointIntegrity:
    def _checkpointed_run(self, tmp_path):
        ds = _chaos_dataset()
        ck = str(tmp_path / "ck")
        run_coordinate_descent(_chaos_coords(ds), 1, seed=2, checkpoint_dir=ck)
        state = json.load(open(os.path.join(ck, "state.json")))
        return ds, ck, state

    def test_checksums_recorded_for_every_model_file(self, tmp_path):
        _, ck, state = self._checkpointed_run(tmp_path)
        assert set(state["checksums"]) == set(state["model_files"].values())
        for c in state["checksums"].values():
            assert c.startswith("crc32:")

    def test_truncated_npz_refused(self, tmp_path):
        ds, ck, state = self._checkpointed_run(tmp_path)
        rel = state["model_files"]["fixed"]
        path = os.path.join(ck, rel)
        data = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(data[: max(1, len(data) // 2)])
        with pytest.raises(
            CheckpointIntegrityError, match="corrupt/torn checkpoint file"
        ):
            CoordinateDescentCheckpoint(ck).load(TaskType.LOGISTIC_REGRESSION)
        # The resume path surfaces the same actionable error.
        with pytest.raises(CheckpointIntegrityError, match="start fresh"):
            run_coordinate_descent(
                _chaos_coords(ds), 2, seed=2, checkpoint_dir=ck
            )

    def test_missing_npz_refused_with_actionable_error(self, tmp_path):
        _, ck, state = self._checkpointed_run(tmp_path)
        os.remove(os.path.join(ck, state["model_files"]["fixed"]))
        with pytest.raises(
            CheckpointIntegrityError, match="missing model file"
        ) as exc:
            CoordinateDescentCheckpoint(ck).load(TaskType.LOGISTIC_REGRESSION)
        assert "delete the checkpoint directory" in str(exc.value)

    def test_pre_checksum_state_still_loads(self, tmp_path):
        """Back-compat: a state.json without a checksums block (written
        before this layer) loads unverified rather than refusing."""
        _, ck, state = self._checkpointed_run(tmp_path)
        del state["checksums"]
        sp = os.path.join(ck, "state.json")
        json.dump(state, open(sp, "w"))
        loaded = CoordinateDescentCheckpoint(ck).load(
            TaskType.LOGISTIC_REGRESSION
        )
        assert set(loaded.models) == set(state["model_files"])

    def test_checkpoint_write_fault_retried(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        ds = _chaos_dataset()
        ck = str(tmp_path / "ck")
        clean = run_coordinate_descent(_chaos_coords(ds), 1, seed=2)
        with faults.inject("checkpoint_write:1"):
            ckpt_run = run_coordinate_descent(
                _chaos_coords(ds), 1, seed=2, checkpoint_dir=ck
            )
        assert faults.counters()["retries"] >= 1
        _assert_bitwise_equal(_model_arrays(clean), _model_arrays(ckpt_run))
        # The retried write committed intact state.
        loaded = CoordinateDescentCheckpoint(ck).load(
            TaskType.LOGISTIC_REGRESSION
        )
        assert loaded.completed_steps == 2


# ----------------------------------------------- fault-injected fit parity


class TestFaultInjectedParity:
    """The acceptance contract: transient decode/upload/solve faults change
    nothing about the trained model, bit for bit."""

    def _sparse_dataset(self, seed=0):
        rng = np.random.default_rng(seed)
        n, k, dim = 180, 4, 50
        sp = SparseFeatures(
            rng.integers(0, dim, size=(n, k)).astype(np.int32),
            rng.normal(size=(n, k)).astype(np.float32),
            dim,
        )
        y = (rng.uniform(size=n) > 0.5).astype(np.float32)
        return GameDataset.build({"s": sp}, y)

    def _fit(self, ds):
        cfg = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(max_iterations=20, tolerance=1e-8),
            regularization=L2,
            reg_weight=1.0,
        )
        coord = FixedEffectCoordinate(
            ds, "s", cfg, TaskType.LOGISTIC_REGRESSION
        )
        return run_coordinate_descent({"s": coord}, 2, seed=6)

    @pytest.mark.chaos
    def test_upload_and_solve_faults_bitwise_parity(self, monkeypatch):
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        clean = self._fit(self._sparse_dataset())
        with faults.inject("upload:1,solve@1") as inj:
            faulted = self._fit(self._sparse_dataset())
        assert inj.injected == {"upload": 1, "solve": 1}
        assert faulted.diverged_steps == 1
        assert faults.counters()["retries"] >= 1
        _assert_bitwise_equal(_model_arrays(clean), _model_arrays(faulted))


# ----------------------------------------------------------- ingest faults


def _native_available():
    try:
        from photon_ml_tpu.native.build import load_native

        return load_native() is not None
    except Exception:
        return False


@pytest.mark.skipif(
    not _native_available(), reason="native avro decoder unavailable"
)
class TestDecodeFaults:
    def _write(self, tmp_path, seed=0):
        from photon_ml_tpu.native.avro_writer import (
            write_training_examples_columnar,
        )

        rng = np.random.default_rng(seed)
        n, k, dim = 300, 3, 20
        path = os.path.join(str(tmp_path), "train.avro")
        write_training_examples_columnar(
            path,
            (rng.uniform(size=n) > 0.5).astype(np.float64),
            np.arange(n + 1, dtype=np.int64) * k,
            rng.integers(0, dim, size=n * k).astype(np.int32),
            rng.normal(size=n * k),
            [f"f{i}" for i in range(dim)],
            tag_key="entityId",
            tag_values=rng.integers(0, 9, size=n).astype(str),
        )
        return path

    def _read(self, path):
        import photon_ml_tpu.io.avro_data as ad

        ds, _ = ad.read_game_dataset(
            path,
            {"g": ad.FeatureShardConfig(("features",), True)},
            id_tag_fields=["entityId"],
        )
        return ds

    def _dense(self, ds):
        """Row-order-insensitive shard content: the native and Python
        codecs may order within-row ELL entries differently; the dense
        matrix is the semantic payload."""
        sp = ds.peek_shard("g")
        idx, val = np.asarray(sp.indices), np.asarray(sp.values)
        out = np.zeros((idx.shape[0], sp.dim), np.float32)
        np.add.at(out, (np.arange(idx.shape[0])[:, None], idx), val)
        return out

    @pytest.mark.chaos
    def test_transient_decode_fault_retried_to_parity(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        path = self._write(tmp_path)
        clean = self._read(path)
        with faults.inject("decode:1"):
            faulted = self._read(path)
        assert faults.counters()["retries"] == 1
        np.testing.assert_array_equal(
            np.asarray(clean.labels), np.asarray(faulted.labels)
        )
        np.testing.assert_array_equal(self._dense(clean), self._dense(faulted))

    @pytest.mark.chaos
    def test_exhausted_decode_degrades_to_python_codec(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        path = self._write(tmp_path)
        clean = self._read(path)
        with faults.inject("decode:99"):  # never native
            degraded = self._read(path)
        np.testing.assert_array_equal(
            np.asarray(clean.labels), np.asarray(degraded.labels)
        )
        np.testing.assert_array_equal(self._dense(clean), self._dense(degraded))


# ------------------------------------------------------------- kill-resume


_CHILD_SCRIPT = """
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {repo!r})
import time

from tests.test_faults import _chaos_coords, _chaos_dataset
from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent


class _Stall:
    # Slows each solve so the parent can SIGKILL mid-run; timing-only,
    # the math is untouched.
    def __init__(self, inner):
        self.inner = inner

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def train(self, *args, **kwargs):
        out = self.inner.train(*args, **kwargs)
        time.sleep(0.5)
        return out


ds = _chaos_dataset()
coords = {{cid: _Stall(c) for cid, c in _chaos_coords(ds).items()}}
run_coordinate_descent(coords, 3, seed=11, checkpoint_dir=sys.argv[1])
print("CHILD_DONE", flush=True)
"""


@pytest.mark.slow
@pytest.mark.chaos
class TestKillResume:
    def test_sigkill_mid_step_resume_bitwise_parity(self, tmp_path):
        ck = str(tmp_path / "ck")
        script = tmp_path / "child.py"
        script.write_text(_CHILD_SCRIPT.format(repo=REPO))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, str(script), ck],
            cwd=REPO,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )
        try:
            # Kill -9 as soon as at least one step has durably committed
            # (state.json is replaced atomically, so a parse race just
            # means "poll again").
            state_path = os.path.join(ck, "state.json")
            deadline = time.monotonic() + 180
            killed = False
            while time.monotonic() < deadline and proc.poll() is None:
                try:
                    if json.load(open(state_path))["completed_steps"] >= 2:
                        proc.send_signal(signal.SIGKILL)
                        killed = True
                        break
                except (OSError, ValueError, KeyError):
                    pass
                time.sleep(0.02)
            proc.wait(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        if killed:
            assert proc.returncode == -signal.SIGKILL
        assert os.path.isfile(state_path), "no step committed before timeout"

        ds = _chaos_dataset()
        straight = run_coordinate_descent(_chaos_coords(ds), 3, seed=11)
        resumed = run_coordinate_descent(
            _chaos_coords(ds), 3, seed=11, checkpoint_dir=ck
        )
        _assert_bitwise_equal(_model_arrays(straight), _model_arrays(resumed))


# ----------------------------------------- sharded kill-resume (ISSUE 10)


_SHARDED_CHILD_SCRIPT = """
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Device count is injected by the parent via XLA_FLAGS
# (--xla_force_host_platform_device_count): the SAME checkpoint resumes
# on 1, 2, and 8 virtual devices.
sys.path.insert(0, {repo!r})
import time
import numpy as np

from tests.test_mesh_faults import N_ENTITIES, _coords
from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent

ck = sys.argv[1]
mode = sys.argv[2]  # "train" (stalled, parent SIGKILLs mid-sweep) | "resume"


class _Stall:
    # Slows each sweep so the parent can SIGKILL mid-run; timing-only.
    def __init__(self, inner):
        self.inner = inner

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def train(self, *args, **kwargs):
        out = self.inner.train(*args, **kwargs)
        time.sleep(0.5)
        return out


coords = _coords(True)  # entity-sharded over however many devices exist
if mode == "train":
    coords = {{cid: _Stall(c) for cid, c in coords.items()}}
res = run_coordinate_descent(coords, 3, seed=11, checkpoint_dir=ck)
if mode == "resume":
    m = np.asarray(res.model.models["re"].coefficients_matrix)
    np.save(sys.argv[3], m[: N_ENTITIES + 1])
print("CHILD_DONE", flush=True)
"""


@pytest.mark.slow
@pytest.mark.chaos
class TestShardedKillResume:
    """The elastic-resume acceptance contract (ISSUE 10): SIGKILL an
    entity-sharded fit mid-sweep on the 8-virtual-device mesh, then resume
    its N-shard checkpoint on 1, 2, and 8 devices — every resumed run must
    land bitwise on the uninterrupted single-device fit."""

    def _env(self, ndev):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={ndev}"
        )
        return env

    def test_sigkill_mid_sweep_resumes_on_1_2_8_devices(self, tmp_path):
        from tests.test_mesh_faults import _coords as _mesh_coords, _matrix

        ck = str(tmp_path / "ck")
        script = tmp_path / "child.py"
        script.write_text(_SHARDED_CHILD_SCRIPT.format(repo=REPO))
        proc = subprocess.Popen(
            [sys.executable, str(script), ck, "train"],
            cwd=REPO,
            env=self._env(8),
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )
        try:
            state_path = os.path.join(ck, "state.json")
            deadline = time.monotonic() + 180
            killed = False
            while time.monotonic() < deadline and proc.poll() is None:
                try:
                    if json.load(open(state_path))["completed_steps"] >= 1:
                        proc.send_signal(signal.SIGKILL)
                        killed = True
                        break
                except (OSError, ValueError, KeyError):
                    pass
                time.sleep(0.02)
            proc.wait(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        if killed:
            assert proc.returncode == -signal.SIGKILL
        assert os.path.isfile(state_path), "no step committed before timeout"
        # The interrupted checkpoint's sharded layout really landed (a
        # mid-fit state.json references per-shard files + checksums).
        state = json.load(open(state_path))
        rels = state["model_files"]["re"]
        assert isinstance(rels, list) and len(rels) == 8

        # Uninterrupted SINGLE-DEVICE reference (in-process, replicated —
        # bitwise-equal to the sharded fit per test_mesh_faults).
        straight = _matrix(
            run_coordinate_descent(_mesh_coords(False), 3, seed=11)
        )
        for ndev in (1, 2, 8):
            out = tmp_path / f"resume{ndev}.npy"
            r = subprocess.run(
                [sys.executable, str(script), ck, "resume", str(out)],
                cwd=REPO,
                env=self._env(ndev),
                capture_output=True,
                text=True,
                timeout=600,
            )
            assert "CHILD_DONE" in r.stdout, (
                f"resume on {ndev} device(s) failed: {r.stderr[-2000:]}"
            )
            resumed = np.load(out)
            np.testing.assert_array_equal(
                straight,
                resumed,
                err_msg=f"resume on {ndev} device(s) diverged bitwise",
            )


# -------------------------------------------- producer-thread degradation


class TestProducerFallbacks:
    def test_failed_background_pack_falls_back_to_sync(self, monkeypatch):
        from photon_ml_tpu.data.game_dataset import HostCSR
        from photon_ml_tpu.ops import pallas_sparse

        monkeypatch.setattr(
            pallas_sparse, "pack_worth_considering", lambda n: True
        )
        monkeypatch.setenv("PHOTON_HOST_THREADS", "4")
        rng = np.random.default_rng(5)
        n, k, dim = 64, 4, 32
        csr = HostCSR(
            np.arange(n + 1, dtype=np.int64) * k,
            rng.integers(0, dim, size=n * k).astype(np.int64),
            rng.normal(size=n * k).astype(np.float32),
            dim,
        )
        with faults.inject("pack:1"):
            pallas_sparse.begin_pack_async(csr, n)
            assert csr.pack_future is not None
            # finish_pack must absorb the producer failure and repack
            # synchronously (here the sync pack declines on CPU -> None,
            # which is the normal keep-the-ELL-path answer, NOT an error).
            pallas_sparse.finish_pack(csr, n)  # must not raise
        assert faults.counters()["fallback_sync_packs"] == 1
        assert csr.pack_future is None

    def test_failed_re_build_producer_falls_back(self, monkeypatch):
        """A prepare-pool producer whose build dies must not kill fit():
        the estimator rebuilds synchronously and the result is identical."""
        import photon_ml_tpu.estimators.game_estimator as ge
        from photon_ml_tpu.data.game_dataset import FixedEffectDataConfig
        from photon_ml_tpu.estimators.game_estimator import GameEstimator

        monkeypatch.setenv("PHOTON_HOST_THREADS", "4")

        def _make(seed=0):
            rng = np.random.default_rng(seed)
            n, d, ents = 160, 4, 4
            X = rng.normal(size=(n, d)).astype(np.float32)
            users = rng.permutation(np.repeat(np.arange(ents), n // ents))
            movies = rng.permutation(np.repeat(np.arange(ents), n // ents))
            y = (rng.uniform(size=n) > 0.5).astype(np.float32)
            return GameDataset.build(
                {"g": jnp.asarray(X)},
                y,
                id_tags={"userId": users, "movieId": movies},
            )

        data_cfgs = {
            "global": FixedEffectDataConfig("g"),
            "per-user": RandomEffectDataConfig("userId", "g", min_bucket=8),
            "per-movie": RandomEffectDataConfig("movieId", "g", min_bucket=8),
        }
        opt = {
            cid: CoordinateOptimizationConfig(
                optimizer=OptimizerConfig(max_iterations=10, tolerance=1e-7),
                regularization=L2,
                reg_weight=1.0,
            )
            for cid in data_cfgs
        }

        def _fit():
            est = GameEstimator(
                TaskType.LOGISTIC_REGRESSION,
                dict(data_cfgs),
                coordinate_descent_iterations=1,
                pipeline=True,
            )
            return est.fit(_make(), None, [opt])[0].model

        clean = _fit()

        real_build = ge.build_random_effect_dataset
        calls = {"n": 0}

        def _flaky_build(dataset, cfg):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("producer thread blew up")
            return real_build(dataset, cfg)

        monkeypatch.setattr(ge, "build_random_effect_dataset", _flaky_build)
        degraded = _fit()
        assert faults.counters()["fallback_sync_builds"] == 1

        out_c, out_d = {}, {}
        for cid in clean.models:
            mc, md = clean.models[cid], degraded.models[cid]
            a = getattr(mc, "coefficients_matrix", None)
            if a is not None:
                out_c[cid], out_d[cid] = np.asarray(a), np.asarray(
                    md.coefficients_matrix
                )
            else:
                out_c[cid] = np.asarray(mc.coefficients.means)
                out_d[cid] = np.asarray(md.coefficients.means)
        _assert_bitwise_equal(out_c, out_d)


# --------------------------------------------------------------- validators


class TestValidatorAggregation:
    def test_all_failed_checks_reported_in_one_error(self):
        from photon_ml_tpu.data.validators import (
            DataValidationError,
            validate_game_dataset,
        )
        from photon_ml_tpu.types import DataValidationType

        ds = GameDataset.build(
            {"s": jnp.asarray([[1.0], [np.nan], [2.0], [3.0]])},
            [1.0, 3.0, np.nan, 0.0],
            weights=[1.0, -1.0, 0.0, 1.0],
            offsets=[0.0, np.inf, 0.0, 0.0],
        )
        with pytest.raises(DataValidationError) as exc:
            validate_game_dataset(
                ds,
                TaskType.LOGISTIC_REGRESSION,
                DataValidationType.VALIDATE_FULL,
            )
        err = exc.value
        names = [f[0] for f in err.failures]
        # Every failed check present at once — not just the first.
        assert "finite label" in names
        assert "finite offset" in names
        assert "positive weight" in names
        assert "binary label" in names
        assert any("finite features" in n for n in names)
        assert err.rows_checked == 4
        # Counts + example indices per check.
        by_name = {f[0]: f for f in err.failures}
        assert by_name["positive weight"][1] == 2
        assert by_name["positive weight"][2] == [1, 2]
        msg = str(err)
        assert "failed check(s) over 4 rows" in msg
        assert "50.0%" in msg  # positive-weight fraction

    def test_max_examples_truncates_indices(self):
        from photon_ml_tpu.data.validators import (
            DataValidationError,
            validate_game_dataset,
        )
        from photon_ml_tpu.types import DataValidationType

        n = 40
        ds = GameDataset.build(
            {"s": jnp.ones((n, 1))},
            np.ones(n, np.float32),
            weights=np.full(n, -1.0, np.float32),
        )
        with pytest.raises(DataValidationError) as exc:
            validate_game_dataset(
                ds,
                TaskType.LOGISTIC_REGRESSION,
                DataValidationType.VALIDATE_FULL,
                max_examples=3,
            )
        (_, count, examples) = [
            f for f in exc.value.failures if f[0] == "positive weight"
        ][0]
        assert count == n
        assert examples == [0, 1, 2]

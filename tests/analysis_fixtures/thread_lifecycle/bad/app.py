"""Known-bad: an unnamed fire-and-forget thread (module scope) and a
class whose thread is never joined inside the class."""

import threading
from threading import Thread


def fire_and_forget(fn):
    threading.Thread(target=fn).start()  # unnamed AND never joined


def fmt(sep, parts):
    # A variable-receiver str.join: its call shape (one non-numeric
    # positional arg) must NOT satisfy the thread-join requirement.
    return sep.join(parts)


class Worker:
    def start(self, fn):
        # Named, but this class never joins it — its teardown story is
        # unwritten.
        self._t = Thread(target=fn, name="fixture-worker")
        self._t.start()

"""Known-good: threads are named and joined in their owning scope;
str.join / os.path.join receivers do not count as thread joins."""

import os
import threading


class Worker:
    def __init__(self, fn):
        self._t = threading.Thread(target=fn, name="fixture-worker")

    def start(self):
        self._t.start()

    def close(self):
        self._t.join(timeout=5)


def run_once(fn):
    t = threading.Thread(target=fn, name="fixture-once")
    t.start()
    label = ", ".join(["a", "b"])  # str.join: not a thread join
    path = os.path.join("/tmp", "x")  # path join: not a thread join
    t.join()
    return label, path

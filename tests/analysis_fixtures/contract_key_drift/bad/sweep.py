"""Known-bad: re-types two sweep-section schema keys (the r12
FIXTURE_SWEEP_KEYS shape) as a literal instead of importing the tuple."""


def check_sweep(section):
    report = {
        k: section[k] for k in ("fixture_trials", "fixture_speedup")
    }  # re-typed sweep schema
    return report

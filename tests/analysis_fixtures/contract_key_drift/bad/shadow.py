"""Known-bad: re-types two shadow-deploy schema keys (the r18
FIXTURE_SHADOW_KEYS shape) as a literal instead of importing the
tuple."""


def check_shadow(block):
    evidence = {
        k: block[k]
        for k in ("fixture_shadow_windows", "fixture_shadow_verdict")
    }  # re-typed shadow schema
    return evidence

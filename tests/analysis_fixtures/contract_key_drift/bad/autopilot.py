"""Known-bad: re-types two autopilot decision-schema keys (the r19
FIXTURE_AUTOPILOT_KEYS shape) as a literal instead of importing the
tuple."""


def check_autopilot(block):
    decision = {
        k: block[k] for k in ("fixture_ap_rule", "fixture_ap_outcome")
    }  # re-typed autopilot schema
    return decision

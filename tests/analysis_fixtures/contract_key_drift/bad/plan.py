"""Known-bad: re-types two plan-block schema keys (the r14
FIXTURE_PLAN_KEYS shape) as a literal instead of importing the tuple."""


def check_plan(block):
    audit = {
        k: block[k] for k in ("fixture_plan_source", "fixture_plan_value")
    }  # re-typed plan schema
    return audit

"""Known-bad: re-types two keys of the sibling contract schema instead
of importing the tuple — the copy a key rename will silently miss."""


def verify(timing):
    required = ("fixture_alpha_s", "fixture_beta_s")  # re-typed schema
    return [k for k in required if k not in timing]

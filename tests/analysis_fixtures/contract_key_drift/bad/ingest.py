"""Known-bad: re-types two ingest-stage schema keys (the r09 INGEST_STAGES
shape) as a literal instead of importing the tuple."""


def check_ingest(timing):
    breakdown = {
        k: timing[k] for k in ("fixture_decode", "fixture_assemble")
    }  # re-typed ingest schema
    return breakdown

"""Known-bad: re-types two multihost-section schema keys (the r17
FIXTURE_MULTIHOST_KEYS shape) as a literal instead of importing the
tuple."""


def check_multihost(section):
    report = {
        k: section[k]
        for k in ("fixture_mh_hosts", "fixture_mh_repeated_sweeps")
    }  # re-typed multihost schema
    return report

"""Known-bad: re-types two tenant-block schema keys (the r15
FIXTURE_TENANT_KEYS shape) as a literal instead of importing the tuple."""


def check_tenant(block):
    report = {
        k: block[k]
        for k in ("fixture_tenant_completed", "fixture_tenant_shed")
    }  # re-typed tenant schema
    return report

"""Known-bad: re-types two delta-bundle schema keys (the r16
FIXTURE_REFRESH_KEYS shape) as a literal instead of importing the tuple."""


def check_delta(manifest):
    report = {
        k: manifest[k]
        for k in ("fixture_delta_rows", "fixture_delta_bytes")
    }  # re-typed refresh schema
    return report

"""Known-bad: re-types two precision-ladder schema keys (the r20
FIXTURE_TIER_KEYS shape) as a literal instead of importing the tuple."""


def check_tier(block):
    ladder = {
        k: block[k] for k in ("fixture_tier_name", "fixture_tier_demotions")
    }  # re-typed tier schema
    return ladder

"""Miniature contract schema module."""

FIXTURE_TIMING_KEYS = ("fixture_alpha_s", "fixture_beta_s", "fixture_gamma_s")
FIXTURE_ALL_KEYS = (*FIXTURE_TIMING_KEYS, "fixture_path")

"""Known-good: the plan-block schema is imported; single-key reads are
use, not duplication."""

from contracts import FIXTURE_PLAN_KEYS


def check_plan(block):
    missing = [k for k in FIXTURE_PLAN_KEYS if k not in block]
    source = block.get("fixture_plan_source")  # one key is vocabulary
    return missing, source

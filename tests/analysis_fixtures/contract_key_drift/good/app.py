"""Known-good: the schema is imported; reading ONE key as a literal is
use, not duplication."""

from contracts import FIXTURE_TIMING_KEYS  # the one source of truth


def verify(timing):
    missing = [k for k in FIXTURE_TIMING_KEYS if k not in timing]
    alpha = timing.get("fixture_alpha_s")  # single-key use is fine
    return missing, alpha

"""Known-good: the autopilot decision schema is imported; single-key
reads are use, not duplication."""

from contracts import FIXTURE_AUTOPILOT_KEYS


def check_autopilot(block):
    missing = [k for k in FIXTURE_AUTOPILOT_KEYS if k not in block]
    rule = block.get("fixture_ap_rule")  # one key is vocabulary
    return missing, rule

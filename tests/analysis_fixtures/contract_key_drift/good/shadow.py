"""Known-good: the shadow-deploy schema is imported; single-key reads
are use, not duplication."""

from contracts import FIXTURE_SHADOW_KEYS


def check_shadow(block):
    missing = [k for k in FIXTURE_SHADOW_KEYS if k not in block]
    drift = block.get("fixture_shadow_drift")  # one key is vocabulary
    return missing, drift

"""Known-good: the precision-ladder schema is imported; single-key
reads are use, not duplication."""

from contracts import FIXTURE_TIER_KEYS


def check_tier(block):
    missing = [k for k in FIXTURE_TIER_KEYS if k not in block]
    rung = block.get("fixture_tier_name")  # one key is vocabulary
    return missing, rung

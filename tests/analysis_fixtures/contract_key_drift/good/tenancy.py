"""Known-good: the tenant-block schema is imported; single-key reads are
use, not duplication."""

from contracts import FIXTURE_TENANT_KEYS


def check_tenant(block):
    missing = [k for k in FIXTURE_TENANT_KEYS if k not in block]
    demoted = block.get("fixture_tenant_demoted")  # one key is vocabulary
    return missing, demoted

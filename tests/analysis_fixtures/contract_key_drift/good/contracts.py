"""Miniature contract schema module."""

FIXTURE_TIMING_KEYS = ("fixture_alpha_s", "fixture_beta_s", "fixture_gamma_s")

# Ingest-stage schema (r09): the streaming data plane's breakdown keys.
FIXTURE_INGEST_STAGES = ("fixture_decode", "fixture_assemble", "fixture_ell")

# Sweep-section schema (r12): the pod-parallel hyperparameter sweep keys.
FIXTURE_SWEEP_KEYS = ("fixture_trials", "fixture_sweep_wall", "fixture_speedup")

# Plan-block schema (r14): the adaptive-runtime planner's audit keys.
FIXTURE_PLAN_KEYS = ("fixture_plan_source", "fixture_plan_value", "fixture_plan_fallback")

# Tenant-block schema (r15): the multi-tenant serving platform keys.
FIXTURE_TENANT_KEYS = ("fixture_tenant_completed", "fixture_tenant_shed", "fixture_tenant_demoted")

# Delta-bundle schema (r16): the continuous-refresh payload keys.
FIXTURE_REFRESH_KEYS = ("fixture_delta_rows", "fixture_delta_bytes", "fixture_delta_source")

# Multihost-section schema (r17): the DCN production-mode section keys.
FIXTURE_MULTIHOST_KEYS = ("fixture_mh_hosts", "fixture_mh_repeated_sweeps", "fixture_mh_failed")

# Shadow-deploy schema (r18): the online shadow evaluation block keys.
FIXTURE_SHADOW_KEYS = ("fixture_shadow_windows", "fixture_shadow_verdict", "fixture_shadow_drift")

# Autopilot decision schema (r19): the closed-loop controller keys.
FIXTURE_AUTOPILOT_KEYS = ("fixture_ap_rule", "fixture_ap_outcome", "fixture_ap_rollbacks")

# Tier-ladder schema (r20): the precision-ladder tenant block keys.
FIXTURE_TIER_KEYS = ("fixture_tier_name", "fixture_tier_demotions", "fixture_tier_restores")

"""Miniature contract schema module."""

FIXTURE_TIMING_KEYS = ("fixture_alpha_s", "fixture_beta_s", "fixture_gamma_s")

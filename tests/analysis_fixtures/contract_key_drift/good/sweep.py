"""Known-good: the sweep-section schema is imported; single-key reads are
use, not duplication."""

from contracts import FIXTURE_SWEEP_KEYS


def check_sweep(section):
    missing = [k for k in FIXTURE_SWEEP_KEYS if k not in section]
    trials = section.get("fixture_trials")  # one key is everyday vocabulary
    return missing, trials

"""Known-good: the delta-bundle schema is imported; single-key reads are
use, not duplication."""

from contracts import FIXTURE_REFRESH_KEYS


def check_delta(manifest):
    missing = [k for k in FIXTURE_REFRESH_KEYS if k not in manifest]
    source = manifest.get("fixture_delta_source")  # one key is vocabulary
    return missing, source

"""Known-good: the ingest-stage schema is imported; single-key reads are
use, not duplication."""

from contracts import FIXTURE_INGEST_STAGES


def check_ingest(timing):
    missing = [k for k in FIXTURE_INGEST_STAGES if k not in timing]
    decode = timing.get("fixture_decode")  # one key is everyday vocabulary
    return missing, decode

"""Known-good: the multihost-section schema is imported; single-key
reads are use, not duplication."""

from contracts import FIXTURE_MULTIHOST_KEYS


def check_multihost(section):
    missing = [k for k in FIXTURE_MULTIHOST_KEYS if k not in section]
    hosts = section.get("fixture_mh_hosts")  # one key is vocabulary
    return missing, hosts

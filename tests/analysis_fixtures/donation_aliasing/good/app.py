"""Known-good: donated names are dead after the call — non-donated
arguments stay readable, and re-binding a donated name first makes
later reads a fresh value."""

import jax


def kernel(buf, other):
    return buf * 2 + other


def run(x, y):
    f = jax.jit(kernel, donate_argnums=(0,))
    out = f(x, y)
    return out + y.sum()  # y (position 1) was not donated


def run_rebound(x, y):
    f = jax.jit(kernel, donate_argnums=(0,))
    x = f(x, y)  # donated name re-bound by the result
    return x + 1  # reads the fresh binding, not the donated buffer

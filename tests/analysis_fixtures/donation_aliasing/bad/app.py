"""Known-bad: a buffer donated to a jitted callable is read again after
the donating call — freed device memory on accelerators (invisible on
the CPU test platform, where donation is a no-op)."""

import jax


def kernel(buf, other):
    return buf * 2 + other


def run(x, y):
    f = jax.jit(kernel, donate_argnums=(0,))
    out = f(x, y)
    return out + x.sum()  # x was donated: this reads freed memory


def run_inline(x, y):
    out = jax.jit(kernel, donate_argnums=(0,))(x, y)
    return out, x.shape  # x was donated to the immediately-invoked jit

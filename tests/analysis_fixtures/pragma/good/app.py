"""Known-good pragma hygiene: a reasoned disable suppresses the finding
on the line it attaches to — trailing, or as a comment line above."""

import threading


def fire(fn):
    t = threading.Thread(target=fn)  # photon-lint: disable=thread-lifecycle — fixture: completion owned by the caller
    t.start()
    # photon-lint: disable=thread-lifecycle — fixture: comment-line pragma
    # attaches past continuation comments to the next code line.
    threading.Thread(target=fn).start()

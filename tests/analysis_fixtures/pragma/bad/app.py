"""Known-bad pragma hygiene: a reasonless disable (suppresses nothing,
and is itself a finding) and a pragma naming an unknown check."""

import threading


def fire(fn):
    # photon-lint: disable=thread-lifecycle
    threading.Thread(target=fn).start()
    # photon-lint: disable=not-a-real-check — the check name is wrong
    threading.Thread(target=fn).start()

"""Known-good: compiled bodies stay pure; host impurity lives outside
the traced region (read the knob / clock BEFORE tracing, pass values in
as arguments)."""

import os
import time

import jax
import jax.numpy as jnp


@jax.jit
def f(x, scale):
    return x * scale + jnp.sum(x)


def run(x):
    t0 = time.perf_counter()  # host timing around the dispatch is fine
    scale = float(os.environ.get("FIXTURE_SCALE", "1.0"))  # outside jit
    out = f(x, scale)

    def step(carry, v):
        return carry + v, v * scale  # closes over a host VALUE, pure

    total, _ = jax.lax.scan(step, 0.0, out)
    return total, time.perf_counter() - t0

"""Known-bad: every impurity class inside compiled bodies — lexically,
in an inner scan step, and one same-module call deep."""

import os
import time

import jax
import numpy as np

COUNT = 0


def helper(y):
    # Impure, and reachable one call deep from the compiled body of g.
    return y * float(os.environ["PHOTON_FIXTURE_SCALE"])


@jax.jit
def f(x):
    t0 = time.perf_counter()  # host clock inside a traced body
    noise = np.random.rand()  # host RNG inside a traced body
    peak = x.max().item()  # device sync inside a traced body
    if os.getenv("PHOTON_FIXTURE_DEBUG"):  # env read inside a traced body
        x = x + 1
    return x * noise + peak + t0


@jax.jit
def g(x):
    return helper(x)


def sweep(xs):
    def step(carry, x):
        global COUNT  # global mutation runs per-trace, not per-call
        COUNT += 1
        return carry + x, x

    return jax.lax.scan(step, 0.0, xs)

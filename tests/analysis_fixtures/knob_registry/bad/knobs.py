"""Miniature knob registry: registers one knob the sibling README.md
does not mention (README-sync direction of the check)."""


def _register(name, type_, default, doc):
    pass


_register("PHOTON_FIXTURE_TILE", int, 8, "a knob the README forgot")
_register(
    "PHOTON_FIXTURE_AUTOPILOT_MS", int, 500,
    "a control-loop tick knob the README also forgot",
)

"""Known-bad: raw PHOTON_* environment reads in every shape the check
resolves, plus a get_knob call naming an unregistered knob."""

import os

_INDIRECT = "PHOTON_FIXTURE_INDIRECT"


def get_knob(name):  # stand-in accessor so the call parses standalone
    return None


def configure():
    a = os.environ.get("PHOTON_FIXTURE_TILE", "8")  # raw .get read
    b = os.environ["PHOTON_FIXTURE_MODE"]  # raw subscript read
    c = os.getenv("PHOTON_FIXTURE_FLAG")  # raw getenv read
    d = os.environ.get(_INDIRECT)  # read through a module constant
    e = get_knob("PHOTON_FIXTURE_UNREGISTERED")  # not in the registry
    return a, b, c, d, e

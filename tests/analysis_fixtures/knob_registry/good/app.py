"""Known-good: every PHOTON_* read goes through the typed accessor;
env WRITES (subprocess setup) are legal; non-PHOTON env reads are out
of this check's scope."""

import os


def get_knob(name):  # stand-in accessor so the call parses standalone
    return 8


def configure():
    tile = get_knob("PHOTON_FIXTURE_TILE")
    tick = get_knob("PHOTON_FIXTURE_AUTOPILOT_MS")  # registered read
    del tick
    os.environ["PHOTON_FIXTURE_TILE"] = "16"  # write: child-process setup
    path = os.environ.get("HOME", "/")  # non-PHOTON read: out of scope
    return tile, path

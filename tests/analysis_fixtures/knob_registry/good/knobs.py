"""Miniature knob registry for the known-good snippet."""


def _register(name, type_, default, doc):
    pass


_register("PHOTON_FIXTURE_TILE", int, 8, "documented in the fixture README")
_register(
    "PHOTON_FIXTURE_AUTOPILOT_MS", int, 500,
    "control-loop tick, documented in the fixture README",
)

"""Miniature knob registry for the known-good snippet."""


def _register(name, type_, default, doc):
    pass


_register("PHOTON_FIXTURE_TILE", int, 8, "documented in the fixture README")

"""Known-bad: inlines parity tolerances at allclose-style call sites —
rtol/atol keywords and the positional numpy spellings — instead of
pinning them in utils/contracts.py's tolerance tables."""

import numpy as np


def gate(val, ref):
    return bool(np.allclose(val, ref, rtol=1e-2))  # keyword finding


def gate_bf16(val, ref):
    # Both tolerance keywords inline: two findings on one call.
    return bool(np.allclose(val, ref, rtol=3e-2, atol=1e-3))


def spot_check(scores, ref):
    return np.isclose(scores, ref, 5e-2)  # positional rtol finding


def assert_parity(actual, desired):
    np.testing.assert_allclose(actual, desired, 1e-2, 1e-3)  # positional

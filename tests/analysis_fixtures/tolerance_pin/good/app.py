"""Known-good: every parity comparison takes its tolerance from the
pinned contracts tables (or a caller-supplied bound), and the one
deliberately local bound carries a reasoned pragma."""

import numpy as np

from photon_ml_tpu.utils.contracts import (
    PALLAS_GATE_TOLERANCES,
    TIER_TOLERANCES,
)


def gate(val, ref):
    return bool(np.allclose(val, ref, **PALLAS_GATE_TOLERANCES["f32"]))


def spot_check(scores, ref, tier):
    tol = TIER_TOLERANCES[tier]
    return np.allclose(scores, ref, rtol=tol["rtol"], atol=tol["atol"])


def assert_parity(actual, desired, rtol):
    np.testing.assert_allclose(actual, desired, rtol=rtol)  # caller-supplied


def calibrate(val, ref):
    # A local exploratory bound documents why it is not a contract:
    return np.isclose(val, ref, rtol=0.5)  # photon-lint: disable=tolerance-pin — coarse sanity bound for a calibration probe, not a parity contract

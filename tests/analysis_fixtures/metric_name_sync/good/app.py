"""Known-good: every incremented name is declared (including the
conditional counter= expression and the parameter default), and every
declared name is incremented. The instance-level recorder call with a
numeric first argument is not a registry call and must be skipped."""


class _Registry:
    def increment(self, name, by=1):
        pass

    def observe(self, name, value):
        pass

    def set_gauge(self, name, value):
        pass


class _Hist:
    def observe(self, value):
        pass


METRICS = _Registry()


def retry(fn, counter="fixture_retries"):
    return fn


def run(mesh, hist: _Hist):
    METRICS.increment("fixture_hits")
    METRICS.increment("fixture_autopilot_rollbacks")
    METRICS.observe("fixture_latency_ms", 1.5)
    METRICS.set_gauge("fixture_depth", 3)
    retry(
        run,
        counter="fixture_alt_retries" if mesh is not None else "fixture_retries",
    )
    hist.observe(0.25)  # instance recorder: a value, not a metric name

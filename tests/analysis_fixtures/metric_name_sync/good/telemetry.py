"""Miniature metric registry: every declared name is incremented."""

METRIC_DESCRIPTIONS = {
    "fixture_hits": "incremented by app.py",
    "fixture_latency_ms": "observed by app.py",
    "fixture_retries": "planted via a counter= default and keyword",
    "fixture_alt_retries": "planted via the conditional counter= branch",
    "fixture_depth": "gauged by app.py",
    "fixture_autopilot_rollbacks": "incremented by app.py (r19 flavor)",
}

"""Miniature metric registry: two declared names, one never incremented."""

METRIC_DESCRIPTIONS = {
    "fixture_hits": "incremented by app.py",
    "fixture_ghost": "declared but never incremented (a finding)",
    "fixture_autopilot_rollbacks": "declared but never incremented "
    "(the r19 controller flavor of the same finding)",
}

"""Known-bad: an undeclared name, a computed name, an unresolvable
counter= keyword, and (via the sibling telemetry.py) a declared-but-
never-incremented metric."""


class _Counters:
    def increment(self, name, by=1):
        pass


COUNTERS = _Counters()


def retry(fn, counter="fixture_hits"):
    return fn


def run(name_var, chosen):
    COUNTERS.increment("fixture_hits")  # fine: declared literal
    COUNTERS.increment("fixture_mystery")  # undeclared name
    COUNTERS.increment(name_var)  # computed: statically unresolvable
    retry(run, counter=chosen)  # counter= with no literal

"""Known-bad: hard-codes planned runtime quantities as magic-number
literals — a function-parameter default, a call keyword, a plain
assignment, and a bucket-shape tuple — instead of routing them through
photon_ml_tpu.planner (planned_value/DEFAULTS) or the knob registry."""


def flush_batcher(engine, max_wait_ms=2.0):  # parameter-default finding
    return engine.flush(max_wait_ms)


def serve(engine):
    return engine.batcher(max_wait_ms=1.0)  # call-keyword finding


def ingest(reader):
    chunk_rows = 262144  # assignment finding
    prefetch_depth = 2  # assignment finding
    bucket_shapes = (64, 128, 256)  # shape-set tuple finding
    return reader.read(chunk_rows, prefetch_depth, bucket_shapes)

"""Known-good: every planned quantity reaches its site through the
planner accessor (or an explicit caller argument), and the one
deliberately pinned measurement value carries a reasoned pragma."""

from photon_ml_tpu import planner


def flush_batcher(engine, max_wait_ms=None):
    if max_wait_ms is None:
        max_wait_ms = planner.planned_value("serving_max_wait_ms")
    return engine.flush(max_wait_ms)


def serve(engine, wait):
    return engine.batcher(max_wait_ms=wait)  # caller-supplied, not a literal


def ingest(reader):
    chunk_rows = int(planner.planned_value("ingest_chunk_rows"))
    prefetch_depth = int(planner.planned_value("prefetch_depth"))
    bucket_shapes = reader.bucket_shapes()
    return reader.read(chunk_rows, prefetch_depth, bucket_shapes)


def calibrate(engine):
    # A measurement section pinning its config on purpose documents why:
    return engine.batcher(max_wait_ms=1.0)  # photon-lint: disable=planner-constant — fixed wait pins this calibration measurement

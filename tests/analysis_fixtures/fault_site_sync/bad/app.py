"""Known-bad: an unregistered site, a computed site name, and (via the
sibling faults.py) a described-but-unplanted site."""


def fault_point(site):
    pass


def run(site_var):
    fault_point("fixture_decode")  # fine: registered and literal
    fault_point("fixture_mystery")  # unregistered site
    fault_point(site_var)  # computed: statically unverifiable

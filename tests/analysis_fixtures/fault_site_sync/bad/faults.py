"""Miniature fault registry: two described sites, one never planted."""

SITE_DESCRIPTIONS = {
    "fixture_decode": "planted by app.py",
    "fixture_upload": "described but never planted (a finding)",
    "fixture_autopilot_act": "described but never planted "
    "(the r19 actuation-site flavor of the same finding)",
}

"""Miniature fault registry: two described sites, one never planted."""

SITE_DESCRIPTIONS = {
    "fixture_decode": "planted by app.py",
    "fixture_upload": "described but never planted (a finding)",
}

"""Known-good: every plant is a registered literal; every described
site is planted."""


def fault_point(site):
    pass


def run():
    fault_point("fixture_decode")
    fault_point("fixture_upload")
    fault_point("fixture_autopilot_act")

"""Miniature fault registry: both sites planted by app.py."""

SITE_DESCRIPTIONS = {
    "fixture_decode": "planted by app.py",
    "fixture_upload": "planted by app.py",
    "fixture_autopilot_act": "planted by app.py",
}

"""Serving-under-fire tests: admission control, deadlines, circuit-broken
degradation, atomic bundle hot-swap, health states, crash-safe replay.

The load-bearing contracts, mirroring ISSUE 5:

* overload sheds with TYPED `Overloaded` rejections — never an unbounded
  backlog, never a hang; admitted requests still complete;
* a request that expires in queue fails with `DeadlineExceeded` BEFORE
  wasting a device slot, and is never co-batched past its budget;
* after K consecutive device-class failures the circuit OPENs and traffic
  degrades to fixed-effect-only answers BITWISE-equal to FE-only
  `GameTransformer` output (the pinned zero-row path), with half-open
  probing to recover;
* a bundle hot-swap under live traffic fails/drops ZERO requests, and
  post-swap answers are bitwise-equal to a cold-started engine on the new
  bundle; staging/commit faults roll back with the old bundle still
  serving;
* a flush-thread death fails every pending future with the error instead
  of hanging them, and close() stays joinable;
* a SIGKILLed replay leaves only readable score parts behind, and a
  re-run completes.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.game_dataset import GameDataset
from photon_ml_tpu.game.model import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.serving import (
    BatcherUnhealthy,
    CircuitBreaker,
    CircuitState,
    DeadlineExceeded,
    HbmBudgetExceeded,
    HealthStateMachine,
    Overloaded,
    ScoreRequest,
    ServingBundle,
    ServingEngine,
    ServingState,
    SwapIncompatible,
)
from photon_ml_tpu.transformers.game_transformer import (
    CoordinateScoringSpec,
    GameTransformer,
)
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import faults

pytestmark = pytest.mark.serving

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TASK = TaskType.LOGISTIC_REGRESSION
D_FE, D_RE, N_ENTITIES = 10, 4, 6


def _fixture(rng, n=9, seed_shift=0):
    """(model, specs, dataset, requests) — one FE + one RE coordinate."""
    X = rng.normal(size=(n, D_FE)).astype(np.float32)
    Xe = rng.normal(size=(n, D_RE)).astype(np.float32)
    entity_ids = rng.integers(0, N_ENTITIES + 2, size=n)
    offsets = rng.normal(size=n).astype(np.float32)
    w = rng.normal(size=D_FE).astype(np.float32)
    matrix = np.zeros((N_ENTITIES + 1, D_RE), np.float32)
    matrix[:N_ENTITIES] = rng.normal(size=(N_ENTITIES, D_RE))
    model = GameModel(
        {
            "fixed": FixedEffectModel(Coefficients(jnp.asarray(w)), TASK),
            "per-e": RandomEffectModel(jnp.asarray(matrix), None, TASK),
        }
    )
    specs = {
        "fixed": CoordinateScoringSpec(shard="g"),
        "per-e": CoordinateScoringSpec(
            shard="re",
            random_effect_type="eid",
            entity_index={str(i): i for i in range(N_ENTITIES)},
        ),
    }
    ds = GameDataset.build(
        {"g": X, "re": Xe},
        np.zeros(n, np.float32),
        offsets=offsets,
        id_tags={"eid": entity_ids.astype(str)},
    )
    reqs = [
        ScoreRequest(
            features={"g": X[i], "re": Xe[i]},
            entity_ids={"eid": str(entity_ids[i])},
            offset=float(offsets[i]),
            uid=str(i),
        )
        for i in range(n)
    ]
    return model, specs, ds, reqs


def _fe_only_ref(model, specs, ds):
    """FE-only GameTransformer scores (offset + fixed effects)."""
    fe_model = GameModel({"fixed": model["fixed"]})
    n = int(np.asarray(ds.offsets).shape[0])
    ds_fe = GameDataset.build(
        {"g": np.asarray(ds.shards["g"])},
        np.zeros(n, np.float32),
        offsets=np.asarray(ds.offsets),
    )
    return np.asarray(
        GameTransformer(fe_model, {"fixed": specs["fixed"]}, TASK)
        .transform(ds_fe)
        .scores
    )


def _scores(results):
    return np.asarray([r.score for r in results], np.float32)


def _slow_engine(eng, delay_s):
    """Wrap score_batch with a stall so the flush thread stays busy and the
    pending queue can actually fill (timing-only, math untouched)."""
    inner = eng.score_batch

    def slow(requests, **kw):
        time.sleep(delay_s)
        return inner(requests, **kw)

    eng.score_batch = slow  # type: ignore[method-assign]
    return eng


# ------------------------------------------------------------- admission


class TestAdmissionControl:
    def test_overload_sheds_typed_and_admitted_complete(self, rng):
        model, specs, _, reqs = _fixture(rng, n=4)
        eng = _slow_engine(
            ServingEngine(ServingBundle.from_model(model, specs, TASK), max_batch=4),
            0.03,
        )
        with eng, eng.batcher(max_wait_ms=1.0, max_pending=2) as b:
            futures, shed = [], 0
            for _ in range(40):
                try:
                    futures.append(b.submit(reqs[0]))
                except Overloaded:
                    shed += 1
            # Typed shedding, no unbounded backlog, and NO hangs: every
            # admitted future resolves within the timeout.
            assert shed > 0
            assert all(
                isinstance(f.result(timeout=20).score, float) for f in futures
            )
            m = b.metrics()
        assert m["shed"] == shed
        assert m["completed"] == len(futures)
        assert faults.COUNTERS.get("serving_shed_requests") == shed

    def test_blocking_submit_backpressures_instead_of_shedding(self, rng):
        model, specs, ds, reqs = _fixture(rng, n=9)
        ref = np.asarray(GameTransformer(model, specs, TASK).transform(ds).scores)
        eng = _slow_engine(
            ServingEngine(ServingBundle.from_model(model, specs, TASK), max_batch=4),
            0.01,
        )
        with eng, eng.batcher(max_wait_ms=1.0, max_pending=2) as b:
            res = b.score_all(reqs)  # closed-loop: block=True inside
            m = b.metrics()
        assert (_scores(res) == ref).all()
        assert m["shed"] == 0

    def test_admit_fault_site_sheds_via_photon_faults(self, rng, monkeypatch):
        """Chaos path for the new `admit` site, armed through the SAME env
        knob production uses."""
        model, specs, _, reqs = _fixture(rng, n=3)
        monkeypatch.setenv("PHOTON_FAULTS", "admit:2")
        faults.clear()  # force env re-read at the next fault_point
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=4
        ) as eng:
            with eng.batcher(max_wait_ms=1.0) as b:
                with pytest.raises(Overloaded):
                    b.submit(reqs[0])
                with pytest.raises(Overloaded):
                    b.submit(reqs[1])
                res = b.score(reqs[2])  # third admit passes
        assert isinstance(res.score, float)
        assert faults.COUNTERS.get("serving_shed_requests") == 2
        assert faults.COUNTERS.get("injected_faults") == 2

    def test_closed_batcher_beats_armed_admit_fault(self, rng):
        """A closed batcher must report its typed state, not consume the
        armed admit fault as a phantom shed."""
        model, specs, _, reqs = _fixture(rng, n=2)
        eng = ServingEngine(ServingBundle.from_model(model, specs, TASK), max_batch=4)
        b = eng.batcher()
        eng.close()
        with faults.inject("admit:1"):
            with pytest.raises(RuntimeError, match="closed"):
                b.submit(reqs[0])
        assert faults.COUNTERS.get("serving_shed_requests") == 0
        assert faults.COUNTERS.get("injected_faults") == 0


# -------------------------------------------------------------- deadlines


class TestDeadlineEnforcement:
    def test_expired_in_queue_fails_typed(self, rng):
        model, specs, _, reqs = _fixture(rng, n=2)
        eng = _slow_engine(
            ServingEngine(ServingBundle.from_model(model, specs, TASK), max_batch=4),
            0.15,
        )
        with eng, eng.batcher(max_wait_ms=1.0) as b:
            blocker = b.submit(reqs[0])  # occupies the device for 150ms
            time.sleep(0.02)  # let the flush thread claim it
            doomed = b.submit(reqs[1], deadline_ms=5.0)
            with pytest.raises(DeadlineExceeded):
                doomed.result(timeout=20)
            assert isinstance(blocker.result(timeout=20).score, float)
            m = b.metrics()
        assert m["deadline_missed"] == 1
        assert faults.COUNTERS.get("serving_deadline_misses") == 1

    def test_request_carried_budget_honored(self, rng):
        model, specs, _, reqs = _fixture(rng, n=2)
        eng = _slow_engine(
            ServingEngine(ServingBundle.from_model(model, specs, TASK), max_batch=4),
            0.15,
        )
        req = ScoreRequest(
            features=dict(reqs[1].features),
            entity_ids=dict(reqs[1].entity_ids),
            deadline_ms=5.0,
        )
        with eng, eng.batcher(max_wait_ms=1.0) as b:
            b.submit(reqs[0])
            time.sleep(0.02)
            with pytest.raises(DeadlineExceeded):
                b.submit(req).result(timeout=20)

    def test_unexpired_neighbors_still_answered(self, rng):
        """Batch assembly drops ONLY the expired request; queued neighbors
        with headroom are co-batched and answered normally."""
        model, specs, ds, reqs = _fixture(rng, n=3)
        ref = np.asarray(GameTransformer(model, specs, TASK).transform(ds).scores)
        eng = _slow_engine(
            ServingEngine(ServingBundle.from_model(model, specs, TASK), max_batch=4),
            0.1,
        )
        with eng, eng.batcher(max_wait_ms=1.0) as b:
            f0 = b.submit(reqs[0])  # claimed; stalls the flush thread
            time.sleep(0.02)
            f1 = b.submit(reqs[1], deadline_ms=5.0)  # expires in queue
            f2 = b.submit(reqs[2])  # no deadline: must survive the purge
            with pytest.raises(DeadlineExceeded):
                f1.result(timeout=20)
            assert f0.result(timeout=20).score == ref[0]
            assert f2.result(timeout=20).score == ref[2]

    def test_stale_service_ewma_decays_instead_of_wedging(self, rng):
        """A service-time spike (one slow batch) must not pre-fail every
        short-budget request forever: dispatch-less expiry rounds decay the
        EWMA until traffic flows again."""
        model, specs, _, reqs = _fixture(rng, n=2)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=4
        ) as eng:
            eng.warmup()
            with eng.batcher(max_wait_ms=1.0) as b:
                with b._cv:
                    b._service_ewma_s = 30.0  # absurd spike
                got_answer = False
                for _ in range(20):
                    try:
                        b.submit(reqs[0], deadline_ms=100.0).result(timeout=20)
                        got_answer = True
                        break
                    except DeadlineExceeded:
                        continue
                assert got_answer, "EWMA margin wedged the batcher"

    def test_no_deadline_means_no_misses(self, rng):
        model, specs, _, reqs = _fixture(rng, n=9)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=4
        ) as eng:
            with eng.batcher(max_wait_ms=1.0) as b:
                b.score_all(reqs)
                m = b.metrics()
        assert m["deadline_missed"] == 0
        assert faults.COUNTERS.get("serving_deadline_misses") == 0


# ----------------------------------------------------- flush-thread death


class TestFlushThreadDeath:
    def test_pending_futures_failed_not_hung(self, rng):
        model, specs, _, reqs = _fixture(rng, n=3)
        eng = ServingEngine(ServingBundle.from_model(model, specs, TASK), max_batch=4)
        eng.warmup()  # READY, so the death shows up as a DEGRADED reason
        b = eng.batcher(max_wait_ms=60_000.0, max_batch=4)  # holds the queue
        boom = RuntimeError("flush bookkeeping bug")

        def broken(batch):
            raise boom

        b._dispatch = broken  # type: ignore[method-assign]
        futures = [b.submit(r) for r in reqs]
        with b._cv:
            b._cv.notify_all()
        # Force a flush by filling max_batch (4th submit may race the dying
        # thread — both Overloaded-free acceptance and unhealthy rejection
        # are legal for IT; the three queued futures must fail, not hang).
        try:
            futures.append(b.submit(reqs[0]))
        except BatcherUnhealthy:
            pass
        for f in futures:
            with pytest.raises(RuntimeError, match="flush bookkeeping bug"):
                f.result(timeout=20)
        # The batcher is typed-unhealthy for new work, close() stays
        # joinable, and the engine is DEGRADED with the recorded reason.
        with pytest.raises(BatcherUnhealthy):
            b.submit(reqs[0])
        assert not b.healthy
        assert faults.COUNTERS.get("serving_flush_thread_failures") == 1
        assert eng.health.state is ServingState.DEGRADED
        assert any(
            "batcher_unhealthy" in r for r in eng.health.degraded_reasons
        )
        eng.close()  # joins the (dead) thread without wedging
        assert b.closed


# --------------------------------------------------------- circuit breaker


class TestCircuitBreakerUnit:
    def test_opens_after_threshold_and_probes_single_file(self):
        t = [0.0]
        br = CircuitBreaker(threshold=3, probe_interval_s=10.0, clock=lambda: t[0])
        for _ in range(2):
            br.on_failure(br.acquire())
        assert br.state is CircuitState.CLOSED
        br.on_failure(br.acquire())  # third consecutive: OPEN
        assert br.state is CircuitState.OPEN
        assert br.acquire() is None  # interval not elapsed
        t[0] = 11.0
        probe = br.acquire()  # the single probe permit
        assert probe is not None and probe.probe
        assert br.acquire() is None  # second caller: still degraded
        br.on_success(probe)
        assert br.state is CircuitState.CLOSED
        assert faults.COUNTERS.get("serving_circuit_opens") == 1

    def test_failed_probe_rearms_interval(self):
        t = [0.0]
        br = CircuitBreaker(threshold=1, probe_interval_s=5.0, clock=lambda: t[0])
        br.on_failure(br.acquire())
        t[0] = 6.0
        probe = br.acquire()
        assert probe is not None
        br.on_failure(probe)  # probe failed: OPEN again, interval restarts
        assert br.state is CircuitState.OPEN
        t[0] = 10.0
        assert br.acquire() is None  # 6.0 + 5.0 not reached
        t[0] = 11.5
        assert br.acquire() is not None

    def test_abandon_returns_probe_permit(self):
        """A probe that failed for a non-device reason must not wedge the
        breaker in HALF_OPEN forever."""
        t = [0.0]
        br = CircuitBreaker(threshold=1, probe_interval_s=1.0, clock=lambda: t[0])
        br.on_failure(br.acquire())
        t[0] = 2.0
        probe = br.acquire()
        br.on_abandon(probe)
        assert br.acquire() is not None  # permit is available again

    def test_success_resets_consecutive_count(self):
        br = CircuitBreaker(threshold=2)
        br.on_failure(br.acquire())
        br.on_success(br.acquire())
        br.on_failure(br.acquire())  # 1 consecutive, not 2
        assert br.state is CircuitState.CLOSED

    def test_stale_free_permit_cannot_clobber_inflight_probe(self):
        """A CLOSED-era permit resolving late must neither release nor
        decide another batcher's half-open probe."""
        t = [0.0]
        br = CircuitBreaker(threshold=3, probe_interval_s=1.0, clock=lambda: t[0])
        stale = br.acquire()  # free permit, acquired while CLOSED
        assert stale is not None and not stale.probe
        for _ in range(3):
            br.on_failure(br.acquire())  # circuit opens
        t[0] = 2.0
        probe = br.acquire()
        assert probe is not None and probe.probe
        br.on_abandon(stale)  # stale resolution: probe still in flight
        assert br.acquire() is None  # no second probe handed out
        br.on_failure(stale)  # stale device failure: counts, doesn't probe
        assert br.acquire() is None
        br.on_success(probe)  # the REAL probe decides the outcome
        assert br.state is CircuitState.CLOSED

    def test_stale_free_permit_success_cannot_close_open_circuit(self):
        """A CLOSED-era permit succeeding LATE (acquired before the device
        died) must not re-close an open circuit — only the probe may route
        traffic back."""
        t = [0.0]
        br = CircuitBreaker(threshold=2, probe_interval_s=1.0, clock=lambda: t[0])
        stale = br.acquire()
        assert stale is not None and not stale.probe
        for _ in range(2):
            br.on_failure(br.acquire())
        assert br.state is CircuitState.OPEN
        br.on_success(stale)  # pre-outage evidence arriving late
        assert br.state is CircuitState.OPEN  # still open, probe decides
        t[0] = 2.0
        probe = br.acquire()
        assert probe is not None and probe.probe
        br.on_success(probe)
        assert br.state is CircuitState.CLOSED


@pytest.mark.chaos
class TestCircuitBreakerServing:
    def test_open_circuit_serves_fe_only_bitwise(self, rng, monkeypatch):
        """Persistent device faults open the circuit after K failures;
        subsequent traffic gets ANSWERS (not errors) bitwise-equal to
        fixed-effect-only GameTransformer output."""
        monkeypatch.setenv("PHOTON_RETRY_MAX_ATTEMPTS", "1")
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        model, specs, ds, reqs = _fixture(rng, n=9)
        fe_ref = _fe_only_ref(model, specs, ds)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK),
            max_batch=4,
            circuit_threshold=2,
            circuit_probe_interval_s=60.0,  # no probe inside this test
        ) as eng:
            eng.warmup()
            with faults.inject("score:1000"):  # device persistently down
                with eng.batcher(max_wait_ms=1.0) as b:
                    failed, fe_answers = 0, {}
                    for i, r in enumerate(reqs):
                        try:
                            fe_answers[i] = b.score(r)
                        except faults.InjectedFault:
                            failed += 1
                    m = b.metrics()
            # The pre-open failures surfaced as errors (the evidence), the
            # rest as FE-only answers.
            assert failed == 2
            assert m["circuit_state"] == "OPEN"
            assert m["circuit_opens"] == 1
            assert faults.COUNTERS.get("serving_circuit_opens") == 1
            assert eng.health.state is ServingState.DEGRADED
            assert "circuit_open" in eng.health.degraded_reasons
            for i, res in fe_answers.items():
                assert res.fe_only
                assert res.score == fe_ref[i]
            assert m["fe_only_answers"] == len(fe_answers)

    def test_half_open_probe_recovers_full_path(self, rng, monkeypatch):
        monkeypatch.setenv("PHOTON_RETRY_MAX_ATTEMPTS", "1")
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        model, specs, ds, reqs = _fixture(rng, n=6)
        ref = np.asarray(GameTransformer(model, specs, TASK).transform(ds).scores)
        fe_ref = _fe_only_ref(model, specs, ds)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK),
            max_batch=4,
            circuit_threshold=1,
            circuit_probe_interval_s=0.05,
        ) as eng:
            eng.warmup()
            # Exactly 2 faulted invocations: the batch attempt + the
            # per-request retry — the ONE device failure that opens the
            # K=1 circuit. Everything after scores clean.
            with faults.inject("score:2"):
                with eng.batcher(max_wait_ms=1.0) as b:
                    with pytest.raises(faults.InjectedFault):
                        b.score(reqs[0])
                    assert eng.breaker.state is CircuitState.OPEN
                    r1 = b.score(reqs[1])  # inside the interval: FE-only
                    assert r1.fe_only and r1.score == fe_ref[1]
                    time.sleep(0.06)  # probe due
                    r2 = b.score(reqs[2])  # the probe: full path, succeeds
                    assert not r2.fe_only and r2.score == ref[2]
                    assert eng.breaker.state is CircuitState.CLOSED
                    rest = b.score_all(reqs[3:])
            assert (_scores(rest) == ref[3:]).all()
            assert eng.health.state is ServingState.READY

    def test_malformed_request_never_trips_breaker(self, rng):
        """A poisoned request fails ITS future; the device is innocent —
        the circuit stays closed and neighbors keep full-path answers."""
        model, specs, ds, reqs = _fixture(rng, n=4)
        ref = np.asarray(GameTransformer(model, specs, TASK).transform(ds).scores)
        poison = ScoreRequest(
            features={"g": np.zeros((3, 3), np.float32)},  # wrong shape
            entity_ids={"eid": "0"},
        )
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK),
            max_batch=4,
            circuit_threshold=1,  # a single device failure WOULD open it
        ) as eng:
            with eng.batcher(max_wait_ms=1.0) as b:
                futs = [b.submit(r) for r in reqs[:3]] + [b.submit(poison)]
                good = [f.result(timeout=20) for f in futs[:3]]
                with pytest.raises(Exception) as ei:
                    futs[3].result(timeout=20)
                assert not isinstance(ei.value, faults.InjectedFault)
            assert eng.breaker.state is CircuitState.CLOSED
        assert (_scores(good) == ref[:3]).all()
        assert faults.COUNTERS.get("serving_circuit_opens") == 0


# ------------------------------------------------------------ bundle swap


def _second_model(rng, model):
    """A same-shape successor (new weights, same E / dims / shards)."""
    w2 = rng.normal(size=D_FE).astype(np.float32)
    matrix2 = np.zeros((N_ENTITIES + 1, D_RE), np.float32)
    matrix2[:N_ENTITIES] = rng.normal(size=(N_ENTITIES, D_RE))
    return GameModel(
        {
            "fixed": FixedEffectModel(Coefficients(jnp.asarray(w2)), TASK),
            "per-e": RandomEffectModel(jnp.asarray(matrix2), None, TASK),
        }
    )


class TestBundleHotSwap:
    def test_swap_under_live_traffic_zero_failures_bitwise(self, rng):
        model, specs, ds, reqs = _fixture(rng, n=9)
        model2 = _second_model(rng, model)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=8
        ) as eng:
            eng.warmup()
            stop = threading.Event()
            failures: list = []
            answered = [0]

            def traffic(b):
                i = 0
                while not stop.is_set():
                    try:
                        b.score(reqs[i % len(reqs)])
                        answered[0] += 1
                    except Exception as exc:  # noqa: BLE001 - recorded
                        failures.append(exc)
                    i += 1

            with eng.batcher(max_wait_ms=0.5) as b:
                t = threading.Thread(target=lambda: traffic(b))
                t.start()
                time.sleep(0.05)  # traffic flowing against version 0
                info = eng.bundle_manager.swap(
                    lambda: ServingBundle.from_model(model2, specs, TASK)
                )
                time.sleep(0.05)  # traffic flowing against version 1
                stop.set()
                t.join(timeout=20)
            assert not t.is_alive()
            assert failures == []
            assert answered[0] > 0
            assert info["version"] == 1 and info["old_released"]
            assert eng.bundle_version == 1
            # Post-swap answers == a cold-started engine on the new bundle.
            with ServingEngine(
                ServingBundle.from_model(model2, specs, TASK), max_batch=8
            ) as cold:
                ref2 = _scores(cold.score_batch(reqs))
            assert (_scores(eng.score_batch(reqs)) == ref2).all()
            # Staging pre-warmed the new parameters: the flip compiled
            # nothing on the hot path.
            assert eng.recompiles_after_warmup == 0
            assert eng.metrics()["bundle_swaps"] == 1
        assert faults.COUNTERS.get("serving_swaps") == 1
        assert faults.COUNTERS.get("serving_swap_rollbacks") == 0

    def test_stage_fault_rolls_back_old_keeps_serving(self, rng, monkeypatch):
        monkeypatch.setenv("PHOTON_RETRY_MAX_ATTEMPTS", "2")
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        model, specs, ds, reqs = _fixture(rng, n=5)
        ref = np.asarray(GameTransformer(model, specs, TASK).transform(ds).scores)
        model2 = _second_model(rng, model)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=8
        ) as eng:
            with faults.inject("swap_stage:1000"):  # beyond the retry budget
                with pytest.raises(faults.InjectedFault):
                    eng.bundle_manager.swap(
                        lambda: ServingBundle.from_model(model2, specs, TASK)
                    )
            assert eng.bundle_version == 0
            assert (_scores(eng.score_batch(reqs)) == ref).all()
            assert eng.metrics()["bundle_swap_rollbacks"] == 1
        assert faults.COUNTERS.get("serving_swap_rollbacks") == 1
        assert faults.COUNTERS.get("serving_swaps") == 0

    def test_transient_stage_fault_is_retried_through(self, rng):
        model, specs, _, reqs = _fixture(rng, n=3)
        model2 = _second_model(rng, model)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=4
        ) as eng:
            with faults.inject("swap_stage:1"):  # one blip: retry absorbs it
                info = eng.bundle_manager.swap(
                    lambda: ServingBundle.from_model(model2, specs, TASK)
                )
            assert info["version"] == 1
        assert faults.COUNTERS.get("serving_swaps") == 1
        assert faults.COUNTERS.get("serving_swap_rollbacks") == 0

    def test_commit_fault_rolls_back(self, rng):
        model, specs, ds, reqs = _fixture(rng, n=5)
        ref = np.asarray(GameTransformer(model, specs, TASK).transform(ds).scores)
        model2 = _second_model(rng, model)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=8
        ) as eng:
            with faults.inject("swap_commit:1"):
                with pytest.raises(faults.InjectedFault):
                    eng.bundle_manager.swap(
                        lambda: ServingBundle.from_model(model2, specs, TASK)
                    )
            assert eng.bundle_version == 0
            assert (_scores(eng.score_batch(reqs)) == ref).all()
        assert faults.COUNTERS.get("serving_swap_rollbacks") == 1

    def test_hbm_budget_refused_before_staging(self, rng):
        model, specs, _, _ = _fixture(rng, n=2)
        built = [0]

        def builder():
            built[0] += 1
            return ServingBundle.from_model(model, specs, TASK)

        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=4
        ) as eng:
            with pytest.raises(HbmBudgetExceeded):
                eng.bundle_manager.swap(
                    builder, expected_bytes=1 << 40, hbm_budget_bytes=1 << 20
                )
        assert built[0] == 0  # refused BEFORE any device allocation
        assert faults.COUNTERS.get("serving_swaps") == 0

    def test_incompatible_bundle_rejected(self, rng):
        model, specs, _, _ = _fixture(rng, n=2)
        rng2 = np.random.default_rng(99)
        w = rng2.normal(size=D_FE + 3).astype(np.float32)  # wrong FE dim
        bad = GameModel(
            {"fixed": FixedEffectModel(Coefficients(jnp.asarray(w)), TASK)}
        )
        bad_specs = {"fixed": CoordinateScoringSpec(shard="g")}
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=4
        ) as eng:
            with pytest.raises(SwapIncompatible):
                eng.bundle_manager.swap(
                    ServingBundle.from_model(bad, bad_specs, TASK)
                )
            assert eng.bundle_version == 0
        assert faults.COUNTERS.get("serving_swap_rollbacks") == 1

    def test_released_bundle_refused(self, rng):
        model, specs, _, _ = _fixture(rng, n=2)
        bundle = ServingBundle.from_model(model, specs, TASK)
        bundle.release()
        assert bundle.released
        with pytest.raises(RuntimeError, match="released"):
            ServingEngine(bundle, max_batch=4)


# ----------------------------------------------------------- health states


class TestHealthStateMachine:
    def test_engine_lifecycle_states(self, rng):
        model, specs, _, reqs = _fixture(rng, n=2)
        eng = ServingEngine(ServingBundle.from_model(model, specs, TASK), max_batch=4)
        assert eng.health.state is ServingState.STARTING
        assert eng.metrics()["state"] == "STARTING"
        eng.warmup()
        assert eng.health.state is ServingState.READY
        eng.close()
        assert eng.health.state is ServingState.CLOSED
        snap = eng.health.snapshot()
        path = [t["to"] for t in snap["transitions"]]
        assert path == ["READY", "DRAINING", "CLOSED"]

    def test_close_drains_pending_then_closes(self, rng):
        model, specs, _, reqs = _fixture(rng, n=5)
        eng = ServingEngine(ServingBundle.from_model(model, specs, TASK), max_batch=4)
        eng.warmup()
        b = eng.batcher(max_wait_ms=10_000.0)  # flush deadline never fires
        futures = [b.submit(r) for r in reqs[:3]]
        eng.close()  # graceful drain: stragglers answered, nothing dropped
        assert all(isinstance(f.result(timeout=5).score, float) for f in futures)
        assert eng.health.state is ServingState.CLOSED

    def test_reason_tracked_degradation(self):
        h = HealthStateMachine()
        h.mark_ready()
        h.add_degraded("circuit_open")
        h.add_degraded("batcher_unhealthy: boom")
        assert h.state is ServingState.DEGRADED
        h.clear_degraded("circuit_open")
        assert h.state is ServingState.DEGRADED  # dead batcher still pins it
        h.clear_degraded("batcher_unhealthy: boom")
        assert h.state is ServingState.READY

    def test_closed_is_terminal(self):
        """CLOSED is terminal: late degradation reports and ready marks
        (shutdown races) are absorbed, never resurrect the state."""
        h = HealthStateMachine()
        h.begin_drain()
        h.close()
        h.add_degraded("too late")
        h.mark_ready()
        assert h.state is ServingState.CLOSED
        # The DRAINING -> READY edge does not exist: draining only closes.
        h2 = HealthStateMachine()
        h2.mark_ready()
        h2.begin_drain()
        h2.clear_degraded("nothing")
        assert h2.state is ServingState.DRAINING
        h2.close()
        assert h2.state is ServingState.CLOSED


# ------------------------------------------------------------ site tooling


class TestFaultSiteTooling:
    def test_list_sites_prints_registered_table(self, capsys):
        assert faults.main(["--list-sites"]) == 0
        out = capsys.readouterr().out
        for site in faults.KNOWN_SITES:
            assert site in out
        for new_site in ("admit", "swap_stage", "swap_commit"):
            assert new_site in out

    def test_list_sites_shows_armed_plan(self, capsys):
        with faults.inject("admit:2,score:p0.5"):
            faults.main(["--list-sites"])
        out = capsys.readouterr().out
        assert "first 2" in out
        assert "p=0.5" in out

    def test_every_new_site_is_parseable_from_env_spec(self):
        # The conftest guard keeps fault_point() calls inside KNOWN_SITES;
        # this keeps the inverse true — every registered site is armable.
        plan = faults.FaultPlan.parse(
            ",".join(f"{s}:1" for s in faults.KNOWN_SITES)
        )
        assert set(plan.sites) == set(faults.KNOWN_SITES)


# ------------------------------------------------------- crash-safe replay


_SERVE_CHILD = """
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {repo!r})
from photon_ml_tpu.cli import serve
serve.REPLAY_WINDOW = 8  # many small windows: a mid-replay kill lands between parts
serve.main([
    "--model-input-directory", sys.argv[1],
    "--requests", sys.argv[2],
    "--root-output-directory", sys.argv[3],
    "--max-batch", "8",
    "--max-wait-ms", "0.5",
])
print("CHILD_DONE", flush=True)
"""


@pytest.mark.chaos
class TestCrashSafeReplay:
    def _model_dir(self, rng, tmp_path):
        from photon_ml_tpu.data.index_map import IndexMap
        from photon_ml_tpu.io import model_bridge, model_store

        model, specs, _, _ = _fixture(rng, n=2)
        index_maps = {
            "g": IndexMap.from_feature_names([f"f{i}" for i in range(D_FE)]),
            "re": IndexMap.from_feature_names([f"r{i}" for i in range(D_RE)]),
        }
        art = model_bridge.artifact_from_game_model(model, specs, TASK)
        mdir = tmp_path / "model"
        model_store.save_game_model(str(mdir), art, index_maps)
        idx_dir = mdir / "feature-indexes"
        os.makedirs(idx_dir)
        for shard, imap in index_maps.items():
            imap.save(str(idx_dir / f"{shard}.json"))
        return str(mdir)

    def _requests_file(self, rng, tmp_path, n):
        path = tmp_path / "requests.jsonl"
        with open(path, "w") as f:
            for i in range(n):
                doc = {
                    "uid": f"r{i}",
                    "ids": {"eid": str(int(rng.integers(0, N_ENTITIES + 2)))},
                    "features": {
                        "g": {f"f{j}": float(rng.normal()) for j in range(3)},
                        "re": {f"r{j}": float(rng.normal()) for j in range(2)},
                    },
                }
                f.write(json.dumps(doc) + "\n")
        return str(path)

    def test_sigkill_mid_replay_leaves_only_readable_parts(self, rng, tmp_path):
        from photon_ml_tpu.io import avro as avro_io

        n_req = 160  # REPLAY_WINDOW=8 in the child -> 20 part files
        mdir = self._model_dir(rng, tmp_path)
        reqfile = self._requests_file(rng, tmp_path, n_req)
        outdir = str(tmp_path / "out")
        script = tmp_path / "child.py"
        script.write_text(_SERVE_CHILD.format(repo=REPO))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, str(script), mdir, reqfile, outdir],
            cwd=REPO,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )
        scores_dir = os.path.join(outdir, "scores")
        try:
            # Kill -9 once at least two parts are durably in place (parts
            # are written to a dot-tmp name and os.replace'd, so anything
            # named part-*.avro must already be complete).
            deadline = time.monotonic() + 180
            killed = False
            while time.monotonic() < deadline and proc.poll() is None:
                try:
                    parts = [
                        p
                        for p in os.listdir(scores_dir)
                        if p.startswith("part-") and p.endswith(".avro")
                    ]
                except OSError:
                    parts = []
                if len(parts) >= 2:
                    proc.send_signal(signal.SIGKILL)
                    killed = True
                    break
                time.sleep(0.01)
            proc.wait(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        if killed:
            assert proc.returncode == -signal.SIGKILL
        parts = sorted(
            p
            for p in os.listdir(scores_dir)
            if p.startswith("part-") and p.endswith(".avro")
        )
        assert parts, "no part committed before the child finished"
        # EVERY committed part is fully readable — no torn Avro container.
        n_read = 0
        for p in parts:
            _, recs = avro_io.read_container(os.path.join(scores_dir, p))
            assert recs, f"{p} is empty"
            n_read += len(recs)
        assert faults.COUNTERS.get("quarantined_blocks") == 0
        # A re-run over the same stream completes end to end and scores
        # every request (same outdir: parts are replaced atomically).
        from photon_ml_tpu.cli import serve

        old_window = serve.REPLAY_WINDOW
        serve.REPLAY_WINDOW = 8
        try:
            summary = serve.run(
                serve.build_parser().parse_args(
                    [
                        "--model-input-directory", mdir,
                        "--requests", reqfile,
                        "--root-output-directory", outdir,
                        "--max-batch", "8",
                        "--max-wait-ms", "0.5",
                    ]
                )
            )
        finally:
            serve.REPLAY_WINDOW = old_window
        assert summary["num_requests"] == n_req
        assert summary["failed_requests"] == 0
        assert summary["malformed_records"] == 0
        assert summary["health"]["state"] == "CLOSED"
        total = 0
        for p in sorted(os.listdir(scores_dir)):
            if p.startswith("part-") and p.endswith(".avro"):
                _, recs = avro_io.read_container(os.path.join(scores_dir, p))
                total += len(recs)
        assert total == n_req

    def test_malformed_replay_records_cost_one_record_each(self, rng, tmp_path):
        """A bad line mid-stream (broken JSON, garbage feature value) is
        skipped and counted — the replay completes and scores everything
        else."""
        from photon_ml_tpu.cli import serve

        mdir = self._model_dir(rng, tmp_path)
        good = 20
        path = tmp_path / "requests.jsonl"
        with open(path, "w") as f:
            for i in range(good // 2):
                f.write(json.dumps({"uid": f"a{i}", "ids": {"eid": "0"},
                                    "features": {"g": {"f0": 1.0}}}) + "\n")
            f.write("{not json at all\n")
            f.write(json.dumps({"uid": "bad", "ids": {"eid": "0"},
                                "features": {"g": {"f0": "garbage"}}}) + "\n")
            for i in range(good // 2):
                f.write(json.dumps({"uid": f"b{i}", "ids": {"eid": "1"},
                                    "features": {"g": {"f1": -1.0}}}) + "\n")
        outdir = str(tmp_path / "out")
        summary = serve.run(
            serve.build_parser().parse_args(
                [
                    "--model-input-directory", mdir,
                    "--requests", str(path),
                    "--root-output-directory", outdir,
                    "--max-batch", "8",
                    "--max-wait-ms", "0.5",
                ]
            )
        )
        assert summary["num_requests"] == good
        assert summary["failed_requests"] == 0
        assert summary["malformed_records"] == 2
        assert summary["health"]["state"] == "CLOSED"

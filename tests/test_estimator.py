"""GameEstimator / GameTransformer tests (reference: GameEstimator.scala,
GameTransformer.scala behavior)."""

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.game_dataset import (
    FixedEffectDataConfig,
    GameDataset,
    RandomEffectDataConfig,
)
from photon_ml_tpu.estimators.game_estimator import GameEstimator, select_best_result
from photon_ml_tpu.evaluation.suite import EvaluatorType
from photon_ml_tpu.game.model import GameModel
from photon_ml_tpu.optimize.config import (
    L2,
    CoordinateOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.types import NormalizationType, TaskType


_TRUTH_RNG = np.random.default_rng(12345)
_W_TRUE = _TRUTH_RNG.normal(size=4)
_B_TRUE = _TRUTH_RNG.normal(size=(20, 3))


def _glmix_data(seed, n=400, n_entities=10, d_fixed=4, d_re=3):
    """Draws from ONE shared ground-truth GLMix model so train/validation
    measure generalization of the same signal."""
    rng = np.random.default_rng(seed)
    Xf = rng.normal(size=(n, d_fixed)).astype(np.float32)
    Xe = rng.normal(size=(n, d_re)).astype(np.float32)
    entity = rng.integers(0, n_entities, size=n)
    margins = Xf @ _W_TRUE + np.einsum("nd,nd->n", Xe, _B_TRUE[entity])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margins))).astype(np.float32)
    return GameDataset.build(
        {"global": jnp.asarray(Xf), "per_entity": jnp.asarray(Xe)},
        y,
        id_tags={"memberId": entity},
    )


DATA_CONFIGS = {
    "fixed": FixedEffectDataConfig("global"),
    "per-member": RandomEffectDataConfig("memberId", "per_entity", min_bucket=4),
}


def _opt_config(weight):
    cfg = CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=30),
        regularization=L2,
        reg_weight=weight,
    )
    return {"fixed": cfg, "per-member": cfg}


class TestGameEstimator:
    def test_fit_sweep_with_validation(self):
        train = _glmix_data(0)
        val = _glmix_data(1)
        est = GameEstimator(
            TaskType.LOGISTIC_REGRESSION,
            DATA_CONFIGS,
            coordinate_descent_iterations=2,
            validation_evaluators=[EvaluatorType("AUC")],
        )
        results = est.fit(train, val, [_opt_config(10.0), _opt_config(0.1)])
        assert len(results) == 2
        for r in results:
            assert r.evaluation is not None
            assert set(r.model.coordinate_ids) == {"fixed", "per-member"}
        # AUC must beat random on both configs.
        assert all(r.evaluation.primary_value > 0.6 for r in results)
        i, best = select_best_result(results)
        assert best.evaluation.primary_value == max(
            r.evaluation.primary_value for r in results
        )

    def test_transform_scores_holdout_with_unseen_entities(self):
        train = _glmix_data(0, n_entities=10)
        est = GameEstimator(TaskType.LOGISTIC_REGRESSION, DATA_CONFIGS)
        results = est.fit(train, None, [_opt_config(1.0)])
        transformer = est.scoring_specs()
        from photon_ml_tpu.transformers.game_transformer import GameTransformer

        t = GameTransformer(results[0].model, transformer, TaskType.LOGISTIC_REGRESSION)
        # Hold-out set with entity ids 0..19 — half unseen at training time.
        holdout = _glmix_data(7, n_entities=20)
        out = t.transform(holdout)
        assert out.scores.shape == (holdout.num_samples,)
        means = np.asarray(out.means)
        assert np.all((means > 0) & (means < 1))
        # Unseen entities score with the zero RE model: their RE contribution
        # must be exactly zero.
        unseen = np.asarray(holdout.id_tags["memberId"]) >= 10
        assert unseen.any()
        re_scores = np.asarray(out.per_coordinate["per-member"])
        np.testing.assert_allclose(re_scores[unseen], 0.0, atol=1e-6)

    def test_locked_coordinate_partial_retrain(self):
        train = _glmix_data(0)
        est0 = GameEstimator(TaskType.LOGISTIC_REGRESSION, DATA_CONFIGS)
        base = est0.fit(train, None, [_opt_config(1.0)])[0].model

        est = GameEstimator(
            TaskType.LOGISTIC_REGRESSION,
            DATA_CONFIGS,
            locked_coordinates={"fixed"},
        )
        results = est.fit(
            train,
            None,
            [{"per-member": _opt_config(0.5)["per-member"]}],
            initial_model=base,
        )
        model = results[0].model
        # Locked coordinate unchanged.
        np.testing.assert_array_equal(
            np.asarray(model["fixed"].coefficients.means),
            np.asarray(base["fixed"].coefficients.means),
        )
        # Retrained coordinate differs.
        assert not np.allclose(
            np.asarray(model["per-member"].coefficients_matrix),
            np.asarray(base["per-member"].coefficients_matrix),
        )

    def test_normalization_path(self):
        train = _glmix_data(0)
        # Scale a feature badly to make normalization matter.
        shards = dict(train.shards)
        shards["global"] = shards["global"] * jnp.asarray([100.0, 1.0, 0.01, 1.0])
        train = GameDataset(shards, train.labels, train.offsets, train.weights, train.id_tags)
        est = GameEstimator(
            TaskType.LOGISTIC_REGRESSION,
            DATA_CONFIGS,
            normalization=NormalizationType.SCALE_WITH_STANDARD_DEVIATION,
            validation_evaluators=[EvaluatorType("AUC")],
        )
        results = est.fit(train, train, [_opt_config(0.1)])
        assert results[0].evaluation.primary_value > 0.7

    def test_config_validation(self):
        with pytest.raises(ValueError):
            GameEstimator(
                TaskType.LOGISTIC_REGRESSION,
                DATA_CONFIGS,
                update_sequence=["fixed", "nope"],
            )
        est = GameEstimator(TaskType.LOGISTIC_REGRESSION, DATA_CONFIGS)
        with pytest.raises(ValueError):
            est.fit(_glmix_data(0), None, [])
        with pytest.raises(ValueError):
            est.fit(_glmix_data(0), None, [{"fixed": _opt_config(1.0)["fixed"]}])

    def test_warm_start_chain_reuses_compiled(self):
        train = _glmix_data(0)
        est = GameEstimator(TaskType.LOGISTIC_REGRESSION, DATA_CONFIGS)
        est.fit(train, None, [_opt_config(10.0), _opt_config(1.0), _opt_config(0.1)])
        # One compiled coordinate object per (cid, static config): the sweep
        # must not grow the cache beyond 2.
        assert len(est._coordinate_cache) == 2

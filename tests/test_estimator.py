"""GameEstimator / GameTransformer tests (reference: GameEstimator.scala,
GameTransformer.scala behavior)."""

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.game_dataset import (
    FixedEffectDataConfig,
    GameDataset,
    RandomEffectDataConfig,
)
from photon_ml_tpu.estimators.game_estimator import GameEstimator, select_best_result
from photon_ml_tpu.evaluation.suite import EvaluatorType
from photon_ml_tpu.game.model import GameModel
from photon_ml_tpu.optimize.config import (
    L2,
    CoordinateOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.types import NormalizationType, TaskType


_TRUTH_RNG = np.random.default_rng(12345)
_W_TRUE = _TRUTH_RNG.normal(size=4)
_B_TRUE = _TRUTH_RNG.normal(size=(20, 3))


def _glmix_data(seed, n=400, n_entities=10, d_fixed=4, d_re=3):
    """Draws from ONE shared ground-truth GLMix model so train/validation
    measure generalization of the same signal."""
    rng = np.random.default_rng(seed)
    Xf = rng.normal(size=(n, d_fixed)).astype(np.float32)
    Xe = rng.normal(size=(n, d_re)).astype(np.float32)
    entity = rng.integers(0, n_entities, size=n)
    margins = Xf @ _W_TRUE + np.einsum("nd,nd->n", Xe, _B_TRUE[entity])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margins))).astype(np.float32)
    return GameDataset.build(
        {"global": jnp.asarray(Xf), "per_entity": jnp.asarray(Xe)},
        y,
        id_tags={"memberId": entity},
    )


DATA_CONFIGS = {
    "fixed": FixedEffectDataConfig("global"),
    "per-member": RandomEffectDataConfig("memberId", "per_entity", min_bucket=4),
}


def _opt_config(weight):
    cfg = CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=30),
        regularization=L2,
        reg_weight=weight,
    )
    return {"fixed": cfg, "per-member": cfg}


class TestGameEstimator:
    def test_fit_sweep_with_validation(self):
        train = _glmix_data(0)
        val = _glmix_data(1)
        est = GameEstimator(
            TaskType.LOGISTIC_REGRESSION,
            DATA_CONFIGS,
            coordinate_descent_iterations=2,
            validation_evaluators=[EvaluatorType("AUC")],
        )
        results = est.fit(train, val, [_opt_config(10.0), _opt_config(0.1)])
        assert len(results) == 2
        for r in results:
            assert r.evaluation is not None
            assert set(r.model.coordinate_ids) == {"fixed", "per-member"}
        # AUC must beat random on both configs.
        assert all(r.evaluation.primary_value > 0.6 for r in results)
        i, best = select_best_result(results)
        assert best.evaluation.primary_value == max(
            r.evaluation.primary_value for r in results
        )

    def test_transform_scores_holdout_with_unseen_entities(self):
        train = _glmix_data(0, n_entities=10)
        est = GameEstimator(TaskType.LOGISTIC_REGRESSION, DATA_CONFIGS)
        results = est.fit(train, None, [_opt_config(1.0)])
        transformer = est.scoring_specs()
        from photon_ml_tpu.transformers.game_transformer import GameTransformer

        t = GameTransformer(results[0].model, transformer, TaskType.LOGISTIC_REGRESSION)
        # Hold-out set with entity ids 0..19 — half unseen at training time.
        holdout = _glmix_data(7, n_entities=20)
        out = t.transform(holdout)
        assert out.scores.shape == (holdout.num_samples,)
        means = np.asarray(out.means)
        assert np.all((means > 0) & (means < 1))
        # Unseen entities score with the zero RE model: their RE contribution
        # must be exactly zero.
        unseen = np.asarray(holdout.id_tags["memberId"]) >= 10
        assert unseen.any()
        re_scores = np.asarray(out.per_coordinate["per-member"])
        np.testing.assert_allclose(re_scores[unseen], 0.0, atol=1e-6)

    def test_locked_coordinate_partial_retrain(self):
        train = _glmix_data(0)
        est0 = GameEstimator(TaskType.LOGISTIC_REGRESSION, DATA_CONFIGS)
        base = est0.fit(train, None, [_opt_config(1.0)])[0].model

        est = GameEstimator(
            TaskType.LOGISTIC_REGRESSION,
            DATA_CONFIGS,
            locked_coordinates={"fixed"},
        )
        results = est.fit(
            train,
            None,
            [{"per-member": _opt_config(0.5)["per-member"]}],
            initial_model=base,
        )
        model = results[0].model
        # Locked coordinate unchanged.
        np.testing.assert_array_equal(
            np.asarray(model["fixed"].coefficients.means),
            np.asarray(base["fixed"].coefficients.means),
        )
        # Retrained coordinate differs.
        assert not np.allclose(
            np.asarray(model["per-member"].coefficients_matrix),
            np.asarray(base["per-member"].coefficients_matrix),
        )

    def test_normalization_path(self):
        train = _glmix_data(0)
        # Scale a feature badly to make normalization matter.
        shards = dict(train.shards)
        shards["global"] = shards["global"] * jnp.asarray([100.0, 1.0, 0.01, 1.0])
        train = GameDataset(shards, train.labels, train.offsets, train.weights, train.id_tags)
        est = GameEstimator(
            TaskType.LOGISTIC_REGRESSION,
            DATA_CONFIGS,
            normalization=NormalizationType.SCALE_WITH_STANDARD_DEVIATION,
            validation_evaluators=[EvaluatorType("AUC")],
        )
        results = est.fit(train, train, [_opt_config(0.1)])
        assert results[0].evaluation.primary_value > 0.7

    def test_config_validation(self):
        with pytest.raises(ValueError):
            GameEstimator(
                TaskType.LOGISTIC_REGRESSION,
                DATA_CONFIGS,
                update_sequence=["fixed", "nope"],
            )
        est = GameEstimator(TaskType.LOGISTIC_REGRESSION, DATA_CONFIGS)
        with pytest.raises(ValueError):
            est.fit(_glmix_data(0), None, [])
        with pytest.raises(ValueError):
            est.fit(_glmix_data(0), None, [{"fixed": _opt_config(1.0)["fixed"]}])

    def test_warm_start_chain_reuses_compiled(self):
        train = _glmix_data(0)
        est = GameEstimator(TaskType.LOGISTIC_REGRESSION, DATA_CONFIGS)
        est.fit(train, None, [_opt_config(10.0), _opt_config(1.0), _opt_config(0.1)])
        # One compiled coordinate object per (cid, static config): the sweep
        # must not grow the cache beyond 2.
        assert len(est._coordinate_cache) == 2


class TestProjectedNormalization:
    """STANDARDIZATION on INDEX_MAP-projected random-effect shards via
    per-entity projected NormalizationContexts
    (IndexMapProjectorRDD.scala:133)."""

    def _sparse_glmix(self, seed, n=400, n_entities=8, d=6, full_support=True):
        """Sparse RE shard with an intercept column (last). When
        `full_support`, every entity sees every feature, making INDEX_MAP
        projection a pure re-indexing — mathematically identical to
        IDENTITY."""
        from photon_ml_tpu.data.containers import SparseFeatures

        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, d)).astype(np.float32) + 2.0  # shifted data
        X[:, d - 1] = 1.0  # intercept
        entity = rng.integers(0, n_entities, size=n)
        if not full_support:
            # Each entity only uses a subset of the non-intercept features.
            for e in range(n_entities):
                drop = rng.choice(d - 1, size=2, replace=False)
                X[np.ix_(entity == e, drop)] = 0.0
        b = rng.normal(size=(n_entities, d))
        margins = np.einsum("nd,nd->n", X, b[entity]) * 0.5
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margins))).astype(np.float32)
        idx = np.broadcast_to(np.arange(d, dtype=np.int32), (n, d)).copy()
        sf = SparseFeatures(jnp.asarray(idx), jnp.asarray(X), d)
        return GameDataset.build({"e": sf}, y, id_tags={"m": entity}), d

    def _fit(self, ds, d, projector):
        from photon_ml_tpu.types import ProjectorType

        est = GameEstimator(
            TaskType.LOGISTIC_REGRESSION,
            {
                "per-m": RandomEffectDataConfig(
                    "m", "e", min_bucket=4, projector_type=projector
                )
            },
            normalization=NormalizationType.STANDARDIZATION,
            intercept_indices={"e": d - 1},
        )
        cfg = {
            "per-m": CoordinateOptimizationConfig(
                optimizer=OptimizerConfig(max_iterations=60, tolerance=1e-9),
                regularization=L2,
                reg_weight=1.0,
            )
        }
        results = est.fit(ds, None, [cfg])
        return est, results[0].model

    def test_index_map_matches_identity_on_full_support(self):
        from photon_ml_tpu.io.model_bridge import artifact_from_game_model
        from photon_ml_tpu.types import ProjectorType

        ds, d = self._sparse_glmix(0, full_support=True)
        est_id, model_id = self._fit(ds, d, ProjectorType.IDENTITY)
        ds2, _ = self._sparse_glmix(0, full_support=True)
        est_ix, model_ix = self._fit(ds2, d, ProjectorType.INDEX_MAP)

        # Original-space artifacts must agree: the projected solve is the
        # same optimization in permuted coordinates.
        art_id = artifact_from_game_model(
            model_id, est_id.scoring_specs(), TaskType.LOGISTIC_REGRESSION
        )
        art_ix = artifact_from_game_model(
            model_ix, est_ix.scoring_specs(), TaskType.LOGISTIC_REGRESSION
        )
        a, b = art_id.coordinates["per-m"], art_ix.coordinates["per-m"]
        assert a.entity_ids == b.entity_ids
        np.testing.assert_allclose(a.means, b.means, rtol=5e-3, atol=2e-3)

        # And the transformers score identically.
        from photon_ml_tpu.transformers.game_transformer import GameTransformer

        t_id = GameTransformer(model_id, est_id.scoring_specs(), TaskType.LOGISTIC_REGRESSION)
        t_ix = GameTransformer(model_ix, est_ix.scoring_specs(), TaskType.LOGISTIC_REGRESSION)
        s_id = np.asarray(t_id.transform(ds).scores)
        s_ix = np.asarray(t_ix.transform(ds2).scores)
        np.testing.assert_allclose(s_id, s_ix, rtol=5e-3, atol=2e-3)

    def test_standardization_trains_on_sparse_support(self):
        """Partial per-entity support: the projected solve must converge and
        round-trip through the model store in original space."""
        from photon_ml_tpu.io import model_store
        from photon_ml_tpu.io.model_bridge import artifact_from_game_model
        from photon_ml_tpu.types import ProjectorType
        from photon_ml_tpu.evaluation.metrics import area_under_roc_curve

        ds, d = self._sparse_glmix(1, full_support=False)
        est, model = self._fit(ds, d, ProjectorType.INDEX_MAP)
        specs = est.scoring_specs()

        from photon_ml_tpu.transformers.game_transformer import GameTransformer

        t = GameTransformer(model, specs, TaskType.LOGISTIC_REGRESSION)
        scores = t.transform(ds).scores
        assert bool(jnp.all(jnp.isfinite(scores)))
        auc = float(area_under_roc_curve(scores, ds.labels))
        assert auc > 0.75

        art = artifact_from_game_model(model, specs, TaskType.LOGISTIC_REGRESSION)
        re_art = art.coordinates["per-m"]
        assert np.all(np.isfinite(re_art.means))
        assert re_art.means.shape[1] == d  # original space

"""Data-plane tests: containers (incl. batched sparse), stats, libsvm, index map."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.containers import SparseFeatures, pack_csr_to_ell
from photon_ml_tpu.data.index_map import INTERCEPT_KEY, IndexMap, feature_key
from photon_ml_tpu.data.libsvm import read_libsvm, write_libsvm
from photon_ml_tpu.data.stats import summarize


def _random_sparse(rng, n=25, d=9, density=0.4):
    dense = rng.normal(size=(n, d)).astype(np.float32)
    dense *= rng.uniform(size=(n, d)) < density
    indptr = [0]
    idxs, vals = [], []
    for r in range(n):
        nz = np.nonzero(dense[r])[0]
        idxs.extend(nz)
        vals.extend(dense[r, nz])
        indptr.append(len(idxs))
    sp = pack_csr_to_ell(
        np.asarray(indptr), np.asarray(idxs), np.asarray(vals, np.float32), d
    )
    return dense, sp


def test_sparse_to_dense_batched(rng):
    """to_dense must be correct with leading batch dims (entity blocks)."""
    indices = jnp.asarray(
        [[[0, 1], [1, 2]], [[2, 0], [0, 1]]], jnp.int32
    )  # (2, 2, 2)
    values = jnp.ones((2, 2, 2), jnp.float32)
    sp = SparseFeatures(indices, values, 3)
    dense = sp.to_dense()
    assert dense.shape == (2, 2, 3)
    np.testing.assert_allclose(dense[0], [[1, 1, 0], [0, 1, 1]])
    np.testing.assert_allclose(dense[1], [[1, 0, 1], [1, 1, 0]])


def test_sparse_rmatvec_rejects_batched():
    sp = SparseFeatures(jnp.zeros((2, 3, 2), jnp.int32), jnp.ones((2, 3, 2)), 4)
    with pytest.raises(ValueError):
        sp.rmatvec(jnp.ones((2, 3)))
    with pytest.raises(ValueError):
        sp.sq_rmatvec(jnp.ones((2, 3)))


def test_sparse_matvec_batched_matches_vmap(rng):
    dense0, sp0 = _random_sparse(rng)
    dense1, sp1 = _random_sparse(rng)
    sp = SparseFeatures(
        jnp.stack([sp0.indices, sp1.indices]),
        jnp.stack([sp0.values, sp1.values]),
        sp0.dim,
    )
    w = jnp.asarray(rng.normal(size=sp0.dim).astype(np.float32))
    out = sp.matvec(w)
    np.testing.assert_allclose(out[0], dense0 @ np.asarray(w), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(out[1], dense1 @ np.asarray(w), rtol=1e-4, atol=1e-5)


def test_summarize_dense_vs_numpy(rng):
    X = rng.normal(size=(50, 6)).astype(np.float32)
    X[:, 2] = 0.0
    s = summarize(jnp.asarray(X))
    np.testing.assert_allclose(s.mean, X.mean(0), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(s.variance, X.var(0, ddof=1), rtol=1e-3, atol=1e-5)
    np.testing.assert_allclose(s.max, X.max(0), rtol=1e-5)
    np.testing.assert_allclose(s.min, X.min(0), rtol=1e-5)
    np.testing.assert_allclose(s.num_nonzeros, (X != 0).sum(0))
    np.testing.assert_allclose(s.norm_l2, np.linalg.norm(X, axis=0), rtol=1e-4)


def test_summarize_sparse_matches_dense(rng):
    """Sparse summary (segment reductions, never densifies) == dense summary."""
    dense, sp = _random_sparse(rng, n=40, d=11)
    sd = summarize(jnp.asarray(dense))
    ss = summarize(sp)
    for field in ("mean", "variance", "num_nonzeros", "max", "min", "norm_l1", "norm_l2", "mean_abs"):
        np.testing.assert_allclose(
            getattr(ss, field), getattr(sd, field), rtol=1e-3, atol=1e-4, err_msg=field
        )


def test_summarize_sparse_all_positive_feature(rng):
    """A feature with entries in every row and no zeros must not see an
    implicit-zero min."""
    n, d = 8, 3
    indices = np.tile(np.arange(3, dtype=np.int32), (n, 1))
    values = rng.uniform(1.0, 2.0, size=(n, d)).astype(np.float32)
    sp = SparseFeatures(jnp.asarray(indices), jnp.asarray(values), d)
    s = summarize(sp)
    assert float(s.min[0]) >= 1.0  # not clamped to 0


def test_libsvm_round_trip(tmp_path, rng):
    path = str(tmp_path / "a.libsvm")
    with open(path, "w") as f:
        f.write("+1 1:0.5 3:2.0\n-1 2:1.5\n# comment line\n\n+1 1:-1.0\n")
    ds = read_libsvm(path)
    assert ds.num_rows == 3
    assert ds.dim == 4  # 3 features + intercept
    np.testing.assert_allclose(ds.labels, [1.0, 0.0, 1.0])
    X = ds.to_dense()
    np.testing.assert_allclose(X[:, -1], 1.0)  # intercept column
    np.testing.assert_allclose(X[0, :3], [0.5, 0.0, 2.0])

    out = str(tmp_path / "b.libsvm")
    write_libsvm(out, ds)
    ds2 = read_libsvm(out, add_intercept=False)
    np.testing.assert_allclose(ds2.to_dense(), X, rtol=1e-5)


def test_libsvm_no_intercept_regression_labels(tmp_path):
    path = str(tmp_path / "c.libsvm")
    with open(path, "w") as f:
        f.write("2.5 1:1.0\n-3.5 2:1.0\n")
    ds = read_libsvm(path, add_intercept=False)
    assert ds.dim == 2
    np.testing.assert_allclose(ds.labels, [2.5, -3.5])  # not 0/1-mapped


def test_index_map_basics():
    im = IndexMap.from_feature_names(["b", "a", "c", "a"], add_intercept=True)
    assert len(im) == 4
    assert im.get_index("a") == 0 and im.get_index("b") == 1  # sorted
    assert im.intercept_index == 3
    assert im.get_feature_name(im[INTERCEPT_KEY]) == INTERCEPT_KEY
    assert im.get_index("missing") == -1
    assert feature_key("age", "18-25") == "age\x0118-25"


def test_index_map_save_load(tmp_path):
    im = IndexMap.from_feature_names(["x", "y"], add_intercept=False)
    p = str(tmp_path / "m" / "map.json")
    im.save(p)
    im2 = IndexMap.load(p)
    assert dict(im2.items()) == dict(im.items())


def test_pack_csr_truncation(rng):
    indptr = np.asarray([0, 3])
    indices = np.asarray([0, 1, 2])
    values = np.asarray([0.1, 5.0, -3.0], np.float32)
    sp = pack_csr_to_ell(indptr, indices, values, 4, max_nnz=2)
    # Keeps the two largest |values|: 5.0 and -3.0.
    kept = set(np.asarray(sp.indices[0]).tolist())
    assert kept == {1, 2}

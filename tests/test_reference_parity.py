"""Parity tests against the reference's OWN golden fixtures and artifacts.

The reference ships real datasets and pre-trained model artifacts under
photon-client/src/integTest/resources (GameTrainingDriverIntegTest.scala:50,
479, 523, 702-706). These tests prove the claims the docstrings make:

  * training on the reference's data (heart.avro, a9a LibSVM) reaches the
    same quality the reference's own integ tests demand, cross-checked
    against sklearn on identical data;
  * `io.model_store.load_game_model` reads the reference's pre-trained
    `gameModel` / `fixedEffectOnlyGAMEModel` / `retrainModels` Avro
    directories byte-for-byte (ModelProcessingUtils.scala:143-265 layout);
  * loaded reference models score data identically to a manual dot product
    over the raw Avro records;
  * our writer round-trips a reference artifact losslessly.

All tests skip when /root/reference is not mounted.
"""

from __future__ import annotations

import glob
import os

import numpy as np
import pytest

from photon_ml_tpu.data.containers import LabeledData, pack_csr_to_ell
from photon_ml_tpu.data.index_map import INTERCEPT_KEY, IndexMap, feature_key
from photon_ml_tpu.data.libsvm import read_libsvm
from photon_ml_tpu.evaluation.metrics import area_under_roc_curve, rmse
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import model_store
from photon_ml_tpu.io.avro_data import FeatureShardConfig, read_game_dataset
from photon_ml_tpu.io.model_bridge import game_model_from_artifact
from photon_ml_tpu.models.training import select_best_model, train_glm_sweep
from photon_ml_tpu.optimize.config import (
    L2,
    CoordinateOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.transformers.game_transformer import GameTransformer
from photon_ml_tpu.types import OptimizerType, TaskType

REF = "/root/reference/photon-client/src/integTest/resources"
DRIVER_IN = os.path.join(REF, "DriverIntegTest", "input")
GAME = os.path.join(REF, "GameIntegTest")

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference fixtures not mounted"
)


def _labeled(ds, shard: str) -> LabeledData:
    return LabeledData(ds.shards[shard], ds.labels, ds.offsets, ds.weights)


def _csr_to_labeled(csr) -> LabeledData:
    import jax.numpy as jnp

    feats = pack_csr_to_ell(csr.indptr, csr.indices, csr.values, csr.dim)
    n = csr.num_rows
    return LabeledData(
        feats,
        jnp.asarray(csr.labels, jnp.float32),
        jnp.zeros(n, jnp.float32),
        jnp.ones(n, jnp.float32),
    )


def _sklearn_auc(X_train, y_train, X_test, y_test, reg_weight: float) -> float:
    from sklearn.linear_model import LogisticRegression
    from sklearn.metrics import roc_auc_score

    # Both sklearn and the reference use the sum-loss convention
    # (L2Regularization adds rw/2 ||w||^2 to the SUMMED weighted loss), so
    # the optima coincide at C = 1 / rw.
    clf = LogisticRegression(
        C=1.0 / reg_weight, fit_intercept=False, max_iter=5000, tol=1e-10
    )
    clf.fit(X_train, y_train)
    return float(roc_auc_score(y_test, X_test @ clf.coef_.ravel()))


# --------------------------------------------------------------------------
# Training parity on the reference's data
# --------------------------------------------------------------------------


class TestHeartTrainingParity:
    """Legacy-driver workflow on DriverIntegTest heart.avro
    (Driver.scala stages; tutorial config README.md:307-345)."""

    @pytest.fixture(scope="class")
    def heart(self):
        shards = {"global": FeatureShardConfig(("features",), True)}
        train, imaps = read_game_dataset(
            os.path.join(DRIVER_IN, "heart.avro"), shards
        )
        val, _ = read_game_dataset(
            os.path.join(DRIVER_IN, "heart_validation.avro"),
            shards,
            index_maps=imaps,
        )
        return train, val, imaps

    def test_trains_to_reference_quality(self, heart):
        """TRON sweep on the RAW (unnormalized) heart data — the fixture's
        own model-spec uses TRON; it handles the raw data's conditioning in
        f32 where first-order methods need normalization."""
        train, val, _ = heart
        cfg = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(OptimizerType.TRON, 50, 1e-9),
            regularization=L2,
        )
        sweep = train_glm_sweep(
            _labeled(train, "global"),
            TaskType.LOGISTIC_REGRESSION,
            cfg,
            [0.1, 1.0, 10.0, 100.0],  # tutorial sweep, README.md:283-292
        )
        best_w, model, best_auc = select_best_model(
            sweep, _labeled(val, "global"), TaskType.LOGISTIC_REGRESSION
        )
        # sklearn on the IDENTICAL design matrix (same index map, same
        # intercept column) must agree.
        Xtr = np.asarray(train.shards["global"].to_dense(), np.float64)
        Xv = np.asarray(val.shards["global"].to_dense(), np.float64)
        sk_auc = _sklearn_auc(
            Xtr, np.asarray(train.labels), Xv, np.asarray(val.labels), best_w
        )
        assert best_auc == pytest.approx(sk_auc, abs=0.005)
        # Pinned floor: measured 0.7708 for this exact config.
        assert best_auc > 0.76

    def test_lbfgs_standardized_matches_tron(self, heart):
        """On the standardized problem (normalization-as-algebra) LBFGS and
        TRON must land on the same optimum — the f32 conditioning story:
        raw heart stalls first-order methods, standardized heart doesn't."""
        train, _, imaps = heart
        from photon_ml_tpu.data.stats import summarize
        from photon_ml_tpu.ops.normalization import from_feature_stats
        from photon_ml_tpu.types import NormalizationType

        icpt = imaps["global"].intercept_index
        stats = summarize(train.shards["global"], intercept_index=icpt)
        norm = from_feature_stats(
            NormalizationType.STANDARDIZATION,
            mean=stats.mean,
            variance=stats.variance,
            max_abs=stats.max_abs,
            intercept_index=icpt,
        )
        data = _labeled(train, "global")
        res = {}
        for opt, iters in ((OptimizerType.LBFGS, 200), (OptimizerType.TRON, 50)):
            cfg = CoordinateOptimizationConfig(
                optimizer=OptimizerConfig(opt, iters, 1e-9),
                regularization=L2,
            )
            sweep = train_glm_sweep(
                data, TaskType.LOGISTIC_REGRESSION, cfg, [10.0], norm=norm
            )
            res[opt] = np.asarray(sweep.models[10.0].coefficients.means)
        np.testing.assert_allclose(
            res[OptimizerType.LBFGS], res[OptimizerType.TRON], atol=2e-3
        )


class TestA9aTrainingParity:
    """The a9a LibSVM pair the reference's DriverIntegTest ships
    (DriverIntegTest/input/a9a, a9a.t) — the dataset the tutorial's a1a flow
    is scaled from."""

    @pytest.fixture(scope="class")
    def a9a(self):
        train = read_libsvm(os.path.join(DRIVER_IN, "a9a"))
        test = read_libsvm(
            os.path.join(DRIVER_IN, "a9a.t"), num_features=train.dim - 1
        )
        assert test.dim == train.dim
        return train, test

    def test_logistic_auc_vs_sklearn(self, a9a):
        train, test = a9a
        cfg = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(OptimizerType.LBFGS, 100, 1e-7),
            regularization=L2,
        )
        sweep = train_glm_sweep(
            _csr_to_labeled(train), TaskType.LOGISTIC_REGRESSION, cfg, [1.0]
        )
        w = np.asarray(sweep.models[1.0].coefficients.means, np.float64)
        test_dense = test.to_dense().astype(np.float64)
        scores = test_dense @ w
        auc = float(
            area_under_roc_curve(
                np.asarray(scores, np.float32),
                np.asarray(test.labels, np.float32),
            )
        )
        from sklearn.linear_model import LogisticRegression
        from sklearn.metrics import roc_auc_score

        clf = LogisticRegression(
            C=1.0, fit_intercept=False, max_iter=500, tol=1e-8
        )
        clf.fit(train.to_dense(), train.labels)
        sk_auc = float(roc_auc_score(test.labels, test_dense @ clf.coef_.ravel()))
        assert auc == pytest.approx(sk_auc, abs=0.005)
        assert auc > 0.89  # a9a logistic test AUC is ~0.90


# --------------------------------------------------------------------------
# Pre-trained reference artifacts
# --------------------------------------------------------------------------


def _index_map_from_model_dir(model_dir: str) -> dict:
    """Build per-shard IndexMaps from the union of feature keys in a
    reference model directory (the test stands in for the PalDB index
    partitions the reference distributes alongside)."""
    shard_keys: dict = {}
    for kind in (model_store.FIXED_EFFECT, model_store.RANDOM_EFFECT):
        kdir = os.path.join(model_dir, kind)
        if not os.path.isdir(kdir):
            continue
        for cid in os.listdir(kdir):
            cdir = os.path.join(kdir, cid)
            with open(os.path.join(cdir, model_store.ID_INFO)) as f:
                lines = f.read().split()
            shard = lines[0] if kind == model_store.FIXED_EFFECT else lines[1]
            keys = shard_keys.setdefault(shard, set())
            for part in sorted(glob.glob(os.path.join(cdir, "coefficients", "*.avro"))):
                _, recs = avro_io.read_container(part)
                for rec in recs:
                    for m in rec["means"]:
                        keys.add(feature_key(m["name"], m["term"]))
    return {
        shard: IndexMap.from_feature_names(sorted(keys), add_intercept=True)
        for shard, keys in shard_keys.items()
    }


class TestLoadReferencePretrainedModels:
    def test_fixed_effect_only_game_model(self):
        mdir = os.path.join(GAME, "fixedEffectOnlyGAMEModel")
        imaps = _index_map_from_model_dir(mdir)
        art = model_store.load_game_model(mdir, imaps)
        assert art.task == TaskType.LINEAR_REGRESSION
        assert set(art.coordinates) == {"globalShard"}
        fe = art.coordinates["globalShard"]
        assert fe.feature_shard == "globalShard"
        # Every record coefficient must land in the vector exactly.
        _, recs = avro_io.read_container(
            os.path.join(mdir, "fixed-effect/globalShard/coefficients/part-00000.avro")
        )
        rec = recs[0]
        assert int(np.count_nonzero(fe.means)) == len(rec["means"])
        imap = imaps["globalShard"]
        for m in rec["means"][:50]:
            idx = imap.get_index(feature_key(m["name"], m["term"]))
            assert fe.means[idx] == pytest.approx(m["value"], rel=1e-6)

    def test_game_model_fixture_with_stripped_random_effects(self):
        """The gameModel fixture ships RE id-info without coefficient files;
        loading must yield 0-entity random effects, not crash."""
        mdir = os.path.join(GAME, "gameModel")
        imaps = _index_map_from_model_dir(mdir)
        # RE shards have no coefficient records -> no index map was built for
        # them; supply empty maps.
        for shard in ("userShard", "songShard"):
            imaps.setdefault(shard, IndexMap.from_feature_names([]))
        art = model_store.load_game_model(mdir, imaps)
        assert art.task == TaskType.LINEAR_REGRESSION
        assert set(art.coordinates) == {
            "globalShard",
            "songId-songShard",
            "userId-userShard",
        }
        fe = art.coordinates["globalShard"]
        imap = imaps["globalShard"]
        icpt = imap.get_index(INTERCEPT_KEY)
        # Value read straight from the reference's Avro bytes.
        assert fe.means[icpt] == pytest.approx(3.5525033712866567, rel=1e-9)
        for cid in ("songId-songShard", "userId-userShard"):
            assert art.coordinates[cid].means.shape[0] == 0

    def test_mixed_effects_retrain_model(self):
        """retrainModels/mixedEffects: 1 fixed effect + 9427 per-song and
        4471 per-artist entity models (full coefficient part files)."""
        mdir = os.path.join(GAME, "retrainModels", "mixedEffects")
        imaps = _index_map_from_model_dir(mdir)
        art = model_store.load_game_model(
            mdir, imaps, coordinates_to_load=["global", "per-song"]
        )
        assert art.task == TaskType.LINEAR_REGRESSION
        fe = art.coordinates["global"]
        assert fe.feature_shard == "shard1"
        song = art.coordinates["per-song"]
        assert song.random_effect_type == "songId"
        assert song.feature_shard == "shard2"
        assert len(song.entity_ids) == 9427
        assert song.means.shape == (9427, imaps["shard2"].size)
        # Spot-check one entity row against the raw Avro record.
        parts = sorted(
            glob.glob(os.path.join(mdir, "random-effect/per-song/coefficients/*.avro"))
        )
        _, recs = avro_io.read_container(parts[0])
        rec = recs[0]
        row = song.entity_ids.index(rec["modelId"])
        imap = imaps["shard2"]
        for m in rec["means"]:
            idx = imap.get_index(feature_key(m["name"], m["term"]))
            assert song.means[row, idx] == pytest.approx(m["value"], rel=1e-6)
        assert int(np.count_nonzero(song.means[row])) == len(rec["means"])

    def test_metadata_opt_configs_loaded(self):
        mdir = os.path.join(GAME, "retrainModels", "mixedEffects")
        imaps = _index_map_from_model_dir(mdir)
        art = model_store.load_game_model(mdir, imaps, coordinates_to_load=["global"])
        # The reference's nested optimizationConfigurations JSON rides along.
        cfgs = art.opt_configs
        assert cfgs and "values" in cfgs
        names = {v["name"] for v in cfgs["values"]}
        assert {"global", "per-song", "per-artist", "per-user"} <= names


class TestScoreWithReferenceModel:
    """Score the reference's yahoo-music records with its own pre-trained
    fixed-effect model and check against a manual dot product over the raw
    Avro bytes (the GameScoringDriver path end-to-end)."""

    def test_fixed_effect_scoring_matches_manual(self):
        mdir = os.path.join(GAME, "fixedEffectOnlyGAMEModel")
        imaps = _index_map_from_model_dir(mdir)
        art = model_store.load_game_model(mdir, imaps)
        model, specs = game_model_from_artifact(art)
        transformer = GameTransformer(model, specs, art.task)

        data_path = os.path.join(GAME, "input/duplicateFeatures/yahoo-music-train.avro")
        shards = {
            "globalShard": FeatureShardConfig(
                ("features", "userFeatures", "songFeatures"), True
            )
        }
        ds, _ = read_game_dataset(
            data_path, shards, index_maps=imaps, id_tag_fields=("userId", "songId")
        )
        result = transformer.transform(ds)
        scores = np.asarray(result.scores)
        assert np.all(np.isfinite(scores))

        # Manual scores from the raw records.
        _, recs = avro_io.read_container(data_path)
        fe = art.coordinates["globalShard"]
        imap = imaps["globalShard"]
        for i, rec in enumerate(recs):
            s = fe.means[imap.get_index(INTERCEPT_KEY)]
            for bag in ("features", "userFeatures", "songFeatures"):
                for f in rec.get(bag) or ():
                    idx = imap.get_index(feature_key(f["name"], f.get("term", "")))
                    if idx >= 0:
                        s += fe.means[idx] * f["value"]
            assert scores[i] == pytest.approx(float(s), rel=1e-4)

        # Sanity: the pre-trained model predicts ratings in a sane range
        # (response values here are ratings; RMSE finite and bounded).
        err = float(rmse(result.scores, ds.labels))
        assert np.isfinite(err)


class TestArtifactRoundTrip:
    def test_reference_artifact_roundtrips_losslessly(self, tmp_path):
        """load(reference) -> save(ours) -> load(ours) must be identical —
        proves our writer emits the layout the reference's reader (and ours)
        consumes (ModelProcessingUtils.scala:77-141)."""
        mdir = os.path.join(GAME, "retrainModels", "fixedEffectsOnly")
        imaps = _index_map_from_model_dir(mdir)
        art = model_store.load_game_model(mdir, imaps)

        out = str(tmp_path / "resaved")
        model_store.save_game_model(out, art, imaps)
        art2 = model_store.load_game_model(out, imaps)

        assert art2.task == art.task
        assert set(art2.coordinates) == set(art.coordinates)
        fe, fe2 = art.coordinates["global"], art2.coordinates["global"]
        assert fe2.feature_shard == fe.feature_shard
        np.testing.assert_allclose(fe2.means, fe.means, rtol=1e-7)
        # Layout check: same directory structure as the reference.
        assert os.path.isfile(os.path.join(out, "model-metadata.json"))
        assert os.path.isfile(os.path.join(out, "fixed-effect/global/id-info"))
        assert glob.glob(os.path.join(out, "fixed-effect/global/coefficients/*.avro"))

    def test_random_effect_artifact_roundtrip(self, tmp_path):
        """Round-trip a slice of the per-artist RE model (entity ids +
        per-entity rows preserved through part files)."""
        mdir = os.path.join(GAME, "retrainModels", "mixedEffects")
        imaps = _index_map_from_model_dir(mdir)
        art = model_store.load_game_model(
            mdir, imaps, coordinates_to_load=["per-artist"]
        )
        re = art.coordinates["per-artist"]
        sliced = model_store.GameModelArtifact(
            task=art.task,
            coordinates={
                "per-artist": model_store.RandomEffectArtifact(
                    re.random_effect_type,
                    re.feature_shard,
                    re.entity_ids[:100],
                    re.means[:100],
                )
            },
        )
        out = str(tmp_path / "re-resaved")
        model_store.save_game_model(out, sliced, imaps, records_per_file=32)
        art2 = model_store.load_game_model(out, imaps)
        re2 = art2.coordinates["per-artist"]
        assert re2.random_effect_type == "artistId"
        assert re2.entity_ids == re.entity_ids[:100]
        np.testing.assert_allclose(re2.means, re.means[:100], rtol=1e-7)
        # records_per_file=32 over 100 entities -> 4 part files like the
        # reference's saveModelsRDDToHDFS partitioned output.
        assert len(glob.glob(os.path.join(out, "random-effect/per-artist/coefficients/*.avro"))) == 4


class TestFeatureSummaryParity:
    """summarize() vs the reference's own expected heart summary fixture
    (photon-api DriverIntegTest/input/heart_summary.txt: rows = mean,
    variance, numNonzeros, max, min, normL1, normL2, meanAbs over the 13
    heart features + intercept)."""

    def test_heart_summary_matches_reference_fixture(self):
        import numpy as np
        from photon_ml_tpu.data.stats import summarize

        ref_file = os.path.join(
            "/root/reference/photon-api/src/integTest/resources",
            "DriverIntegTest/input/heart_summary.txt",
        )
        rows = [
            [float(v) for v in line.strip().split(",")]
            for line in open(ref_file)
            if line.strip()
        ]
        mean_r, var_r, nnz_r, max_r, min_r, l1_r, l2_r, meanabs_r = rows

        shards = {"global": FeatureShardConfig(("features",), True)}
        ds, imaps = read_game_dataset(os.path.join(DRIVER_IN, "heart.avro"), shards)
        imap = imaps["global"]
        stats = summarize(ds.shards["global"], intercept_index=imap.intercept_index)

        # Fixture columns are features "1".."13" then the intercept.
        order = [imap.get_index(str(i)) for i in range(1, 14)] + [imap.intercept_index]
        assert all(i >= 0 for i in order)
        for ours, ref in (
            (stats.mean, mean_r),
            (stats.variance, var_r),
            (stats.num_nonzeros, nnz_r),
            (stats.max, max_r),
            (stats.min, min_r),
            (stats.norm_l1, l1_r),
            (stats.norm_l2, l2_r),
            (stats.mean_abs, meanabs_r),
        ):
            np.testing.assert_allclose(
                np.asarray(ours)[order], np.asarray(ref), rtol=2e-4
            )

    def test_write_basic_statistics_roundtrip(self, tmp_path):
        import numpy as np
        from photon_ml_tpu.data.stats import summarize
        from photon_ml_tpu.io import avro as avro_io
        from photon_ml_tpu.io.model_store import write_basic_statistics

        shards = {"global": FeatureShardConfig(("features",), True)}
        ds, imaps = read_game_dataset(os.path.join(DRIVER_IN, "heart.avro"), shards)
        imap = imaps["global"]
        stats = summarize(ds.shards["global"], intercept_index=imap.intercept_index)
        out = str(tmp_path / "summary" / "global")
        n = write_basic_statistics(out, stats, imap)
        assert n == imap.size - 1  # intercept excluded
        _, recs = avro_io.read_container(os.path.join(out, "part-00000.avro"))
        assert len(recs) == n
        by_name = {r["featureName"]: r["metrics"] for r in recs}
        i3 = imap.get_index("3")
        m = by_name["3"]
        assert set(m) == {"max", "min", "mean", "normL1", "normL2", "numNonzeros", "variance"}
        assert m["mean"] == pytest.approx(float(np.asarray(stats.mean)[i3]), rel=1e-6)
        assert m["variance"] == pytest.approx(float(np.asarray(stats.variance)[i3]), rel=1e-6)


class TestYahooMusicGameFlow:
    """GAME-level flows on the reference's yahoo-music records with its own
    integ-test feature-shard configurations
    (GameTrainingDriverIntegTest.scala:763-765: shard1 = features ∪
    userFeatures ∪ songFeatures, shard2 = features ∪ userFeatures,
    shard3 = songFeatures)."""

    SHARDS = {
        "shard1": FeatureShardConfig(("features", "userFeatures", "songFeatures"), True),
        "shard2": FeatureShardConfig(("features", "userFeatures"), True),
        "shard3": FeatureShardConfig(("songFeatures",), True),
    }
    DATA = os.path.join(GAME, "input/duplicateFeatures/yahoo-music-train.avro")

    def test_multi_bag_shards_read(self):
        ds, imaps = read_game_dataset(
            self.DATA, self.SHARDS, id_tag_fields=("userId", "songId", "artistId")
        )
        assert ds.num_samples == 6
        assert set(ds.shards) == {"shard1", "shard2", "shard3"}
        # shard1 unions every bag; shard3 sees only song features + intercept.
        assert imaps["shard1"].size > imaps["shard3"].size
        for tag in ("userId", "songId", "artistId"):
            assert tag in ds.id_tags

    def test_game_training_on_reference_records(self):
        """Fixed + per-song random effect trains end to end on the actual
        reference records (LINEAR_REGRESSION, as the fixture's model-spec)."""
        from photon_ml_tpu.data.game_dataset import (
            FixedEffectDataConfig,
            RandomEffectDataConfig,
        )
        from photon_ml_tpu.estimators.game_estimator import GameEstimator

        ds, imaps = read_game_dataset(
            self.DATA, self.SHARDS, id_tag_fields=("userId", "songId")
        )
        est = GameEstimator(
            TaskType.LINEAR_REGRESSION,
            {
                "global": FixedEffectDataConfig("shard1"),
                "per-song": RandomEffectDataConfig("songId", "shard3", min_bucket=2),
            },
            intercept_indices={
                s: imaps[s].intercept_index for s in imaps
            },
        )
        cfg = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(OptimizerType.TRON, 10, 1e-5),
            regularization=L2,
            reg_weight=10.0,  # the fixture model-spec's global config
        )
        results = est.fit(ds, None, [{"global": cfg, "per-song": cfg}])
        from photon_ml_tpu.transformers.game_transformer import GameTransformer

        t = GameTransformer(results[0].model, est.scoring_specs(), TaskType.LINEAR_REGRESSION)
        out = t.transform(ds)
        assert bool(np.all(np.isfinite(np.asarray(out.scores))))
        # Training reduced the residual against the (rating) responses.
        base_err = float(rmse(np.zeros(6, np.float32), ds.labels))
        fit_err = float(rmse(out.scores, ds.labels))
        assert fit_err < base_err

    def test_score_with_reference_random_effect_model(self):
        """Load the reference's pre-trained per-song entity models and score
        records whose songIds the model knows: the RE contribution must match
        a manual dot product over the raw Avro coefficients."""
        mdir = os.path.join(GAME, "retrainModels", "mixedEffects")
        imaps = _index_map_from_model_dir(mdir)
        art = model_store.load_game_model(mdir, imaps, coordinates_to_load=["per-song"])
        model, specs = game_model_from_artifact(art)

        ds, _ = read_game_dataset(
            self.DATA,
            {"shard2": FeatureShardConfig(("features", "userFeatures"), True)},
            index_maps=imaps,
            id_tag_fields=("songId",),
        )
        transformer = GameTransformer(model, specs, art.task)
        scores = np.asarray(transformer.transform(ds).scores)

        song_art = art.coordinates["per-song"]
        row_of = {eid: i for i, eid in enumerate(song_art.entity_ids)}
        imap = imaps["shard2"]
        _, recs = avro_io.read_container(self.DATA)
        known = 0
        for i, rec in enumerate(recs):
            sid = str(rec["songId"])
            row = row_of.get(sid)
            if row is None:
                assert scores[i] == pytest.approx(0.0, abs=1e-5)
                continue
            known += 1
            s = song_art.means[row, imap.get_index(INTERCEPT_KEY)]
            for bag in ("features", "userFeatures"):
                for f in rec.get(bag) or ():
                    j = imap.get_index(feature_key(f["name"], f.get("term", "")))
                    if j >= 0:
                        s += song_art.means[row, j] * f["value"]
            assert scores[i] == pytest.approx(float(s), rel=1e-4, abs=1e-5)
        assert known >= 1  # the fixture's songs overlap the model


class TestPoissonParity:
    """Poisson regression on the reference's poisson_test.avro (4521 real
    rows, count responses 0..187), cross-checked against sklearn's
    PoissonRegressor on the identical design matrix."""

    def test_poisson_training_matches_sklearn(self):
        from sklearn.linear_model import PoissonRegressor

        shards = {"g": FeatureShardConfig(("features",), True)}
        ds, imaps = read_game_dataset(
            os.path.join(DRIVER_IN, "poisson_test.avro"), shards
        )
        data = _labeled(ds, "g")
        rw = 10.0
        cfg = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(OptimizerType.TRON, 50, 1e-9),
            regularization=L2,
        )
        sweep = train_glm_sweep(data, TaskType.POISSON_REGRESSION, cfg, [rw])
        w = np.asarray(sweep.models[rw].coefficients.means, np.float64)

        X = np.asarray(ds.shards["g"].to_dense(), np.float64)
        y = np.asarray(ds.labels, np.float64)
        n = len(y)
        # sklearn minimizes (1/n) sum(exp(z) - y z) + alpha/2 ||w||^2 (no
        # intercept penalty via fit_intercept; use our appended column and
        # fit_intercept=False => alpha = rw / n matches our sum-loss + rw/2.
        clf = PoissonRegressor(alpha=rw / n, fit_intercept=False, max_iter=2000, tol=1e-10)
        clf.fit(X, y)
        wk = clf.coef_

        def obj(w):
            z = X @ w
            return float(np.sum(np.exp(z) - y * z) + rw / 2 * np.dot(w, w))

        # Same optimum to f32 resolution (the exp link amplifies rounding:
        # measured ~3e-4 relative objective gap vs sklearn's f64 solve).
        assert obj(w) == pytest.approx(obj(wk), rel=1e-3)

        from photon_ml_tpu.data.containers import LabeledData as _LD
        from photon_ml_tpu.evaluation import legacy

        m = legacy.evaluate_glm(sweep.models[rw], data)
        assert legacy.DATA_LOG_LIKELIHOOD in m
        assert m[legacy.ROOT_MEAN_SQUARE_ERROR] < np.std(y)  # better than mean-only


class TestBadWeightsRejection:
    """The reference's bad-weights fixtures (heart data with zero/negative
    weights injected; GameTrainingDriverIntegTest bad-weight rejection) must
    fail row validation (DataValidators.sanityCheckDataFrameForTraining)."""

    @pytest.mark.parametrize("fixture", ["zero-weights.avro", "negative-weights.avro"])
    def test_validation_rejects(self, fixture):
        from photon_ml_tpu.data.validators import validate_game_dataset
        from photon_ml_tpu.types import DataValidationType

        ds, _ = read_game_dataset(
            os.path.join(DRIVER_IN, "bad-weights", fixture),
            {"g": FeatureShardConfig(("features",), True)},
        )
        with pytest.raises(ValueError, match="weight"):
            validate_game_dataset(
                ds, TaskType.LOGISTIC_REGRESSION, DataValidationType.VALIDATE_FULL
            )


class TestDifferentColumnNames:
    """The reference's different-column-names fixture (AvroDataReader with a
    customized InputColumnsNames: the_label/w/intercept/metadata)."""

    def test_renamed_columns_read(self):
        from photon_ml_tpu.io.avro_data import InputColumnNames

        cols = InputColumnNames.parse(
            "response=the_label,weight=w,offset=intercept,metadataMap=metadata"
        )
        ds, _ = read_game_dataset(
            os.path.join(DRIVER_IN, "different-column-names", "diff-col-names.avro"),
            {"g": FeatureShardConfig(("features",), True)},
            columns=cols,
        )
        labels = np.asarray(ds.labels)
        assert set(np.unique(labels)) <= {0.0, 1.0}
        assert labels.sum() > 0  # the_label actually populated the response
        np.testing.assert_array_equal(np.asarray(ds.weights), 1.0)
        np.testing.assert_array_equal(np.asarray(ds.offsets), 0.0)
        # Same file with DEFAULT columns: the response column is absent, so
        # every label falls back to 0 — proving the renames were load-bearing.
        ds_default, _ = read_game_dataset(
            os.path.join(DRIVER_IN, "different-column-names", "diff-col-names.avro"),
            {"g": FeatureShardConfig(("features",), True)},
        )
        assert np.asarray(ds_default.labels).sum() == 0.0

    def test_parse_rejects_unknown_keys(self):
        from photon_ml_tpu.io.avro_data import InputColumnNames

        with pytest.raises(ValueError):
            InputColumnNames.parse("nope=x")

    def test_parse_rejects_collisions(self):
        from photon_ml_tpu.io.avro_data import InputColumnNames

        with pytest.raises(ValueError, match="unique"):
            InputColumnNames.parse("response=weight")
        with pytest.raises(ValueError, match="duplicate"):
            InputColumnNames.parse("weight=a,weight=b")
        with pytest.raises(ValueError, match="columns"):
            read_game_dataset(
                os.path.join(DRIVER_IN, "heart.avro"),
                {"g": FeatureShardConfig(("features",), True)},
                response_field="label",
                columns=InputColumnNames(),
            )


class TestRemainingDriverFixtures:
    def test_linear_regression_pair_vs_ridge(self):
        """linear_regression_train/val.avro: TRON linear fit matches sklearn
        Ridge on the identical design matrix."""
        from sklearn.linear_model import Ridge
        from sklearn.metrics import mean_squared_error

        shards = {"g": FeatureShardConfig(("features",), True)}
        tr, imaps = read_game_dataset(
            os.path.join(DRIVER_IN, "linear_regression_train.avro"), shards
        )
        va, _ = read_game_dataset(
            os.path.join(DRIVER_IN, "linear_regression_val.avro"),
            shards,
            index_maps=imaps,
        )
        rw = 1.0
        cfg = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(OptimizerType.TRON, 50, 1e-9),
            regularization=L2,
        )
        sweep = train_glm_sweep(_labeled(tr, "g"), TaskType.LINEAR_REGRESSION, cfg, [rw])
        w = np.asarray(sweep.models[rw].coefficients.means, np.float64)
        Xtr = np.asarray(tr.shards["g"].to_dense(), np.float64)
        Xv = np.asarray(va.shards["g"].to_dense(), np.float64)
        clf = Ridge(alpha=rw, fit_intercept=False)
        clf.fit(Xtr, np.asarray(tr.labels))
        ours = float(np.sqrt(mean_squared_error(np.asarray(va.labels), Xv @ w)))
        sk = float(np.sqrt(mean_squared_error(np.asarray(va.labels), Xv @ clf.coef_)))
        assert ours == pytest.approx(sk, rel=1e-3)

    def test_empty_feature_vectors_read(self):
        """empty.avro (heart rows with EMPTY feature lists): rows reduce to
        the intercept pseudo-feature; training still runs (intercept-only
        fit = base-rate model)."""
        shards = {"g": FeatureShardConfig(("features",), True)}
        ds, imaps = read_game_dataset(os.path.join(DRIVER_IN, "empty.avro"), shards)
        assert ds.num_samples == 250
        assert imaps["g"].size == 1  # intercept only
        dense = np.asarray(ds.shards["g"].to_dense())
        np.testing.assert_array_equal(dense, 1.0)
        cfg = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(OptimizerType.LBFGS, 50, 1e-9),
            regularization=L2,
        )
        sweep = train_glm_sweep(
            _labeled(ds, "g"), TaskType.LOGISTIC_REGRESSION, cfg, [1.0]
        )
        w0 = float(np.asarray(sweep.models[1.0].coefficients.means)[0])
        base_rate = float(np.asarray(ds.labels).mean())
        # Intercept-only logistic optimum ~= logit of the base rate.
        assert 1 / (1 + np.exp(-w0)) == pytest.approx(base_rate, abs=0.02)

    def test_feed_avro_map_columns(self):
        """GameIntegTest avroMap/feed.avro: id tags resolved from MAP-typed
        columns via dotted paths, responses from renamed numeric columns."""
        from photon_ml_tpu.io.avro_data import InputColumnNames

        ds, _ = read_game_dataset(
            os.path.join(GAME, "input", "avroMap", "feed.avro"),
            {"g": FeatureShardConfig(("features",), True)},
            columns=InputColumnNames.parse("response=click"),
            id_tag_fields=("ids.activityId", "updateInfo.actorType", "ids.viewerId"),
        )
        assert ds.num_samples == 2
        # Record 0 carries activityId + actorType; record 1's maps hold
        # different keys (viewerId) -> empty-string tag, not a crash.
        assert ds.id_tags["ids.activityId"][0] == "urn:li:activity:6489565768462716928"
        assert ds.id_tags["ids.activityId"][1] == ""
        assert ds.id_tags["updateInfo.actorType"][0] == "linkedin:company"
        assert ds.id_tags["ids.viewerId"][1] == "355852286"
        assert set(np.asarray(ds.labels)) <= {0.0, 1.0}

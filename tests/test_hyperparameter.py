"""Hyperparameter search tests: GP regression accuracy, slice sampler
distribution sanity, rescaling round-trips, random + Bayesian search on
analytic objectives.

Counterpart of photon-lib src/test/.../hyperparameter (GaussianProcess
EstimatorTest, SliceSamplerTest, VectorRescalingTest, RandomSearchTest,
GaussianProcessSearchTest): known-function recovery and better-than-random
convergence checks.
"""

import numpy as np
import pytest

from photon_ml_tpu.hyperparameter import (
    GaussianProcessSearch,
    HyperparameterConfig,
    HyperparameterTuningMode,
    RandomSearch,
    backward_scale,
    config_from_json,
    fit_gp,
    forward_scale,
    get_tuner,
    priors_from_json,
)
from photon_ml_tpu.hyperparameter.search import Observation
from photon_ml_tpu.hyperparameter.slice_sampler import slice_sample


def test_rescaling_roundtrip():
    configs = [
        HyperparameterConfig("linear", -2.0, 6.0),
        HyperparameterConfig("logscale", 1e-4, 1e2, transform="LOG"),
        HyperparameterConfig("count", 1.0, 10.0, discrete=True),
    ]
    pts = np.array([[0.0, 1e-1, 3.0], [-2.0, 1e-4, 1.0], [6.0, 1e2, 10.0]])
    unit = forward_scale(pts, configs)
    assert unit.min() >= -1e-9 and unit.max() <= 1 + 1e-9
    back = backward_scale(unit, configs)
    np.testing.assert_allclose(back, pts, rtol=1e-10)


def test_backward_scale_discrete_rounds():
    configs = [HyperparameterConfig("k", 1.0, 5.0, discrete=True)]
    vals = backward_scale(np.array([[0.1], [0.6]]), configs)
    assert vals[0, 0] == round(vals[0, 0])


def test_slice_sampler_gaussian():
    logpdf = lambda x: float(-0.5 * np.sum((x - 2.0) ** 2))
    rng = np.random.default_rng(7)
    samples = slice_sample(
        logpdf, np.zeros(1), rng, num_samples=600, burn_in=50
    )
    assert abs(np.mean(samples) - 2.0) < 0.15
    assert abs(np.std(samples) - 1.0) < 0.15


def test_gp_fit_predicts_function():
    rng = np.random.default_rng(3)
    x = rng.uniform(0, 1, size=(25, 1))
    y = np.sin(4.0 * x[:, 0]) + 0.01 * rng.normal(size=25)
    model = fit_gp(x, y, num_samples=5, burn_in=30, seed=1)
    xt = np.linspace(0.05, 0.95, 10)[:, None]
    mean, var = model.predict(xt)
    # Recover in standardized space: undo the standardization.
    pred = mean * model.y_std + model.y_mean
    np.testing.assert_allclose(pred, np.sin(4.0 * xt[:, 0]), atol=0.25)
    assert np.all(var > 0)


def _quadratic_eval(point):
    # Minimum at (0.3, 0.7) with value 1.0.
    return 1.0 + (point[0] - 0.3) ** 2 + (point[1] - 0.7) ** 2


CONFIGS_2D = [
    HyperparameterConfig("a", 0.0, 1.0),
    HyperparameterConfig("b", 0.0, 1.0),
]


def test_random_search_minimizes():
    rs = RandomSearch(CONFIGS_2D, _quadratic_eval, seed=5)
    result = rs.find(32)
    assert result.best_value < 1.1
    assert len(result.observations) == 32


def test_gp_search_beats_or_matches_random():
    gp = GaussianProcessSearch(CONFIGS_2D, _quadratic_eval, seed=11)
    result = gp.find(15)
    assert result.best_value < 1.05
    np.testing.assert_allclose(result.best_point, [0.3, 0.7], atol=0.25)


def test_gp_search_with_priors():
    gp = GaussianProcessSearch(CONFIGS_2D, _quadratic_eval, seed=2)
    priors = [(np.array([0.31, 0.69]), 1.0004)]
    result = gp.find_with_priors(6, priors)
    assert result.best_value < 1.1
    assert len(gp.prior_observations) == 1


def test_maximize_direction():
    eval_fn = lambda p: -_quadratic_eval(p)
    rs = RandomSearch(CONFIGS_2D, eval_fn, maximize=True, seed=5)
    result = rs.find(32)
    assert result.best_value > -1.1


def test_tuner_facade_modes():
    tuner = get_tuner(HyperparameterTuningMode.BAYESIAN)
    assert (
        tuner.search(0, CONFIGS_2D, HyperparameterTuningMode.NONE, _quadratic_eval)
        is None
    )
    res = tuner.search(
        5, CONFIGS_2D, HyperparameterTuningMode.RANDOM, _quadratic_eval, seed=3
    )
    assert len(res.observations) == 5


def test_config_json_parsing():
    doc = {
        "variables": [
            {"name": "alpha", "min": 0.01, "max": 100, "transform": "LOG"},
            {"name": "k", "min": 1, "max": 8, "type": "DISCRETE"},
        ]
    }
    configs = config_from_json(doc)
    assert configs[0].transform == "LOG"
    assert configs[1].discrete

    priors = priors_from_json(
        {"records": [{"alpha": 1.0, "k": 4, "evaluationValue": 0.25}]}, configs
    )
    assert len(priors) == 1
    np.testing.assert_allclose(priors[0][0], [1.0, 4.0])
    assert priors[0][1] == 0.25


def test_batched_random_search_matches_serial_quality():
    rs = RandomSearch(CONFIGS_2D, _quadratic_eval, seed=5)
    result = rs.find_batched(32, batch_size=8)
    assert len(result.observations) == 32
    assert result.best_value < 1.1


def test_batched_gp_proposals_are_diverse():
    """Constant-liar qEI must not propose k copies of the same argmax."""
    gp = GaussianProcessSearch(CONFIGS_2D, _quadratic_eval, seed=7)
    # Seed enough observations for the model to engage.
    for _ in range(4):
        p = gp.propose()
        gp.observations.append(Observation(p, _quadratic_eval(p)))
    batch = gp.propose_batch(4)
    assert batch.shape == (4, 2)
    # All pairwise distinct in the unit cube.
    unit = forward_scale(batch, CONFIGS_2D)
    for i in range(4):
        for j in range(i + 1, 4):
            assert np.linalg.norm(unit[i] - unit[j]) > 1e-6


def test_batched_gp_search_converges():
    gp = GaussianProcessSearch(CONFIGS_2D, _quadratic_eval, seed=11)
    result = gp.find_batched(16, batch_size=4)
    assert len(result.observations) == 16
    assert result.best_value < 1.1


def test_batch_evaluation_function_vmapped():
    """Parallel trial evaluation: all k candidates of a round evaluated in a
    single vectorized call (the pattern a pod-slice driver would use)."""
    import jax
    import jax.numpy as jnp

    calls = []

    def batch_eval(points: np.ndarray):
        calls.append(len(points))
        pts = jnp.asarray(points)
        vals = jax.vmap(lambda p: jnp.sum((p - 1.0) ** 2))(pts)
        return np.asarray(vals).tolist()

    rs = RandomSearch(CONFIGS_2D, _quadratic_eval, seed=13)
    result = rs.find_batched(12, batch_size=4, batch_evaluation_function=batch_eval)
    assert calls == [4, 4, 4]
    assert len(result.observations) == 12


def test_tuner_facade_batched_with_priors():
    tuner = get_tuner(HyperparameterTuningMode.BAYESIAN)
    priors = [(np.asarray([1.0, 1.0]), 0.0)]
    res = tuner.search(
        8,
        CONFIGS_2D,
        HyperparameterTuningMode.BAYESIAN,
        _quadratic_eval,
        priors=priors,
        seed=3,
        batch_size=4,
    )
    assert len(res.observations) == 8
    assert res.best_value < 2.0


def test_batch_evaluation_function_not_dropped_at_batch_size_one():
    """A provided batch evaluator must be used even when batch_size=1."""
    calls = []

    def batch_eval(points):
        calls.append(len(points))
        return [float(np.sum((p - 1.0) ** 2)) for p in points]

    def scalar_stub(p):
        raise AssertionError("scalar path must not run")

    rs = RandomSearch(CONFIGS_2D, scalar_stub, seed=17)
    result = rs.find_batched(3, batch_size=1, batch_evaluation_function=batch_eval)
    assert calls == [1, 1, 1]
    assert len(result.observations) == 3


def test_sobol_draws_never_warn():
    """scipy's Sobol.random warns on every non-power-of-two draw; the
    searchers draw 250-point pools and arbitrary-k batches constantly, so
    they buffer power-of-two blocks and slice (ISSUE 12 satellite)."""
    import warnings

    gp = GaussianProcessSearch(CONFIGS_2D, _quadratic_eval, seed=7)
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        gp.propose()  # cold: Sobol path
        batch = gp.propose_batch(5)  # arbitrary k
        assert batch.shape == (5, 2)
        for _ in range(3):
            p = gp.propose()
            gp.observations.append(Observation(p, _quadratic_eval(p)))
        gp.propose()  # GP path: 250-point candidate pool draw
        gp.propose_batch(3)


def test_sobol_buffer_preserves_sequence_prefix():
    """The served point stream is the SAME Sobol sequence prefix a direct
    power-of-two draw produces — buffering changes warnings, not values."""
    from scipy.stats import qmc

    rs = RandomSearch(CONFIGS_2D, _quadratic_eval, seed=21)
    served = np.vstack([
        rs._sobol_draw(1),
        rs._sobol_draw(5),
        rs._sobol_draw(2),
    ])
    direct = qmc.Sobol(d=2, scramble=True, seed=21).random(8)
    np.testing.assert_array_equal(served, direct)


def test_constant_liar_batch_deterministic():
    """Two searchers with identical seed + observations propose identical
    batches (the sweep executor's round inputs must be reproducible)."""

    def make():
        gp = GaussianProcessSearch(CONFIGS_2D, _quadratic_eval, seed=13)
        rng = np.random.default_rng(3)
        for _ in range(5):
            p = backward_scale(rng.uniform(size=2), CONFIGS_2D)
            gp.observations.append(Observation(p, _quadratic_eval(p)))
        return gp.propose_batch(4)

    np.testing.assert_array_equal(make(), make())


def test_constant_liar_no_duplicates_at_degenerate_ei():
    """With every observation identical, EI is ~0 everywhere — the picked
    pool points must STILL be distinct (taken-mask, not EI diversity)."""
    gp = GaussianProcessSearch(CONFIGS_2D, _quadratic_eval, seed=5)
    rng = np.random.default_rng(11)
    for _ in range(6):
        p = backward_scale(rng.uniform(size=2), CONFIGS_2D)
        gp.observations.append(Observation(p, 1.0))  # constant objective
    batch = gp.propose_batch(5)
    unit = forward_scale(batch, CONFIGS_2D)
    for i in range(5):
        for j in range(i + 1, 5):
            assert np.linalg.norm(unit[i] - unit[j]) > 0, (
                f"picks {i} and {j} identical at degenerate EI"
            )


def test_find_batched_tail_round():
    """n % batch_size != 0: the last round proposes exactly the remainder."""
    calls = []

    def batch_eval(points):
        calls.append(len(points))
        return [float(np.sum((p - 1.0) ** 2)) for p in points]

    rs = RandomSearch(CONFIGS_2D, _quadratic_eval, seed=9)
    result = rs.find_batched(10, 4, batch_eval)
    assert calls == [4, 4, 2]
    assert len(result.observations) == 10


def test_find_batched_length_mismatch_raises():
    rs = RandomSearch(CONFIGS_2D, _quadratic_eval, seed=9)
    with pytest.raises(ValueError, match="returned 1 values for 3"):
        rs.find_batched(3, 3, lambda points: [0.5])


def test_priors_seeded_batched_search():
    """seed_priors + find_batched: priors engage the GP from round one and
    stay separate from evaluated observations."""
    gp = GaussianProcessSearch(CONFIGS_2D, _quadratic_eval, seed=2)
    priors = [
        (np.array([0.31, 0.69]), 1.0004),
        (np.array([0.9, 0.1]), 1.52),
    ]
    gp.seed_priors(priors)
    result = gp.find_batched(8, 4)
    assert len(gp.prior_observations) == 2
    assert len(result.observations) == 8  # priors not double-counted
    assert result.best_value < 1.2


def test_shrink_search_range():
    from photon_ml_tpu.hyperparameter.search import shrink_search_range

    configs = [
        HyperparameterConfig("a", 0.0, 1.0),
        HyperparameterConfig("reg", 1e-3, 1e3, transform="LOG"),
    ]
    rng = np.random.default_rng(0)
    priors = []
    for _ in range(12):
        a = rng.uniform(0, 1)
        r = 10 ** rng.uniform(-3, 3)
        # Optimum near a=0.3, reg=10.
        val = (a - 0.3) ** 2 + (np.log10(r) - 1.0) ** 2
        priors.append((np.array([a, r]), val))
    narrowed = shrink_search_range(configs, priors, radius=0.2, seed=5)
    for orig, new in zip(configs, narrowed):
        assert new.min_value >= orig.min_value
        assert new.max_value <= orig.max_value
        assert new.min_value < new.max_value
    # The narrowed window should contain the optimum region.
    assert narrowed[0].min_value <= 0.45 and narrowed[0].max_value >= 0.15
    assert narrowed[1].min_value <= 100 and narrowed[1].max_value >= 1.0

"""Optimizer tests: convergence on analytic + GLM problems, OWLQN sparsity,
box projection, TRON vs LBFGS agreement, and vmapped batched solves.

Counterpart of the reference's OptimizerIntegTest / IntegTestObjective
(photon-lib src/integTest/.../optimization): analytic objectives with known
optima, plus sklearn as an external oracle for logistic regression.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.containers import dense_data
from photon_ml_tpu.ops import losses, objective
from photon_ml_tpu.optimize.common import ConvergenceReason
from photon_ml_tpu.optimize.lbfgs import minimize_lbfgs
from photon_ml_tpu.optimize.tron import minimize_tron


def _quadratic(center, scale=1.0):
    c = jnp.asarray(center)

    def vg(w):
        diff = w - c
        return 0.5 * scale * jnp.dot(diff, diff), scale * diff

    return vg


def _rosenbrock_vg(w):
    f = lambda x: jnp.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2 + (1.0 - x[:-1]) ** 2)
    return f(w), jax.grad(f)(w)


def _logistic_problem(rng, n=200, d=8, l2=1e-3):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-(X @ w_true)))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    data = dense_data(X, y)
    vg = lambda w: objective.value_and_gradient(losses.LOGISTIC, w, data, None, l2)
    hvp = lambda w, v: objective.hessian_vector(losses.LOGISTIC, w, v, data, None, l2)
    return data, vg, hvp


def test_lbfgs_quadratic():
    center = jnp.arange(5.0, dtype=jnp.float32)
    res = minimize_lbfgs(_quadratic(center), jnp.zeros(5, jnp.float32))
    np.testing.assert_allclose(res.coefficients, center, atol=1e-4)
    assert int(res.reason) in (
        ConvergenceReason.FUNCTION_VALUES_CONVERGED,
        ConvergenceReason.GRADIENT_CONVERGED,
    )
    assert int(res.iterations) < 10


def test_lbfgs_rosenbrock():
    res = minimize_lbfgs(
        _rosenbrock_vg, jnp.zeros(4, jnp.float32), max_iterations=300, tolerance=1e-10
    )
    np.testing.assert_allclose(res.coefficients, jnp.ones(4), atol=2e-2)


def test_lbfgs_logistic_matches_sklearn(rng):
    from sklearn.linear_model import LogisticRegression

    n, d, l2 = 200, 8, 1e-2
    _, vg, _ = _logistic_problem(rng, n, d, l2)
    # Rebuild the same data for sklearn (regenerate with same seed path).
    rng2 = np.random.default_rng(20260729)
    X = rng2.normal(size=(n, d)).astype(np.float32)
    w_true = rng2.normal(size=d).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-(X @ w_true)))
    y = (rng2.uniform(size=n) < p).astype(np.float32)

    res = minimize_lbfgs(vg, jnp.zeros(d, jnp.float32), tolerance=1e-9)
    skl = LogisticRegression(
        C=1.0 / l2, fit_intercept=False, tol=1e-10, max_iter=2000
    ).fit(X, y)
    np.testing.assert_allclose(res.coefficients, skl.coef_[0], rtol=2e-2, atol=2e-3)


def test_owlqn_produces_sparse_solution(rng):
    _, vg, _ = _logistic_problem(rng, n=150, d=20, l2=0.0)
    dense_res = minimize_lbfgs(vg, jnp.zeros(20, jnp.float32))
    sparse_res = minimize_lbfgs(vg, jnp.zeros(20, jnp.float32), l1_weight=8.0)
    n_zero_dense = int(jnp.sum(jnp.abs(dense_res.coefficients) < 1e-8))
    n_zero_sparse = int(jnp.sum(jnp.abs(sparse_res.coefficients) < 1e-8))
    assert n_zero_sparse > n_zero_dense
    assert n_zero_sparse >= 5
    # The OWLQN objective value (smooth + L1) must beat the L1 value of the
    # dense solution.
    l1_of = lambda w: 8.0 * float(jnp.sum(jnp.abs(w)))
    f_sparse = float(vg(sparse_res.coefficients)[0]) + l1_of(sparse_res.coefficients)
    f_dense = float(vg(dense_res.coefficients)[0]) + l1_of(dense_res.coefficients)
    assert f_sparse <= f_dense + 1e-3


def test_owlqn_zero_l1_close_to_lbfgs(rng):
    _, vg, _ = _logistic_problem(rng, n=100, d=6, l2=1e-2)
    a = minimize_lbfgs(vg, jnp.zeros(6, jnp.float32), tolerance=1e-9)
    b = minimize_lbfgs(vg, jnp.zeros(6, jnp.float32), l1_weight=0.0, tolerance=1e-9)
    np.testing.assert_allclose(a.coefficients, b.coefficients, atol=5e-3)


def test_box_constraints():
    center = jnp.asarray([2.0, -3.0, 0.5], jnp.float32)
    res = minimize_lbfgs(
        _quadratic(center),
        jnp.zeros(3, jnp.float32),
        lower_bounds=jnp.asarray([-1.0, -1.0, -1.0], jnp.float32),
        upper_bounds=jnp.asarray([1.0, 1.0, 1.0], jnp.float32),
    )
    np.testing.assert_allclose(res.coefficients, [1.0, -1.0, 0.5], atol=1e-4)


def test_tron_quadratic():
    center = jnp.arange(4.0, dtype=jnp.float32)
    vg = _quadratic(center, scale=2.0)
    hvp = lambda w, v: 2.0 * v
    res = minimize_tron(vg, hvp, jnp.zeros(4, jnp.float32))
    np.testing.assert_allclose(res.coefficients, center, atol=1e-4)
    # Newton on a quadratic: one step.
    assert int(res.iterations) <= 3


def test_tron_matches_lbfgs_on_logistic(rng):
    _, vg, hvp = _logistic_problem(rng, l2=0.1)
    a = minimize_tron(vg, hvp, jnp.zeros(8, jnp.float32), tolerance=1e-9)
    b = minimize_lbfgs(vg, jnp.zeros(8, jnp.float32), tolerance=1e-9)
    np.testing.assert_allclose(a.coefficients, b.coefficients, rtol=5e-3, atol=5e-4)
    assert int(a.iterations) <= 15


def test_vmapped_lbfgs_batched_problems(rng):
    """Many independent problems in one kernel — the random-effect pattern."""
    B, d = 16, 4
    centers = jnp.asarray(rng.normal(size=(B, d)).astype(np.float32))

    def one(w0, center):
        vg = lambda w: (
            0.5 * jnp.dot(w - center, w - center),
            w - center,
        )
        return minimize_lbfgs(vg, w0)

    res = jax.vmap(one)(jnp.zeros((B, d), jnp.float32), centers)
    np.testing.assert_allclose(res.coefficients, centers, atol=1e-3)
    assert res.reason.shape == (B,)
    assert bool(jnp.all(res.reason != ConvergenceReason.NOT_CONVERGED))


def test_vmapped_tron_batched_glms(rng):
    """vmapped TRON over per-entity GLM blocks with padding rows."""
    B, n, d = 8, 30, 3
    X = rng.normal(size=(B, n, d)).astype(np.float32)
    w_true = rng.normal(size=(B, d)).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-np.einsum("bnd,bd->bn", X, w_true)))
    y = (rng.uniform(size=(B, n)) < p).astype(np.float32)
    weights = np.ones((B, n), np.float32)
    weights[:, 25:] = 0.0  # simulate ragged entities via padding

    def solve(Xb, yb, wb):
        data = dense_data(Xb, yb, weights=wb)
        vg = lambda w: objective.value_and_gradient(losses.LOGISTIC, w, data, None, 0.5)
        hvp = lambda w, v: objective.hessian_vector(losses.LOGISTIC, w, v, data, None, 0.5)
        return minimize_tron(vg, hvp, jnp.zeros(d, jnp.float32))

    res = jax.vmap(solve)(jnp.asarray(X), jnp.asarray(y), jnp.asarray(weights))
    assert res.coefficients.shape == (B, d)
    # Each batched solution must match its individually-solved counterpart.
    single = solve(jnp.asarray(X[0]), jnp.asarray(y[0]), jnp.asarray(weights[0]))
    np.testing.assert_allclose(res.coefficients[0], single.coefficients, atol=1e-4)


def test_tracking_records_monotone_losses(rng):
    _, vg, _ = _logistic_problem(rng)
    res = minimize_lbfgs(vg, jnp.zeros(8, jnp.float32), tracking=True)
    hist = np.asarray(res.loss_history)
    valid = hist[~np.isnan(hist)]
    assert len(valid) == int(res.iterations) + 1
    assert np.all(np.diff(valid) <= 1e-5)  # non-increasing losses


def test_coefficient_history_tracking():
    """Opt-in per-iteration coefficient snapshots (the reference
    OptimizationStatesTracker keeps full OptimizerStates)."""
    A = jnp.asarray(np.diag([1.0, 4.0, 9.0]), jnp.float32)
    b = jnp.asarray([1.0, -2.0, 3.0], jnp.float32)

    def vg(w):
        r = A @ w - b
        return 0.5 * jnp.dot(r, A @ w - b), A.T @ r

    res = minimize_lbfgs(vg, jnp.zeros(3, jnp.float32), tracking=True,
                         track_coefficients=True, max_iterations=20)
    hist = np.asarray(res.coefficients_history)
    its = int(res.iterations)
    assert hist.shape == (21, 3)
    np.testing.assert_array_equal(hist[0], 0.0)  # w0 snapshot
    np.testing.assert_allclose(hist[its], np.asarray(res.coefficients), rtol=1e-6)
    assert np.all(np.isnan(hist[its + 1:]))  # untouched rows stay NaN

    res_t = minimize_tron(vg, lambda w, v: A.T @ (A @ v),
                          jnp.zeros(3, jnp.float32), tracking=True,
                          track_coefficients=True, max_iterations=10)
    hist_t = np.asarray(res_t.coefficients_history)
    np.testing.assert_allclose(
        hist_t[int(res_t.iterations)], np.asarray(res_t.coefficients), rtol=1e-6
    )
    # Off by default: no history allocated.
    res_off = minimize_lbfgs(vg, jnp.zeros(3, jnp.float32), tracking=True)
    assert res_off.coefficients_history is None
    # track_coefficients alone implies tracking (no silent None).
    res_imp = minimize_lbfgs(vg, jnp.zeros(3, jnp.float32), track_coefficients=True)
    assert res_imp.coefficients_history is not None
    assert res_imp.loss_history.shape[0] > 0


def test_tron_diagnostic_histories():
    """TRON per-iteration trust radius + CG counts under tracking
    (TRON.scala:217-218's per-iteration log line, as returned arrays)."""
    A = jnp.asarray(np.diag([1.0, 4.0, 9.0]), jnp.float32)
    b = jnp.asarray([1.0, -2.0, 3.0], jnp.float32)

    def vg(w):
        r = A @ w - b
        return 0.5 * jnp.dot(r, r), A.T @ r

    res = minimize_tron(vg, lambda w, v: A.T @ (A @ v),
                        jnp.zeros(3, jnp.float32), tracking=True,
                        max_iterations=10)
    its = int(res.iterations)
    deltas = np.asarray(res.trust_radius_history)
    cgs = np.asarray(res.cg_iterations_history)
    assert deltas.shape == (11,) and cgs.shape == (11,)
    assert np.all(deltas[: its + 1] > 0)  # radius stays positive
    assert np.all(cgs[1 : its + 1] >= 1)  # every accepted step ran CG
    assert np.all(np.isnan(deltas[its + 1:]))
    # Off when not tracking.
    res2 = minimize_tron(vg, lambda w, v: A.T @ (A @ v), jnp.zeros(3, jnp.float32))
    assert res2.trust_radius_history is None


def test_tron_rejected_steps_preserve_diagnostics():
    """A rejected trust-region attempt must not overwrite the accepted
    history slots (iteration does not advance on rejection)."""
    # Highly non-quadratic scalar-ish objective that forces rejections: the
    # Newton model overshoots for exp-sum curvature far from the optimum.
    def vg(w):
        z = jnp.sum(jnp.exp(2.0 * w))
        return z, 2.0 * jnp.exp(2.0 * w)

    def hvp(w, v):
        return 4.0 * jnp.exp(2.0 * w) * v

    w0 = jnp.full((4,), 3.0, jnp.float32)
    res = minimize_tron(vg, hvp, w0, max_iterations=30, tolerance=1e-10,
                        tracking=True)
    its = int(res.iterations)
    deltas = np.asarray(res.trust_radius_history)
    cgs = np.asarray(res.cg_iterations_history)
    # Slot 0 keeps the INITIAL radius (||g0||) and the NaN cg sentinel even
    # if the very first attempt was rejected.
    g0 = float(np.linalg.norm(2.0 * np.exp(2.0 * np.full(4, 3.0))))
    assert deltas[0] == pytest.approx(g0, rel=1e-5)
    assert np.isnan(cgs[0])
    assert np.all(cgs[1 : its + 1] >= 1)

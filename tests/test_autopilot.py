"""Closed-loop autoscaling suite (ISSUE 19).

The load-bearing contracts of the autopilot:

  * HYSTERESIS — a sawtooth signal that crosses the fire band on every
    crest actuates ONCE per band crossing, not once per crest;
  * COOLDOWN and the ACTION BUDGET bound actuation frequency no matter
    how eager the policy set is;
  * one rollback QUARANTINES a rule, and only an operator `reset_rule`
    lifts it — the loop never self-forgives;
  * every actuator path rolls back under an injected `autopilot_act`
    fault with ZERO failed client requests and bitwise-unchanged
    answers;
  * every decision the loop takes validates against its journal schema.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from photon_ml_tpu.autopilot import (
    Action,
    Autopilot,
    ControlRule,
    SensorSnapshot,
    read_sensors,
)
from photon_ml_tpu.game.model import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.serving import ScoreRequest, ServingBundle, TenantRegistry
from photon_ml_tpu.transformers.game_transformer import CoordinateScoringSpec
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import faults, telemetry

pytestmark = pytest.mark.serving

TASK = TaskType.LOGISTIC_REGRESSION
D_FE, D_RE, E = 7, 5, 24


# ------------------------------------------------------- synthetic sensors


def _snap(sig: float = 0.0) -> SensorSnapshot:
    """A synthetic snapshot; `failed_requests` doubles as the scripted
    signal channel the unit rules below read."""
    return SensorSnapshot(
        tenants={},
        hbm_budget=None,
        hbm_used=0,
        latency_p95_ms=None,
        latency_p99_ms=None,
        queue_wait_p95_ms=None,
        batch_p50=None,
        failed_requests=sig,
    )


def _scripted(values):
    """sensor_fn replaying one scripted signal value per tick."""
    it = iter(values)

    def fn(_registry):
        return _snap(next(it))

    return fn


def _unit_rule(
    name="unit-rule",
    *,
    fire_above=10.0,
    rearm_below=2.0,
    cooldown_s=None,
    fail=None,
    none_below=None,
):
    """A custom rule over the scripted signal channel. `fail` is a
    mutable [bool] — apply raises while it holds True. `none_below`
    makes the signal return None (no evidence) under that value."""
    applied = []
    undone = []

    def signal(cur, prev):
        v = float(cur.failed_requests)
        if none_below is not None and v < none_below:
            return None
        return v

    def decide(cur, prev, sig):
        def apply_fn():
            if fail is not None and fail[0]:
                raise RuntimeError("deliberately bad actuation")
            applied.append(sig)

        return Action(
            kind="custom",
            evidence={"sig": sig},
            apply_fn=apply_fn,
            undo_fn=lambda: undone.append(sig),
        )

    rule = ControlRule(
        name=name,
        signal=signal,
        fire_above=fire_above,
        rearm_below=rearm_below,
        decide=decide,
        cooldown_s=cooldown_s,
    )
    return rule, applied, undone


class _FakeTenant:
    def __init__(self):
        self.failed = 0


class _FakeRegistry:
    """Just enough registry for the probe: one tenant, a failed counter
    tests can bump from inside an actuation."""

    def __init__(self):
        self._t = _FakeTenant()

    @property
    def tenant_names(self):
        return ["a"]

    def tenant(self, name):
        return self._t


def _pilot(values, rules, **kw):
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("max_actions", 100)
    return Autopilot(
        _FakeRegistry(),
        rules=rules,
        sensor_fn=_scripted(values),
        start=False,
        **kw,
    )


# ------------------------------------------------------------- real fleet


def _make_model(seed: int, n_entities: int = E):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=D_FE).astype(np.float32)
    M = np.zeros((n_entities + 1, D_RE), np.float32)
    M[:n_entities] = rng.normal(size=(n_entities, D_RE))
    model = GameModel(
        {
            "fixed": FixedEffectModel(Coefficients(jnp.asarray(w)), TASK),
            "per-e": RandomEffectModel(jnp.asarray(M), None, TASK),
        }
    )
    specs = {
        "fixed": CoordinateScoringSpec(shard="g"),
        "per-e": CoordinateScoringSpec(
            shard="re",
            random_effect_type="eid",
            entity_index={str(i): i for i in range(n_entities)},
        ),
    }
    return model, specs


def _bundle(seed: int, n_entities: int = E) -> ServingBundle:
    model, specs = _make_model(seed, n_entities)
    return ServingBundle.from_model(model, specs, TASK)


def _requests(seed: int, n: int, n_entities: int = E):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, D_FE)).astype(np.float32)
    Xe = rng.normal(size=(n, D_RE)).astype(np.float32)
    ids = rng.integers(0, n_entities, size=n)
    return [
        ScoreRequest(
            features={"g": X[i], "re": Xe[i]},
            entity_ids={"eid": str(int(ids[i]))},
            offset=float(i) * 0.125,
            uid=str(i),
        )
        for i in range(n)
    ]


def _scores(reg, name, reqs) -> np.ndarray:
    return np.asarray([reg.score(name, r).score for r in reqs], np.float64)


# ================================================================ hysteresis


class TestHysteresis:
    def test_sawtooth_actuates_once_per_band_crossing(self):
        """A sawtooth oscillating between 5 and 12 (band: fire>=10,
        rearm<=2) actuates on the FIRST crest only; crests while the
        trough never reaches the re-arm watermark are held. Dropping to
        1 re-arms, and the next crest fires again."""
        rule, applied, _ = _unit_rule()
        pilot = _pilot([12, 5, 12, 5, 12, 1, 12], [rule])
        for _ in range(7):
            pilot.tick()
        assert applied == [12.0, 12.0]
        s = pilot.summary()
        assert s["actions"] == 2
        assert s["rollbacks"] == 0
        assert s["ticks"] == 7
        # Holds are silent: only the two actuations were decisions.
        assert s["decisions"] == 2

    def test_none_signal_neither_fires_nor_rearms(self):
        """None = no evidence: it must not fire, and it must not re-arm
        a disarmed rule (absence of data is not a calm signal)."""
        rule, applied, _ = _unit_rule(none_below=2.0)
        # 0 -> None (below none_below): would re-arm if treated as low.
        pilot = _pilot([12, 0, 12, 3, 12], [rule])
        for _ in range(5):
            pilot.tick()
        # Fired once; the None tick did NOT re-arm (3 > rearm_below so
        # the later ticks never re-arm either).
        assert applied == [12.0]

    def test_inverted_band_is_rejected(self):
        with pytest.raises(ValueError, match="rearm_below"):
            ControlRule(
                name="inverted",
                signal=lambda cur, prev: 0.0,
                fire_above=1.0,
                rearm_below=5.0,
                decide=lambda cur, prev, sig: None,
            )

    def test_duplicate_rule_names_rejected(self):
        r1, _, _ = _unit_rule("dup")
        r2, _, _ = _unit_rule("dup")
        with pytest.raises(ValueError, match="duplicate"):
            _pilot([], [r1, r2])


# ========================================================= cooldown / budget


class TestCooldownAndBudget:
    def test_cooldown_suppresses_refire(self):
        """A re-armed rule inside its cooldown is SUPPRESSED (journaled,
        counted) rather than actuated."""
        rule, applied, _ = _unit_rule(cooldown_s=3600.0)
        pilot = _pilot([12, 1, 12], [rule], cooldown_s=3600.0)
        for _ in range(3):
            pilot.tick()
        assert applied == [12.0]
        s = pilot.summary()
        assert s["actions"] == 1
        assert s["suppressed"] == 1
        assert s["last_outcome"] == "suppressed_cooldown"
        assert telemetry.METRICS.get_counter("autopilot_suppressed") == 1

    def test_action_budget_bounds_the_whole_policy_set(self):
        """With a budget of 1 action per window, the second eager rule
        of the SAME tick is suppressed — a misbehaving policy set
        degrades to slow, never to thrashing."""
        r1, a1, _ = _unit_rule("eager-1", cooldown_s=0.0)
        r2, a2, _ = _unit_rule("eager-2", cooldown_s=0.0)
        pilot = _pilot([12], [r1, r2], cooldown_s=0.0, max_actions=1)
        pilot.tick()
        assert a1 == [12.0]
        assert a2 == []
        s = pilot.summary()
        assert s["actions"] == 1
        assert s["suppressed"] == 1
        assert s["last_outcome"] == "suppressed_budget"

    def test_knob_deferral_and_validation(self, monkeypatch):
        monkeypatch.setenv("PHOTON_AUTOPILOT_MS", "123")
        monkeypatch.setenv("PHOTON_AUTOPILOT_COOLDOWN_S", "7.5")
        monkeypatch.setenv("PHOTON_AUTOPILOT_MAX_ACTIONS", "9")
        pilot = Autopilot(_FakeRegistry(), rules=[], start=False)
        assert pilot.tick_ms == 123
        assert pilot.cooldown_s == 7.5
        assert pilot.max_actions == 9
        with pytest.raises(ValueError):
            Autopilot(_FakeRegistry(), rules=[], tick_ms=0, start=False)
        with pytest.raises(ValueError):
            Autopilot(
                _FakeRegistry(), rules=[], max_actions=0, start=False
            )


# ================================================================ quarantine


class TestQuarantine:
    def test_rollback_quarantines_until_operator_reset(self):
        """One failed actuation quarantines the rule; the quarantined
        rule stays OFF (suppressed, journaled) however loud its signal,
        until reset_rule — after which it may actuate again."""
        fail = [True]
        rule, applied, _ = _unit_rule(fail=fail)
        pilot = _pilot([12, 1, 12, 1, 12], [rule])
        pilot.tick()  # fires -> apply raises -> rollback + quarantine
        assert applied == []
        assert rule.quarantined
        assert rule.rollbacks == 1
        counters = faults.counters()
        assert counters.get("autopilot_rollbacks") == 1
        assert counters.get("autopilot_quarantines") == 1
        pilot.tick()  # 1: re-arms (quarantine does not block re-arming)
        pilot.tick()  # 12: armed but quarantined -> suppressed
        assert applied == []
        assert pilot.summary()["last_outcome"] == "suppressed_quarantined"
        assert pilot.summary()["quarantined"] == [rule.name]
        # Operator reset is the only way out.
        fail[0] = False
        pilot.reset_rule(rule.name)
        pilot.tick()  # 1: calm
        pilot.tick()  # 12: fires and applies this time
        assert applied == [12.0]
        assert not rule.quarantined

    def test_reset_unknown_rule_raises(self):
        pilot = _pilot([], [])
        with pytest.raises(KeyError):
            pilot.reset_rule("no-such-rule")

    def test_probe_regression_rolls_back_with_undo(self):
        """An actuation that makes a client request FAIL between the
        pre and post probes is undone (the undo closure runs) and the
        rule is quarantined."""
        reg = _FakeRegistry()
        applied = []
        undone = []

        def decide(cur, prev, sig):
            def apply_fn():
                applied.append(sig)
                reg.tenant("a").failed += 1  # the regression

            return Action(
                kind="custom",
                apply_fn=apply_fn,
                undo_fn=lambda: undone.append(sig),
            )

        rule = ControlRule(
            name="regressing",
            signal=lambda cur, prev: float(cur.failed_requests),
            fire_above=10.0,
            rearm_below=2.0,
            decide=decide,
        )
        pilot = Autopilot(
            reg,
            rules=[rule],
            sensor_fn=_scripted([12]),
            cooldown_s=0.0,
            max_actions=100,
            start=False,
        )
        pilot.tick()
        assert applied == [12.0]
        assert undone == [12.0]
        assert rule.quarantined
        s = pilot.summary()
        assert s["rollbacks"] == 1
        assert s["actions"] == 0
        assert s["last_outcome"] == "rolled_back"


# ====================================================== fault-injected paths


class TestActuatorRollbackUnderInjection:
    def test_every_actuator_path_rolls_back_with_zero_failed(self):
        """All five built-in actuator kinds, each armed by its own rule,
        hit an injected `autopilot_act` fault: every one rolls back, its
        rule is quarantined, and the fleet's answers stay bitwise with
        ZERO failed client requests."""
        reqs_a, reqs_b = _requests(31, 6), _requests(32, 6)
        kinds = (
            ("reshard", "a", {}),
            ("rebalance", "b", {"cid": "per-e"}),
            ("demote", "a", {}),
            ("restore", "b", {}),
            ("retune", None, {"serving_max_wait_ms": 1.0}),
        )
        rules = [
            ControlRule(
                name=f"inj-{kind}",
                signal=lambda cur, prev: 12.0,
                fire_above=10.0,
                rearm_below=2.0,
                decide=(
                    lambda cur, prev, sig, k=kind, t=tenant, p=params: Action(
                        kind=k, tenant=t, params=dict(p)
                    )
                ),
                cooldown_s=0.0,
            )
            for kind, tenant, params in kinds
        ]
        with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
            reg.admit("a", _bundle(1))
            reg.admit("b", _bundle(2))
            reg.demote("b", hot_rows=4)  # makes "restore" a live path
            ref_a = _scores(reg, "a", reqs_a)
            ref_b = _scores(reg, "b", reqs_b)
            pilot = Autopilot(
                reg,
                rules=rules,
                probe_requests={"a": reqs_a[0], "b": reqs_b[0]},
                cooldown_s=0.0,
                max_actions=100,
                start=False,
            )
            with faults.inject("autopilot_act:5"):
                pilot.tick()
            s = pilot.summary()
            assert s["rollbacks"] == 5
            assert s["actions"] == 0
            assert sorted(s["quarantined"]) == sorted(
                r.name for r in rules
            )
            counters = faults.counters()
            assert counters.get("autopilot_rollbacks") == 5
            assert counters.get("autopilot_quarantines") == 5
            # The contract: injection at the actuation site never
            # reaches a client. Answers bitwise, zero failed.
            assert np.array_equal(_scores(reg, "a", reqs_a), ref_a)
            assert np.array_equal(_scores(reg, "b", reqs_b), ref_b)
            m = reg.metrics()
            assert m["tenants"]["a"]["failed"] == 0
            assert m["tenants"]["b"]["failed"] == 0
            reg.close(release_bundles=True)


# ============================================================ real actuators


class TestRealActuators:
    def test_demote_restore_ladder_is_bitwise(self):
        """The new `restore` actuator is the exact inverse of demote:
        the tenant comes back single-tier and answers BITWISE what it
        answered before demotion."""
        reqs = _requests(41, 8)
        with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
            reg.admit("a", _bundle(1))
            ref = _scores(reg, "a", reqs)
            assert reg.demote("a", hot_rows=4) > 0
            assert reg.tenant("a").demoted
            assert np.array_equal(_scores(reg, "a", reqs), ref)
            assert reg.restore("a") > 0
            t = reg.tenant("a")
            assert not t.demoted
            # Single-tier again: no two-tier store on the RE coordinate.
            assert all(
                c.store is None
                for c in t.engine._state.bundle.coordinates.values()
            )
            assert np.array_equal(_scores(reg, "a", reqs), ref)
            assert faults.counters().get("tenant_restores") == 1
            # Restoring a tenant that is not demoted is a free no-op.
            assert reg.restore("a") == 0
            reg.close(release_bundles=True)

    def test_retune_updates_live_wait_and_round_trips(self):
        with TenantRegistry(max_batch=16, max_wait_ms=4.0) as reg:
            prev = reg.retune(max_wait_ms=1.0)
            assert prev == {"max_wait_ms": 4.0}
            assert reg.max_wait_s == pytest.approx(1e-3)
            reg.retune(max_wait_ms=prev["max_wait_ms"])
            assert reg.max_wait_s == pytest.approx(4e-3)
            with pytest.raises(ValueError):
                reg.retune(max_wait_ms=-1.0)

    def test_apply_online_decision_round_trips_fallback(self):
        from photon_ml_tpu import planner

        d1 = planner.apply_online_decision("serving_max_wait_ms", 1.0)
        assert d1 is not None
        assert d1.source == "autopilot"
        assert float(planner.planned_value("serving_max_wait_ms")) == 1.0
        d2 = planner.apply_online_decision("serving_max_wait_ms", 0.5)
        assert d2.fallback == 1.0  # rollback target = displaced value
        planner.apply_online_decision("serving_max_wait_ms", d2.fallback)
        assert float(planner.planned_value("serving_max_wait_ms")) == 1.0

    def test_read_sensors_over_live_fleet(self):
        """The sensor surface over a real registry: per-tenant labeled
        p95s, shard loads, HBM accounting, demotion flags."""
        reqs = _requests(51, 8)
        with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
            reg.admit("a", _bundle(1))
            reg.admit("b", _bundle(2))
            _scores(reg, "a", reqs)
            snap = read_sensors(reg)
            assert set(snap.tenants) == {"a", "b"}
            ta = snap.tenants["a"]
            assert ta.completed == len(reqs)
            assert ta.p95_ms is not None  # labeled histogram populated
            assert snap.tenants["b"].p95_ms is None  # no traffic yet
            assert ta.coords and ta.coords[0].total_load > 0
            assert snap.hbm_used > 0
            assert snap.failed_requests == 0
            reg.close(release_bundles=True)


# ================================================================== journal


class TestJournal:
    def test_every_decision_validates_against_its_schema(self, tmp_path):
        """Drive applied, suppressed, and rolled-back outcomes with an
        ambient journal: every line must validate, and the three
        autopilot event types must all appear."""
        path = str(tmp_path / "journal.jsonl")
        journal = telemetry.install_journal(telemetry.RunJournal(path))
        try:
            good, _, _ = _unit_rule("good", cooldown_s=3600.0)
            fail = [True]
            bad, _, _ = _unit_rule("bad", fail=fail, cooldown_s=0.0)
            pilot = _pilot(
                [12, 1, 12], [good, bad], cooldown_s=3600.0
            )
            for _ in range(3):
                pilot.tick()
        finally:
            telemetry.uninstall_journal()
            journal.close()
        n_ok, errors = telemetry.validate_journal(path)
        assert errors == []
        import json

        types = [
            json.loads(line)["type"]
            for line in open(path)
            if line.strip()
        ]
        assert "autopilot_decision" in types
        assert "autopilot_rollback" in types
        assert "rule_quarantined" in types
        outcomes = {
            json.loads(line).get("outcome")
            for line in open(path)
            if line.strip()
        }
        assert {"applied", "rolled_back", "suppressed_cooldown"} <= outcomes

    def test_worker_thread_lifecycle(self):
        """start=True spawns the photon-autopilot worker; close joins
        it (the conftest leak guard enforces this fleet-wide)."""
        import threading

        pilot = Autopilot(
            _FakeRegistry(),
            rules=[],
            tick_ms=10,
            sensor_fn=lambda reg: _snap(0),
            start=True,
        )
        try:
            assert any(
                t.name == "photon-autopilot" for t in threading.enumerate()
            )
        finally:
            pilot.close()
        assert not any(
            t.name == "photon-autopilot" and t.is_alive()
            for t in threading.enumerate()
        )
        assert pilot.summary()["status"] == "stopped"
        pilot.close()  # idempotent

"""GAME layer tests: entity blocking, coordinates, coordinate descent.

Counterpart of the reference's GameEstimator/CoordinateDescent integ tests —
synthetic mixed-effects data with known structure, property assertions
(loss decreases, mixed model beats fixed-only) rather than exact values.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from photon_ml_tpu.data.game_dataset import (
    FixedEffectDataConfig,
    GameDataset,
    RandomEffectDataConfig,
    build_random_effect_dataset,
    gather_block_data,
)
from photon_ml_tpu.evaluation.suite import EvaluationSuite, EvaluatorType
from photon_ml_tpu.game.coordinate import FixedEffectCoordinate, RandomEffectCoordinate
from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent
from photon_ml_tpu.game.model import GameModel
from photon_ml_tpu.ops import losses, objective
from photon_ml_tpu.optimize.config import (
    L2,
    CoordinateOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.types import OptimizerType, TaskType, VarianceComputationType


def _mixed_effects_data(rng, n_entities=12, rows_per_entity=(5, 40), d_fixed=6, d_re=3):
    """Synthetic GLMix logistic data: y ~ sigmoid(x_f.w + x_e.u_e)."""
    rows = rng.integers(*rows_per_entity, size=n_entities)
    n = int(rows.sum())
    entity = np.repeat(np.arange(n_entities), rows)
    rng.shuffle(entity)
    Xf = rng.normal(size=(n, d_fixed)).astype(np.float32)
    Xf[:, -1] = 1.0
    Xe = rng.normal(size=(n, d_re)).astype(np.float32)
    w_fixed = rng.normal(size=d_fixed).astype(np.float32)
    u = rng.normal(size=(n_entities, d_re)).astype(np.float32) * 1.5
    margin = Xf @ w_fixed + np.einsum("nd,nd->n", Xe, u[entity])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(np.float32)
    ds = GameDataset.build(
        {"global": jnp.asarray(Xf), "per_entity": jnp.asarray(Xe)},
        y,
        id_tags={"entityId": entity},
    )
    return ds, entity


def _config(optimizer=OptimizerType.LBFGS, reg_weight=0.1, variance=VarianceComputationType.NONE):
    return CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(optimizer_type=optimizer, max_iterations=60, tolerance=1e-7),
        regularization=L2,
        reg_weight=reg_weight,
        variance_computation=variance,
    )


def test_random_effect_dataset_blocking(rng):
    ds, entity = _mixed_effects_data(rng)
    red = build_random_effect_dataset(
        ds, RandomEffectDataConfig("entityId", "per_entity", min_bucket=8)
    )
    assert red.num_entities == len(np.unique(entity))
    # Every sample's entity row agrees with the host entity array.
    for ent, row in red.entity_index.items():
        mask = entity == ent
        np.testing.assert_array_equal(
            np.asarray(red.sample_entity_rows)[mask], row
        )
    # Bucket gathers cover each active entity's rows exactly once.
    total = sum(int(b.mask.sum()) for b in red.buckets)
    assert total == red.num_active_samples == ds.num_samples
    # Capacities are powers of two >= min_bucket.
    for b in red.buckets:
        assert b.capacity >= 8 and (b.capacity & (b.capacity - 1)) == 0


def test_random_effect_caps_and_lower_bound(rng):
    ds, entity = _mixed_effects_data(rng, n_entities=10, rows_per_entity=(3, 30))
    red = build_random_effect_dataset(
        ds,
        RandomEffectDataConfig(
            "entityId", "per_entity", active_upper_bound=10, active_lower_bound=5
        ),
    )
    counts = np.bincount(entity)
    # Entities under the lower bound contribute no active rows.
    expected_active = sum(min(c, 10) for c in counts if c >= 5)
    assert red.num_active_samples == expected_active
    assert red.num_passive_samples == ds.num_samples - expected_active
    for b in red.buckets:
        assert b.capacity <= 16  # cap 10 -> padded 16 max
    # Determinism: same build twice -> identical gathers.
    red2 = build_random_effect_dataset(
        ds,
        RandomEffectDataConfig(
            "entityId", "per_entity", active_upper_bound=10, active_lower_bound=5
        ),
    )
    for b1, b2 in zip(red.buckets, red2.buckets):
        np.testing.assert_array_equal(b1.gather, b2.gather)


def test_fixed_effect_coordinate_matches_direct_solve(rng):
    ds, _ = _mixed_effects_data(rng)
    cfg = _config()
    coord = FixedEffectCoordinate(ds, "global", cfg, TaskType.LOGISTIC_REGRESSION)
    model, res = coord.train(ds.offsets)
    # Direct solve on the same data must agree.
    from photon_ml_tpu.optimize import problem

    direct = problem.solve(
        losses.LOGISTIC,
        ds.labeled_data("global"),
        cfg,
        jnp.zeros(6, jnp.float32),
    )
    np.testing.assert_allclose(
        model.coefficients.means, direct.coefficients, rtol=1e-5, atol=1e-6
    )
    # Scoring = margins without offsets.
    np.testing.assert_allclose(
        coord.score(model),
        objective.compute_margins(
            direct.coefficients, ds.labeled_data("global", jnp.zeros(ds.num_samples))
        ),
        rtol=1e-5,
        atol=1e-5,
    )


def test_random_effect_coordinate_trains_entities(rng):
    ds, entity = _mixed_effects_data(rng)
    red = build_random_effect_dataset(
        ds, RandomEffectDataConfig("entityId", "per_entity")
    )
    coord = RandomEffectCoordinate(ds, red, _config(reg_weight=1.0), TaskType.LOGISTIC_REGRESSION)
    model, stats = coord.train(ds.offsets)
    assert model.coefficients_matrix.shape == (red.num_entities + 1, 3)
    # The pinned unseen row stays zero.
    np.testing.assert_array_equal(model.coefficients_matrix[-1], 0.0)
    # Per-entity solution matches an isolated solve for one entity.
    from photon_ml_tpu.data.containers import dense_data
    from photon_ml_tpu.optimize import problem

    ent0 = list(red.entity_index)[0]
    row0 = red.entity_index[ent0]
    mask = entity == ent0
    Xe = np.asarray(ds.shards["per_entity"])[mask]
    y = np.asarray(ds.labels)[mask]
    direct = problem.solve(
        losses.LOGISTIC,
        dense_data(Xe, y),
        _config(reg_weight=1.0),
        jnp.zeros(3, jnp.float32),
    )
    np.testing.assert_allclose(
        model.coefficients_matrix[row0], direct.coefficients, rtol=1e-3, atol=1e-4
    )
    # Scores: per-sample entity-row dot product.
    s = coord.score(model)
    expected = np.einsum(
        "nd,nd->n", np.asarray(ds.shards["per_entity"]), np.asarray(model.coefficients_matrix)[entity]
    )
    np.testing.assert_allclose(s, expected, rtol=1e-4, atol=1e-4)


def test_coordinate_descent_mixed_beats_fixed_only(rng):
    ds, _ = _mixed_effects_data(rng, n_entities=20, rows_per_entity=(10, 50))
    red = build_random_effect_dataset(ds, RandomEffectDataConfig("entityId", "per_entity"))
    fixed = FixedEffectCoordinate(ds, "global", _config(), TaskType.LOGISTIC_REGRESSION)
    rand = RandomEffectCoordinate(ds, red, _config(reg_weight=1.0), TaskType.LOGISTIC_REGRESSION)

    result = run_coordinate_descent({"fixed": fixed, "per-entity": rand}, 3)
    model = result.model
    total_scores = fixed.score(model["fixed"]) + rand.score(model["per-entity"])

    fixed_only = run_coordinate_descent({"fixed": fixed}, 1).model
    fixed_scores = fixed.score(fixed_only["fixed"])

    from photon_ml_tpu.evaluation import metrics

    auc_mixed = float(metrics.area_under_roc_curve(total_scores, ds.labels))
    auc_fixed = float(metrics.area_under_roc_curve(fixed_scores, ds.labels))
    assert auc_mixed > auc_fixed + 0.02, (auc_mixed, auc_fixed)

    # Residual bookkeeping: training loss decreases across CD iterations is
    # implied by AUC; also check scores consistency with a fresh rescore.
    np.testing.assert_allclose(
        rand.score(model["per-entity"]),
        rand.score(model["per-entity"]),
        rtol=1e-6,
    )


def test_coordinate_descent_locked_coordinate(rng):
    ds, _ = _mixed_effects_data(rng)
    red = build_random_effect_dataset(ds, RandomEffectDataConfig("entityId", "per_entity"))
    fixed = FixedEffectCoordinate(ds, "global", _config(), TaskType.LOGISTIC_REGRESSION)
    rand = RandomEffectCoordinate(ds, red, _config(reg_weight=1.0), TaskType.LOGISTIC_REGRESSION)

    pre = run_coordinate_descent({"fixed": fixed}, 1).model
    result = run_coordinate_descent(
        {"fixed": fixed, "re": rand},
        2,
        initial_models=pre,
        locked_coordinates={"fixed"},
    )
    # Locked model is the exact same object/values.
    np.testing.assert_array_equal(
        result.model["fixed"].coefficients.means, pre["fixed"].coefficients.means
    )
    assert "re" in result.model.models

    # Missing initial model for a locked coordinate must raise.
    with pytest.raises(ValueError):
        run_coordinate_descent(
            {"fixed": fixed, "re": rand}, 1, locked_coordinates={"fixed"}
        )


def test_coordinate_descent_validation_tracking(rng):
    ds, entity = _mixed_effects_data(rng, n_entities=15)
    red = build_random_effect_dataset(ds, RandomEffectDataConfig("entityId", "per_entity"))
    fixed = FixedEffectCoordinate(ds, "global", _config(), TaskType.LOGISTIC_REGRESSION)
    rand = RandomEffectCoordinate(ds, red, _config(reg_weight=1.0), TaskType.LOGISTIC_REGRESSION)

    # Validation on the training set itself (smoke): scorer reuses coordinates.
    suite = EvaluationSuite([EvaluatorType("AUC")], ds.labels)

    def scorer(cid, model):
        return {"fixed": fixed, "re": rand}[cid].score(model)

    result = run_coordinate_descent(
        {"fixed": fixed, "re": rand},
        2,
        validation_scorer=scorer,
        validation_suite=suite,
    )
    assert len(result.validation_history) == 4  # 2 iters x 2 coordinates
    aucs = [r.primary_value for _, _, r in result.validation_history]
    assert max(aucs) == pytest.approx(
        result.validation_history[-1][2].results["AUC"], abs=0.05
    )
    assert result.best_model is not None


def test_variance_computation(rng):
    ds, _ = _mixed_effects_data(rng)
    cfg = _config(variance=VarianceComputationType.SIMPLE)
    coord = FixedEffectCoordinate(ds, "global", cfg, TaskType.LOGISTIC_REGRESSION)
    model, _ = coord.train(ds.offsets)
    v = model.coefficients.variances
    assert v is not None and v.shape == (6,)
    # SIMPLE = 1/diag(H) against a direct Hessian diagonal.
    diag = objective.hessian_diagonal(
        losses.LOGISTIC, model.coefficients.means, ds.labeled_data("global"), None, 0.1
    )
    np.testing.assert_allclose(v, 1.0 / np.asarray(diag), rtol=1e-4)

    cfg_full = _config(variance=VarianceComputationType.FULL)
    coord_f = FixedEffectCoordinate(ds, "global", cfg_full, TaskType.LOGISTIC_REGRESSION)
    model_f, _ = coord_f.train(ds.offsets)
    H = objective.hessian_matrix(
        losses.LOGISTIC, model_f.coefficients.means, ds.labeled_data("global"), None, 0.1
    )
    np.testing.assert_allclose(
        model_f.coefficients.variances,
        np.diagonal(np.linalg.inv(np.asarray(H))),
        rtol=1e-3,
    )


def test_down_sampling_smoke(rng):
    ds, _ = _mixed_effects_data(rng)
    import dataclasses as dc

    cfg = dc.replace(_config(), down_sampling_rate=0.5)
    coord = FixedEffectCoordinate(ds, "global", cfg, TaskType.LOGISTIC_REGRESSION)
    import jax

    m1, _ = coord.train(ds.offsets, key=jax.random.PRNGKey(1))
    m2, _ = coord.train(ds.offsets, key=jax.random.PRNGKey(1))
    m3, _ = coord.train(ds.offsets, key=jax.random.PRNGKey(2))
    np.testing.assert_array_equal(m1.coefficients.means, m2.coefficients.means)
    assert not np.allclose(m1.coefficients.means, m3.coefficients.means)


def test_tron_random_effect(rng):
    ds, _ = _mixed_effects_data(rng)
    red = build_random_effect_dataset(ds, RandomEffectDataConfig("entityId", "per_entity"))
    coord = RandomEffectCoordinate(
        ds, red, _config(optimizer=OptimizerType.TRON, reg_weight=1.0), TaskType.LOGISTIC_REGRESSION
    )
    model, _ = coord.train(ds.offsets)
    coord_l = RandomEffectCoordinate(ds, red, _config(reg_weight=1.0), TaskType.LOGISTIC_REGRESSION)
    model_l, _ = coord_l.train(ds.offsets)
    np.testing.assert_allclose(
        model.coefficients_matrix, model_l.coefficients_matrix, rtol=5e-2, atol=5e-3
    )


class TestPearsonFeatureSelection:
    def _dataset(self, rng, n=240, d=10, entities=4):
        import numpy as np
        import jax.numpy as jnp
        from photon_ml_tpu.data.game_dataset import GameDataset

        X = rng.normal(size=(n, d)).astype(np.float32)
        X[:, d - 1] = 1.0  # intercept pseudo-feature
        ent = rng.integers(0, entities, size=n)
        # Label driven by features 0 and 1 only.
        y = (X[:, 0] + 2 * X[:, 1] + 0.1 * rng.normal(size=n) > 0).astype(np.float32)
        ds = GameDataset.build({"e": jnp.asarray(X)}, y, id_tags={"m": ent})
        return ds

    def test_masks_keep_correlated_and_intercept(self, rng):
        import numpy as np
        from photon_ml_tpu.data.game_dataset import (
            RandomEffectDataConfig,
            build_random_effect_dataset,
        )

        ds = self._dataset(rng)
        red = build_random_effect_dataset(
            ds,
            RandomEffectDataConfig(
                "m", "e", num_features_to_samples_ratio_upper_bound=0.08
            ),
        )
        mask = np.asarray(red.feature_mask)
        assert mask.shape == (red.num_entities + 1, 10)
        # Unseen-entity row keeps everything.
        np.testing.assert_array_equal(mask[-1], 1.0)
        for e in range(red.num_entities):
            # ceil(0.08 * ~60 rows) = 5 of 10 features kept.
            assert 0 < mask[e].sum() < 10
            # The informative features and the intercept survive selection.
            assert mask[e, 0] == 1.0 and mask[e, 1] == 1.0
            assert mask[e, 9] == 1.0  # constant-one intercept column

    def test_deselected_features_train_to_zero(self, rng):
        import numpy as np
        import jax.numpy as jnp
        from photon_ml_tpu.data.game_dataset import (
            RandomEffectDataConfig,
            build_random_effect_dataset,
        )
        from photon_ml_tpu.game.coordinate import RandomEffectCoordinate
        from photon_ml_tpu.optimize.config import (
            L2,
            CoordinateOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.types import TaskType

        ds = self._dataset(rng)
        red = build_random_effect_dataset(
            ds,
            RandomEffectDataConfig(
                "m", "e", num_features_to_samples_ratio_upper_bound=0.08
            ),
        )
        cfg = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(max_iterations=30),
            regularization=L2,
            reg_weight=0.1,
        )
        rc = RandomEffectCoordinate(ds, red, cfg, TaskType.LOGISTIC_REGRESSION)
        model, _ = rc.train(jnp.zeros(ds.num_samples))
        coeffs = np.asarray(model.coefficients_matrix)
        mask = np.asarray(red.feature_mask)
        # Coefficients of deselected features stay exactly zero.
        np.testing.assert_array_equal(coeffs[:-1] * (1.0 - mask[:-1]), 0.0)
        # And the kept informative features are actually used.
        assert np.abs(coeffs[:-1, :2]).max() > 0.1


    def test_sparse_masks_match_dense(self, rng):
        """The ELL-moment Pearson path (no densification) must select the
        same features as the dense path on identical data."""
        import numpy as np
        import jax.numpy as jnp
        from photon_ml_tpu.data.containers import SparseFeatures
        from photon_ml_tpu.data.game_dataset import (
            GameDataset,
            RandomEffectDataConfig,
            build_random_effect_dataset,
        )

        n, d, entities = 240, 12, 4
        X = rng.normal(size=(n, d)).astype(np.float32)
        X[rng.uniform(size=(n, d)) < 0.6] = 0.0  # sparsify
        X[:, d - 1] = 1.0  # intercept pseudo-feature
        ent = rng.integers(0, entities, size=n)
        y = (X[:, 0] + 2 * X[:, 1] + 0.1 * rng.normal(size=n) > 0).astype(np.float32)

        # ELL encoding of the same matrix (k = max nnz per row).
        k = int((X != 0).sum(axis=1).max())
        idx = np.zeros((n, k), np.int32)
        val = np.zeros((n, k), np.float32)
        for r in range(n):
            nz = np.flatnonzero(X[r])
            idx[r, : len(nz)] = nz
            val[r, : len(nz)] = X[r, nz]
        sf = SparseFeatures(jnp.asarray(idx), jnp.asarray(val), d)

        cfg = RandomEffectDataConfig(
            "m", "e", num_features_to_samples_ratio_upper_bound=0.1
        )
        dense_red = build_random_effect_dataset(
            GameDataset.build({"e": jnp.asarray(X)}, y, id_tags={"m": ent}), cfg
        )
        sparse_red = build_random_effect_dataset(
            GameDataset.build({"e": sf}, y, id_tags={"m": ent}), cfg
        )
        np.testing.assert_array_equal(
            np.asarray(dense_red.feature_mask), np.asarray(sparse_red.feature_mask)
        )


    def test_sparse_pearson_stable_under_large_offsets(self, rng):
        """Large-magnitude, small-spread columns (1e4 +/- 1, the largest
        offset float32 storage can carry without quantizing the signal away)
        must keep their correlation signal — the reason the reference ships
        stableComputePearsonCorrelationScore (raw-moment formulas cancel)."""
        import numpy as np
        import jax.numpy as jnp
        from photon_ml_tpu.data.containers import SparseFeatures
        from photon_ml_tpu.data.game_dataset import (
            GameDataset,
            RandomEffectDataConfig,
            build_random_effect_dataset,
        )

        n, d = 60, 4
        y = rng.normal(size=n).astype(np.float32)
        X = np.zeros((n, d), np.float32)
        X[:, 0] = 1e4 + y  # informative but offset-dominated
        X[:, 1] = rng.normal(size=n)  # uninformative
        X[:, 3] = 1.0  # intercept
        idx = np.broadcast_to(np.arange(d, dtype=np.int32), (n, d)).copy()
        sf = SparseFeatures(jnp.asarray(idx), jnp.asarray(X), d)
        ds = GameDataset.build(
            {"e": sf}, (y > 0).astype(np.float32), id_tags={"m": np.zeros(n, np.int64)}
        )
        red = build_random_effect_dataset(
            ds,
            RandomEffectDataConfig(
                "m", "e", num_features_to_samples_ratio_upper_bound=2 / n
            ),
        )
        mask = np.asarray(red.feature_mask)[0]
        assert mask[0] == 1.0  # offset-dominated informative column survives
        assert mask[3] == 1.0  # intercept survives
        assert mask[1] == 0.0


class TestSweepScan:
    """Scan-dispatched random-effect sweep (PHOTON_SWEEP_SCAN): the
    same-shape bucket groups run as one lax.scan program; results must be
    BITWISE equal to the per-bucket dispatch loop — the scan only changes
    how many XLA programs a sweep costs, never what they compute."""

    def _dataset(self, n=6000, d_re=8, n_entities=300, seed=3):
        rng = np.random.default_rng(seed)
        Xe = rng.normal(size=(n, d_re)).astype(np.float32)
        entity = rng.integers(0, n_entities, size=n)
        y = (rng.uniform(size=n) > 0.5).astype(np.float32)
        ds = GameDataset.build(
            {"pe": jnp.asarray(Xe)}, y, id_tags={"entityId": entity}
        )
        red = build_random_effect_dataset(
            ds,
            RandomEffectDataConfig(
                "entityId", "pe", active_upper_bound=32, min_bucket=8
            ),
        )
        return ds, red

    def test_sweep_scan_matches_bucket_loop(self, monkeypatch):
        from photon_ml_tpu.game.coordinate import sweep_scan_enabled

        ds, red = self._dataset()
        assert len(red.buckets) > 1  # the scan must have something to fuse
        cfg = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(max_iterations=10, tolerance=1e-7),
            regularization=L2,
            reg_weight=5.0,
            variance_computation=VarianceComputationType.SIMPLE,
        )
        coord = RandomEffectCoordinate(ds, red, cfg, TaskType.LOGISTIC_REGRESSION)
        assert sweep_scan_enabled()
        m_scan, stats_scan = coord.train(ds.offsets)
        monkeypatch.setenv("PHOTON_SWEEP_SCAN", "0")
        assert not sweep_scan_enabled()
        m_loop, stats_loop = coord.train(ds.offsets)
        np.testing.assert_array_equal(
            np.asarray(m_scan.coefficients_matrix),
            np.asarray(m_loop.coefficients_matrix),
        )
        np.testing.assert_array_equal(
            np.asarray(m_scan.variances_matrix),
            np.asarray(m_loop.variances_matrix),
        )
        assert stats_scan == stats_loop

    def test_sweep_scan_warm_start_matches(self, monkeypatch):
        """Warm start reads the coefficient matrix through the scan carry —
        per-entity rows must round-trip exactly as in the loop."""
        ds, red = self._dataset(seed=11)
        cfg = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(max_iterations=5, tolerance=1e-7),
            regularization=L2,
            reg_weight=2.0,
        )
        coord = RandomEffectCoordinate(ds, red, cfg, TaskType.LOGISTIC_REGRESSION)
        warm, _ = coord.train(ds.offsets)
        m_scan, _ = coord.train(ds.offsets, warm)
        monkeypatch.setenv("PHOTON_SWEEP_SCAN", "0")
        m_loop, _ = coord.train(ds.offsets, warm)
        np.testing.assert_array_equal(
            np.asarray(m_scan.coefficients_matrix),
            np.asarray(m_loop.coefficients_matrix),
        )

    def test_scan_groups_cover_every_bucket_once(self):
        ds, red = self._dataset(seed=7)
        cfg = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(max_iterations=2, tolerance=1e-6),
            regularization=L2,
            reg_weight=1.0,
        )
        coord = RandomEffectCoordinate(ds, red, cfg, TaskType.LOGISTIC_REGRESSION)
        groups = coord._scan_group_list()
        seen = sorted(i for idxs, *_ in groups for i in idxs)
        assert seen == list(range(len(red.buckets)))
        for idxs, gathers, masks, ents in groups:
            assert gathers.shape[0] == len(idxs)
            assert masks.shape == gathers.shape
            assert ents.shape == gathers.shape[:2]

"""Pod-scale serving: two-tier entity store + entity-sharded bundles.

The load-bearing contract is unchanged from PR 4: every score must be
BITWISE-identical to the single-tier replicated path, whatever storage mode
the bundle stages — hot-tier hit, cold-tier override row, entity-sharded
psum gather, or the pinned zero-row miss. On top of that the two-tier store
must promote asynchronously, evict under a tiny hot-set budget without ever
changing an answer, and the HBM budget accounting must charge the hot tier
plus warmup buffers per shard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.game.model import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.serving import (
    HbmBudgetExceeded,
    ScoreRequest,
    ServingBundle,
    ServingEngine,
    TwoTierEntityStore,
)
from photon_ml_tpu.transformers.game_transformer import (
    CoordinateScoringSpec,
    GameTransformer,
)
from photon_ml_tpu.types import TaskType

pytestmark = pytest.mark.serving

TASK = TaskType.LOGISTIC_REGRESSION
D_FE, D_RE, E = 7, 5, 24


def _fixture(rng, n=16):
    """(model, specs, requests, dataset): FE + RE coordinates; the request
    stream mixes repeated hot entities, one-shot cold entities, and
    unknowns."""
    w = rng.normal(size=D_FE).astype(np.float32)
    M = np.zeros((E + 1, D_RE), np.float32)
    M[:E] = rng.normal(size=(E, D_RE))
    model = GameModel(
        {
            "fixed": FixedEffectModel(Coefficients(jnp.asarray(w)), TASK),
            "per-e": RandomEffectModel(jnp.asarray(M), None, TASK),
        }
    )
    specs = {
        "fixed": CoordinateScoringSpec(shard="g"),
        "per-e": CoordinateScoringSpec(
            shard="re",
            random_effect_type="eid",
            entity_index={str(i): i for i in range(E)},
        ),
    }
    X = rng.normal(size=(n, D_FE)).astype(np.float32)
    Xe = rng.normal(size=(n, D_RE)).astype(np.float32)
    # hot (preloaded prefix), cold (tail rows), unknown — all in one batch:
    # even ids 0..E-1 are trained entities (low ones preloaded hot), even
    # values >= E resolve to nothing (zero-row cold starts).
    ids = [str((2 * i) % (E + 6)) for i in range(n)]
    offsets = rng.normal(size=n).astype(np.float32)
    reqs = [
        ScoreRequest(
            features={"g": X[i], "re": Xe[i]},
            entity_ids={"eid": ids[i]},
            offset=float(offsets[i]),
            uid=str(i),
        )
        for i in range(n)
    ]
    from photon_ml_tpu.data.game_dataset import GameDataset

    ds = GameDataset.build(
        {"g": X, "re": Xe},
        np.zeros(n, np.float32),
        offsets=offsets,
        id_tags={"eid": np.asarray(ids)},
    )
    return model, specs, reqs, ds


def _scores(results):
    return np.asarray([r.score for r in results], np.float64)


def _ref_scores(model, specs, reqs):
    with ServingEngine(
        ServingBundle.from_model(model, specs, TASK), max_batch=16
    ) as eng:
        return _scores(eng.score_batch(reqs))


class TestTwoTierStore:
    def test_mixed_hot_cold_unknown_bitwise(self, rng):
        """One batch mixing hot-tier hits, cold-tier override rows and
        unknown entities scores bitwise-equal to the single-tier path AND
        to the offline transformer."""
        model, specs, reqs, ds = _fixture(rng)
        ref = _ref_scores(model, specs, reqs)
        offline = np.asarray(
            GameTransformer(model, specs, TASK).transform(ds).scores,
            np.float64,
        )
        bundle = ServingBundle.from_model(model, specs, TASK, hot_rows=6)
        with ServingEngine(bundle, max_batch=16) as eng:
            got = _scores(eng.score_batch(reqs))
            m = eng.metrics()
        assert np.array_equal(got, ref)
        assert np.array_equal(got, offline)
        assert m["cold_tier_hits"] > 0 and m["hot_tier_hits"] > 0
        # Unknown entities are COLD STARTS (zero row), not cold-tier hits.
        assert m["cold_start_lookups"] > 0

    def test_promotion_moves_cold_rows_hot(self, rng):
        model, specs, reqs, _ = _fixture(rng)
        ref = _ref_scores(model, specs, reqs)
        bundle = ServingBundle.from_model(model, specs, TASK, hot_rows=8)
        store = bundle.coordinates["per-e"].store
        with ServingEngine(bundle, max_batch=16) as eng:
            s1 = _scores(eng.score_batch(reqs))
            store.drain()
            s2 = _scores(eng.score_batch(reqs))
            store.drain()  # pass 2's own cold hits re-queue (LRU thrash)
            m = eng.metrics()
        assert np.array_equal(s1, ref) and np.array_equal(s2, ref)
        assert m["promotions"] > 0
        sm = store.metrics()
        assert sm["pending_promotions"] == 0
        # Promoted rows really moved tiers: the promoted entities resolve
        # hot on the second pass (hot hits grew across passes).
        assert sm["hot_tier_hits"] > 0

    def test_eviction_under_tiny_budget_never_changes_answers(self, rng):
        """hot_rows=2: every distinct entity beyond two forces an LRU
        eviction; answers stay bitwise-correct throughout."""
        model, specs, reqs, _ = _fixture(rng)
        ref = _ref_scores(model, specs, reqs)
        bundle = ServingBundle.from_model(model, specs, TASK, hot_rows=2)
        store = bundle.coordinates["per-e"].store
        with ServingEngine(bundle, max_batch=16) as eng:
            for _ in range(3):
                got = _scores(eng.score_batch(reqs))
                assert np.array_equal(got, ref)
                store.drain()
            m = eng.metrics()
        assert m["evictions"] > 0
        assert store.capacity == 2
        assert len(store._slot_of_row) <= 2

    def test_zero_capacity_serves_everything_from_cold_tier(self, rng):
        model, specs, reqs, _ = _fixture(rng)
        ref = _ref_scores(model, specs, reqs)
        bundle = ServingBundle.from_model(model, specs, TASK, hot_rows=0)
        with ServingEngine(bundle, max_batch=16) as eng:
            got = _scores(eng.score_batch(reqs))
            m = eng.metrics()
        assert np.array_equal(got, ref)
        assert m["hot_tier_hits"] == 0 and m["promotions"] == 0
        assert m["sharding"]["hot_set_fraction"] == 0.0

    def test_unknown_entity_is_zero_row_fallback(self, rng):
        """The final miss tier: ids in neither tier score FE-only."""
        model, specs, _, _ = _fixture(rng)
        n = 4
        X = rng.normal(size=(n, D_FE)).astype(np.float32)
        Xe = rng.normal(size=(n, D_RE)).astype(np.float32)
        reqs = [
            ScoreRequest(
                features={"g": X[i], "re": Xe[i]},
                entity_ids={"eid": f"nope-{i}"},
            )
            for i in range(n)
        ]
        ref = _ref_scores(model, specs, reqs)
        bundle = ServingBundle.from_model(model, specs, TASK, hot_rows=4)
        with ServingEngine(bundle, max_batch=8) as eng:
            res = eng.score_batch(reqs)
        assert all(r.cold_start for r in res)
        assert np.array_equal(_scores(res), ref)

    def test_store_unit_lru_and_snapshot_consistency(self):
        cold = np.arange(12, dtype=np.float32).reshape(6, 2)
        cold[5] = 0.0  # pinned zero row
        store = TwoTierEntityStore(cold, hot_rows=2)
        try:
            # rows 0,1 preloaded hot; 3 is a cold hit with override row.
            slots, ovr, flags, snap = store.lookup(
                np.asarray([0, 3, 5]), bucket=4
            )
            assert slots[0] == 0 and not flags[0]
            assert flags[1] and np.array_equal(ovr[1], cold[3])
            assert slots[2] == store.zero_slot and not flags[2]
            got = np.asarray(snap)[slots]
            got = np.where(flags[:, None], ovr, got)
            assert np.array_equal(got, cold[[0, 3, 5, 5]])
            store.drain()
            # 3 promoted, evicting the LRU slot (row 1: never touched).
            slots2, _, flags2, snap2 = store.lookup(
                np.asarray([3]), bucket=1
            )
            assert not flags2[0]
            assert np.array_equal(np.asarray(snap2)[slots2[0]], cold[3])
            assert 1 not in store._slot_of_row
        finally:
            store.close()

    def test_released_bundle_closes_store(self, rng):
        model, specs, reqs, _ = _fixture(rng)
        bundle = ServingBundle.from_model(model, specs, TASK, hot_rows=4)
        store = bundle.coordinates["per-e"].store
        with ServingEngine(bundle, max_batch=16) as eng:
            eng.score_batch(reqs)
        bundle.release()
        assert store._closed
        # conftest's leak check asserts no photon-serving-promote survivor.


class TestNormalizedParity:
    def test_norm_with_shifts_stays_bitwise_across_storage_modes(self, rng):
        """A shifted+scaled normalization must not break bitwise parity:
        every margin path reduces the shift ROW-WISE (batch-invariant), so
        the (E+1, D) matrix-folded replicated path and the (N, D)
        gathered two-tier/sharded paths agree to the last bit."""
        from photon_ml_tpu.ops.normalization import NormalizationContext
        from photon_ml_tpu.parallel.mesh import make_mesh

        model, specs, reqs, _ = _fixture(rng)
        norm = NormalizationContext(
            factors=jnp.asarray(
                rng.uniform(0.5, 2.0, size=D_RE).astype(np.float32)
            ),
            shifts=jnp.asarray(rng.normal(size=D_RE).astype(np.float32)),
        )
        specs = dict(specs)
        specs["per-e"] = CoordinateScoringSpec(
            shard="re",
            norm=norm,
            random_effect_type="eid",
            entity_index={str(i): i for i in range(E)},
        )
        ref = _ref_scores(model, specs, reqs)
        for kw in ({"hot_rows": 6}, {"mesh": make_mesh()}):
            bundle = ServingBundle.from_model(model, specs, TASK, **kw)
            with ServingEngine(bundle, max_batch=16) as eng:
                got = _scores(eng.score_batch(reqs))
            assert np.array_equal(got, ref), kw


class TestEntityShardedServing:
    def test_sharded_bundle_bitwise_and_sharding_metrics(self, rng):
        from photon_ml_tpu.parallel.mesh import make_mesh

        model, specs, reqs, _ = _fixture(rng)
        ref = _ref_scores(model, specs, reqs)
        mesh = make_mesh()
        bundle = ServingBundle.from_model(model, specs, TASK, mesh=mesh)
        c = bundle.coordinates["per-e"]
        assert c.mesh is mesh and c.logical_rows == E + 1
        assert c.unseen_row == E  # the LOGICAL pinned row, not a pad row
        shard_bytes = [s.data.nbytes for s in c.params.addressable_shards]
        assert len(shard_bytes) == mesh.devices.size
        assert max(shard_bytes) <= c.params.nbytes // mesh.devices.size
        with ServingEngine(bundle, max_batch=16) as eng:
            eng.warmup()
            got = _scores(eng.score_batch(reqs))
            m = eng.metrics()
            assert eng.recompiles_after_warmup == 0
        assert np.array_equal(got, ref)
        assert m["sharding"]["entity_sharded"] is True
        assert m["sharding"]["axis_size"] == mesh.devices.size
        assert m["sharding"]["all_to_all_bytes_per_batch"] > 0

    def test_mesh_trained_model_adopts_sharding(self, rng):
        """A row-sharded trained matrix stages sharded with NO mesh
        argument: training's sharding decision flows into serving."""
        from photon_ml_tpu.parallel.mesh import make_mesh, matrix_row_sharding

        model, specs, reqs, _ = _fixture(rng)
        ref = _ref_scores(model, specs, reqs)
        mesh = make_mesh()
        M = np.asarray(model["per-e"].coefficients_matrix)
        padded = np.zeros((-(-(E + 1) // 8) * 8, D_RE), np.float32)
        padded[: E + 1] = M
        sharded_m = RandomEffectModel(
            jax.device_put(jnp.asarray(padded), matrix_row_sharding(mesh)),
            None,
            TASK,
            n_entities=E,
        )
        bundle = ServingBundle.from_model(
            GameModel({"fixed": model["fixed"], "per-e": sharded_m}),
            specs,
            TASK,
        )
        assert bundle.coordinates["per-e"].mesh is not None
        with ServingEngine(bundle, max_batch=16) as eng:
            got = _scores(eng.score_batch(reqs))
        assert np.array_equal(got, ref)


class TestPromotionFaults:
    """ISSUE 10 promotion-worker fault cases: an armed `promote` fault
    never loses a request, never leaks the `photon-serving-promote` thread
    (conftest guard), and the cold row still scores bitwise through the
    override-buffer path."""

    pytestmark = [pytest.mark.serving, pytest.mark.chaos]

    def test_failed_promotion_leaves_rows_cold_and_bitwise(
        self, rng, monkeypatch
    ):
        from photon_ml_tpu.utils import faults

        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        model, specs, reqs, _ = _fixture(rng)
        ref = _ref_scores(model, specs, reqs)
        bundle = ServingBundle.from_model(model, specs, TASK, hot_rows=6)
        store = bundle.coordinates["per-e"].store
        with faults.inject("promote:1"):
            with ServingEngine(bundle, max_batch=16) as eng:
                s1 = _scores(eng.score_batch(reqs))
                store.drain()
                s2 = _scores(eng.score_batch(reqs))
                store.drain()
                m = eng.metrics()
        # Never a lost request, never a changed answer.
        assert np.array_equal(s1, ref) and np.array_equal(s2, ref)
        # The first promotion batch failed (counted), the worker LIVED ON
        # (not fatal): later touches re-queued and promoted successfully.
        assert m["promote_failures"] > 0
        assert m["promotions"] > 0
        assert not store._closed
        assert faults.counters()["promote_failures"] == m["promote_failures"]
        bundle.release()

    def test_persistent_promotion_failure_serves_from_cold_tier(
        self, rng, monkeypatch
    ):
        from photon_ml_tpu.utils import faults

        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        model, specs, reqs, _ = _fixture(rng)
        ref = _ref_scores(model, specs, reqs)
        bundle = ServingBundle.from_model(model, specs, TASK, hot_rows=6)
        store = bundle.coordinates["per-e"].store
        with faults.inject("promote:9999"):
            with ServingEngine(bundle, max_batch=16) as eng:
                for _ in range(3):
                    got = _scores(eng.score_batch(reqs))
                    assert np.array_equal(got, ref)
                    store.drain()
                m = eng.metrics()
        # Rows stayed cold forever — counted, never fatal, never wrong.
        assert m["promote_failures"] > 0
        assert m["cold_tier_hits"] > 0
        assert not store._closed
        bundle.release()
        # conftest's leak guard asserts no photon-serving-promote survivor.


class TestShardLossDegradation:
    """ISSUE 10 serving shard loss: the engine keeps serving — requests
    resolving to a LOST shard get the pinned zero row (bitwise FE-only
    for exactly those entities), per-shard health reports in
    metrics()["sharding"], and recovery re-stages ONLY the lost shard."""

    pytestmark = [pytest.mark.serving, pytest.mark.chaos]

    def _fe_only_ref(self, model, specs, reqs):
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=16
        ) as eng:
            return _scores(eng.score_batch_fe_only(reqs))

    def test_lost_shard_serves_fe_only_exactly_its_entities(self, rng):
        from photon_ml_tpu.parallel.mesh import make_mesh

        model, specs, reqs, _ = _fixture(rng)
        ref = _ref_scores(model, specs, reqs)
        ref_fe = self._fe_only_ref(model, specs, reqs)
        mesh = make_mesh()
        bundle = ServingBundle.from_model(model, specs, TASK, mesh=mesh)
        c = bundle.coordinates["per-e"]
        assert c.shard_health.n_shards == mesh.devices.size
        with ServingEngine(bundle, max_batch=16) as eng:
            assert np.array_equal(_scores(eng.score_batch(reqs)), ref)
            lo, hi = eng.mark_shard_lost("per-e", 1)
            degraded = _scores(eng.score_batch(reqs))
            m = eng.metrics()
            # Exactly the lost shard's entities are FE-only; all others
            # keep their full-fidelity bitwise answers.
            rows, _ = c.lookup_rows(
                [r.entity_ids.get("eid") for r in reqs]
            )
            lost_mask = (rows >= lo) & (rows < hi)
            assert lost_mask.any() and not lost_mask.all()
            expected = np.where(lost_mask, ref_fe, ref)
            assert np.array_equal(degraded, expected)
            assert m["state"] == "DEGRADED"
            assert "shard_loss:per-e/1" in m["degraded_reasons"]
            assert m["sharding"]["shards_lost"] == 1
            assert m["sharding"]["shard_loss_fallbacks"] == int(
                lost_mask.sum()
            )
            # Recovery: restage ONLY the lost shard, back to bitwise-full.
            nbytes = eng.restage_shard("per-e", 1)
            assert nbytes == (hi - lo) * c.dim * 4
            assert np.array_equal(_scores(eng.score_batch(reqs)), ref)
            m2 = eng.metrics()
            assert m2["state"] == "READY"
            assert m2["sharding"]["shards_lost"] == 0

    def test_failed_restage_keeps_serving_degraded(self, rng, monkeypatch):
        from photon_ml_tpu.parallel.mesh import make_mesh
        from photon_ml_tpu.utils import faults

        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        model, specs, reqs, _ = _fixture(rng)
        ref = _ref_scores(model, specs, reqs)
        ref_fe = self._fe_only_ref(model, specs, reqs)
        mesh = make_mesh()
        bundle = ServingBundle.from_model(model, specs, TASK, mesh=mesh)
        c = bundle.coordinates["per-e"]
        with ServingEngine(bundle, max_batch=16) as eng:
            lo, hi = eng.mark_shard_lost("per-e", 0)
            with faults.inject("shard_upload:9999"):
                with pytest.raises(faults.InjectedFault):
                    eng.restage_shard("per-e", 0)
                # Still serving, still degraded, still bitwise FE-only for
                # the lost shard's entities.
                degraded = _scores(eng.score_batch(reqs))
            assert faults.counters()["shard_upload_retries"] > 0
            rows, _ = c.lookup_rows([r.entity_ids.get("eid") for r in reqs])
            lost_mask = (rows >= lo) & (rows < hi)
            assert np.array_equal(
                degraded, np.where(lost_mask, ref_fe, ref)
            )
            assert eng.metrics()["state"] == "DEGRADED"
            # A later (un-faulted) restage recovers fully.
            eng.restage_shard("per-e", 0)
            assert np.array_equal(_scores(eng.score_batch(reqs)), ref)

    def test_two_coordinate_shard_loss_is_isolated(self, rng):
        """ISSUE 13 satellite: per-coordinate ShardHealth isolation with
        TWO random-effect coordinates — losing cid_a's shard 0 degrades
        ONLY cid_a's rows in that range (cid_b keeps every full-fidelity
        answer, bitwise), and each coordinate's shards recover
        independently. PR 10's drill only exercised a single-RE bundle,
        which could not catch a health/loss state accidentally shared
        across coordinates."""
        from photon_ml_tpu.parallel.mesh import make_mesh

        n = 16
        E2 = 16
        w = rng.normal(size=D_FE).astype(np.float32)
        Ma = np.zeros((E + 1, D_RE), np.float32)
        Ma[:E] = rng.normal(size=(E, D_RE))
        Mb = np.zeros((E2 + 1, D_RE), np.float32)
        Mb[:E2] = rng.normal(size=(E2, D_RE))
        task = TASK

        def _model(a, b):
            return GameModel(
                {
                    "fixed": FixedEffectModel(Coefficients(jnp.asarray(w)), task),
                    "cid_a": RandomEffectModel(jnp.asarray(a), None, task),
                    "cid_b": RandomEffectModel(jnp.asarray(b), None, task),
                }
            )

        specs = {
            "fixed": CoordinateScoringSpec(shard="g"),
            "cid_a": CoordinateScoringSpec(
                shard="ra",
                random_effect_type="aid",
                entity_index={str(i): i for i in range(E)},
            ),
            "cid_b": CoordinateScoringSpec(
                shard="rb",
                random_effect_type="bid",
                entity_index={str(i): i for i in range(E2)},
            ),
        }
        X = rng.normal(size=(n, D_FE)).astype(np.float32)
        Xa = rng.normal(size=(n, D_RE)).astype(np.float32)
        Xb = rng.normal(size=(n, D_RE)).astype(np.float32)
        reqs = [
            ScoreRequest(
                features={"g": X[i], "ra": Xa[i], "rb": Xb[i]},
                entity_ids={"aid": str(i % E), "bid": str(i % E2)},
            )
            for i in range(n)
        ]

        def _ref(a, b):
            with ServingEngine(
                ServingBundle.from_model(_model(a, b), specs, task),
                max_batch=16,
            ) as eng:
                return _scores(eng.score_batch(reqs))

        ref = _ref(Ma, Mb)
        mesh = make_mesh()
        bundle = ServingBundle.from_model(
            _model(Ma, Mb), specs, task, mesh=mesh
        )
        ca, cb = bundle.coordinates["cid_a"], bundle.coordinates["cid_b"]
        assert ca.shard_health is not cb.shard_health
        with ServingEngine(bundle, max_batch=16) as eng:
            assert np.array_equal(_scores(eng.score_batch(reqs)), ref)
            # Lose cid_a shard 0: expected = the reference with cid_a's
            # lost LOGICAL rows zeroed (lost entities score the pinned
            # zero row for cid_a ONLY); cid_b untouched.
            lo_a, hi_a = eng.mark_shard_lost("cid_a", 0)
            Ma_deg = Ma.copy()
            Ma_deg[lo_a : min(hi_a, E)] = 0.0
            expected_a = _ref(Ma_deg, Mb)
            assert not np.array_equal(expected_a, ref)  # the drill bites
            assert np.array_equal(_scores(eng.score_batch(reqs)), expected_a)
            m = eng.metrics()
            assert m["sharding"]["shards_lost"] == 1
            assert "shard_loss:cid_a/0" in m["degraded_reasons"]
            assert cb.shard_health.lost == ()
            # Lose cid_b shard 1 ON TOP: both degradations compose, each
            # scoped to its own coordinate's rows.
            lo_b, hi_b = eng.mark_shard_lost("cid_b", 1)
            Mb_deg = Mb.copy()
            Mb_deg[lo_b : min(hi_b, E2)] = 0.0
            expected_ab = _ref(Ma_deg, Mb_deg)
            assert np.array_equal(
                _scores(eng.score_batch(reqs)), expected_ab
            )
            assert eng.metrics()["sharding"]["shards_lost"] == 2
            # Independent recovery: restaging cid_a/0 restores cid_a's
            # rows while cid_b/1 stays degraded...
            eng.restage_shard("cid_a", 0)
            assert np.array_equal(
                _scores(eng.score_batch(reqs)), _ref(Ma, Mb_deg)
            )
            m2 = eng.metrics()
            assert "shard_loss:cid_a/0" not in m2["degraded_reasons"]
            assert "shard_loss:cid_b/1" in m2["degraded_reasons"]
            # ...and recovering cid_b/1 returns the full bitwise answers.
            eng.restage_shard("cid_b", 1)
            assert np.array_equal(_scores(eng.score_batch(reqs)), ref)
            assert eng.metrics()["state"] == "READY"

    def test_staging_fault_retried_bitwise(self, rng, monkeypatch):
        from photon_ml_tpu.utils import faults

        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        model, specs, reqs, _ = _fixture(rng)
        ref = _ref_scores(model, specs, reqs)
        with faults.inject("shard_upload:1") as inj:
            bundle = ServingBundle.from_model(model, specs, TASK)
        assert inj.injected == {"shard_upload": 1}
        assert faults.counters()["shard_upload_retries"] == 1
        with ServingEngine(bundle, max_batch=16) as eng:
            assert np.array_equal(_scores(eng.score_batch(reqs)), ref)


class TestServingWatchdog:
    """ISSUE 10 hang watchdog in the serving score path: an over-deadline
    dispatch becomes a typed DeviceHang, the health machine goes DEGRADED,
    and every request still gets an answer (FE-only once the circuit
    opens) — never a hang, never a lost future."""

    pytestmark = [pytest.mark.serving, pytest.mark.chaos]

    def test_wedged_dispatch_degrades_to_fe_only_answers(
        self, rng, monkeypatch
    ):
        import time as _time

        from photon_ml_tpu.utils import faults

        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        model, specs, reqs, _ = _fixture(rng)
        ref_fe = None
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=16
        ) as ref_eng:
            ref = _scores(ref_eng.score_batch(reqs))
            ref_fe = _scores(ref_eng.score_batch_fe_only(reqs))
        eng = ServingEngine(
            ServingBundle.from_model(model, specs, TASK),
            max_batch=16,
            circuit_threshold=1,
            circuit_probe_interval_s=60.0,
            watchdog_ms_override=10.0,
        )
        eng.warmup()  # warmup is watchdog-exempt (compiles are slow)
        real = eng._dispatch_device

        def wedged(packed, state):
            out = real(packed, state)
            _time.sleep(0.08)  # every full-path dispatch blows the 10ms
            return out

        eng._dispatch_device = wedged
        with eng, eng.batcher(max_wait_ms=0.5) as batcher:
            futs = [batcher.submit(r, block=True) for r in reqs]
            results = [f.result(timeout=120) for f in futs]
            m = eng.metrics()
        # Every request answered — the hang hole is closed with ANSWERS.
        assert len(results) == len(reqs)
        assert faults.counters()["watchdog_trips"] >= 1
        assert m["circuit_state"] == "OPEN"
        assert m["state"] == "DEGRADED"
        # FE-only answers are bitwise the FE-only reference; any requests
        # answered before the circuit opened are bitwise the full path.
        got = _scores(results)
        fe_mask = np.asarray([r.fe_only for r in results])
        assert fe_mask.any()
        assert np.array_equal(got[fe_mask], ref_fe[fe_mask])
        assert np.array_equal(got[~fe_mask], ref[~fe_mask])

    def test_recovered_dispatch_clears_degradation(self, rng):
        """A guarded dispatch finishing inside its deadline clears the
        device_hang reason (self-healing)."""
        model, specs, reqs, _ = _fixture(rng)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK),
            max_batch=16,
            watchdog_ms_override=60_000.0,
        ) as eng:
            eng.warmup()
            eng._hang_seen = True
            eng.health.add_degraded("device_hang")
            eng.score_batch(reqs)
            m = eng.metrics()
        assert "device_hang" not in m["degraded_reasons"]
        assert m["state"] in ("READY", "DRAINING", "CLOSED")


class TestBudgetAccounting:
    def test_device_bytes_per_shard_divides_sharded_state(self, rng):
        from photon_ml_tpu.parallel.mesh import make_mesh

        model, specs, _, _ = _fixture(rng)
        mesh = make_mesh()
        repl = ServingBundle.from_model(model, specs, TASK)
        sh = ServingBundle.from_model(model, specs, TASK, mesh=mesh)
        tt = ServingBundle.from_model(model, specs, TASK, hot_rows=4)
        # Sharded: the RE matrix divides by the mesh; FE vector replicated.
        fe_bytes = D_FE * 4
        assert sh.device_bytes_per_shard() < repl.device_bytes_per_shard()
        assert sh.device_bytes_per_shard() >= fe_bytes
        # Two-tier: only the hot set counts against device budgets.
        assert tt.device_bytes() == fe_bytes + (4 + 1) * D_RE * 4

    def test_swap_budget_counts_hot_tier_and_warmup_buffers(self, rng):
        """The swap's HBM check must include the staged bundle's hot tier
        AND the per-bucket warmup request buffers — a budget that fits the
        matrices alone but not the buffers must refuse before staging."""
        model, specs, reqs, _ = _fixture(rng)
        bundle = ServingBundle.from_model(model, specs, TASK, hot_rows=4)
        with ServingEngine(bundle, max_batch=16) as eng:
            eng.score_batch(reqs)
            warm = eng.warmup_buffer_bytes()
            assert warm > 0
            have = bundle.device_bytes_per_shard()
            next_builder_calls = [0]

            def builder():
                next_builder_calls[0] += 1
                return ServingBundle.from_model(
                    model, specs, TASK, hot_rows=4
                )

            # Budget covers both generations but NOT the warmup buffers.
            budget = 2 * have + warm // 2
            with pytest.raises(HbmBudgetExceeded, match="warmup"):
                eng.bundle_manager.swap(
                    builder, expected_bytes=have, hbm_budget_bytes=budget
                )
            assert next_builder_calls[0] == 0  # refused BEFORE staging
            # With the buffers accounted, the same swap fits and commits.
            info = eng.bundle_manager.swap(
                builder,
                expected_bytes=have,
                hbm_budget_bytes=2 * have + warm + 1024,
            )
            assert info["version"] == 1
            assert next_builder_calls[0] == 1

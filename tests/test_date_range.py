"""Date-range input resolution (util/DateRange.scala, DaysRange.scala,
IOUtils.scala:30-155, GameDriver.pathsForDateRange:248)."""

import datetime
import os

import pytest

from photon_ml_tpu.utils.date_range import (
    DateRange,
    DaysRange,
    paths_for_date_range,
    resolve_range,
)


class TestDateRange:
    def test_parse_and_days(self):
        r = DateRange.parse("20160228-20160302")  # leap year crossing
        assert r.start == datetime.date(2016, 2, 28)
        assert r.end == datetime.date(2016, 3, 2)
        assert [d.day for d in r.days()] == [28, 29, 1, 2]
        assert str(r) == "20160228-20160302"

    def test_invalid_ranges(self):
        with pytest.raises(ValueError):
            DateRange.parse("20160301-20160201")  # reversed
        with pytest.raises(ValueError):
            DateRange.parse("20160301")  # no delimiter


class TestDaysRange:
    def test_to_date_range(self):
        today = datetime.date(2026, 7, 30)
        r = DaysRange.parse("90-1").to_date_range(today)
        assert r.end == today - datetime.timedelta(days=1)
        assert r.start == today - datetime.timedelta(days=90)

    def test_invalid(self):
        with pytest.raises(ValueError):
            DaysRange.parse("1-90")  # start more recent than end


class TestResolveRange:
    def test_exclusive(self):
        with pytest.raises(ValueError):
            resolve_range("20160101-20160201", "90-1")
        assert resolve_range(None, None) is None
        assert resolve_range("20160101-20160102", None).start == datetime.date(2016, 1, 1)


class TestPathsForDateRange:
    def test_daily_expansion(self, tmp_path):
        base = tmp_path / "daily"
        for d in ("2016/01/01", "2016/01/03", "2016/02/01"):
            (base / d).mkdir(parents=True)
        got = paths_for_date_range([str(base)], DateRange.parse("20160101-20160131"))
        assert got == [
            str(base / "2016/01/01"),
            str(base / "2016/01/03"),
        ]

    def test_no_range_passes_through(self, tmp_path):
        assert paths_for_date_range(["a", "b"], None) == ["a", "b"]

    def test_empty_range_raises(self, tmp_path):
        base = tmp_path / "daily"
        (base / "2016/01/01").mkdir(parents=True)
        with pytest.raises(FileNotFoundError):
            paths_for_date_range([str(base)], DateRange.parse("20170101-20170102"))

    def test_error_on_missing(self, tmp_path):
        base = tmp_path / "daily"
        (base / "2016/01/01").mkdir(parents=True)
        with pytest.raises(FileNotFoundError):
            paths_for_date_range(
                [str(base)],
                DateRange.parse("20160101-20160102"),
                error_on_missing=True,
            )

    def test_reference_ioutils_fixture_layout(self):
        """The reference's own IOUtilsTest daily fixture tree resolves."""
        base = (
            "/root/reference/photon-client/src/integTest/resources/"
            "IOUtilsTest/input/daily"
        )
        if not os.path.isdir(base):
            pytest.skip("reference fixtures not mounted")
        got = paths_for_date_range([base], DateRange.parse("20160101-20160401"))
        assert [p[-10:] for p in got] == ["2016/01/01", "2016/02/01", "2016/03/01"]

"""Test harness configuration.

Mirrors the reference's SparkTestUtils strategy (photon-test-utils
SparkTestUtils.scala:55-75): where the reference spins up a local[*] Spark
cluster so shuffles/broadcasts/treeAggregate run the real code paths with
threads as executors, we force an 8-device virtual CPU mesh so pjit/shard_map
and the XLA collectives run the real multi-chip code paths on one host.

Env vars must be set before jax initializes a backend. Some environments
additionally install a TPU plugin that re-forces `jax_platforms` at interpreter
startup (sitecustomize), so the config is also overridden after import —
that keeps backend init strictly on the virtual CPU mesh.
"""

import os

from photon_ml_tpu.utils.knobs import get_knob

# Light import: utils.knobs is stdlib-only, so reading the platform knob
# through the typed registry cannot initialize a backend early.
_PLATFORM = str(get_knob("PHOTON_TEST_PLATFORM"))
os.environ["JAX_PLATFORMS"] = _PLATFORM
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", _PLATFORM)

import threading
import time

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 run"
    )
    config.addinivalue_line(
        "markers",
        "chaos: failure-domain tests (fault injection, kill-resume parity); "
        "the serving subset (-m 'chaos and serving') runs inside tier-1",
    )
    config.addinivalue_line(
        "markers",
        "serving: online serving engine tests (bundle/engine/batcher/"
        "lifecycle)",
    )
    config.addinivalue_line(
        "markers",
        "perf: perf-regression guards (engagement + non-dominance contracts "
        "on bench-like shapes); the heavy ones are also slow-marked",
    )
    config.addinivalue_line(
        "markers",
        "multihost: OS-process jax.distributed dryruns (coordinator + "
        "workers over virtual CPU devices); always slow-marked — tier-1 "
        "covers the sharded code paths on the single-process 8-device mesh",
    )
    config.addinivalue_line(
        "markers",
        "elastic: live mesh elasticity (reshard under traffic, mid-fit "
        "mesh-loss resume); the multi-device reshard drills are slow+"
        "elastic and out of tier-1",
    )
    _assert_fault_sites_registered()


def _assert_fault_sites_registered():
    """Guard: planted fault sites and SITE_DESCRIPTIONS must agree at
    collection time. Promoted from a local regex to photon-lint's
    AST-based `fault-site-sync` check (photon_ml_tpu/analysis/), which
    also enforces the REVERSE direction — a described site nobody plants
    is advertised chaos coverage that does not exist — and that every
    site is a string literal."""
    from photon_ml_tpu.analysis import run_checks

    # Pragma-hygiene findings also ride along in any run; those belong to
    # the tier-1 analysis gate (test_analysis.py), not this collection
    # guard, which must fail ONLY for fault-site drift.
    findings = [
        f
        for f in run_checks(checks=["fault-site-sync"])
        if f.check == "fault-site-sync"
    ]
    if findings:
        import pytest as _pytest

        raise _pytest.UsageError(
            "fault-site-sync findings (run `python -m "
            "photon_ml_tpu.analysis --check fault-site-sync`):\n  "
            + "\n  ".join(f.render() for f in findings)
        )


@pytest.fixture
def rng():
    return np.random.default_rng(20260729)


@pytest.fixture(autouse=True)
def _failure_domain_hygiene(monkeypatch):
    """Per-test failure-domain invariants:

    * fault injection armed by one test never leaks into the next (the
      registry is process-global by design — production arms it once via
      env), and an ambient PHOTON_FAULTS/PHOTON_RETRY_* exported in the
      developer's shell never arms injection inside unrelated tests
      (faults.clear() forces an env re-read, so the env must be scrubbed);
    * robustness counters start at zero so tests can assert exact counts;
    * no `photon-async-upload` thread outlives the test that spawned it —
      AsyncUploader workers are per-job and must drain once their job
      completes; a lingering one means a job wedged (or a future leaked)
      and would make later tests' upload behavior order-dependent;
    * no `photon-serving-flush` thread outlives the test — a MicroBatcher's
      flush thread must be joined by engine/batcher close(); a survivor
      means serving work kept running against a torn-down fixture;
    * no `photon-serving-promote` thread outlives the test — a two-tier
      store's promotion worker is short-lived and joined by
      store.close()/bundle.release(); a survivor means promotions kept
      mutating a torn-down store;
    * no `photon-ckpt-write` thread outlives the test — a staged
      checkpoint write is joined by save() before the state.json commit
      (sharded checkpoints fan out `photon-ckpt-write-shard<k>` workers,
      joined the same way); a survivor means a step committed without its
      model file durable;
    * no `photon-watchdog` monitor outlives the test — a Watchdog is
      joined by its owner's close() (the serving engine, the sweep's
      per-train instance); a survivor means deadlines kept arming against
      a torn-down dispatcher;
    * no `photon-reshard` staging worker outlives the test — the live
      reshard orchestrator joins its per-shard upload workers before the
      generation flip; a survivor means staged uploads kept running
      against a rolled-back (or torn-down) generation;
    * no `photon-tenant-*` worker outlives the test — the multi-tenant
      registry's dispatch thread and per-tenant flush threads are joined
      by `TenantRegistry.close()`; a survivor means one tenant's traffic
      kept dispatching against a torn-down fleet;
    * no `photon-refresh-*` worker outlives the test — continuous-refresh
      loop helpers (traffic replays riding a delta apply) join before the
      loop returns; a survivor means requests kept scoring against a
      retired generation;
    * no `photon-hostmesh-*` heartbeat outlives the test — a multi-host
      worker's HostHeartbeat is stopped by its owner (the worker's
      finally); a survivor would keep writing beat files into a
      torn-down rendezvous and could declare phantom host losses;
    * no `photon-shadow-*` evaluation worker outlives the test — a
      ShadowController's window-evaluation thread is joined by
      `close()`; a survivor means mirrored windows kept scoring (and
      could journal verdicts) against a torn-down registry;
    * no `photon-tier-*` worker outlives the test — precision-ladder
      helpers (traffic replays riding a quantize/restore flip) join
      before the transition commits; a survivor means requests kept
      scoring against a drained generation.
    """
    from photon_ml_tpu.utils import faults, telemetry

    for var in (
        "PHOTON_FAULTS",
        "PHOTON_FAULTS_SEED",
        "PHOTON_RETRY_MAX_ATTEMPTS",
        "PHOTON_RETRY_BASE_DELAY_S",
        "PHOTON_RETRY_MAX_DELAY_S",
        "PHOTON_SOLVE_RETRIES",
        "PHOTON_WATCHDOG_MS",
        "PHOTON_COLLECTIVE_RETRIES",
        "PHOTON_SHARD_UPLOAD_RETRIES",
        "PHOTON_RESHARD_RETRIES",
        "PHOTON_REBALANCE_MIN_PROMOTIONS",
        # Multi-tenant serving (ISSUE 15): ambient quota/budget knobs in
        # the developer's shell must never reshape admission control or
        # HBM-pressure demotion inside unrelated tests.
        "PHOTON_TENANT_MAX_PENDING",
        "PHOTON_TENANT_HBM_FRACTION",
        # The adaptive planner (ISSUE 14): an ambient PHOTON_PLAN* in the
        # developer's shell must never install a plan inside unrelated
        # tests, and a plan installed by one test never leaks into the
        # next (estimator fits call ensure_ambient_plan).
        "PHOTON_PLAN",
        "PHOTON_PLAN_PROFILE",
        # Continuous refresh (ISSUE 16): ambient refresh knobs must never
        # resize delta batches or flip the full-refit escape hatch inside
        # unrelated tests.
        "PHOTON_REFRESH_BATCH_ROWS",
        "PHOTON_REFRESH_MAX_DELTA_FRACTION",
        # Multi-host production mode (ISSUE 17): an ambient mode flag or
        # heartbeat/retry tuning in the developer's shell must never make
        # unrelated tests believe they run inside a process group (knob
        # readers branch on PHOTON_MULTIHOST) or reshape loss detection.
        "PHOTON_MULTIHOST",
        "PHOTON_HOST_HEARTBEAT_MS",
        "PHOTON_HOST_LOSS_RETRIES",
        # Shadow deployment (ISSUE 18): ambient decision-loop tuning in
        # the developer's shell must never reshape verdict hysteresis,
        # regression tolerance, cooldowns, or mirror sampling inside
        # unrelated tests.
        "PHOTON_SHADOW_MIN_WINDOWS",
        "PHOTON_SHADOW_REGRESSION_TOL",
        "PHOTON_SHADOW_COOLDOWN_S",
        "PHOTON_SHADOW_MIRROR_FRACTION",
        # Closed-loop autoscaling (ISSUE 19): ambient control-loop tuning
        # in the developer's shell must never reshape tick cadence,
        # action budgets, or cooldowns inside unrelated tests.
        "PHOTON_AUTOPILOT_MS",
        "PHOTON_AUTOPILOT_MAX_ACTIONS",
        "PHOTON_AUTOPILOT_COOLDOWN_S",
        # Precision ladder (ISSUE 20): an ambient ladder opt-in or
        # pressure/ceiling tuning in the developer's shell must never
        # switch unrelated tests from host-tier demotion to quantization
        # or reshape the characterized-error gate.
        "PHOTON_TIER_LADDER",
        "PHOTON_TIER_BF16_PRESSURE",
        "PHOTON_TIER_INT8_PRESSURE",
        "PHOTON_TIER_INT8_ERROR_CEILING",
    ):
        monkeypatch.delenv(var, raising=False)
    from photon_ml_tpu import planner as _planner

    _planner.uninstall_plan()
    faults.clear()
    telemetry.METRICS.reset()  # counters AND histograms/gauges start clean
    yield
    _planner.uninstall_plan()
    faults.clear()
    telemetry.METRICS.reset()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        leaked = [
            t
            for t in threading.enumerate()
            if t.name.startswith(
                (
                    "photon-async-upload",
                    "photon-serving-flush",
                    "photon-serving-promote",
                    "photon-ckpt-write",
                    "photon-watchdog",
                    "photon-reshard",
                    "photon-tenant",
                    "photon-refresh",
                    "photon-hostmesh",
                    "photon-shadow",
                    "photon-autopilot",
                    "photon-tier",
                )
            )
            and t.is_alive()
        ]
        if not leaked:
            break
        time.sleep(0.02)
    assert not leaked, f"leaked async-upload/serving-flush threads: {leaked}"

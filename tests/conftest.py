"""Test harness configuration.

Mirrors the reference's SparkTestUtils strategy (photon-test-utils
SparkTestUtils.scala:55-75): where the reference spins up a local[*] Spark
cluster so shuffles/broadcasts/treeAggregate run the real code paths with
threads as executors, we force an 8-device virtual CPU mesh so pjit/shard_map
and the XLA collectives run the real multi-chip code paths on one host.

Env vars must be set before jax initializes a backend. Some environments
additionally install a TPU plugin that re-forces `jax_platforms` at interpreter
startup (sitecustomize), so the config is also overridden after import —
that keeps backend init strictly on the virtual CPU mesh.
"""

import os

_PLATFORM = os.environ.get("PHOTON_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _PLATFORM
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", _PLATFORM)

import threading
import time

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 run"
    )
    config.addinivalue_line(
        "markers",
        "chaos: failure-domain tests (fault injection, kill-resume parity); "
        "the serving subset (-m 'chaos and serving') runs inside tier-1",
    )
    config.addinivalue_line(
        "markers",
        "serving: online serving engine tests (bundle/engine/batcher/"
        "lifecycle)",
    )
    config.addinivalue_line(
        "markers",
        "perf: perf-regression guards (engagement + non-dominance contracts "
        "on bench-like shapes); the heavy ones are also slow-marked",
    )
    config.addinivalue_line(
        "markers",
        "multihost: OS-process jax.distributed dryruns (coordinator + "
        "workers over virtual CPU devices); always slow-marked — tier-1 "
        "covers the sharded code paths on the single-process 8-device mesh",
    )
    _assert_fault_sites_registered()


def _assert_fault_sites_registered():
    """Guard: every `fault_point("<site>")` call in the tree must name a
    site registered in utils.faults.KNOWN_SITES — an unregistered site is
    unreachable from PHOTON_FAULTS (plans naming it fail to parse), i.e. a
    fault point no chaos test can ever arm."""
    import re

    from photon_ml_tpu.utils import faults

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pat = re.compile(r"fault_point\(\s*[\"']([A-Za-z0-9_]+)[\"']")
    offenders = []
    roots = [os.path.join(repo, "photon_ml_tpu"), os.path.join(repo, "bench.py")]
    for root in roots:
        files = [root] if os.path.isfile(root) else [
            os.path.join(dirpath, fn)
            for dirpath, _, fns in os.walk(root)
            for fn in fns
            if fn.endswith(".py")
        ]
        for path in files:
            with open(path, encoding="utf-8") as f:
                text = f.read()
            for m in pat.finditer(text):
                if m.group(1) not in faults.KNOWN_SITES:
                    line = text.count("\n", 0, m.start()) + 1
                    offenders.append(f"{path}:{line}: {m.group(1)!r}")
    if offenders:
        import pytest as _pytest

        raise _pytest.UsageError(
            "fault_point() calls with unregistered sites (add them to "
            "photon_ml_tpu.utils.faults.KNOWN_SITES):\n  "
            + "\n  ".join(offenders)
        )


@pytest.fixture
def rng():
    return np.random.default_rng(20260729)


@pytest.fixture(autouse=True)
def _failure_domain_hygiene(monkeypatch):
    """Per-test failure-domain invariants:

    * fault injection armed by one test never leaks into the next (the
      registry is process-global by design — production arms it once via
      env), and an ambient PHOTON_FAULTS/PHOTON_RETRY_* exported in the
      developer's shell never arms injection inside unrelated tests
      (faults.clear() forces an env re-read, so the env must be scrubbed);
    * robustness counters start at zero so tests can assert exact counts;
    * no `photon-async-upload` thread outlives the test that spawned it —
      AsyncUploader workers are per-job and must drain once their job
      completes; a lingering one means a job wedged (or a future leaked)
      and would make later tests' upload behavior order-dependent;
    * no `photon-serving-flush` thread outlives the test — a MicroBatcher's
      flush thread must be joined by engine/batcher close(); a survivor
      means serving work kept running against a torn-down fixture;
    * no `photon-serving-promote` thread outlives the test — a two-tier
      store's promotion worker is short-lived and joined by
      store.close()/bundle.release(); a survivor means promotions kept
      mutating a torn-down store.
    """
    from photon_ml_tpu.utils import faults

    for var in (
        "PHOTON_FAULTS",
        "PHOTON_FAULTS_SEED",
        "PHOTON_RETRY_MAX_ATTEMPTS",
        "PHOTON_RETRY_BASE_DELAY_S",
        "PHOTON_RETRY_MAX_DELAY_S",
        "PHOTON_SOLVE_RETRIES",
    ):
        monkeypatch.delenv(var, raising=False)
    faults.clear()
    faults.reset_counters()
    yield
    faults.clear()
    faults.reset_counters()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        leaked = [
            t
            for t in threading.enumerate()
            if t.name.startswith(
                (
                    "photon-async-upload",
                    "photon-serving-flush",
                    "photon-serving-promote",
                )
            )
            and t.is_alive()
        ]
        if not leaked:
            break
        time.sleep(0.02)
    assert not leaked, f"leaked async-upload/serving-flush threads: {leaked}"

"""Test harness configuration.

Mirrors the reference's SparkTestUtils strategy (photon-test-utils
SparkTestUtils.scala:55-75): where the reference spins up a local[*] Spark
cluster so shuffles/broadcasts/treeAggregate run the real code paths with
threads as executors, we force an 8-device virtual CPU mesh so pjit/shard_map
and the XLA collectives run the real multi-chip code paths on one host.

Env vars must be set before jax initializes a backend. Some environments
additionally install a TPU plugin that re-forces `jax_platforms` at interpreter
startup (sitecustomize), so the config is also overridden after import —
that keeps backend init strictly on the virtual CPU mesh.
"""

import os

_PLATFORM = os.environ.get("PHOTON_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _PLATFORM
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", _PLATFORM)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(20260729)

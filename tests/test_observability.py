"""Observability tests (Timed / PhotonLogger / EventEmitter / summaries)."""

import logging
import os

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.optimize.common import ConvergenceReason, OptResult
from photon_ml_tpu.utils.observability import (
    Event,
    EventEmitter,
    PhotonFailureEvent,
    PhotonLogger,
    Timed,
    TimingRegistry,
    TrainingStartEvent,
    summarize_opt_result,
)


class TestTimed:
    def test_context_and_registry(self, caplog):
        reg = TimingRegistry()
        with caplog.at_level(logging.INFO, logger="photon_ml_tpu"):
            with Timed("sectionA", registry=reg) as t:
                pass
            with Timed("sectionA", registry=reg):
                pass
        assert t.elapsed is not None and t.elapsed >= 0
        assert reg.counts["sectionA"] == 2
        assert "sectionA" in caplog.text
        assert "sectionA" in reg.summary()

    def test_decorator_and_failure_logged(self, caplog):
        @Timed("work")
        def boom():
            raise RuntimeError("x")

        with caplog.at_level(logging.INFO, logger="photon_ml_tpu"):
            with pytest.raises(RuntimeError):
                boom()
        assert "FAILED" in caplog.text


class TestPhotonLogger:
    def test_writes_file_at_level(self, tmp_path):
        path = str(tmp_path / "job.log")
        prev = logging.getLogger("photon_ml_tpu").level
        with PhotonLogger(path, level="INFO"):
            logging.getLogger("photon_ml_tpu.test").info("hello-info")
            logging.getLogger("photon_ml_tpu.test").debug("hello-debug")
        text = open(path).read()
        assert "hello-info" in text
        assert "hello-debug" not in text
        # Package logger level restored after close.
        assert logging.getLogger("photon_ml_tpu").level == prev
        # Unknown levels fall back to INFO instead of aborting the job.
        with PhotonLogger(str(tmp_path / "x.log"), level="NOPE"):
            logging.getLogger("photon_ml_tpu.test").info("still-works")
        assert "still-works" in open(str(tmp_path / "x.log")).read()


class TestEventEmitter:
    def test_dispatch_by_type_and_isolation(self):
        bus = EventEmitter()
        seen = []
        bus.register(lambda e: seen.append(("all", type(e).__name__)))
        bus.register(lambda e: seen.append(("train", e.num_samples)), TrainingStartEvent)
        bus.register(lambda e: 1 / 0, PhotonFailureEvent)  # must not break send
        bus.send(TrainingStartEvent(num_samples=7))
        bus.send(PhotonFailureEvent(error="e"))
        assert ("train", 7) in seen
        assert ("all", "TrainingStartEvent") in seen
        assert ("all", "PhotonFailureEvent") in seen


class TestSummaries:
    def test_vmapped_summary(self):
        result = OptResult(
            coefficients=jnp.zeros((3, 2)),
            loss=jnp.asarray([0.5, 0.2, 0.9]),
            gradient_norm=jnp.asarray([1e-8, 1e-3, 1e-9]),
            iterations=jnp.asarray([4, 100, 7]),
            reason=jnp.asarray([
                int(ConvergenceReason.GRADIENT_CONVERGED),
                int(ConvergenceReason.MAX_ITERATIONS),
                int(ConvergenceReason.GRADIENT_CONVERGED),
            ]),
            loss_history=jnp.zeros((3, 0)),
        )
        s = summarize_opt_result(result, "re-bucket")
        assert "3 problem(s)" in s
        assert "GRADIENT_CONVERGED" in s and "MAX_ITERATIONS" in s
        assert "max 100" in s

"""I/O layer tests: Avro codec round-trips, model store layout + round-trip,
score store, training-data reader.

Counterpart of the reference's Avro/model-processing integ tests
(photon-client src/integTest/.../data/avro/ModelProcessingUtilsIntegTest,
AvroDataReaderIntegTest): write -> read -> exact content equality, directory
layout assertions, and a reader path driven off writer output (golden-file
self-consistency, since the reference's .avro fixtures are not portable).
"""

import json
import os

import numpy as np
import pytest

from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_data import (
    FeatureShardConfig,
    read_game_dataset,
    write_training_examples,
)
from photon_ml_tpu.io.model_store import (
    FixedEffectArtifact,
    GameModelArtifact,
    RandomEffectArtifact,
    load_game_model,
    save_game_model,
)
from photon_ml_tpu.io.score_store import load_scores, save_scores
from photon_ml_tpu.types import TaskType


# ---------------------------------------------------------------------------
# Avro codec


def test_avro_primitives_roundtrip(tmp_path):
    schema = {
        "name": "T",
        "type": "record",
        "fields": [
            {"name": "l", "type": "long"},
            {"name": "i", "type": "int"},
            {"name": "f", "type": "float"},
            {"name": "d", "type": "double"},
            {"name": "s", "type": "string"},
            {"name": "b", "type": "boolean"},
            {"name": "by", "type": "bytes"},
            {"name": "n", "type": ["null", "string"], "default": None},
            {"name": "arr", "type": {"type": "array", "items": "long"}},
            {"name": "m", "type": {"type": "map", "values": "double"}},
        ],
    }
    recs = [
        {
            "l": -(2**40),
            "i": -1,
            "f": 1.5,
            "d": 2.25,
            "s": "héllox",
            "b": True,
            "by": b"\x00\xff",
            "n": None,
            "arr": [0, -1, 2**33],
            "m": {"a": 1.0, "b": -2.5},
        },
        {
            "l": 0,
            "i": 2**30,
            "f": -0.25,
            "d": 1e300,
            "s": "",
            "b": False,
            "by": b"",
            "n": "x",
            "arr": [],
            "m": {},
        },
    ]
    p = str(tmp_path / "t.avro")
    for codec in ("null", "deflate"):
        avro_io.write_container(p, schema, recs, codec=codec)
        rschema, out = avro_io.read_container(p)
        assert rschema == schema
        assert out[0]["l"] == recs[0]["l"]
        assert out[0]["s"] == recs[0]["s"]
        assert out[0]["arr"] == recs[0]["arr"]
        assert out[1]["n"] == "x"
        np.testing.assert_allclose(out[0]["f"], 1.5)
        assert out[0]["by"] == b"\x00\xff"


def test_avro_zigzag_edge_values(tmp_path):
    schema = {"name": "L", "type": "record", "fields": [{"name": "v", "type": "long"}]}
    vals = [0, -1, 1, 63, 64, -64, -65, 2**62, -(2**62)]
    p = str(tmp_path / "l.avro")
    avro_io.write_container(p, schema, [{"v": v} for v in vals])
    _, out = avro_io.read_container(p)
    assert [r["v"] for r in out] == vals


def test_avro_multiblock(tmp_path):
    schema = {"name": "R", "type": "record", "fields": [{"name": "v", "type": "long"}]}
    recs = [{"v": i} for i in range(10_000)]
    p = str(tmp_path / "many.avro")
    avro_io.write_container(p, schema, recs, block_records=256)
    _, out = avro_io.read_container(p)
    assert [r["v"] for r in out] == list(range(10_000))


class TestCorruptBlockQuarantine:
    """With quarantine=True (replay/ingest: row-shaped data),
    iter_container skips-and-counts a corrupt block (resyncing at the next
    sync marker) instead of aborting the file; loud only when EVERY block
    is bad. The DEFAULT stays loud — a model artifact silently missing a
    block of coefficients would serve wrong answers, not degraded ones."""

    SCHEMA = {
        "name": "R",
        "type": "record",
        "fields": [{"name": "v", "type": "long"}],
    }

    def _three_block_file(self, tmp_path):
        p = str(tmp_path / "q.avro")
        avro_io.write_container(
            p, self.SCHEMA, [{"v": i} for i in range(6)], block_records=2
        )
        data = bytearray(open(p, "rb").read())
        _, _, sync, _ = avro_io.read_header(bytes(data), p)
        # Sync occurrences: end-of-header, then one per block.
        marks = []
        at = bytes(data).find(sync)
        while at >= 0:
            marks.append(at)
            at = bytes(data).find(sync, at + 1)
        assert len(marks) == 4  # header + 3 blocks
        return p, data, sync, marks

    def _smash(self, data, lo, hi):
        # 0xFF floods the varint reader (continuation bit always set), so
        # the block fails framing deterministically, whatever the codec.
        data[lo:hi] = b"\xff" * (hi - lo)

    def test_middle_block_quarantined(self, tmp_path):
        from photon_ml_tpu.utils import faults

        p, data, sync, marks = self._three_block_file(tmp_path)
        self._smash(data, marks[1] + len(sync), marks[2])
        open(p, "wb").write(bytes(data))
        recs = [r for _, r in avro_io.iter_container(p, quarantine=True)]
        assert [r["v"] for r in recs] == [0, 1, 4, 5]  # block 2 skipped
        assert faults.COUNTERS.get("quarantined_blocks") == 1

    def test_all_blocks_bad_is_loud(self, tmp_path):
        p, data, sync, marks = self._three_block_file(tmp_path)
        for k in range(3):
            self._smash(data, marks[k] + len(sync), marks[k + 1])
        open(p, "wb").write(bytes(data))
        with pytest.raises(ValueError, match="all 3 block"):
            list(avro_io.iter_container(p, quarantine=True))

    def test_torn_tail_block_quarantined(self, tmp_path):
        from photon_ml_tpu.utils import faults

        p, data, sync, marks = self._three_block_file(tmp_path)
        open(p, "wb").write(bytes(data[: marks[3] - 4]))  # crash mid-block 3
        recs = [r for _, r in avro_io.iter_container(p, quarantine=True)]
        assert [r["v"] for r in recs] == [0, 1, 2, 3]
        assert faults.COUNTERS.get("quarantined_blocks") == 1

    def test_clean_file_counts_nothing(self, tmp_path):
        from photon_ml_tpu.utils import faults

        p, _, _, _ = self._three_block_file(tmp_path)
        recs = [r for _, r in avro_io.iter_container(p, quarantine=True)]
        assert [r["v"] for r in recs] == list(range(6))
        assert faults.COUNTERS.get("quarantined_blocks") == 0

    def test_default_read_stays_loud(self, tmp_path):
        """Completeness-critical consumers (model stores, checkpoints,
        scores) must still get a hard error on the FIRST corrupt block —
        quarantine is opt-in for row-shaped reads only."""
        from photon_ml_tpu.utils import faults

        p, data, sync, marks = self._three_block_file(tmp_path)
        self._smash(data, marks[1] + len(sync), marks[2])
        open(p, "wb").write(bytes(data))
        with pytest.raises(ValueError, match="corrupt block"):
            list(avro_io.iter_container(p))
        with pytest.raises(ValueError, match="corrupt block"):
            avro_io.read_container(p)
        assert faults.COUNTERS.get("quarantined_blocks") == 0


def test_bayesian_model_record_roundtrip(tmp_path):
    rec = {
        "modelId": "fixed-effect",
        "modelClass": "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel",
        "means": [{"name": "f1", "term": "t", "value": 0.5}],
        "variances": None,
        "lossFunction": None,
    }
    p = str(tmp_path / "m.avro")
    avro_io.write_container(p, schemas.BAYESIAN_LINEAR_MODEL, [rec])
    _, out = avro_io.read_container(p)
    assert out[0]["modelId"] == "fixed-effect"
    assert out[0]["means"][0]["value"] == 0.5
    assert out[0]["variances"] is None


# ---------------------------------------------------------------------------
# Model store


def _index_map(d):
    return IndexMap.from_feature_names(
        [feature_key(f"f{i}", "t") for i in range(d - 1)], add_intercept=True
    )


def test_model_store_roundtrip(tmp_path, rng):
    d = 6
    imap = _index_map(d)
    fe = FixedEffectArtifact(
        "globalShard",
        rng.normal(size=d),
        np.abs(rng.normal(size=d)),
    )
    ents = [f"user{i}" for i in range(5)]
    re = RandomEffectArtifact(
        "userId", "globalShard", ents, rng.normal(size=(5, d)), None
    )
    art = GameModelArtifact(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinates={"global": fe, "per-user": re},
        opt_configs={"global": {"regularizationWeight": 1.0}},
    )
    out = str(tmp_path / "model")
    save_game_model(out, art, {"globalShard": imap})

    # Reference directory layout (ModelProcessingUtils/AvroConstants).
    assert os.path.exists(os.path.join(out, "model-metadata.json"))
    assert os.path.exists(os.path.join(out, "fixed-effect", "global", "id-info"))
    assert os.path.exists(
        os.path.join(out, "fixed-effect", "global", "coefficients", "part-00000.avro")
    )
    assert os.path.isdir(os.path.join(out, "random-effect", "per-user", "coefficients"))
    meta = json.load(open(os.path.join(out, "model-metadata.json")))
    assert meta["modelType"] == "LOGISTIC_REGRESSION"

    loaded = load_game_model(out, {"globalShard": imap})
    assert loaded.task == TaskType.LOGISTIC_REGRESSION
    lfe = loaded.coordinates["global"]
    np.testing.assert_allclose(lfe.means, fe.means, rtol=1e-12)
    np.testing.assert_allclose(lfe.variances, fe.variances, rtol=1e-12)
    lre = loaded.coordinates["per-user"]
    assert lre.random_effect_type == "userId"
    assert sorted(lre.entity_ids) == sorted(ents)
    order = [lre.entity_ids.index(e) for e in ents]
    # RE matrices load in device precision (float32) by default.
    np.testing.assert_allclose(lre.means[order], re.means, rtol=1e-6)
    loaded64 = load_game_model(out, {"globalShard": imap}, dtype=np.float64)
    np.testing.assert_allclose(
        loaded64.coordinates["per-user"].means[order], re.means, rtol=1e-12
    )


def test_model_store_sparsity_threshold(tmp_path):
    imap = _index_map(4)
    means = np.array([1.0, 1e-9, -2.0, 0.0])
    art = GameModelArtifact(
        TaskType.LINEAR_REGRESSION,
        {"g": FixedEffectArtifact("s", means)},
    )
    out = str(tmp_path / "m")
    save_game_model(out, art, {"s": imap}, sparsity_threshold=1e-6)
    loaded = load_game_model(out, {"s": imap})
    got = loaded.coordinates["g"].means
    np.testing.assert_allclose(got, [1.0, 0.0, -2.0, 0.0])


def test_model_store_partial_load(tmp_path, rng):
    imap = _index_map(3)
    art = GameModelArtifact(
        TaskType.LINEAR_REGRESSION,
        {
            "a": FixedEffectArtifact("s", rng.normal(size=3)),
            "b": FixedEffectArtifact("s", rng.normal(size=3)),
        },
    )
    out = str(tmp_path / "m")
    save_game_model(out, art, {"s": imap})
    loaded = load_game_model(out, {"s": imap}, coordinates_to_load=["a"])
    assert set(loaded.coordinates) == {"a"}


def test_random_effect_file_limit(tmp_path, rng):
    imap = _index_map(3)
    ents = [f"e{i}" for i in range(10)]
    art = GameModelArtifact(
        TaskType.LINEAR_REGRESSION,
        {"r": RandomEffectArtifact("uid", "s", ents, rng.normal(size=(10, 3)))},
    )
    out = str(tmp_path / "m")
    save_game_model(out, art, {"s": imap}, random_effect_file_limit=3)
    parts = os.listdir(os.path.join(out, "random-effect", "r", "coefficients"))
    assert len(parts) == 3
    loaded = load_game_model(out, {"s": imap})
    assert len(loaded.coordinates["r"].entity_ids) == 10


# ---------------------------------------------------------------------------
# Scores


def test_score_store_roundtrip(tmp_path, rng):
    n = 1000
    scores = rng.normal(size=n)
    labels = (rng.uniform(size=n) > 0.5).astype(np.float64)
    out = str(tmp_path / "scores")
    count = save_scores(
        out,
        scores,
        "my-model",
        uids=[f"u{i}" for i in range(n)],
        labels=labels,
        id_tags={"userId": [f"user{i % 7}" for i in range(n)]},
        records_per_file=300,
    )
    assert count == n
    items = load_scores(out)
    assert len(items) == n
    by_uid = {it.uid: it for it in items}
    np.testing.assert_allclose(by_uid["u3"].prediction_score, scores[3])
    assert by_uid["u3"].ids["userId"] == "user3"


def test_score_store_roundtrip_missing_fields(tmp_path, rng):
    """ScoredItems with every optional field absent (uid/label/weight/ids)
    must round-trip — the schema's nullable unions, not just the fully
    populated shape the test above exercises."""
    n = 17
    scores = rng.normal(size=n)
    out = str(tmp_path / "scores")
    count = save_scores(out, scores, "bare-model", chunk_size=5)
    assert count == n
    items = load_scores(out)
    assert len(items) == n
    for i, it in enumerate(items):
        np.testing.assert_allclose(it.prediction_score, scores[i])
        assert it.uid is None
        assert it.label is None
        assert it.weight is None
        assert it.ids == {}


def test_score_store_chunked_matches_whole(tmp_path, rng):
    """The fixed-size-chunk record stream is a pure refactor: chunked and
    chunk-size-1 writes produce identical records, device (jax) columns
    included."""
    import jax.numpy as jnp

    from photon_ml_tpu.io.score_store import score_records

    n = 23
    scores = jnp.asarray(rng.normal(size=n).astype(np.float32))
    labels = rng.uniform(size=n)
    uids = np.asarray([f"u{i}" for i in range(n)])
    a = list(
        score_records(scores, "m", uids=uids, labels=labels, chunk_size=7)
    )
    b = list(
        score_records(scores, "m", uids=uids, labels=labels, chunk_size=1)
    )
    assert a == b
    assert len(a) == n
    assert a[0]["uid"] == "u0" and a[0]["label"] == labels[0]
    # Degenerate chunk sizes clamp to 1 instead of silently yielding nothing.
    assert len(list(score_records(scores, "m", chunk_size=0))) == n
    assert len(list(score_records(scores, "m", chunk_size=-3))) == n


# ---------------------------------------------------------------------------
# Training data reader


def test_training_data_roundtrip(tmp_path, rng):
    n, d = 50, 8
    keys = [feature_key(f"f{j}", "") for j in range(d)]
    feats = []
    labels = []
    users = []
    for i in range(n):
        nnz = rng.integers(1, d)
        cols = rng.choice(d, size=nnz, replace=False)
        feats.append([(keys[c], float(rng.normal())) for c in cols])
        labels.append(float(rng.integers(0, 2)))
        users.append(f"user{i % 5}")
    p = str(tmp_path / "train.avro")
    write_training_examples(
        p, feats, labels, uids=[str(i) for i in range(n)], id_tags={"userId": users}
    )

    ds, imaps = read_game_dataset(
        p,
        {"global": FeatureShardConfig(feature_bags=("features",), has_intercept=True)},
        id_tag_fields=["userId"],
        response_field="label",
    )
    assert ds.num_samples == n
    assert list(ds.id_tags["userId"]) == users
    imap = imaps["global"]
    assert imap.intercept_index is not None
    # Spot-check: densify row 0 and compare against written features.
    dense = np.asarray(ds.shards["global"].to_dense())
    for key, value in feats[0]:
        np.testing.assert_allclose(dense[0, imap.get_index(key)], value, rtol=1e-6)
    np.testing.assert_allclose(dense[:, imap.intercept_index], 1.0)
    np.testing.assert_allclose(np.asarray(ds.labels), labels)


def test_reader_with_fixed_index_map_drops_unseen(tmp_path):
    p = str(tmp_path / "t.avro")
    write_training_examples(p, [[("known", 1.0), ("unknown", 2.0)]], [1.0])
    imap = IndexMap.from_feature_names(["known"], add_intercept=False)
    ds, maps = read_game_dataset(
        p,
        {"g": FeatureShardConfig(has_intercept=False)},
        index_maps={"g": imap},
        response_field="label",
    )
    dense = np.asarray(ds.shards["g"].to_dense())
    assert dense.shape == (1, 1)
    np.testing.assert_allclose(dense[0, 0], 1.0)


def test_reader_rejects_intercept_shard_with_interceptless_index_map(tmp_path):
    """A prebuilt index map without the intercept entry must fail loudly when
    the shard is configured has_intercept=True — silently training without a
    bias term is the failure mode this guards against."""
    p = str(tmp_path / "t.avro")
    write_training_examples(p, [[("f1", 1.0)]], [1.0])
    imap = IndexMap.from_feature_names(["f1"], add_intercept=False)
    with pytest.raises(ValueError, match="intercept"):
        read_game_dataset(
            p,
            {"g": FeatureShardConfig(has_intercept=True)},
            index_maps={"g": imap},
            response_field="label",
        )


def test_model_store_empty_part_file_keeps_variances(tmp_path, rng):
    """Spark writes zero-record part files when partitions > entities; they
    must not drop the coordinate's variances (ModelProcessingUtils layout)."""
    from photon_ml_tpu.io import avro as avro_io
    from photon_ml_tpu.io import schemas

    d = 4
    imap = _index_map(d)
    re = RandomEffectArtifact(
        "userId",
        "globalShard",
        ["u0", "u1"],
        rng.normal(size=(2, d)),
        np.abs(rng.normal(size=(2, d))),
    )
    art = GameModelArtifact(
        task=TaskType.LOGISTIC_REGRESSION, coordinates={"per-user": re}
    )
    out = str(tmp_path / "model")
    save_game_model(out, art, {"globalShard": imap})
    avro_io.write_container(
        os.path.join(out, "random-effect", "per-user", "coefficients", "part-00001.avro"),
        schemas.BAYESIAN_LINEAR_MODEL,
        [],
    )
    loaded = load_game_model(out, {"globalShard": imap})
    lre = loaded.coordinates["per-user"]
    assert len(lre.entity_ids) == 2
    assert lre.variances is not None


class TestMultihostIngest:
    """File-sliced multi-host ingest: each process reads a deterministic
    round-robin slice; shared index maps keep feature ids consistent."""

    def _write(self, tmp_path, n_files=4, rows=50):
        import photon_ml_tpu.io.avro_data as ad

        rng = np.random.default_rng(5)
        d = os.path.join(str(tmp_path), "train")
        os.makedirs(d, exist_ok=True)
        all_labels = []
        for fi in range(n_files):
            feats = [
                [(f"f{j}", float(rng.normal())) for j in rng.choice(20, size=3, replace=False)]
                for _ in range(rows)
            ]
            labels = (rng.uniform(size=rows) > 0.5).astype(float)
            all_labels.append(labels)
            ad.write_training_examples(
                os.path.join(d, f"part-{fi}.avro"), feats, labels
            )
        return d, all_labels

    def test_slices_partition_and_union(self, tmp_path):
        import photon_ml_tpu.io.avro_data as ad
        from photon_ml_tpu.data.index_map import IndexMap

        d, all_labels = self._write(tmp_path)
        cfgs = {"g": ad.FeatureShardConfig(("features",), True)}
        imap = {"g": IndexMap.from_feature_names(
            {f"f{i}" for i in range(20)}, add_intercept=True)}
        parts = []
        for pi in range(2):
            ds, _ = ad.read_game_dataset(
                d, cfgs, index_maps=imap, process_index=pi, process_count=2
            )
            parts.append(np.asarray(ds.labels))
        # Byte-balanced assignment (greedy LPT): ~equal-size files split
        # 2/2, each slice is a concat of whole files in name order, and the
        # two slices partition the file set.
        import itertools

        assert len(parts[0]) == len(parts[1]) == 2 * len(all_labels[0])
        assigned = []
        for part in parts:
            match = next(
                combo
                for combo in itertools.combinations(range(len(all_labels)), 2)
                if np.array_equal(
                    part,
                    np.concatenate([all_labels[i] for i in combo]).astype(
                        np.float32
                    ),
                )
            )
            assigned.append(set(match))
        assert assigned[0] | assigned[1] == {0, 1, 2, 3}
        assert not (assigned[0] & assigned[1])

    def test_requires_shared_index_maps(self, tmp_path):
        import photon_ml_tpu.io.avro_data as ad

        d, _ = self._write(tmp_path, n_files=2)
        cfgs = {"g": ad.FeatureShardConfig(("features",), True)}
        with pytest.raises(ValueError, match="shared"):
            ad.read_game_dataset(d, cfgs, process_index=0, process_count=2)

    def test_too_few_files_errors(self, tmp_path):
        import photon_ml_tpu.io.avro_data as ad
        from photon_ml_tpu.data.index_map import IndexMap

        d, _ = self._write(tmp_path, n_files=1)
        cfgs = {"g": ad.FeatureShardConfig(("features",), True)}
        imap = {"g": IndexMap.from_feature_names({"f0"}, add_intercept=True)}
        with pytest.raises(ValueError, match="at least one container file"):
            ad.read_game_dataset(
                d, cfgs, index_maps=imap, process_index=1, process_count=2
            )

"""Adaptive runtime planner tests (ISSUE 14, photon_ml_tpu/planner/).

The load-bearing contracts:

* NO plan installed (or PHOTON_PLAN=0) == the pre-planner tree, bit for
  bit: every consulting site returns its built-in default.
* Precedence: explicit PHOTON_* knob > plan decision > default, with the
  knob override recorded as `source: "knob"` in the plan block.
* A profile from a mismatched device topology refuses LOUDLY, naming the
  field (a profile written on an 8-vdev mesh must not plan a 1-device
  run); an r06-era profile (no `plan` block) still loads for the
  planner's cold-start path.
* A planner-on fit from a matching-topology profile is bitwise-equal to
  the default fit, and its plan block round-trips through
  write_profile/read_profile.
"""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu import planner
from photon_ml_tpu.data.game_dataset import (
    FixedEffectDataConfig,
    GameDataset,
    RandomEffectDataConfig,
)
from photon_ml_tpu.estimators.game_estimator import GameEstimator
from photon_ml_tpu.optimize.config import (
    L2,
    CoordinateOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import telemetry
from photon_ml_tpu.utils.contracts import (
    PLAN_BLOCK_KEYS,
    PLAN_DECISION_KEYS,
)


# ---------------------------------------------------------------- fixtures


def _fit_profile(**overrides):
    """A synthetic fit profile shaped exactly like est.run_profile()'s
    output on THIS machine's topology (so plan_from_profile accepts it)."""
    profile = {
        "kind": "fit",
        "wall_s": 10.0,
        "stages": {
            "re_build": 1.0,
            "projector": 0.5,
            "stats": 0.1,
            "pack": 0.5,
            "upload": 0.2,
            "compile": 0.5,
            "other": 0.2,
            "prepare_s": 3.0,
            "solve_s": 7.0,
        },
        "dispatch": {
            "pack_path": "native",
            "re_path": "host",
            "sharding": {"entity_sharded": False, "axis_size": 1},
            "pipeline": False,
            "layout": "grouped",
        },
        "bucket_shapes": {"per-member": [[4, 8], [2, 16]]},
        "device_topology": telemetry.device_topology(),
        "roofline": {"hbm_gb_per_s": None},
        "metrics": {},
        "fit_timing": {
            "pack_device_s": 0.0,
            "pack_host_s": 0.5,
            "pack_path": "native",
            "re_device_s": 0.0,
            "re_host_s": 1.0,
            "re_path": "host",
            "robustness": {"collective_retries": 0, "watchdog_trips": 0},
        },
        "ingest": {},
    }
    profile.update(overrides)
    return profile


def _serve_profile(**overrides):
    profile = {
        "kind": "serve",
        "wall_s": 5.0,
        "stages": {"warmup_s": 1.0, "replay_s": 4.0},
        "dispatch": {"max_batch": 256, "max_wait_ms": 2.0, "sharding": None},
        "bucket_shapes": {"engine_buckets": [1, 2, 4, 8]},
        "device_topology": telemetry.device_topology(),
        "roofline": {"hbm_gb_per_s": None},
        "metrics": {},
        "serving": {"p50_ms": 4.0, "batch_size_p95": 24},
    }
    profile.update(overrides)
    return profile


_TRUTH = np.random.default_rng(7)
_W = _TRUTH.normal(size=4)
_B = _TRUTH.normal(size=(12, 3))


def _data(seed, n=300):
    rng = np.random.default_rng(seed)
    Xf = rng.normal(size=(n, 4)).astype(np.float32)
    Xe = rng.normal(size=(n, 3)).astype(np.float32)
    ent = rng.integers(0, 12, size=n)
    margins = Xf @ _W + np.einsum("nd,nd->n", Xe, _B[ent])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margins))).astype(np.float32)
    return GameDataset.build(
        {"g": jnp.asarray(Xf), "e": jnp.asarray(Xe)},
        y,
        id_tags={"memberId": ent},
    )


def _estimator():
    return GameEstimator(
        TaskType.LOGISTIC_REGRESSION,
        {
            "fixed": FixedEffectDataConfig("g"),
            "per-member": RandomEffectDataConfig("memberId", "e", min_bucket=4),
        },
        seed=3,
    )


_CFG = {
    "fixed": CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=15),
        regularization=L2,
        reg_weight=1.0,
    ),
    "per-member": CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=10),
        regularization=L2,
        reg_weight=10.0,
    ),
}


# ---------------------------------------------------------------- defaults


class TestDefaults:
    def test_no_plan_returns_pre_planner_defaults(self):
        assert planner.current_plan() is None
        assert planner.planned_value("prefetch_depth") == 1
        assert planner.planned_value("scan_fusion_max") == 0
        assert planner.planned_value("ingest_chunk_rows") == 262_144
        assert planner.planned_value("serving_max_batch") == 256
        assert planner.planned_value("serving_max_wait_ms") == 2.0
        assert planner.planned_value("pack_routing") == "auto"
        assert planner.planned_value("sparse_layout") == "auto"

    def test_unknown_quantity_raises(self):
        with pytest.raises(KeyError):
            planner.planned_value("no_such_quantity")

    def test_inactive_block_shape(self):
        block = planner.plan_block()
        assert tuple(block) == PLAN_BLOCK_KEYS
        assert block["active"] is False
        assert block["source"] == "off"
        assert block["decisions"] == []

    def test_photon_plan_off_blocks_everything(self, monkeypatch, tmp_path):
        path = str(tmp_path / "profile.json")
        telemetry.write_profile(path, _fit_profile())
        monkeypatch.setenv("PHOTON_PLAN", "0")
        monkeypatch.setenv("PHOTON_PLAN_PROFILE", path)
        assert planner.ensure_ambient_plan() is None
        assert planner.current_plan() is None


# ------------------------------------------------------------------- rules


class TestProfileRules:
    def test_fit_rules_adopt_measured_run(self):
        plan = planner.plan_from_profile(_fit_profile())
        d = plan.decisions
        assert d["pack_routing"].value == "host"
        assert d["pack_routing"].source == "profile"
        assert d["assembly_routing"].value == "host"
        assert d["sparse_layout"].value == "grouped"
        assert d["prefetch_depth"].value == 1  # pipeline off in evidence
        assert d["ingest_chunk_rows"].value == 262_144  # no streaming data
        assert d["scan_fusion_max"].value == 0  # clean robustness
        assert d["re_bucket_shapes"].value == {"per-member": [[4, 8], [2, 16]]}
        # Every decision is a full audit record.
        for dec in d.values():
            rec = dec.as_dict()
            assert tuple(rec) == PLAN_DECISION_KEYS
            assert isinstance(rec["evidence"], dict)

    def test_prefetch_deepens_on_pipelined_fit_with_host_cores(
        self, monkeypatch
    ):
        """Depth 2 needs BOTH a pipelined fit and live host cores to feed
        concurrent uploads (the upload-stage wall is deliberately not the
        evidence: it cannot distinguish hidden from un-hidden work)."""
        from photon_ml_tpu.data import pipeline as pipeline_mod

        profile = _fit_profile()
        profile["dispatch"]["pipeline"] = True
        monkeypatch.setattr(
            pipeline_mod, "effective_host_parallelism", lambda: 8
        )
        plan = planner.plan_from_profile(profile)
        dec = plan.decisions["prefetch_depth"]
        assert dec.value == 2
        assert dec.evidence == {"pipeline": True, "host_parallelism": 8}
        # Unpipelined fits stay 1-deep regardless of cores.
        profile["dispatch"]["pipeline"] = False
        assert (
            planner.plan_from_profile(profile)
            .decisions["prefetch_depth"].value
            == 1
        )

    def test_ingest_skew_moves_chunk_rows_bounded(self):
        decode_bound = _fit_profile(
            ingest={"streaming": True, "decode": 8.0, "assemble": 1.0}
        )
        assert (
            planner.plan_from_profile(decode_bound)
            .decisions["ingest_chunk_rows"].value
            == 131_072
        )
        assemble_bound = _fit_profile(
            ingest={"streaming": True, "decode": 1.0, "assemble": 8.0}
        )
        assert (
            planner.plan_from_profile(assemble_bound)
            .decisions["ingest_chunk_rows"].value
            == 524_288
        )

    def test_flaky_collectives_cap_scan_fusion(self):
        profile = _fit_profile()
        profile["fit_timing"]["robustness"] = {
            "collective_retries": 2,
            "watchdog_trips": 0,
        }
        plan = planner.plan_from_profile(profile)
        assert plan.decisions["scan_fusion_max"].value == 8

    def test_serve_rules_shrink_bucket_and_wait(self):
        plan = planner.plan_from_profile(_serve_profile())
        assert plan.decisions["serving_max_batch"].value == 32  # p95=24 -> 32
        assert plan.decisions["serving_max_wait_ms"].value == 2.0  # p50/2=2.0
        fast = _serve_profile(
            serving={"p50_ms": 1.0, "batch_size_p95": 300}
        )
        plan2 = planner.plan_from_profile(fast)
        assert plan2.decisions["serving_max_batch"].value == 256  # capped
        assert plan2.decisions["serving_max_wait_ms"].value == 0.5

    def test_serve_rules_are_not_a_downward_ratchet(self):
        """Re-planning from a PLANNED run's profile must be able to
        recover: saturated batch evidence (p95 at the prior shrunk
        ceiling) plans back up to the default, and the wait derives from
        each round's fresh p50, not min'd against the prior wait."""
        shrunk = _serve_profile(
            dispatch={"max_batch": 16, "max_wait_ms": 0.5, "sharding": None},
            serving={"p50_ms": 6.0, "batch_size_p95": 16},  # saturated
        )
        plan = planner.plan_from_profile(shrunk)
        assert plan.decisions["serving_max_batch"].value == 256  # recovered
        assert plan.decisions["serving_max_wait_ms"].value == 2.0  # p50/2=3
        # Unsaturated evidence on a shrunk run still plans the evidence.
        light = _serve_profile(
            dispatch={"max_batch": 64, "max_wait_ms": 0.5, "sharding": None},
            serving={"p50_ms": 6.0, "batch_size_p95": 9},
        )
        assert (
            planner.plan_from_profile(light)
            .decisions["serving_max_batch"].value
            == 16
        )
        # An operator-validated tiny ceiling with genuinely tiny traffic
        # is NOT saturation (saturation compares p95 itself, not the
        # 8-floored ladder value): the plan keeps the small bucket set.
        tiny = _serve_profile(
            dispatch={"max_batch": 8, "max_wait_ms": 1.0, "sharding": None},
            serving={"p50_ms": 6.0, "batch_size_p95": 2},
        )
        assert (
            planner.plan_from_profile(tiny)
            .decisions["serving_max_batch"].value
            == 8
        )
        # A LARGER operator-validated ceiling with unsaturated p95 above
        # the built-in default must not clamp DOWN below demonstrated
        # traffic: p95=300 under a 512 ceiling plans 512, not 256.
        big = _serve_profile(
            dispatch={"max_batch": 512, "max_wait_ms": 2.0, "sharding": None},
            serving={"p50_ms": 6.0, "batch_size_p95": 300},
        )
        assert (
            planner.plan_from_profile(big)
            .decisions["serving_max_batch"].value
            == 512
        )

    def test_larger_validated_wait_raises_the_clamp_ceiling(self):
        """A recorded wait ABOVE the built-in default raises the
        evidence clamp's ceiling (the bucket-ceiling discipline): p50
        evidence can tighten within it but never ignores the bigger
        budget the profiled run validated."""
        big_wait = _serve_profile(
            dispatch={"max_batch": 256, "max_wait_ms": 10.0, "sharding": None},
            serving={"p50_ms": 30.0, "batch_size_p95": 24},
        )
        assert (
            planner.plan_from_profile(big_wait)
            .decisions["serving_max_wait_ms"].value
            == 10.0  # min(upper=10, p50/2=15)
        )
        tighter = _serve_profile(
            dispatch={"max_batch": 256, "max_wait_ms": 10.0, "sharding": None},
            serving={"p50_ms": 8.0, "batch_size_p95": 24},
        )
        assert (
            planner.plan_from_profile(tighter)
            .decisions["serving_max_wait_ms"].value
            == 4.0  # evidence tightens inside the validated ceiling
        )

    def test_zero_wait_config_survives_replanning(self):
        """A recorded max_wait_ms of 0.0 (immediate flush) is adopted,
        not silently replanned to the default by a falsy-zero `or`."""
        zero_wait = _serve_profile(
            dispatch={"max_batch": 256, "max_wait_ms": 0.0, "sharding": None},
            serving={},  # no p50 evidence -> adopt the recorded wait
        )
        assert (
            planner.plan_from_profile(zero_wait)
            .decisions["serving_max_wait_ms"].value
            == 0.0
        )

    def test_plan_block_overrides_resource_as_knob(self, tmp_path):
        """Explicit CLI flags re-source their decisions to 'knob' in the
        recorded block — the audit must show what actually served."""
        planner.install_plan(planner.plan_from_profile(_serve_profile()))
        block = planner.plan_block(
            overrides={"serving_max_wait_ms": 5.0}
        )
        by_name = {d["decision"]: d for d in block["decisions"]}
        assert by_name["serving_max_wait_ms"]["value"] == 5.0
        assert by_name["serving_max_wait_ms"]["source"] == "knob"
        assert by_name["serving_max_wait_ms"]["evidence"]["explicit_override"]
        assert by_name["serving_max_batch"]["source"] == "profile"
        # A flag that HAPPENS to equal the plan's choice is still pinned
        # by the operator — the audit must say "knob" regardless.
        planned = by_name["serving_max_batch"]["value"]
        same = planner.plan_block(overrides={"serving_max_batch": planned})
        by_name2 = {d["decision"]: d for d in same["decisions"]}
        assert by_name2["serving_max_batch"]["source"] == "knob"
        assert by_name2["serving_max_batch"]["value"] == planned
        # The installed plan itself is untouched (the overlay is a copy).
        assert (
            planner.current_plan()
            .decisions["serving_max_wait_ms"].source
            == "profile"
        )

    def test_calibration_plan_matches_auto_on_this_backend(self):
        plan = planner.plan_from_calibration()
        assert plan.source == "calibration"
        # On the CPU test backend the routing rules must equal the auto
        # policies (bitwise parity of the calibration cold start).
        assert plan.decisions["pack_routing"].value == "host"
        assert plan.decisions["assembly_routing"].value == "host"


# -------------------------------------------------------------- precedence


class TestPrecedence:
    def test_knob_beats_plan_at_consult_time(self, monkeypatch):
        planner.install_plan(planner.plan_from_profile(_fit_profile()))
        monkeypatch.setenv("PHOTON_STREAM_CHUNK_ROWS", "777")
        assert planner.planned_value("ingest_chunk_rows") == 777
        monkeypatch.setenv("PHOTON_DEVICE_PACK", "1")
        assert planner.planned_value("pack_routing") == "device"

    def test_knob_recorded_as_source_knob_at_build_time(self, monkeypatch):
        monkeypatch.setenv("PHOTON_STREAM_CHUNK_ROWS", "777")
        plan = planner.plan_from_profile(_fit_profile())
        dec = plan.decisions["ingest_chunk_rows"]
        assert dec.value == 777
        assert dec.source == "knob"
        assert dec.evidence["knob"] == "PHOTON_STREAM_CHUNK_ROWS"
        assert dec.fallback == 262_144

    def test_plan_beats_default(self):
        planner.install_plan(planner.plan_from_profile(_fit_profile()))
        assert planner.planned_value("sparse_layout") == "grouped"
        assert planner.planned_value("pack_routing") == "host"


# ------------------------------------------------------------- portability


class TestProfilePortability:
    def test_mismatched_device_count_refuses_naming_field(self):
        """A profile written on a bigger mesh (e.g. 8 vdevs) loudly
        refuses when planned onto a run with fewer devices — naming the
        mismatching topology field. The test harness itself runs 8
        forced host devices, so the mismatch is driven the other way:
        the profile claims a mesh this run does not have."""
        profile = _fit_profile()
        profile["device_topology"] = dict(profile["device_topology"])
        claimed = int(profile["device_topology"]["device_count"]) * 8
        profile["device_topology"]["device_count"] = claimed
        with pytest.raises(planner.PlanTopologyError) as exc:
            planner.plan_from_profile(profile)
        assert "device_count" in str(exc.value)
        assert str(claimed) in str(exc.value)

    def test_one_device_profile_refuses_on_this_mesh(self):
        """The satellite direction proper: an explicit current-topology
        override proves a 1-device run refuses an 8-vdev profile."""
        profile = _fit_profile()
        profile["device_topology"] = dict(
            profile["device_topology"], device_count=8
        )
        one_dev = dict(profile["device_topology"], device_count=1)
        with pytest.raises(planner.PlanTopologyError) as exc:
            planner.check_topology(
                profile["device_topology"], current=one_dev
            )
        assert "device_count" in str(exc.value)

    def test_platform_mismatch_names_platform(self):
        profile = _fit_profile()
        profile["device_topology"] = dict(profile["device_topology"])
        profile["device_topology"]["platform"] = "tpu-v999"
        with pytest.raises(planner.PlanTopologyError) as exc:
            planner.plan_from_profile(profile)
        assert "platform" in str(exc.value)

    def test_r06_era_profile_without_plan_block_loads(self, tmp_path):
        """read_profile of a pre-planner profile (no `plan` key) still
        loads, and the planner cold-starts from it."""
        profile = _fit_profile()
        assert "plan" not in profile  # the r06-era shape
        path = str(tmp_path / "r06.json")
        telemetry.write_profile(path, profile)
        back = telemetry.read_profile(path, kind="fit")
        assert "plan" not in back
        plan = planner.plan_from_profile(back, path)
        assert plan.profile_path == path
        assert plan.decisions  # cold start produced a real plan

    def test_ensure_ambient_plan_from_env_profile(self, monkeypatch, tmp_path):
        path = str(tmp_path / "profile.json")
        telemetry.write_profile(path, _fit_profile())
        monkeypatch.setenv("PHOTON_PLAN_PROFILE", path)
        plan = planner.ensure_ambient_plan()
        assert plan is not None and plan.profile_path == path
        # Idempotent: a second call returns the installed plan.
        assert planner.ensure_ambient_plan() is plan

    def test_env_profile_path_bootstraps_when_missing(
        self, monkeypatch, tmp_path
    ):
        """PHOTON_PLAN_PROFILE is a cache handle: pointing it at a
        not-yet-written path (the first bench round) runs unplanned
        instead of crashing — but an explicit --profile stays loud."""
        missing = str(tmp_path / "not_written_yet.json")
        monkeypatch.setenv("PHOTON_PLAN_PROFILE", missing)
        assert planner.ensure_ambient_plan() is None
        assert planner.current_plan() is None
        with pytest.raises(FileNotFoundError):
            planner.ensure_ambient_plan(missing)  # the explicit argument

    def test_plan_suppression_scopes_everything(self, monkeypatch, tmp_path):
        path = str(tmp_path / "profile.json")
        telemetry.write_profile(path, _fit_profile())
        planner.install_plan(
            planner.plan_from_profile(telemetry.read_profile(path), path)
        )
        monkeypatch.setenv("PHOTON_PLAN_PROFILE", path)
        with planner.plan_suppressed():
            # Consults fall back to defaults, the block reads inactive,
            # and the gate installs nothing.
            assert planner.planned_value("pack_routing") == "auto"
            assert planner.plan_block()["active"] is False
            planner.uninstall_plan()
            assert planner.ensure_ambient_plan() is None
            # Explicit per-quantity knobs still win (operator intent).
            monkeypatch.setenv("PHOTON_DEVICE_PACK", "1")
            assert planner.planned_value("pack_routing") == "device"

    def test_estimator_owns_its_env_installed_plan(
        self, monkeypatch, tmp_path
    ):
        """A plan the FIT installed from the env is uninstalled when the
        fit returns — a later fit under a changed env must never reuse
        it — while the fit's own plan block still records it active."""
        est_a = _estimator()
        est_a.fit(_data(5), None, [_CFG])
        path = str(tmp_path / "profile.json")
        telemetry.write_profile(path, est_a.run_profile())
        monkeypatch.setenv("PHOTON_PLAN_PROFILE", path)
        est_b = _estimator()
        est_b.fit(_data(5), None, [_CFG])
        assert est_b.fit_timing["plan"]["active"] is True
        assert planner.current_plan() is None  # released on exit


# ------------------------------------------------------- end-to-end parity


class TestFitParity:
    def test_planned_fit_bitwise_equals_default_and_records_block(
        self, tmp_path
    ):
        est_a = _estimator()
        res_a = est_a.fit(_data(0), None, [_CFG])[0]
        block_a = est_a.fit_timing["plan"]
        assert block_a["active"] is False

        path = str(tmp_path / "profile.json")
        telemetry.write_profile(path, est_a.run_profile())
        plan = planner.plan_from_profile(
            telemetry.read_profile(path, kind="fit"), path
        )
        planner.install_plan(plan)
        est_b = _estimator()
        res_b = est_b.fit(_data(0), None, [_CFG])[0]
        block_b = est_b.fit_timing["plan"]
        assert block_b["active"] is True
        assert block_b["source"] == "profile"
        assert block_b["profile"] == path
        assert {d["decision"] for d in block_b["decisions"]} >= {
            "assembly_routing",
            "prefetch_depth",
            "re_bucket_shapes",
            "scan_fusion_max",
        }
        np.testing.assert_array_equal(
            np.asarray(res_a.model["fixed"].coefficients.means),
            np.asarray(res_b.model["fixed"].coefficients.means),
        )
        np.testing.assert_array_equal(
            np.asarray(res_a.model["per-member"].coefficients_matrix),
            np.asarray(res_b.model["per-member"].coefficients_matrix),
        )
        # The planned run's profile carries its plan block and
        # round-trips through the loud contract unchanged.
        path_b = str(tmp_path / "planned.json")
        telemetry.write_profile(path_b, est_b.run_profile())
        assert telemetry.read_profile(path_b, kind="fit")["plan"] == block_b

    def test_scan_fusion_cap_is_bitwise(self, tmp_path):
        """Chunked scan groups (fusion cap 1: one bucket per program)
        reproduce the unbounded-fusion model bit for bit."""
        est_a = _estimator()
        res_a = est_a.fit(_data(2), None, [_CFG])[0]
        profile = est_a.run_profile()
        profile["fit_timing"]["robustness"] = {
            "collective_retries": 1,  # trips the fusion-cap rule
            "watchdog_trips": 0,
        }
        profile["bucket_shapes"] = {}  # every shape is "novel" too
        plan = planner.plan_from_profile(profile)
        assert plan.decisions["scan_fusion_max"].value == 8
        planner.install_plan(plan)
        est_b = _estimator()
        res_b = est_b.fit(_data(2), None, [_CFG])[0]
        np.testing.assert_array_equal(
            np.asarray(res_a.model["per-member"].coefficients_matrix),
            np.asarray(res_b.model["per-member"].coefficients_matrix),
        )

    def test_fusion_chunks_unit(self):
        from photon_ml_tpu.game.coordinate import _fusion_chunks

        idxs = [0, 1, 2, 3, 4]
        # No plan: unbounded.
        assert _fusion_chunks(idxs, (4, 8), None) == [idxs]
        # Proven shape: unbounded even with shape evidence present.
        assert _fusion_chunks(idxs, (4, 8), {(4, 8)}) == [idxs]
        # Novel shape: conservative chunks of NOVEL_SHAPE_FUSE.
        many = list(range(20))
        chunks = _fusion_chunks(many, (4, 8), {(2, 16)})
        assert chunks == [many[0:8], many[8:16], many[16:20]]
        assert [i for c in chunks for i in c] == many  # order preserved


# ---------------------------------------------------------------- serving


class TestLayoutEvidence:
    def test_merge_note_collapses_disagreement_to_mixed(self):
        from photon_ml_tpu.utils.observability import TimingRegistry

        reg = TimingRegistry()
        reg.merge_note("sparse_layout", "rowalign", "mixed")
        assert reg.get_note("sparse_layout") == "rowalign"
        reg.merge_note("sparse_layout", "rowalign", "mixed")
        assert reg.get_note("sparse_layout") == "rowalign"
        reg.merge_note("sparse_layout", "grouped", "mixed")
        assert reg.get_note("sparse_layout") == "mixed"
        # Sticky: later agreement cannot un-mix a mixed fit.
        reg.merge_note("sparse_layout", "grouped", "mixed")
        assert reg.get_note("sparse_layout") == "mixed"

    def test_mixed_layout_plans_nothing(self):
        profile = _fit_profile()
        profile["dispatch"]["layout"] = "mixed"
        plan = planner.plan_from_profile(profile)
        assert "sparse_layout" not in plan.decisions

    def test_layout_evidence_is_per_fit_not_per_estimator(self):
        """A later fit on the same estimator must not inherit a previous
        fit's layout note as its own profile evidence — the notes clear
        at fit start (a fit that packed nothing honestly reports
        'none', and a one-time 'mixed' cannot pin future profiles)."""
        est = _estimator()
        ds = _data(11)
        est.fit(ds, None, [_CFG])
        # A stale note from a hypothetical earlier sparse fit:
        est.timing_registry.merge_note("sparse_layout", "rowalign", "mixed")
        est.fit(ds, None, [_CFG])  # dense refit: packs nothing
        assert est.run_profile()["dispatch"]["layout"] == "none"


class TestServingConsultation:
    def test_engine_and_batcher_resolve_from_plan(self):
        plan = planner.plan_from_profile(_serve_profile())
        planner.install_plan(plan)
        assert planner.planned_value("serving_max_batch") == 32
        assert planner.planned_value("serving_max_wait_ms") == 2.0
        from photon_ml_tpu.serving.engine import _bucket_sizes

        assert _bucket_sizes(int(planner.planned_value("serving_max_batch"))) \
            == (1, 2, 4, 8, 16, 32)


# ----------------------------------------------------------------- journal


class TestJournalAndDiff:
    def test_install_plan_journals_valid_plan_decisions(self, tmp_path):
        path = str(tmp_path / "journal.jsonl")
        journal = telemetry.RunJournal(path)
        telemetry.install_journal(journal)
        try:
            planner.install_plan(planner.plan_from_profile(_fit_profile()))
        finally:
            telemetry.uninstall_journal()
            journal.close()
        n_ok, errors = telemetry.validate_journal(path)
        assert errors == []
        types = [
            json.loads(line)["type"] for line in open(path) if line.strip()
        ]
        assert types.count("plan_decision") == len(
            planner.current_plan().decisions
        )
        assert n_ok == len(types)

    def test_profile_diff_cli(self, tmp_path, capsys):
        from photon_ml_tpu.cli import obs

        a = str(tmp_path / "a.json")
        b = str(tmp_path / "b.json")
        prof_a = _fit_profile()
        telemetry.write_profile(a, prof_a)
        prof_b = _fit_profile()
        prof_b["stages"] = dict(prof_a["stages"], solve_s=5.0)
        prof_b["dispatch"] = dict(prof_a["dispatch"], layout="rowalign")
        prof_b["plan"] = planner.plan_from_profile(prof_a).block()
        telemetry.write_profile(b, prof_b)

        assert obs.main(["profile", "diff", a, b]) == 0
        out = capsys.readouterr().out
        assert "solve_s" in out and "-2.000s" in out  # stage delta
        assert "layout" in out and "rowalign" in out  # dispatch change
        assert "+ pack_routing" in out  # plan-block decision added

    def test_profile_diff_contract_violation_exits_nonzero(
        self, tmp_path, capsys
    ):
        from photon_ml_tpu.cli import obs

        a = str(tmp_path / "a.json")
        telemetry.write_profile(a, _fit_profile())
        broken = str(tmp_path / "broken.json")
        doc = _fit_profile()
        del doc["stages"]
        with open(broken, "w") as f:
            json.dump(doc, f)  # bypass write_profile's validation
        assert obs.main(["profile", "diff", a, broken]) == 1
        assert "CONTRACT VIOLATION" in capsys.readouterr().out

    def test_profile_diff_kind_mismatch_exits_nonzero(self, tmp_path, capsys):
        from photon_ml_tpu.cli import obs

        a = str(tmp_path / "a.json")
        b = str(tmp_path / "b.json")
        telemetry.write_profile(a, _fit_profile())
        telemetry.write_profile(b, _serve_profile())
        assert obs.main(["profile", "diff", a, b]) == 1
        assert "kinds differ" in capsys.readouterr().out

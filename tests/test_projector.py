"""Projector tests (reference: photon-api projector/* behavior)."""

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.containers import SparseFeatures, pack_csr_to_ell
from photon_ml_tpu.data.game_dataset import (
    GameDataset,
    RandomEffectDataConfig,
    build_random_effect_dataset,
)
from photon_ml_tpu.game.projector import (
    IdentityProjector,
    IndexMapProjector,
    RandomProjector,
    build_projector,
    project_shard,
)
from photon_ml_tpu.types import ProjectorType


def _sparse_fixture():
    # 6 samples, 3 entities, global dim 10. Each entity touches few features.
    rows = [
        [(0, 1.0), (7, 2.0)],  # entity 0
        [(7, 3.0)],  # entity 0
        [(2, 1.5), (3, -1.0)],  # entity 1
        [(3, 4.0)],  # entity 1
        [(9, 1.0)],  # entity 2
        [(9, -2.0), (1, 0.5)],  # entity 2
    ]
    indptr = np.cumsum([0] + [len(r) for r in rows])
    indices = np.array([i for r in rows for i, _ in r])
    values = np.array([v for r in rows for _, v in r], np.float32)
    feats = pack_csr_to_ell(indptr, indices, values, dim=10)
    entity_rows = np.array([0, 0, 1, 1, 2, 2])
    return feats, entity_rows


class TestIndexMapProjector:
    def test_margins_preserved(self):
        feats, ent = _sparse_fixture()
        proj = IndexMapProjector.build(feats, ent, num_entities=3, pad_multiple=1)
        assert proj.projected_dim == 2  # max distinct features per entity
        pfeats = proj.project_features(feats, ent)
        assert pfeats.dim == 2
        # Margins in projected space with projected weights must equal
        # original-space margins with the back-projected weights.
        w_proj = jnp.asarray(np.random.default_rng(0).normal(size=(4, 2)), jnp.float32)
        w_orig = proj.back_project_matrix(w_proj)
        m_proj = np.array(
            [float(pfeats.matvec(w_proj[e])[r]) for r, e in enumerate(ent)]
        )
        m_orig = np.array(
            [float(feats.matvec(w_orig[e])[r]) for r, e in enumerate(ent)]
        )
        np.testing.assert_allclose(m_proj, m_orig, rtol=1e-6)

    def test_pad_multiple(self):
        feats, ent = _sparse_fixture()
        proj = IndexMapProjector.build(feats, ent, num_entities=3, pad_multiple=8)
        assert proj.projected_dim == 8

    def test_back_project_scatter(self):
        feats, ent = _sparse_fixture()
        proj = IndexMapProjector.build(feats, ent, num_entities=3, pad_multiple=1)
        w = jnp.ones((4, proj.projected_dim), jnp.float32)
        back = np.asarray(proj.back_project_matrix(w))
        # entity 0 used features {0, 7}; entity 1 {2, 3}; entity 2 {1, 9}.
        assert back.shape == (4, 10)
        np.testing.assert_array_equal(np.nonzero(back[0])[0], [0, 7])
        np.testing.assert_array_equal(np.nonzero(back[1])[0], [2, 3])
        np.testing.assert_array_equal(np.nonzero(back[2])[0], [1, 9])
        assert back[3].sum() == 0  # unseen row empty

    def test_unseen_entity_rows_zeroed(self):
        # Samples mapped to the unseen-entity row (empty slot table) must be
        # zeroed, not crash (regression: empty-table searchsorted).
        feats = SparseFeatures(
            jnp.asarray([[0], [1]], jnp.int32), jnp.asarray([[1.0], [2.0]]), 5
        )
        proj = IndexMapProjector.build(
            feats, np.array([0, 1]), num_entities=1, pad_multiple=1
        )
        pfeats = proj.project_features(feats, np.array([0, 1]))
        assert float(pfeats.values[1, 0]) == 0.0

    def test_entity_coefficients_sparse_map(self):
        feats, ent = _sparse_fixture()
        proj = IndexMapProjector.build(feats, ent, num_entities=3, pad_multiple=1)
        m = jnp.asarray([[1.0, 2.0], [0.0, 3.0], [4.0, 0.0], [0.0, 0.0]])
        assert proj.entity_coefficients(m, 0) == {0: 1.0, 7: 2.0}
        assert proj.entity_coefficients(m, 1) == {3: 3.0}


class TestRandomProjector:
    def test_shapes_and_consistency(self):
        feats, ent = _sparse_fixture()
        proj = RandomProjector.build(10, 4, seed=1)
        pfeats = proj.project_features(feats, ent)
        assert pfeats.shape == (6, 4)
        # Projecting sparse == densify-then-matmul.
        dense = np.asarray(feats.to_dense())
        np.testing.assert_allclose(
            np.asarray(pfeats), dense @ np.asarray(proj.matrix), rtol=1e-5, atol=1e-6
        )
        # Back-projection consistency: score in projected space equals
        # original-space score with P @ w.
        w = jnp.asarray(np.random.default_rng(2).normal(size=(4,)), jnp.float32)
        s_proj = np.asarray(pfeats) @ np.asarray(w)
        w_orig = np.asarray(proj.matrix) @ np.asarray(w)
        np.testing.assert_allclose(s_proj, dense @ w_orig, rtol=1e-4, atol=1e-5)


class TestBuildAndWire:
    def test_identity_for_dense(self):
        X = jnp.ones((4, 3))
        proj = build_projector(ProjectorType.INDEX_MAP, X, np.zeros(4, int), 1)
        assert isinstance(proj, IdentityProjector)

    def test_random_requires_dim(self):
        feats, ent = _sparse_fixture()
        with pytest.raises(ValueError):
            build_projector(ProjectorType.RANDOM, feats, ent, 3)

    def test_project_shard_rewires_dataset(self):
        feats, ent = _sparse_fixture()
        ds = GameDataset.build(
            {"re_shard": feats},
            np.zeros(6, np.float32),
            id_tags={"memberId": ent},
        )
        red = build_random_effect_dataset(
            ds, RandomEffectDataConfig("memberId", "re_shard", min_bucket=2)
        )
        ps = project_shard(ds, red, ProjectorType.INDEX_MAP)
        assert ps.shard_name == "re_shard@memberId"
        assert ps.shard_name in ds.shards
        assert red.feature_shard == ps.shard_name
        assert ds.shards[ps.shard_name].dim == ps.projector.projected_dim
        assert ps.projector.projected_dim < 10

"""Online serving engine: bundle staging, bucketed scoring, micro-batching.

The load-bearing contract is OFFLINE/ONLINE PARITY: every score the engine
(or the batcher, or the fault-degraded per-request fallback) produces must
be bitwise-identical to `GameTransformer.transform` on the same rows. The
engine's kernels are batch-size invariant by construction (see
`dense_margins`), so the tests exercise the shapes that would break a
naive port: odd batch sizes padding into different buckets, duplicate
entities in one batch, all-cold-start batches, and injected faults at the
lookup/score sites.
"""

from __future__ import annotations

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.game_dataset import GameDataset
from photon_ml_tpu.game.model import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.serving import (
    MicroBatcher,
    ScoreRequest,
    ServingBundle,
    ServingEngine,
    load_bundle,
)
from photon_ml_tpu.transformers.game_transformer import (
    CoordinateScoringSpec,
    GameTransformer,
)
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import faults

pytestmark = pytest.mark.serving

TASK = TaskType.LOGISTIC_REGRESSION
D_FE, D_RE, N_ENTITIES = 12, 5, 6


def _fixture(rng, n=13, entity_ids=None):
    """(model, specs, dataset, requests): one FE + one RE coordinate over
    dense shards, some entities unseen (cold starts)."""
    X = rng.normal(size=(n, D_FE)).astype(np.float32)
    Xe = rng.normal(size=(n, D_RE)).astype(np.float32)
    if entity_ids is None:
        entity_ids = rng.integers(0, N_ENTITIES + 3, size=n)  # some >= E: cold
    entity_ids = np.asarray(entity_ids)
    offsets = rng.normal(size=n).astype(np.float32)
    w = rng.normal(size=D_FE).astype(np.float32)
    matrix = np.zeros((N_ENTITIES + 1, D_RE), np.float32)
    matrix[:N_ENTITIES] = rng.normal(size=(N_ENTITIES, D_RE))
    model = GameModel(
        {
            "fixed": FixedEffectModel(Coefficients(jnp.asarray(w)), TASK),
            "per-e": RandomEffectModel(jnp.asarray(matrix), None, TASK),
        }
    )
    specs = {
        "fixed": CoordinateScoringSpec(shard="g"),
        "per-e": CoordinateScoringSpec(
            shard="re",
            random_effect_type="eid",
            entity_index={str(i): i for i in range(N_ENTITIES)},
        ),
    }
    ds = GameDataset.build(
        {"g": X, "re": Xe},
        np.zeros(n, np.float32),
        offsets=offsets,
        id_tags={"eid": entity_ids.astype(str)},
    )
    requests = [
        ScoreRequest(
            features={"g": X[i], "re": Xe[i]},
            entity_ids={"eid": str(entity_ids[i])},
            offset=float(offsets[i]),
            uid=str(i),
        )
        for i in range(n)
    ]
    return model, specs, ds, requests


def _scores(results):
    return np.asarray([r.score for r in results], np.float32)


def _means(results):
    return np.asarray([r.mean for r in results], np.float32)


class TestEngineParity:
    def test_engine_matches_transformer_bitwise(self, rng):
        model, specs, ds, reqs = _fixture(rng)
        ref = GameTransformer(model, specs, TASK).transform(ds)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=16
        ) as eng:
            res = eng.score_batch(reqs)
        assert (_scores(res) == np.asarray(ref.scores)).all()
        assert (_means(res) == np.asarray(ref.means)).all()

    def test_every_bucket_size_matches(self, rng):
        """The same rows must score identically from ANY bucket — the
        batch-invariance that makes micro-batch composition irrelevant."""
        model, specs, ds, reqs = _fixture(rng, n=8)
        ref = np.asarray(GameTransformer(model, specs, TASK).transform(ds).scores)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=32
        ) as eng:
            # One per batch (bucket 1), pairs (bucket 2), odd triple
            # (bucket 4), all 8 (bucket 8).
            singles = np.concatenate(
                [_scores(eng.score_batch([r])) for r in reqs]
            )
            pairs = np.concatenate(
                [_scores(eng.score_batch(reqs[i : i + 2])) for i in range(0, 8, 2)]
            )
            triple = _scores(eng.score_batch(reqs[:3]))
            full = _scores(eng.score_batch(reqs))
        assert (singles == ref).all()
        assert (pairs == ref).all()
        assert (triple == ref[:3]).all()
        assert (full == ref).all()

    def test_duplicate_entities_in_one_batch(self, rng):
        ids = np.asarray([2, 2, 2, 0, 2, 1, 1])
        model, specs, ds, reqs = _fixture(rng, n=7, entity_ids=ids)
        ref = np.asarray(GameTransformer(model, specs, TASK).transform(ds).scores)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=8
        ) as eng:
            assert (_scores(eng.score_batch(reqs)) == ref).all()

    def test_all_cold_start_batch_is_fixed_effect_only(self, rng):
        """Unknown entities score with the fixed effects + offset only —
        GLMix's prior-model semantics (the pinned zero row)."""
        ids = np.asarray([99, 100, 101, 102])
        model, specs, ds, reqs = _fixture(rng, n=4, entity_ids=ids)
        ref = np.asarray(GameTransformer(model, specs, TASK).transform(ds).scores)
        fe_only = GameModel({"fixed": model["fixed"]})
        ds_fe = GameDataset.build(
            {"g": np.asarray(ds.shards["g"])},
            np.zeros(4, np.float32),
            offsets=np.asarray(ds.offsets),
        )
        fe_ref = np.asarray(
            GameTransformer(fe_only, {"fixed": specs["fixed"]}, TASK)
            .transform(ds_fe)
            .scores
        )
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=8
        ) as eng:
            res = eng.score_batch(reqs)
        assert all(r.cold_start for r in res)
        assert all(r.n_cold == 1 for r in res)
        assert (_scores(res) == ref).all()
        assert (_scores(res) == fe_ref).all()

    def test_missing_entity_id_is_cold(self, rng):
        model, specs, _, _ = _fixture(rng, n=2)
        req = ScoreRequest(
            features={
                "g": np.zeros(D_FE, np.float32),
                "re": np.ones(D_RE, np.float32),
            }
        )
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=4
        ) as eng:
            res = eng.score_batch([req])[0]
        assert res.cold_start

    def test_shared_shard_coordinates_match(self, rng):
        """Two coordinates reading the SAME feature shard (the train-CLI's
        default GLMix config): the engine ships one buffer per shard, and
        scores still match the transformer bitwise."""
        n = 7
        X = rng.normal(size=(n, D_RE)).astype(np.float32)
        ids = rng.integers(0, N_ENTITIES, size=n)
        w = rng.normal(size=D_RE).astype(np.float32)
        matrix = np.zeros((N_ENTITIES + 1, D_RE), np.float32)
        matrix[:N_ENTITIES] = rng.normal(size=(N_ENTITIES, D_RE))
        model = GameModel(
            {
                "fixed": FixedEffectModel(Coefficients(jnp.asarray(w)), TASK),
                "per-e": RandomEffectModel(jnp.asarray(matrix), None, TASK),
            }
        )
        specs = {
            "fixed": CoordinateScoringSpec(shard="g"),
            "per-e": CoordinateScoringSpec(
                shard="g",
                random_effect_type="eid",
                entity_index={str(i): i for i in range(N_ENTITIES)},
            ),
        }
        ds = GameDataset.build(
            {"g": X}, np.zeros(n, np.float32), id_tags={"eid": ids.astype(str)}
        )
        ref = np.asarray(GameTransformer(model, specs, TASK).transform(ds).scores)
        reqs = [
            ScoreRequest(features={"g": X[i]}, entity_ids={"eid": str(ids[i])})
            for i in range(n)
        ]
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=8
        ) as eng:
            assert (_scores(eng.score_batch(reqs)) == ref).all()

    def test_oversized_batch_splits(self, rng):
        model, specs, ds, reqs = _fixture(rng, n=13)
        ref = np.asarray(GameTransformer(model, specs, TASK).transform(ds).scores)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=4
        ) as eng:
            assert (_scores(eng.score_batch(reqs)) == ref).all()


class TestCompileSet:
    def test_zero_recompiles_after_warmup(self, rng):
        model, specs, _, reqs = _fixture(rng, n=13)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=16
        ) as eng:
            assert eng.buckets == (1, 2, 4, 8, 16)
            n_programs = eng.warmup()
            assert n_programs == len(eng.buckets)
            # Varying batch sizes, including ones that pad: no new programs.
            for size in (1, 3, 13, 7, 2, 16, 5, 11):
                eng.score_batch(reqs[:size])
            assert eng.recompiles_after_warmup == 0
            assert eng.metrics()["recompiles_after_warmup"] == 0

    def test_padding_waste_accounted(self, rng):
        model, specs, _, reqs = _fixture(rng, n=13)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=16
        ) as eng:
            eng.score_batch(reqs[:3])  # bucket 4: 1 padded slot
            m = eng.metrics()
        assert m["padding_waste"] == pytest.approx(0.25)


class TestBatcher:
    def test_batcher_matches_transformer_bitwise(self, rng):
        model, specs, ds, reqs = _fixture(rng, n=13)
        ref = np.asarray(GameTransformer(model, specs, TASK).transform(ds).scores)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=4
        ) as eng:
            with eng.batcher(max_wait_ms=1.0) as b:
                assert (_scores(b.score_all(reqs)) == ref).all()
                m = b.metrics()
        assert m["completed"] == 13
        assert m["p50_ms"] is not None and m["p99_ms"] is not None
        assert m["degraded_batches"] == 0

    def test_deadline_flushes_partial_batch(self, rng):
        """A lone request must not wait for max_batch peers — the deadline
        bound flushes it."""
        model, specs, _, reqs = _fixture(rng, n=2)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=64
        ) as eng:
            with eng.batcher(max_wait_ms=5.0) as b:
                t0 = time.monotonic()
                res = b.score(reqs[0])
                wall = time.monotonic() - t0
        assert isinstance(res.score, float)
        assert wall < 5.0  # flushed by deadline, not wedged forever

    def test_flush_thread_joined_on_engine_close(self, rng):
        model, specs, _, _ = _fixture(rng, n=2)
        eng = ServingEngine(ServingBundle.from_model(model, specs, TASK), max_batch=4)
        b = eng.batcher(max_wait_ms=1.0)
        assert any(
            t.name == "photon-serving-flush" for t in threading.enumerate()
        )
        eng.close()
        assert b.closed
        assert not any(
            t.name == "photon-serving-flush" and t.is_alive()
            for t in threading.enumerate()
        )

    def test_close_drains_pending(self, rng):
        model, specs, _, reqs = _fixture(rng, n=13)
        eng = ServingEngine(ServingBundle.from_model(model, specs, TASK), max_batch=4)
        b = eng.batcher(max_wait_ms=10_000.0)  # deadline never fires
        futures = [b.submit(r) for r in reqs[:3]]  # below max_batch
        eng.close()  # must answer the stragglers, then join
        assert all(isinstance(f.result(timeout=5).score, float) for f in futures)

    def test_submit_after_close_raises(self, rng):
        model, specs, _, reqs = _fixture(rng, n=2)
        eng = ServingEngine(ServingBundle.from_model(model, specs, TASK), max_batch=4)
        b = eng.batcher()
        eng.close()
        with pytest.raises(RuntimeError):
            b.submit(reqs[0])
        # A batcher created after close would leak its flush thread (the
        # idempotent close() never revisits _batchers) — refused.
        with pytest.raises(RuntimeError):
            eng.batcher()

    def test_cancelled_future_does_not_kill_flush_thread(self, rng):
        """A client cancelling a queued request must not blow
        InvalidStateError through the flush thread — later requests still
        get answers."""
        model, specs, _, reqs = _fixture(rng, n=13)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=4
        ) as eng:
            with eng.batcher(max_wait_ms=60_000.0, max_batch=4) as b:
                doomed = b.submit(reqs[0])  # deadline far away: still queued
                assert doomed.cancel()
                later = [b.submit(r) for r in reqs[1:5]]  # fills max_batch
                results = [f.result(timeout=5) for f in later]
        assert all(isinstance(r.score, float) for r in results)
        assert doomed.cancelled()

    def test_batcher_rejects_oversized_max_batch(self, rng):
        model, specs, _, _ = _fixture(rng, n=2)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=4
        ) as eng:
            with pytest.raises(ValueError):
                eng.batcher(max_batch=8)
            # A zero/negative batch bound would busy-spin the flush loop
            # forming empty batches and deadlock close(); rejected up front.
            with pytest.raises(ValueError):
                eng.batcher(max_batch=0)
            with pytest.raises(ValueError):
                eng.batcher(max_batch=-1)


@pytest.mark.chaos
class TestServingFaultDomain:
    def test_score_fault_degrades_bitwise(self, rng):
        """An injected device-dispatch fault degrades the batch to
        per-request dispatch; answers stay bitwise-identical and the
        degradation is counted."""
        model, specs, ds, reqs = _fixture(rng, n=9)
        ref = np.asarray(GameTransformer(model, specs, TASK).transform(ds).scores)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=16
        ) as eng:
            eng.warmup()
            with faults.inject("score:1"):
                with eng.batcher(max_wait_ms=1.0) as b:
                    res = b.score_all(reqs)
        assert (_scores(res) == ref).all()
        assert faults.COUNTERS.get("serving_degraded_batches") == 1
        assert faults.COUNTERS.get("injected_faults") >= 1

    def test_lookup_fault_degrades_bitwise(self, rng):
        model, specs, ds, reqs = _fixture(rng, n=9)
        ref = np.asarray(GameTransformer(model, specs, TASK).transform(ds).scores)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=16
        ) as eng:
            with faults.inject("lookup:1"):
                with eng.batcher(max_wait_ms=1.0) as b:
                    res = b.score_all(reqs)
        assert (_scores(res) == ref).all()
        assert faults.COUNTERS.get("serving_degraded_batches") == 1

    def test_odd_sizes_and_cold_under_probability_faults(self, rng):
        """Sustained seeded fault pressure at both sites: every answer
        still bitwise-matches the offline transformer."""
        ids = np.asarray([0, 99, 1, 1, 99, 2, 3])  # duplicates + cold mixed
        model, specs, ds, reqs = _fixture(rng, n=7, entity_ids=ids)
        ref = np.asarray(GameTransformer(model, specs, TASK).transform(ds).scores)
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=4
        ) as eng:
            with faults.inject("score:p0.3,lookup:p0.2", seed=7):
                with eng.batcher(max_wait_ms=1.0) as b:
                    res = b.score_all(reqs)
        assert (_scores(res) == ref).all()

    def test_warmup_immune_to_armed_faults(self, rng):
        """Warmup is bring-up, not traffic: an armed lookup/score fault must
        neither kill it nor be consumed by it — the scheduled fault fires on
        the first REAL batch (which then degrades, bitwise-unchanged)."""
        model, specs, ds, reqs = _fixture(rng, n=5)
        ref = np.asarray(GameTransformer(model, specs, TASK).transform(ds).scores)
        with faults.inject("score:1,lookup:1"):
            with ServingEngine(
                ServingBundle.from_model(model, specs, TASK), max_batch=8
            ) as eng:
                eng.warmup()  # would raise if warmup consumed the fault
                with eng.batcher(max_wait_ms=1.0) as b:
                    res = b.score_all(reqs)
        assert (_scores(res) == ref).all()
        assert faults.COUNTERS.get("serving_degraded_batches") >= 1

    def test_non_transient_error_fails_futures_not_thread(self, rng):
        model, specs, _, reqs = _fixture(rng, n=3)
        eng = ServingEngine(ServingBundle.from_model(model, specs, TASK), max_batch=4)
        boom = ValueError("programming error")

        def broken(requests):
            raise boom

        eng.score_batch = broken  # type: ignore[assignment]
        with eng.batcher(max_wait_ms=1.0) as b:
            fut = b.submit(reqs[0])
            with pytest.raises(ValueError):
                fut.result(timeout=5)
            assert b.metrics()["failed"] == 1
        eng.close()


class TestBundle:
    def test_projected_coordinate_rejected(self, rng):
        model, specs, _, _ = _fixture(rng, n=2)
        specs = dict(specs)
        specs["per-e"] = CoordinateScoringSpec(
            shard="re",
            random_effect_type="eid",
            entity_index=specs["per-e"].entity_index,
            projector=object(),
        )
        with pytest.raises(ValueError, match="projected space"):
            ServingBundle.from_model(model, specs, TASK)

    def test_artifact_save_load_serve_parity(self, rng, tmp_path):
        """The production path: save the artifact (model store layout +
        feature-index JSONs, as the training driver does), `load_bundle`,
        and serve — bitwise-identical to a transformer built from the same
        reloaded artifact."""
        import os

        from photon_ml_tpu.data.index_map import IndexMap
        from photon_ml_tpu.io import model_bridge, model_store

        model, specs, ds, reqs = _fixture(rng, n=9)
        index_maps = {
            "g": IndexMap.from_feature_names([f"f{i}" for i in range(D_FE)]),
            "re": IndexMap.from_feature_names([f"r{i}" for i in range(D_RE)]),
        }
        art = model_bridge.artifact_from_game_model(model, specs, TASK)
        mdir = tmp_path / "model"
        model_store.save_game_model(str(mdir), art, index_maps)
        idx_dir = mdir / "feature-indexes"
        os.makedirs(idx_dir)
        for shard, imap in index_maps.items():
            imap.save(str(idx_dir / f"{shard}.json"))

        bundle = load_bundle(str(mdir))
        art2 = model_store.load_game_model(str(mdir), index_maps)
        model2, specs2 = model_bridge.game_model_from_artifact(art2)
        ref = np.asarray(GameTransformer(model2, specs2, art2.task).transform(ds).scores)
        with ServingEngine(bundle, max_batch=16) as eng:
            assert (_scores(eng.score_batch(reqs)) == ref).all()
        assert bundle.upload_bytes > 0

    def test_encode_request_named_features(self, rng):
        from photon_ml_tpu.data.index_map import IndexMap

        model, specs, _, _ = _fixture(rng, n=2)
        imap = IndexMap.from_feature_names([f"f{i}" for i in range(D_FE)])
        bundle = ServingBundle.from_model(
            model, specs, TASK, index_maps={"g": imap}
        )
        req = bundle.encode_request(
            {"g": {"f0": 1.5, "f3": -2.0, "nope": 9.0}}, uid="x"
        )
        idx, vals = req.features["g"]
        expected = sorted([imap.get_index("f0"), imap.get_index("f3")])
        assert sorted(idx.tolist()) == expected  # unknown feature dropped
        assert set(vals.tolist()) == {1.5, -2.0}

    def test_sparse_duplicate_indices_accumulate(self, rng):
        model, specs, _, _ = _fixture(rng, n=2)
        w = np.asarray(model["fixed"].coefficients.means)
        req = ScoreRequest(
            features={
                "g": (
                    np.asarray([1, 1, 2], np.int32),
                    np.asarray([0.5, 0.25, 1.0], np.float32),
                )
            },
            entity_ids={"eid": "0"},
        )
        dense = np.zeros(D_FE, np.float32)
        dense[1], dense[2] = 0.75, 1.0
        req_dense = ScoreRequest(features={"g": dense}, entity_ids={"eid": "0"})
        with ServingEngine(
            ServingBundle.from_model(model, specs, TASK), max_batch=4
        ) as eng:
            sparse_score = eng.score_batch([req])[0].score
            dense_score = eng.score_batch([req_dense])[0].score
        assert sparse_score == dense_score

    def test_request_from_record_applies_intercept(self, rng):
        from photon_ml_tpu.data.index_map import INTERCEPT_KEY, IndexMap
        from photon_ml_tpu.io.avro_data import FeatureShardConfig
        from photon_ml_tpu.serving.bundle import request_from_record

        model, specs, _, _ = _fixture(rng, n=2)
        imap = IndexMap.from_feature_names(
            [f"f{i}" for i in range(D_FE - 1)], add_intercept=True
        )
        bundle = ServingBundle.from_model(
            model, specs, TASK, index_maps={"g": imap}
        )
        rec = {
            "uid": "u1",
            "features": [{"name": "f0", "term": "", "value": 2.0}],
            "eid": "3",
        }
        req = request_from_record(
            bundle, rec, {"g": FeatureShardConfig(("features",), True)}
        )
        idx, vals = req.features["g"]
        icpt = imap.get_index(INTERCEPT_KEY)
        assert icpt in idx.tolist()
        assert req.entity_ids["eid"] == "3"
        assert req.uid == "u1"

    def test_request_from_record_missing_id_resolves_like_ingest(self, rng):
        """Offline ingest tags a record with NO id field as entity "" (a
        trainable key) — replay must resolve the same coefficient row, not
        invent a cold start the offline path wouldn't have."""
        from photon_ml_tpu.data.index_map import IndexMap
        from photon_ml_tpu.io.avro_data import FeatureShardConfig
        from photon_ml_tpu.serving.bundle import request_from_record

        model, specs, _, _ = _fixture(rng, n=2)
        specs = dict(specs)
        specs["per-e"] = CoordinateScoringSpec(
            shard="re",
            random_effect_type="eid",
            entity_index={"": 0, "m1": 1},  # "" trained, as ingest produces
        )
        bundle = ServingBundle.from_model(
            model,
            specs,
            TASK,
            index_maps={
                "g": IndexMap.from_feature_names([f"f{i}" for i in range(D_FE)])
            },
        )
        req = request_from_record(
            bundle,
            {"features": [], "metadataMap": None},
            {"g": FeatureShardConfig(("features",), False)},
        )
        assert req.entity_ids["eid"] == ""
        rows, cold = bundle.coordinates["per-e"].lookup_rows([req.entity_ids["eid"]])
        assert rows[0] == 0 and cold == 0  # the trained "" row, not unseen

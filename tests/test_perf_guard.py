"""Perf-regression guards for the sparse hot path (r06 raw-speed sprint).

Tier-1 runs only the cheap structural checks; the `slow`+`perf` marked
guards pack bench-like shapes and assert the two r06 contracts that keep
the sprint's wins from silently regressing:

  * the fused sparse objective ENGAGES on the bench shape (r03 shipped a
    gate bug that silently kept it off for a whole round), and
  * the pack no longer dominates the sparse wall: on the device path the
    placement pass leaves the host CPU entirely (pack_host stage == 0),
    and the host fallback's native counting sort beats the numpy argsort
    oracle it replaced.
"""

import json

import numpy as np
import jax.numpy as jnp
import pytest

from photon_ml_tpu.data import bucketed
from photon_ml_tpu.ops import pallas_glm, pallas_sparse
from photon_ml_tpu.utils.observability import TimingRegistry, stage_scope


@pytest.fixture
def interpret_kernels():
    old = pallas_glm.FORCE_INTERPRET
    pallas_glm.FORCE_INTERPRET = True
    yield
    pallas_glm.FORCE_INTERPRET = old


def _bench_like_coo(n=131072, d=4096, k=32, seed=17):
    rng = np.random.default_rng(seed)
    rows = np.repeat(np.arange(n, dtype=np.int64), k)
    cols = rng.integers(0, d, size=n * k).astype(np.int64)
    vals = rng.normal(size=n * k).astype(np.float32)
    return rows, cols, vals, n, d


class TestDispatchJson:
    def test_dispatch_decisions_are_machine_comparable(self):
        """Satellite: bench artifacts must carry dispatch decisions as JSON
        booleans/objects, never repr() strings (BENCH_r05 shipped
        "dispatch": "True")."""
        import bench

        for mode, expect in ((True, True), (False, False), (None, None)):
            assert bench._dispatch_json(mode) is expect

        class _FakeMesh:
            class devices:
                size = 8

        class _FakeSharded:
            axis = "batch"
            mesh = _FakeMesh()

        out = bench._dispatch_json(_FakeSharded())
        assert out["sharded"] is True and out["devices"] == 8
        # Every shape must survive a JSON round trip unchanged.
        for mode in (True, False, None, _FakeSharded()):
            enc = bench._dispatch_json(mode)
            assert json.loads(json.dumps(enc)) == enc


@pytest.mark.slow
@pytest.mark.perf
class TestSparsePerfGuards:
    def test_fused_path_engages_on_bench_shape(
        self, interpret_kernels, monkeypatch
    ):
        """kernel_engaged on the (scaled) bench shape: the pack gates must
        accept it AND the fused single-stream kernel must be the dispatch
        (should_use + fused_feasible) — the r03 regression shape."""
        monkeypatch.setenv("PHOTON_DEVICE_PACK", "1")
        rows, cols, vals, n, d = _bench_like_coo()
        bf = pallas_sparse.maybe_pack_coo(rows, cols, vals, n, d)
        assert bf is not None, "pack gates declined the bench shape"
        assert pallas_sparse.should_use(bf)
        assert pallas_sparse.fused_feasible(bf), (
            "bench shape fell off the fused kernel onto the composed path"
        )
        assert bf.density_report()["pad_blowup"] <= pallas_sparse.MAX_PAD_BLOWUP

    def test_device_pack_leaves_host_cpu(self, interpret_kernels, monkeypatch):
        """Pack non-dominance, device path: the placement pass must record
        NO host-placement wall — everything lands under pack_device (plus
        the small level-2 spill tail)."""
        monkeypatch.setenv("PHOTON_DEVICE_PACK", "1")
        rows, cols, vals, n, d = _bench_like_coo(n=65536, k=16)
        reg = TimingRegistry()
        with stage_scope(reg):
            bf = pallas_sparse.maybe_pack_coo(rows, cols, vals, n, d)
        assert bf is not None
        assert reg.get_note("pack_path") == "device"
        assert reg.get("pack_device") > 0.0
        # Level 1 — ~99% of entries on this uniform shape — must not have
        # paid a host placement pass; only the spill tail may.
        assert reg.get("pack_host") <= 0.25 * reg.get("pack_device") + 0.05

    def test_native_pack_beats_numpy_oracle(self, monkeypatch):
        """Pack non-dominance, host fallback: the native counting sort must
        beat the numpy argsort oracle it replaced (generous 1.5x slack —
        this is a regression tripwire, not a benchmark)."""
        import time

        from photon_ml_tpu.native.bucketed_pack import pack_level_native

        rows, cols, vals, n, d = _bench_like_coo(n=65536, k=32)
        monkeypatch.setenv("PHOTON_DEVICE_PACK", "0")
        monkeypatch.setenv("PHOTON_DISABLE_NATIVE", "1")
        t0 = time.perf_counter()
        bucketed.pack_bucketed(rows, cols, vals, n, d, host_only=True)
        numpy_wall = time.perf_counter() - t0
        monkeypatch.delenv("PHOTON_DISABLE_NATIVE")
        probe = pack_level_native(
            np.zeros(0, np.int32), np.zeros(0, np.int32),
            np.zeros(0, np.float32), 1, 1, 11, 1024,
        )
        if probe is None:
            pytest.skip("native library unavailable (no compiler)")
        t0 = time.perf_counter()
        bucketed.pack_bucketed(rows, cols, vals, n, d, host_only=True)
        native_wall = time.perf_counter() - t0
        assert native_wall < numpy_wall * 1.5, (
            f"native pack {native_wall:.3f}s vs numpy {numpy_wall:.3f}s — "
            "the counting sort regressed below the oracle it replaced"
        )

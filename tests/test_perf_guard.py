"""Perf-regression guards for the sparse hot path (r06 raw-speed sprint).

Tier-1 runs only the cheap structural checks; the `slow`+`perf` marked
guards pack bench-like shapes and assert the two r06 contracts that keep
the sprint's wins from silently regressing:

  * the fused sparse objective ENGAGES on the bench shape (r03 shipped a
    gate bug that silently kept it off for a whole round), and
  * the pack no longer dominates the sparse wall: on the device path the
    placement pass leaves the host CPU entirely (pack_host stage == 0),
    and the host fallback's native counting sort beats the numpy argsort
    oracle it replaced.
"""

import json

import numpy as np
import jax.numpy as jnp
import pytest

from photon_ml_tpu.data import bucketed
from photon_ml_tpu.ops import pallas_glm, pallas_sparse
from photon_ml_tpu.utils.observability import TimingRegistry, stage_scope


@pytest.fixture
def interpret_kernels():
    old = pallas_glm.FORCE_INTERPRET
    pallas_glm.FORCE_INTERPRET = True
    yield
    pallas_glm.FORCE_INTERPRET = old


def _bench_like_coo(n=131072, d=4096, k=32, seed=17):
    rng = np.random.default_rng(seed)
    rows = np.repeat(np.arange(n, dtype=np.int64), k)
    cols = rng.integers(0, d, size=n * k).astype(np.int64)
    vals = rng.normal(size=n * k).astype(np.float32)
    return rows, cols, vals, n, d


class TestDispatchJson:
    def test_dispatch_decisions_are_machine_comparable(self):
        """Satellite: bench artifacts must carry dispatch decisions as JSON
        booleans/objects, never repr() strings (BENCH_r05 shipped
        "dispatch": "True")."""
        import bench

        for mode, expect in ((True, True), (False, False), (None, None)):
            assert bench._dispatch_json(mode) is expect

        class _FakeMesh:
            class devices:
                size = 8

        class _FakeSharded:
            axis = "batch"
            mesh = _FakeMesh()

        out = bench._dispatch_json(_FakeSharded())
        assert out["sharded"] is True and out["devices"] == 8
        # Every shape must survive a JSON round trip unchanged.
        for mode in (True, False, None, _FakeSharded()):
            enc = bench._dispatch_json(mode)
            assert json.loads(json.dumps(enc)) == enc


@pytest.mark.slow
@pytest.mark.perf
class TestSparsePerfGuards:
    def test_fused_path_engages_on_bench_shape(
        self, interpret_kernels, monkeypatch
    ):
        """kernel_engaged on the (scaled) bench shape: the pack gates must
        accept it AND the fused single-stream kernel must be the dispatch
        (should_use + fused_feasible) — the r03 regression shape."""
        monkeypatch.setenv("PHOTON_DEVICE_PACK", "1")
        rows, cols, vals, n, d = _bench_like_coo()
        bf = pallas_sparse.maybe_pack_coo(rows, cols, vals, n, d)
        assert bf is not None, "pack gates declined the bench shape"
        assert pallas_sparse.should_use(bf)
        assert pallas_sparse.fused_feasible(bf), (
            "bench shape fell off the fused kernel onto the composed path"
        )
        assert bf.density_report()["pad_blowup"] <= pallas_sparse.MAX_PAD_BLOWUP

    def test_device_pack_leaves_host_cpu(self, interpret_kernels, monkeypatch):
        """Pack non-dominance, device path: the placement pass must record
        NO host-placement wall — everything lands under pack_device (plus
        the small level-2 spill tail)."""
        monkeypatch.setenv("PHOTON_DEVICE_PACK", "1")
        rows, cols, vals, n, d = _bench_like_coo(n=65536, k=16)
        reg = TimingRegistry()
        with stage_scope(reg):
            bf = pallas_sparse.maybe_pack_coo(rows, cols, vals, n, d)
        assert bf is not None
        assert reg.get_note("pack_path") == "device"
        assert reg.get("pack_device") > 0.0
        # Level 1 — ~99% of entries on this uniform shape — must not have
        # paid a host placement pass; only the spill tail may.
        assert reg.get("pack_host") <= 0.25 * reg.get("pack_device") + 0.05

    def test_native_pack_beats_numpy_oracle(self, monkeypatch):
        """Pack non-dominance, host fallback: the native counting sort must
        beat the numpy argsort oracle it replaced (generous 1.5x slack —
        this is a regression tripwire, not a benchmark)."""
        import time

        from photon_ml_tpu.native.bucketed_pack import pack_level_native

        rows, cols, vals, n, d = _bench_like_coo(n=65536, k=32)
        monkeypatch.setenv("PHOTON_DEVICE_PACK", "0")
        monkeypatch.setenv("PHOTON_DISABLE_NATIVE", "1")
        t0 = time.perf_counter()
        bucketed.pack_bucketed(rows, cols, vals, n, d, host_only=True)
        numpy_wall = time.perf_counter() - t0
        monkeypatch.delenv("PHOTON_DISABLE_NATIVE")
        probe = pack_level_native(
            np.zeros(0, np.int32), np.zeros(0, np.int32),
            np.zeros(0, np.float32), 1, 1, 11, 1024,
        )
        if probe is None:
            pytest.skip("native library unavailable (no compiler)")
        t0 = time.perf_counter()
        bucketed.pack_bucketed(rows, cols, vals, n, d, host_only=True)
        native_wall = time.perf_counter() - t0
        assert native_wall < numpy_wall * 1.5, (
            f"native pack {native_wall:.3f}s vs numpy {numpy_wall:.3f}s — "
            "the counting sort regressed below the oracle it replaced"
        )


class TestDataPlaneGuards:
    """r09 streaming data plane: cheap structural gate checks run in
    tier-1; the scaled-down e2e guard (slow+perf) asserts the two walls
    the tentpole exists to move — device RE assembly engaged, prepare not
    dominating solve."""

    def test_device_assembly_auto_on_for_accelerators(self, monkeypatch):
        """The auto gate must engage on accelerator backends (the r03
        pack-gate bug class: a silently-off fast path for a whole round).
        Backend is monkeypatched — this checks the DECISION, not the
        hardware."""
        import jax

        from photon_ml_tpu.data import device_assemble

        monkeypatch.delenv("PHOTON_DEVICE_ASSEMBLY", raising=False)
        for backend, expect in (("tpu", True), ("gpu", True), ("cpu", False)):
            monkeypatch.setattr(jax, "default_backend", lambda b=backend: b)
            assert device_assemble.enabled() is expect, backend

    def test_stream_ingest_auto_gates_on_cores(self, monkeypatch):
        from photon_ml_tpu.io import avro_fast

        monkeypatch.delenv("PHOTON_STREAM_INGEST", raising=False)
        monkeypatch.setenv("PHOTON_HOST_THREADS", "1")
        assert avro_fast.stream_ingest_enabled() is False
        monkeypatch.setenv("PHOTON_HOST_THREADS", "4")
        assert avro_fast.stream_ingest_enabled() is True
        monkeypatch.setenv("PHOTON_STREAM_INGEST", "0")
        assert avro_fast.stream_ingest_enabled() is False


@pytest.mark.slow
@pytest.mark.perf
class TestPrepareNotDominantGuard:
    def test_scaled_e2e_prepare_below_solve(self, monkeypatch, tmp_path):
        """Scaled-down e2e_from_disk shape (the r05 469 s wall, shrunk):
        with the streaming data plane forced on, device RE assembly must
        ENGAGE and the prepare wall must come in under the solve wall —
        the acceptance shape of ISSUE 9, as a regression tripwire."""
        import photon_ml_tpu.io.avro_data as ad
        from photon_ml_tpu.data.game_dataset import (
            FixedEffectDataConfig,
            RandomEffectDataConfig,
        )
        from photon_ml_tpu.estimators.game_estimator import GameEstimator
        from photon_ml_tpu.native.avro_writer import (
            write_training_examples_columnar,
        )
        from photon_ml_tpu.optimize.config import (
            L2,
            CoordinateOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.types import TaskType
        from photon_ml_tpu.utils.contracts import (
            INGEST_TIMING_REQUIRED_KEYS,
        )

        monkeypatch.setenv("PHOTON_DEVICE_ASSEMBLY", "1")
        monkeypatch.setenv("PHOTON_DEVICE_PACK", "1")
        monkeypatch.setenv("PHOTON_STREAM_INGEST", "1")
        monkeypatch.setenv("PHOTON_HOST_THREADS", "4")
        rows_n, d, k = 120_000, 200, 8
        n_users, n_movies = rows_n // 145, rows_n // 740
        rng = np.random.default_rng(11)
        users = rng.integers(0, n_users, size=rows_n)
        movies = rng.integers(0, n_movies, size=rows_n)
        indptr = np.arange(rows_n + 1, dtype=np.int64) * k
        ids = rng.integers(0, d, size=rows_n * k).astype(np.int32)
        vals = rng.normal(size=rows_n * k)
        labels = (rng.uniform(size=rows_n) > 0.5).astype(np.float64)
        names = [f"f{i}" for i in range(d)]
        half = rows_n // 2
        for fi, (lo, hi) in enumerate([(0, half), (half, rows_n)]):
            write_training_examples_columnar(
                str(tmp_path / f"part-{fi}.avro"),
                labels[lo:hi],
                indptr[lo : hi + 1] - indptr[lo],
                ids[indptr[lo] : indptr[hi]],
                vals[indptr[lo] : indptr[hi]],
                names,
                int_tags={"userId": users[lo:hi], "movieId": movies[lo:hi]},
            )
        ds, _ = ad.read_game_dataset(
            str(tmp_path),
            {"g": ad.FeatureShardConfig(("features",), True)},
            id_tag_fields=["userId", "movieId"],
        )
        missing = [
            k2 for k2 in INGEST_TIMING_REQUIRED_KEYS if k2 not in ds.ingest_timing
        ]
        assert not missing, missing
        est = GameEstimator(
            TaskType.LOGISTIC_REGRESSION,
            {
                "global": FixedEffectDataConfig("g"),
                "per-user": RandomEffectDataConfig(
                    "userId", "g", active_upper_bound=128
                ),
                "per-movie": RandomEffectDataConfig(
                    "movieId", "g", active_upper_bound=256
                ),
            },
        )
        cfgs = {
            "global": CoordinateOptimizationConfig(
                optimizer=OptimizerConfig(max_iterations=10, tolerance=1e-6),
                regularization=L2,
                reg_weight=1.0,
            ),
            "per-user": CoordinateOptimizationConfig(
                optimizer=OptimizerConfig(max_iterations=5, tolerance=1e-5),
                regularization=L2,
                reg_weight=10.0,
            ),
            "per-movie": CoordinateOptimizationConfig(
                optimizer=OptimizerConfig(max_iterations=5, tolerance=1e-5),
                regularization=L2,
                reg_weight=10.0,
            ),
        }
        est.fit(ds, None, [cfgs])
        ft = est.fit_timing
        assert ft["re_path"] == "device", (
            "device-side RE assembly did not engage on the e2e shape"
        )
        assert ft["re_host_s"] == 0.0
        assert ft["prepare_s"] < ft["solve_s"], (
            f"prepare {ft['prepare_s']:.1f}s dominates solve "
            f"{ft['solve_s']:.1f}s — the r05 wall is back"
        )

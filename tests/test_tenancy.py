"""Multi-tenant serving isolation suite (ISSUE 15).

The load-bearing contract is the serving-platform generalization of the
engine's bitwise story: N named tenants on one device fleet, where

  * a co-batched request (one device dispatch carrying several tenants'
    slots) scores BITWISE-equal to serving that tenant alone;
  * one tenant's injected faults, overload, or demotion NEVER degrade
    another tenant's answers, counters, or typed rejections — the
    isolation Spark's one-job-per-model deployment gave Photon ML for
    free, enforced in-process here;
  * HBM-pressure eviction demotes (never fails) a READY tenant to the
    host tier, and the demoted tenant keeps answering bitwise through
    the TwoTierEntityStore override path.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from photon_ml_tpu.game.model import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.serving import (
    DeadlineExceeded,
    HbmBudgetExceeded,
    Overloaded,
    ScoreRequest,
    ServingBundle,
    ServingEngine,
    TenantRegistry,
)
from photon_ml_tpu.transformers.game_transformer import CoordinateScoringSpec
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import faults, telemetry

pytestmark = pytest.mark.serving

TASK = TaskType.LOGISTIC_REGRESSION
D_FE, D_RE, E = 7, 5, 24


def _make_model(seed: int, n_entities: int = E):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=D_FE).astype(np.float32)
    M = np.zeros((n_entities + 1, D_RE), np.float32)
    M[:n_entities] = rng.normal(size=(n_entities, D_RE))
    model = GameModel(
        {
            "fixed": FixedEffectModel(Coefficients(jnp.asarray(w)), TASK),
            "per-e": RandomEffectModel(jnp.asarray(M), None, TASK),
        }
    )
    specs = {
        "fixed": CoordinateScoringSpec(shard="g"),
        "per-e": CoordinateScoringSpec(
            shard="re",
            random_effect_type="eid",
            entity_index={str(i): i for i in range(n_entities)},
        ),
    }
    return model, specs


def _bundle(seed: int, n_entities: int = E) -> ServingBundle:
    model, specs = _make_model(seed, n_entities)
    return ServingBundle.from_model(model, specs, TASK)


def _requests(seed: int, n: int, n_entities: int = E):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, D_FE)).astype(np.float32)
    Xe = rng.normal(size=(n, D_RE)).astype(np.float32)
    ids = rng.integers(0, n_entities + 6, size=n)  # trained + cold starts
    return [
        ScoreRequest(
            features={"g": X[i], "re": Xe[i]},
            entity_ids={"eid": str(int(ids[i]))},
            offset=float(i) * 0.125,
            uid=str(i),
        )
        for i in range(n)
    ]


def _solo_scores(seed: int, reqs, n_entities: int = E) -> np.ndarray:
    """The parity anchor: that tenant's bundle alone on a plain engine."""
    with ServingEngine(_bundle(seed, n_entities), max_batch=32) as eng:
        return np.asarray(
            [r.score for r in eng.score_batch(reqs)], np.float64
        )


def _scores(results) -> np.ndarray:
    return np.asarray([r.score for r in results], np.float64)


class TestCoBatchParity:
    def test_cobatched_scores_bitwise_equal_solo(self, rng):
        """Interleaved traffic from two tenants with DIFFERENT bundles
        (different entity counts, too) co-batches into shared device
        dispatches and stays bitwise-equal to serving each alone."""
        req_a, req_b = _requests(11, 16), _requests(12, 16, 40)
        ref_a = _solo_scores(1, req_a)
        ref_b = _solo_scores(2, req_b, 40)
        with TenantRegistry(max_batch=32, max_wait_ms=5.0) as reg:
            reg.admit("a", _bundle(1))
            reg.admit("b", _bundle(2, 40))
            futs = []
            for i in range(16):
                futs.append(("a", reg.submit("a", req_a[i], block=True)))
                futs.append(("b", reg.submit("b", req_b[i], block=True)))
            got = {"a": [], "b": []}
            for name, f in futs:
                got[name].append(f.result(timeout=30).score)
            m = reg.metrics()
            reg.close(release_bundles=True)
        assert np.array_equal(np.asarray(got["a"], np.float64), ref_a)
        assert np.array_equal(np.asarray(got["b"], np.float64), ref_b)
        # The point of co-batching: interleaved cross-tenant traffic
        # shares device dispatches instead of going one-by-one.
        assert m["cobatch_dispatches"] >= 1
        assert m["tenants"]["a"]["cobatched_requests"] == 16
        assert m["tenants"]["b"]["cobatched_requests"] == 16
        assert m["tenants"]["a"]["failed"] == 0
        assert m["tenants"]["b"]["failed"] == 0

    def test_single_tenant_registry_matches_solo(self):
        reqs = _requests(21, 10)
        ref = _solo_scores(3, reqs)
        with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
            reg.admit("only", _bundle(3))
            got = _scores([reg.score("only", r) for r in reqs])
            reg.close(release_bundles=True)
        assert np.array_equal(got, ref)

    def test_unknown_tenant_raises(self):
        with TenantRegistry(max_batch=8, max_wait_ms=1.0) as reg:
            with pytest.raises(KeyError, match="unknown tenant"):
                reg.submit("ghost", ScoreRequest())


@pytest.mark.chaos
class TestIsolation:
    def test_faults_in_one_tenant_never_degrade_the_other(self):
        """Armed lookup/score faults confined to the chaos tenant (its
        engine's injection gate): the clean tenant's answers stay
        bitwise, zero failed, zero degraded — including its LABELED
        robustness sub-counters, the per-tenant clean-run zero contract."""
        req_c, req_x = _requests(31, 16), _requests(32, 16)
        ref_c = _solo_scores(5, req_c)
        with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
            reg.admit("chaos", _bundle(4), inject_faults=True)
            reg.admit("clean", _bundle(5), inject_faults=False)
            with faults.inject("score:2,lookup:1"):
                futs = []
                for i in range(16):
                    futs.append(reg.submit("chaos", req_x[i], block=True))
                    futs.append(reg.submit("clean", req_c[i], block=True))
                res = [f.result(timeout=60) for f in futs]
            m = reg.metrics()
            reg.close(release_bundles=True)
        got_clean = _scores([r for i, r in enumerate(res) if i % 2 == 1])
        assert np.array_equal(got_clean, ref_c)
        clean = m["tenants"]["clean"]
        assert clean["failed"] == 0
        assert clean["degraded_batches"] == 0
        assert clean["shed"] == 0
        assert clean["deadline_missed"] == 0
        assert clean["fe_only_answers"] == 0
        # The chaos tenant absorbed every injection...
        assert faults.COUNTERS.get("injected_faults") > 0
        assert m["tenants"]["chaos"]["degraded_batches"] > 0
        # ...and the labeled sub-counters prove the blast radius: the
        # clean tenant's slice of every serving robustness counter is 0.
        for counter in (
            "serving_degraded_batches",
            "serving_shed_requests",
            "serving_deadline_misses",
            "serving_fe_only_requests",
        ):
            labeled = telemetry.METRICS.labeled_counters(counter)
            assert labeled.get("tenant=clean", 0) == 0, counter
        # Every chaos-tenant future still resolved (answers or typed
        # rejections — no hangs, no co-batched collateral).
        assert all(r is not None for r in res)

    def test_overload_sheds_typed_naming_the_tenant(self):
        """A tenant past its admission quota sheds with Overloaded
        NAMING it; the other tenant keeps admitting."""
        reqs = _requests(41, 12)
        with TenantRegistry(max_batch=64, max_wait_ms=250.0) as reg:
            # max_wait holds the queue open so the quota genuinely fills.
            reg.admit("small", _bundle(6), max_pending=3)
            reg.admit("roomy", _bundle(7))
            for i in range(3):
                reg.submit("small", reqs[i])
            with pytest.raises(Overloaded) as exc_info:
                reg.submit("small", reqs[3])
            assert exc_info.value.tenant == "small"
            # The neighbor's admission is untouched by small's overload.
            fut = reg.submit("roomy", reqs[4])
            assert fut.result(timeout=30) is not None
            shed_labeled = telemetry.METRICS.labeled_counters(
                "serving_shed_requests"
            )
            assert shed_labeled.get("tenant=small", 0) == 1
            assert shed_labeled.get("tenant=roomy", 0) == 0
            reg.close(release_bundles=True)

    def test_malformed_cobatch_request_never_kills_the_registry(self):
        """A co-batch-eligible tenant's malformed request (wrong feature
        width) poisons the shared pack — the dispatch must degrade per
        tenant (the offending future fails, neighbors answer bitwise)
        and the dispatch thread must survive for later traffic."""
        reqs = _requests(81, 8)
        ref = _solo_scores(21, reqs)
        with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
            reg.admit("bad", _bundle(20))
            reg.admit("good", _bundle(21))
            poison = ScoreRequest(
                features={"g": np.zeros(3, np.float32)},  # d_fe is 7
                entity_ids={"eid": "0"},
                uid="poison",
            )
            bad_fut = reg.submit("bad", poison, block=True)
            good_futs = [
                reg.submit("good", r, block=True) for r in reqs
            ]
            with pytest.raises(Exception):
                bad_fut.result(timeout=30)
            got = _scores([f.result(timeout=30) for f in good_futs])
            assert np.array_equal(got, ref)
            # The registry survives: both tenants keep answering.
            assert reg.score("good", reqs[0]).score == ref[0]
            m = reg.metrics()
            assert m["tenants"]["good"]["failed"] == 0
            reg.close(release_bundles=True)

    def test_cancelled_queued_future_releases_the_admission_slot(self):
        """Client-cancelled futures claimed out of the tenant queue must
        release their in_flight slot — a leak would wedge the quota shut
        and shed every later submit."""
        reqs = _requests(91, 8)
        with TenantRegistry(max_batch=64, max_wait_ms=150.0) as reg:
            reg.admit("t", _bundle(22), max_pending=3)
            futs = [reg.submit("t", reqs[i]) for i in range(3)]
            cancelled = [f.cancel() for f in futs]
            assert all(cancelled)
            # After the cancelled items are claimed (and dropped), the
            # quota must be whole again: three fresh submits admit and
            # answer.
            import time as _time

            deadline = _time.monotonic() + 10.0
            while _time.monotonic() < deadline:
                try:
                    fresh = [
                        reg.submit("t", reqs[3 + i]) for i in range(3)
                    ]
                    break
                except Overloaded:
                    _time.sleep(0.05)
            else:
                pytest.fail("cancelled futures leaked the tenant quota")
            for f in fresh:
                assert f.result(timeout=30) is not None
            reg.close(release_bundles=True)

    def test_deadline_budget_enforced_per_tenant(self):
        with TenantRegistry(max_batch=8, max_wait_ms=50.0) as reg:
            reg.admit("t", _bundle(8), deadline_ms=0.0)
            fut = reg.submit("t", _requests(51, 1)[0])
            with pytest.raises(DeadlineExceeded) as exc_info:
                fut.result(timeout=30)
            assert exc_info.value.tenant == "t"
            assert reg.metrics()["tenants"]["t"]["deadline_missed"] == 1
            reg.close(release_bundles=True)


class TestEviction:
    def test_hbm_pressure_demotes_coldest_and_stays_bitwise(self, tmp_path):
        """Admission of tenant N+1 over budget demotes (never fails) the
        coldest READY tenant to the host tier; the demoted tenant's
        answers stay bitwise through the TwoTierEntityStore overrides —
        the eviction round trip. Journal events validate."""
        reqs = _requests(61, 12)
        ref = _solo_scores(10, reqs)
        b0, b1, b2 = _bundle(10), _bundle(11), _bundle(12)
        per = b0.device_bytes_per_shard()
        journal_path = str(tmp_path / "journal.jsonl")
        journal = telemetry.install_journal(
            telemetry.RunJournal(journal_path)
        )
        try:
            with TenantRegistry(
                max_batch=16,
                max_wait_ms=2.0,
                hbm_budget_bytes=int(per * 2.5),
            ) as reg:
                reg.admit("cold", b0)
                reg.admit("warm", b1)
                # Touch "warm" so "cold" is the least-recently-active.
                reg.score("warm", _requests(62, 1)[0])
                reg.admit("new", b2)  # over budget -> demote, don't fail
                m = reg.metrics()
                assert m["tenants"]["cold"]["demoted"]
                assert not m["tenants"]["warm"]["demoted"]
                assert not m["tenants"]["new"]["demoted"]
                # Host-tier answers, bitwise — and the demoted tenant is
                # now out of the co-batch group (solo dispatch).
                got = _scores([reg.score("cold", r) for r in reqs])
                assert np.array_equal(got, ref)
                m2 = reg.metrics()
                assert m2["tenants"]["cold"]["cobatched_requests"] == 0
                assert (
                    m2["tenants"]["cold"]["device_bytes"]
                    < m["tenants"]["warm"]["device_bytes"]
                )
                assert faults.COUNTERS.get("tenant_demotions") == 1
                reg.close(release_bundles=True)
        finally:
            telemetry.uninstall_journal()
            journal.close()
        n_ok, errors = telemetry.validate_journal(journal_path)
        assert errors == []
        import json

        events = [json.loads(l) for l in open(journal_path)]
        admits = [e for e in events if e["type"] == "tenant_admit"]
        evicts = [e for e in events if e["type"] == "tenant_evict"]
        assert [e["tenant"] for e in admits] == ["cold", "warm", "new"]
        assert admits[-1]["demoted_tenants"] == ["cold"]
        assert len(evicts) == 1 and evicts[0]["tenant"] == "cold"
        assert evicts[0]["reason"] == "hbm_pressure"
        assert evicts[0]["freed_bytes"] > 0

    def test_sharded_tenant_is_never_an_eviction_victim(self):
        """An entity-sharded tenant cannot demote to the host tier;
        HBM-pressure eviction must skip it (even when it is coldest) and
        demote the next candidate instead of crashing the admission."""
        from photon_ml_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()
        model_sh, specs_sh = _make_model(23, 16 * int(mesh.devices.size))
        sharded = ServingBundle.from_model(
            model_sh, specs_sh, TASK, mesh=mesh
        )
        b_rep, b_new = _bundle(24), _bundle(25)
        budget = (
            sharded.device_bytes_per_shard()
            + b_rep.device_bytes_per_shard()
            + b_new.device_bytes_per_shard() // 2
        )
        with TenantRegistry(
            max_batch=16, max_wait_ms=2.0, hbm_budget_bytes=int(budget)
        ) as reg:
            reg.admit("sharded", sharded)  # admitted first: the coldest
            reg.admit("rep", b_rep)
            reg.score("rep", _requests(92, 1)[0])
            reg.admit("new", b_new)  # over budget: must demote "rep"
            m = reg.metrics()
            assert not m["tenants"]["sharded"]["demoted"]
            assert m["tenants"]["rep"]["demoted"]
            reg.close(release_bundles=True)

    def test_budget_unfit_after_all_demotions_refuses(self):
        b0, b1 = _bundle(13), _bundle(14)
        per = b0.device_bytes_per_shard()
        with TenantRegistry(
            max_batch=8, max_wait_ms=1.0, hbm_budget_bytes=int(per * 0.5)
        ) as reg:
            # Even an empty fleet cannot fit this tenant, and there is
            # nobody to demote: typed refusal, registry unchanged.
            with pytest.raises(HbmBudgetExceeded):
                reg.admit("big", b0)
            assert reg.tenant_names == []
            reg.close()
        b0.release()
        b1.release()

    def test_admit_fault_leaves_registry_unchanged(self):
        built = []

        def builder():
            b = _bundle(15)
            built.append(b)
            return b

        with TenantRegistry(max_batch=8, max_wait_ms=1.0) as reg:
            with faults.inject("tenant_admit:99"):
                with pytest.raises(faults.InjectedFault):
                    reg.admit("doomed", builder)
            assert reg.tenant_names == []
            # The same admission succeeds once the fault clears (one
            # bounded-retry trace, no residue).
            reg.admit("doomed", builder)
            assert reg.tenant_names == ["doomed"]
            reg.close(release_bundles=True)

    def test_evict_fault_rolls_back_and_keeps_serving(self):
        reqs = _requests(71, 8)
        ref = _solo_scores(16, reqs)
        with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
            reg.admit("t", _bundle(16))
            with faults.inject("tenant_evict:99"):
                with pytest.raises(faults.InjectedFault):
                    reg.demote("t", reason="drill")
            m = reg.metrics()
            assert not m["tenants"]["t"]["demoted"]
            got = _scores([reg.score("t", r) for r in reqs])
            assert np.array_equal(got, ref)
            # And a clean demotion afterwards still round-trips bitwise.
            reg.demote("t", reason="drill")
            got2 = _scores([reg.score("t", r) for r in reqs])
            assert np.array_equal(got2, ref)
            reg.close(release_bundles=True)


class TestLifecycle:
    def test_closed_registry_refuses_submits(self):
        reg = TenantRegistry(max_batch=8, max_wait_ms=1.0)
        reg.admit("t", _bundle(17))
        reg.close(release_bundles=True)
        with pytest.raises(RuntimeError, match="closed"):
            reg.submit("t", ScoreRequest())
        reg.close()  # idempotent

    def test_duplicate_admit_refused(self):
        with TenantRegistry(max_batch=8, max_wait_ms=1.0) as reg:
            reg.admit("t", _bundle(18))
            with pytest.raises(ValueError, match="already admitted"):
                reg.admit("t", _bundle(19))
            reg.close(release_bundles=True)

"""Shadow deployment & online evaluation suite (ISSUE 18).

The load-bearing contracts:

  * the online windowed evaluator runs the EXACT metric programs offline
    evaluation runs — `StreamingWindowEvaluator.evaluate_window` is
    bitwise-equal to `EvaluationSuite.evaluate` on identical arrays, so
    an online regression tolerance means the same thing in both worlds;
  * mirrored traffic NEVER touches the champion: a mirror or label-join
    fault degrades to champion-only serving (counted), the champion's
    answers stay bitwise vs. serving solo, and zero client requests
    fail;
  * verdicts actuate the existing machinery: reject tears the shadow
    tenant down (champion untouched), promote flips the challenger in
    through the BundleManager's atomic generation flip, and a promotion
    failure leaves the champion serving its OLD generation bitwise.
"""

from __future__ import annotations

import json
import time

import numpy as np
import jax.numpy as jnp
import pytest

from photon_ml_tpu.evaluation.suite import (
    EvaluationSuite,
    EvaluatorType,
    StreamingWindowEvaluator,
    regression,
)
from photon_ml_tpu.game.model import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.serving import (
    ScoreRequest,
    ServingBundle,
    ServingEngine,
    TenantRegistry,
)
from photon_ml_tpu.serving.shadow import ShadowController
from photon_ml_tpu.transformers.game_transformer import CoordinateScoringSpec
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import faults, telemetry

pytestmark = pytest.mark.serving

TASK = TaskType.LOGISTIC_REGRESSION
D_FE, D_RE, E = 7, 5, 24


def _make_model(seed: int, n_entities: int = E, scale: float = 1.0):
    rng = np.random.default_rng(seed)
    w = (scale * rng.normal(size=D_FE)).astype(np.float32)
    M = np.zeros((n_entities + 1, D_RE), np.float32)
    M[:n_entities] = scale * rng.normal(size=(n_entities, D_RE))
    model = GameModel(
        {
            "fixed": FixedEffectModel(Coefficients(jnp.asarray(w)), TASK),
            "per-e": RandomEffectModel(jnp.asarray(M), None, TASK),
        }
    )
    specs = {
        "fixed": CoordinateScoringSpec(shard="g"),
        "per-e": CoordinateScoringSpec(
            shard="re",
            random_effect_type="eid",
            entity_index={str(i): i for i in range(n_entities)},
        ),
    }
    return model, specs


def _bundle(seed: int, scale: float = 1.0) -> ServingBundle:
    model, specs = _make_model(seed, scale=scale)
    return ServingBundle.from_model(model, specs, TASK)


def _requests(seed: int, n: int):
    """Offset-free traffic: the negated-weights challenger in the reject
    drill must score the EXACT inverse of the champion."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, D_FE)).astype(np.float32)
    Xe = rng.normal(size=(n, D_RE)).astype(np.float32)
    ids = rng.integers(0, E + 6, size=n)  # trained + cold starts
    return [
        ScoreRequest(
            features={"g": X[i], "re": Xe[i]},
            entity_ids={"eid": str(int(ids[i]))},
            uid=str(i),
        )
        for i in range(n)
    ]


def _solo_scores(seed: int, reqs, scale: float = 1.0) -> np.ndarray:
    """The parity anchor: that bundle alone on a plain engine."""
    with ServingEngine(_bundle(seed, scale=scale), max_batch=32) as eng:
        return np.asarray(
            [r.score for r in eng.score_batch(reqs)], np.float64
        )


def _labels_from(scores: np.ndarray) -> np.ndarray:
    """Champion-separable labels: the champion ranks them perfectly
    (AUC exactly 1.0), so verdicts are deterministic functions of the
    challenger's ordering."""
    return (scores > 0.0).astype(np.float64)


def _drive(reg, controller, reqs, labels):
    """The serving loop's shadow hookup: submit to the champion, mirror,
    join the label. Returns the champion's scores (every future MUST
    resolve — a failed client request fails the test)."""
    futs = []
    for req, lab in zip(reqs, labels):
        fut = reg.submit("champ", req, block=True)
        futs.append(fut)
        if controller.mirror(req, fut):
            controller.record_label(req.uid, float(lab))
    return np.asarray([f.result(timeout=30).score for f in futs], np.float64)


class TestStreamingEvaluator:
    def test_windowed_matches_offline_bitwise(self):
        """One metric program, two worlds: the streaming window evaluator
        and the offline suite produce bitwise-identical values on
        identical (scores, labels, weights) arrays."""
        rng = np.random.default_rng(5)
        n = 96
        scores = jnp.asarray(rng.normal(size=n).astype(np.float32))
        labels = jnp.asarray(
            (rng.uniform(size=n) < 0.5).astype(np.float32)
        )
        weights = jnp.asarray(
            rng.uniform(0.5, 2.0, size=n).astype(np.float32)
        )
        ets = [EvaluatorType("AUC"), EvaluatorType("RMSE")]
        offline = EvaluationSuite(ets, labels, weights).evaluate(scores)
        online = StreamingWindowEvaluator(ets).evaluate_window(
            scores, labels, weights
        )
        assert online.results == offline.results  # exact, not approx
        assert online.primary_value == offline.primary_value

    def test_single_row_window(self):
        res = StreamingWindowEvaluator(
            [EvaluatorType("RMSE")]
        ).evaluate_window(jnp.asarray([0.25]), jnp.asarray([1.0]))
        assert np.isfinite(res.primary_value)

    def test_empty_window_refused(self):
        ev = StreamingWindowEvaluator([EvaluatorType("AUC")])
        with pytest.raises(ValueError, match="empty evaluation window"):
            ev.evaluate_window(jnp.zeros((0,)), jnp.zeros((0,)))

    def test_grouped_evaluators_refused(self):
        with pytest.raises(ValueError, match="grouped"):
            StreamingWindowEvaluator([EvaluatorType.parse("AUC:eid")])

    def test_regression_direction_aware(self):
        # AUC down and RMSE up must BOTH read as positive regressions.
        assert regression(EvaluatorType("AUC"), 0.7, 0.9) > 0
        assert regression(EvaluatorType("RMSE"), 0.9, 0.7) > 0
        assert regression(EvaluatorType("AUC"), 0.9, 0.7) < 0


class TestHistogramMerge:
    def test_merge_order_independent(self):
        """Per-window drift/calibration snapshots merge to the same
        histogram regardless of window arrival order."""
        h = telemetry.METRICS
        for v in (0.1, 0.2, 0.3):
            h.observe("shadow_score_drift", v)
        snap_a = h.histogram("shadow_score_drift").snapshot()
        h.reset()
        for v in (0.6, 0.7):
            h.observe("shadow_score_drift", v)
        snap_b = h.histogram("shadow_score_drift").snapshot()
        ab = telemetry.merge_histogram_snapshots(snap_a, snap_b)
        ba = telemetry.merge_histogram_snapshots(snap_b, snap_a)
        assert ab == ba
        assert telemetry.snapshot_quantile(
            ab, 0.5
        ) == telemetry.snapshot_quantile(ba, 0.5)


@pytest.mark.chaos
class TestMirrorIsolation:
    def test_mirror_fault_degrades_to_champion_only(self):
        """An armed `shadow_mirror` fault drops the MIRROR, never the
        client request: every champion future resolves bitwise vs. solo
        and the failure is counted."""
        reqs = _requests(31, 12)
        ref = _solo_scores(1, reqs)
        with TenantRegistry(max_batch=32, max_wait_ms=2.0) as reg:
            reg.admit("champ", _bundle(1))
            controller = ShadowController(
                reg, "champ", "cand", _bundle(2),
                window_size=64, min_windows=1, cooldown_s=0.0,
            )
            try:
                with faults.inject("shadow_mirror:2"):
                    got = _drive(
                        reg, controller, reqs, _labels_from(ref)
                    )
                summary = controller.summary()
            finally:
                controller.close()
            m = reg.metrics()
            reg.close(release_bundles=True)
        assert np.array_equal(got, ref)
        assert m["tenants"]["champ"]["failed"] == 0
        assert summary["mirror_failures"] == 2
        assert summary["mirrored_requests"] == len(reqs) - 2
        assert faults.COUNTERS.get("shadow_mirror_failures") == 2

    def test_label_join_fault_drops_label_only(self):
        reqs = _requests(32, 6)
        ref = _solo_scores(1, reqs)
        with TenantRegistry(max_batch=32, max_wait_ms=2.0) as reg:
            reg.admit("champ", _bundle(1))
            controller = ShadowController(
                reg, "champ", "cand", _bundle(2),
                window_size=64, min_windows=1, cooldown_s=0.0,
            )
            try:
                futs = [reg.submit("champ", r, block=True) for r in reqs]
                for r, f in zip(reqs, futs):
                    assert controller.mirror(r, f)
                with faults.inject("label_join:1"):
                    assert not controller.record_label(reqs[0].uid, 1.0)
                assert controller.record_label(reqs[1].uid, 1.0)
                got = np.asarray(
                    [f.result(timeout=30).score for f in futs], np.float64
                )
                assert controller.summary()["label_join_failures"] == 1
            finally:
                controller.close()
            reg.close(release_bundles=True)
        assert np.array_equal(got, ref)
        assert faults.COUNTERS.get("label_join_failures") == 1

    def test_mirror_fraction_deterministic(self):
        """fraction=0.5 mirrors exactly every 2nd eligible request — a
        credit accumulator, not an RNG."""
        reqs = _requests(33, 8)
        with TenantRegistry(max_batch=32, max_wait_ms=2.0) as reg:
            reg.admit("champ", _bundle(1))
            controller = ShadowController(
                reg, "champ", "cand", _bundle(2),
                window_size=64, min_windows=1, mirror_fraction=0.5,
            )
            try:
                picks = []
                for r in reqs:
                    fut = reg.submit("champ", r, block=True)
                    picks.append(controller.mirror(r, fut))
                    fut.result(timeout=30)
                # No uid -> no join key -> never mirrored.
                anon = ScoreRequest(
                    features=dict(reqs[0].features),
                    entity_ids=dict(reqs[0].entity_ids),
                )
                fut = reg.submit("champ", anon, block=True)
                assert not controller.mirror(anon, fut)
                fut.result(timeout=30)
            finally:
                controller.close()
            reg.close(release_bundles=True)
        assert picks == [False, True] * 4


@pytest.mark.chaos
class TestVerdicts:
    def test_reject_tears_down_shadow_champion_untouched(self, tmp_path):
        """A regressed challenger (negated weights: the exact inverse
        ranking, AUC 0 vs. the champion's 1) is rejected from shadow
        metrics ALONE and torn down; the champion serves bitwise
        throughout and after."""
        reqs = _requests(41, 16)
        ref = _solo_scores(1, reqs)
        labels = _labels_from(ref)
        journal_path = str(tmp_path / "journal.jsonl")
        journal = telemetry.install_journal(
            telemetry.RunJournal(journal_path)
        )
        try:
            with TenantRegistry(max_batch=32, max_wait_ms=2.0) as reg:
                reg.admit("champ", _bundle(1))
                v0 = int(reg.tenant("champ").engine._state.version)
                controller = ShadowController(
                    reg, "champ", "cand", _bundle(1, scale=-1.0),
                    window_size=len(reqs), min_windows=1, cooldown_s=0.0,
                )
                try:
                    got = _drive(reg, controller, reqs, labels)
                    assert (
                        controller.wait_for_verdict(timeout_s=60.0)
                        == "reject"
                    )
                    assert controller.status == "rejected"
                    # The shadow tenant is GONE from the fleet.
                    with pytest.raises(KeyError):
                        reg.tenant("cand")
                finally:
                    controller.close()
                # Champion: same generation, bitwise on fresh traffic.
                assert int(reg.tenant("champ").engine._state.version) == v0
                reqs2 = _requests(42, 8)
                ref2 = _solo_scores(1, reqs2)
                got2 = np.asarray(
                    [
                        reg.submit("champ", r, block=True)
                        .result(timeout=30)
                        .score
                        for r in reqs2
                    ],
                    np.float64,
                )
                m = reg.metrics()
                reg.close(release_bundles=True)
        finally:
            telemetry.uninstall_journal()
            journal.close()
        assert np.array_equal(got, ref)
        assert np.array_equal(got2, ref2)
        assert m["tenants"]["champ"]["failed"] == 0
        assert faults.COUNTERS.get("shadow_rollbacks") == 1
        n_ok, errors = telemetry.validate_journal(journal_path)
        assert errors == []
        events = [json.loads(l) for l in open(journal_path)]
        by_type = {}
        for e in events:
            by_type.setdefault(e["type"], []).append(e)
        assert len(by_type["shadow_start"]) == 1
        assert by_type["shadow_window"][0]["healthy"] is False
        (verdict,) = by_type["shadow_verdict"]
        assert verdict["decision"] == "reject"
        assert verdict["champion_metric"] == 1.0  # separable by design
        (rollback,) = by_type["shadow_rollback"]
        assert rollback["challenger"] == "cand"
        assert "shadow_promote" not in by_type

    def test_promote_flips_generation_atomically(self):
        """A healthy challenger (identical ranking) promotes through the
        BundleManager generation flip: the champion tenant now serves
        the challenger's bundle at version+1, and the shadow tenant is
        retired."""
        reqs = _requests(43, 16)
        ref = _solo_scores(1, reqs)
        labels = _labels_from(ref)
        chall_bundle = _bundle(1)  # same weights: equal metric, new bundle
        with TenantRegistry(max_batch=32, max_wait_ms=2.0) as reg:
            reg.admit("champ", _bundle(1))
            v0 = int(reg.tenant("champ").engine._state.version)
            controller = ShadowController(
                reg, "champ", "cand", chall_bundle,
                window_size=len(reqs), min_windows=1, cooldown_s=0.0,
            )
            try:
                got = _drive(reg, controller, reqs, labels)
                assert (
                    controller.wait_for_verdict(timeout_s=60.0) == "promote"
                )
                assert controller.status == "promoted"
            finally:
                controller.close()
            engine = reg.tenant("champ").engine
            assert int(engine._state.version) == v0 + 1
            assert engine._state.bundle is chall_bundle
            with pytest.raises(KeyError):
                reg.tenant("cand")
            # Post-promotion serving: bitwise vs. the challenger solo
            # (same weights as the old champion here, so the same ref).
            got2 = np.asarray(
                [
                    reg.submit("champ", r, block=True)
                    .result(timeout=30)
                    .score
                    for r in reqs
                ],
                np.float64,
            )
            m = reg.metrics()
            reg.close(release_bundles=True)
        assert np.array_equal(got, ref)
        assert np.array_equal(got2, ref)
        assert m["tenants"]["champ"]["failed"] == 0
        assert faults.COUNTERS.get("shadow_rollbacks") == 0

    def test_promotion_failure_keeps_old_generation_bitwise(self):
        """`shadow_promote` faults past the retry budget abort the
        promotion BEFORE the swap stages: the champion keeps serving its
        old generation bitwise and the failed promotion is a rollback."""
        reqs = _requests(44, 16)
        ref = _solo_scores(1, reqs)
        labels = _labels_from(ref)
        chall_bundle = _bundle(1)
        with TenantRegistry(max_batch=32, max_wait_ms=2.0) as reg:
            reg.admit("champ", _bundle(1))
            v0 = int(reg.tenant("champ").engine._state.version)
            controller = ShadowController(
                reg, "champ", "cand", chall_bundle,
                window_size=len(reqs), min_windows=1, cooldown_s=0.0,
                auto_actuate=False,
            )
            try:
                _drive(reg, controller, reqs, labels)
                assert (
                    controller.wait_for_verdict(timeout_s=60.0) == "promote"
                )
                assert controller.status == "promote_ready"
                with faults.inject("shadow_promote:99"):
                    assert (
                        controller.promote(raise_on_failure=False) is None
                    )
                assert controller.status == "rejected"
            finally:
                controller.close()
            assert int(reg.tenant("champ").engine._state.version) == v0
            got = np.asarray(
                [
                    reg.submit("champ", r, block=True)
                    .result(timeout=30)
                    .score
                    for r in reqs
                ],
                np.float64,
            )
            m = reg.metrics()
            reg.close(release_bundles=True)
        assert chall_bundle.released  # a failed promotion cleans up
        assert np.array_equal(got, ref)
        assert m["tenants"]["champ"]["failed"] == 0
        assert faults.COUNTERS.get("shadow_rollbacks") == 1


class TestDrain:
    def test_drain_digests_backlog_without_verdict(self):
        """A short replay can outrun the async evaluation worker (the
        first metric compile alone costs more than the replay): drain()
        must block until every already-joined full window has been
        evaluated, then return immediately — None when min_windows has
        not been reached — instead of sleeping out its full timeout."""
        reqs = _requests(61, 20)
        ref = _solo_scores(1, reqs)
        labels = _labels_from(ref)
        with TenantRegistry(max_batch=32, max_wait_ms=2.0) as reg:
            reg.admit("champ", _bundle(1))
            controller = ShadowController(
                reg, "champ", "cand", _bundle(2),
                window_size=8, min_windows=5, cooldown_s=0.0,
            )
            try:
                _drive(reg, controller, reqs, labels)
                t0 = time.monotonic()
                verdict = controller.drain(timeout_s=60.0)
                waited = time.monotonic() - t0
                # 20 rows at window_size=8 -> exactly 2 full windows
                # digested; the 4-row remainder must not stall drain
                # until the deadline.
                assert verdict is None
                assert controller.status == "observing"
                assert controller.summary()["windows"] == 2
                assert waited < 50.0
            finally:
                controller.close()
            reg.close(release_bundles=True)

    def test_drain_returns_verdict_after_actuation(self):
        """When the backlog holds enough windows for a verdict, drain()
        returns it only after the actuation has landed: an identical-
        weights challenger comes back 'promote' with the generation
        already flipped."""
        reqs = _requests(62, 16)
        ref = _solo_scores(1, reqs)
        labels = _labels_from(ref)
        chall_bundle = _bundle(1)
        with TenantRegistry(max_batch=32, max_wait_ms=2.0) as reg:
            reg.admit("champ", _bundle(1))
            v0 = int(reg.tenant("champ").engine._state.version)
            controller = ShadowController(
                reg, "champ", "cand", chall_bundle,
                window_size=len(reqs), min_windows=1, cooldown_s=0.0,
            )
            try:
                _drive(reg, controller, reqs, labels)
                assert controller.drain(timeout_s=60.0) == "promote"
                assert controller.status == "promoted"
            finally:
                controller.close()
            engine = reg.tenant("champ").engine
            assert int(engine._state.version) == v0 + 1
            reg.close(release_bundles=True)


class TestRegistryRemove:
    def test_remove_drains_and_refuses_new_submits(self):
        reqs = _requests(51, 4)
        with TenantRegistry(max_batch=32, max_wait_ms=2.0) as reg:
            reg.admit("a", _bundle(1))
            for r in reqs:
                reg.submit("a", r, block=True).result(timeout=30)
            reg.remove("a", release_bundle=True)
            assert "a" not in reg.tenant_names
            with pytest.raises(KeyError):
                reg.submit("a", reqs[0])
            reg.close()

    def test_remove_unknown_tenant_raises(self):
        with TenantRegistry(max_batch=32, max_wait_ms=2.0) as reg:
            with pytest.raises(KeyError):
                reg.remove("ghost")
            reg.close()


class TestShadowGatedRefresh:
    def test_one_round_gated_loop_commits_on_clean_verdict(
        self, tmp_path, monkeypatch
    ):
        """End-to-end refresh gate (cli/refresh --shadow-gate): the
        round's delta lands as a shadow tenant, earns a promote verdict
        on labelled probe traffic, and only then commits through the
        normal apply_delta generation flip."""
        from photon_ml_tpu.cli.refresh import run_refresh_loop

        # The challenger is the champion plus one tiny delta batch; on
        # 8-row probe windows the verdict needs a tolerance wider than
        # small-sample AUC noise (the strict default belongs to
        # production-sized windows).
        monkeypatch.setenv("PHOTON_SHADOW_REGRESSION_TOL", "0.35")
        journal_path = str(tmp_path / "journal.jsonl")
        journal = telemetry.install_journal(
            telemetry.RunJournal(journal_path)
        )
        try:
            summary = run_refresh_loop(
                str(tmp_path),
                rounds=1,
                base_rows=96,
                batch_rows=48,
                entities=8,
                new_entities_per_round=1,
                churn_entities=2,
                task=TASK,
                seed=0,
                shadow_gate=True,
                probe_rows=16,
            )
        finally:
            telemetry.uninstall_journal()
            journal.close()
        (rec,) = summary["rounds"]
        assert rec["shadow_verdict"] == "promote"
        assert rec["committed"] is True
        block = rec["shadow"]
        assert block["champion"] == "live"
        assert block["challenger"] == "delta-r0"
        assert block["windows"] == 2
        assert block["mirror_failures"] == 0
        n_ok, errors = telemetry.validate_journal(journal_path)
        assert errors == []
        events = [json.loads(l) for l in open(journal_path)]
        types = [e["type"] for e in events]
        assert "shadow_start" in types
        assert "shadow_verdict" in types
        assert "delta_apply" in types
        assert "delta_rollback" not in types

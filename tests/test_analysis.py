"""photon-lint (photon_ml_tpu/analysis/): the tier-1 invariant gate.

Three layers:

1. Fixture corpus: every check FIRES on its known-bad snippet under
   tests/analysis_fixtures/<check>/bad/ and stays SILENT on the
   known-good sibling — so a refactor that quietly lobotomizes a checker
   fails here, not months later when the invariant rots.
2. Pragma engine: reasoned pragmas suppress exactly their line; a
   reasonless or unknown-check pragma is itself a finding.
3. The live tree: zero findings across the package, bench.py, and
   tests/ — the machine-checked statement that every invariant photon-lint
   encodes actually HOLDS right now (and that no disable pragma exists
   without a reason, since pragma hygiene is unsuppressable).
"""

import os
import subprocess
import sys

import pytest

from photon_ml_tpu.analysis import CHECKS, run_checks
from photon_ml_tpu.analysis.__main__ import main as lint_main

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")

# check name -> fixture directory (underscored)
CHECK_DIRS = {
    "knob-registry": "knob_registry",
    "fault-site-sync": "fault_site_sync",
    "jit-purity": "jit_purity",
    "thread-lifecycle": "thread_lifecycle",
    "donation-aliasing": "donation_aliasing",
    "contract-key-drift": "contract_key_drift",
    "metric-name-sync": "metric_name_sync",
    "planner-constant": "planner_constant",
    "tolerance-pin": "tolerance_pin",
}


def _fixture(check: str, kind: str) -> str:
    return os.path.join(FIXTURES, CHECK_DIRS[check], kind)


def test_every_check_has_fixtures():
    assert set(CHECK_DIRS) == set(CHECKS), (
        "every registered check needs a bad/good fixture pair "
        "(tests/analysis_fixtures/<check>/{bad,good}) and an entry here"
    )
    for check, d in CHECK_DIRS.items():
        for kind in ("bad", "good"):
            path = os.path.join(FIXTURES, d, kind)
            assert os.path.isdir(path), f"missing fixture dir {path}"


@pytest.mark.parametrize("check", sorted(CHECK_DIRS))
def test_check_fires_on_bad_fixture(check):
    findings = run_checks(paths=[_fixture(check, "bad")], checks=[check])
    own = [f for f in findings if f.check == check]
    assert own, f"{check} reported nothing on its known-bad fixture"
    for f in own:
        # knob-registry's stale-table-row direction anchors at README.md;
        # everything else anchors at python source.
        assert f.line > 0 and f.path.endswith((".py", "README.md"))


@pytest.mark.parametrize("check", sorted(CHECK_DIRS))
def test_check_silent_on_good_fixture(check):
    findings = run_checks(paths=[_fixture(check, "good")], checks=[check])
    assert not findings, (
        f"{check} false-positived on its known-good fixture:\n"
        + "\n".join(f.render() for f in findings)
    )


def test_bad_fixtures_cover_every_direction():
    """Spot-check the multi-direction checks: the bad fixtures must
    exercise each rule, not just the easiest one."""
    fs = run_checks(
        paths=[_fixture("fault-site-sync", "bad")], checks=["fault-site-sync"]
    )
    msgs = "\n".join(f.message for f in fs)
    assert "not registered" in msgs  # unknown plant
    assert "no fault_point() plants it" in msgs  # unplanted description
    assert "string literal" in msgs  # computed site

    ks = run_checks(
        paths=[_fixture("knob-registry", "bad")], checks=["knob-registry"]
    )
    msgs = "\n".join(f.message for f in ks)
    assert "raw environment read" in msgs
    assert "unregistered knob" in msgs
    # Table sync is row-based in BOTH directions: a prose mention is not
    # a row, and a stale row is flagged too.
    assert "has no row in the README knob table" in msgs
    assert "stale row" in msgs
    # The indirect (module-constant) read resolves too: 4 raw reads.
    assert sum("raw environment read" in f.message for f in ks) == 4

    ts = run_checks(
        paths=[_fixture("thread-lifecycle", "bad")],
        checks=["thread-lifecycle"],
    )
    msgs = "\n".join(f.message for f in ts)
    assert "without name=" in msgs
    # sep.join(parts) in the fixture must not count as the module's join.
    assert "never joined" in msgs

    js = run_checks(paths=[_fixture("jit-purity", "bad")], checks=["jit-purity"])
    msgs = "\n".join(f.message for f in js)
    for needle in ("time.", "np.random", ".item()", "os.getenv", "global",
                   "one call deep"):
        assert needle in msgs, f"jit-purity bad fixture missed {needle!r}"

    ds = run_checks(
        paths=[_fixture("donation-aliasing", "bad")],
        checks=["donation-aliasing"],
    )
    assert len(ds) == 2  # named-callable AND immediately-invoked forms

    ms = run_checks(
        paths=[_fixture("metric-name-sync", "bad")],
        checks=["metric-name-sync"],
    )
    msgs = "\n".join(f.message for f in ms)
    assert "not declared" in msgs  # undeclared increment
    assert "nothing increments it" in msgs  # declared-but-unincremented
    assert "statically resolvable" in msgs  # computed name
    assert "counter= argument" in msgs  # unresolvable retry counter

    ps = run_checks(
        paths=[_fixture("planner-constant", "bad")],
        checks=["planner-constant"],
    )
    msgs = "\n".join(f.message for f in ps)
    # All four binding forms must fire: parameter default, call keyword,
    # plain assignment, and the bucket-shape tuple literal.
    assert "max_wait_ms=2.0" in msgs
    assert "max_wait_ms=1.0" in msgs
    assert "chunk_rows=262144" in msgs
    assert "prefetch_depth=2" in msgs
    assert "bucket_shapes=(64, 128, 256)" in msgs


# ------------------------------------------------------------------ pragmas


def test_reasonless_and_unknown_pragmas_are_findings():
    bad = os.path.join(FIXTURES, "pragma", "bad")
    findings = run_checks(paths=[bad], checks=["thread-lifecycle"])
    pragma = [f for f in findings if f.check == "pragma"]
    assert any("without a reason" in f.message for f in pragma)
    assert any("unknown check" in f.message for f in pragma)
    # A reasonless pragma suppresses nothing: the thread finding survives.
    assert any(f.check == "thread-lifecycle" for f in findings)


def test_reasoned_pragma_suppresses_trailing_and_comment_line():
    good = os.path.join(FIXTURES, "pragma", "good")
    findings = run_checks(paths=[good], checks=["thread-lifecycle"])
    assert not findings, "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------- live tree


def test_live_tree_is_clean():
    """THE gate: zero findings over the package, bench.py, and tests/.
    Also proves no disable pragma anywhere lacks a reason (pragma
    hygiene cannot be suppressed)."""
    findings = run_checks()
    assert not findings, "photon-lint findings on the live tree:\n" + "\n".join(
        f.render() for f in findings
    )


def test_contracts_match_live_producers():
    """The schemas the drift check defends must match what the code
    actually emits — a wrong schema with no duplicates is still wrong."""
    from photon_ml_tpu.utils import contracts

    # Key order is part of the zipped producer schema.
    assert contracts.SERVING_SHARDING_KEYS[0] == "entity_sharded"
    for name, keys in contracts.ALL_CONTRACTS.items():
        assert len(keys) == len(set(keys)), f"{name} has duplicate keys"
        assert keys, f"{name} is empty"


# ---------------------------------------------------------------------- CLI


def test_cli_list_checks_and_exit_codes(capsys):
    assert lint_main(["--list-checks"]) == 0
    out = capsys.readouterr().out
    for name in CHECKS:
        assert name in out

    bad = _fixture("thread-lifecycle", "bad")
    assert lint_main([bad]) == 1  # findings -> nonzero (CI/pre-commit hook)
    assert "thread-lifecycle" in capsys.readouterr().out

    good = _fixture("thread-lifecycle", "good")
    assert lint_main([good]) == 0
    assert lint_main(["--check", "no-such-check"]) == 2


@pytest.mark.slow
def test_cli_subprocess_matches_faults_list_sites_convention():
    """`python -m photon_ml_tpu.analysis --list-checks` works as a real
    subprocess, mirroring `python -m photon_ml_tpu.utils.faults
    --list-sites` (slow: pays a fresh interpreter+import)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.analysis", "--list-checks"],
        capture_output=True,
        text=True,
        timeout=120,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr
    assert "knob-registry" in out.stdout

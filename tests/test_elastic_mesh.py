"""Live mesh elasticity (ISSUE 13): reshard under traffic, mid-fit
mesh-loss resume, and hot-row rebalancing.

The contracts:

* `plan_reshard` computes the honest row-movement plan between shard
  layouts — only rows whose owning device changes count, padding never;
* a live reshard (shrink 8->4, regrow 4->8, collapse to replicated)
  keeps every answer BITWISE-equal to a cold-started engine at the new
  shape, drops zero requests under live traffic, and any failure at any
  step (staging, commit, a SIGKILL mid-restage) rolls back to the old
  generation with zero failed requests;
* a mid-fit `MeshLoss` costs exactly one repeated sweep: the resumed fit
  is bitwise the uninterrupted one, whether the state reassembles in
  memory or through the durable-checkpoint fallback;
* hot-row rebalancing closes the telemetry->placement loop: the two-tier
  store's observed promotions become the new hot-tier preload through
  the same stage/flip/rollback machinery, bitwise-neutral by
  construction.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.game.model import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.parallel.mesh import make_mesh, surviving_mesh
from photon_ml_tpu.serving import (
    ScoreRequest,
    ServingBundle,
    ServingEngine,
    plan_rebalance,
    plan_reshard,
)
from photon_ml_tpu.transformers.game_transformer import CoordinateScoringSpec
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import faults, telemetry

pytestmark = pytest.mark.serving

TASK = TaskType.LOGISTIC_REGRESSION
D_FE, D_RE, E = 7, 5, 24


def _fixture(rng, n=16):
    w = rng.normal(size=D_FE).astype(np.float32)
    M = np.zeros((E + 1, D_RE), np.float32)
    M[:E] = rng.normal(size=(E, D_RE))
    model = GameModel(
        {
            "fixed": FixedEffectModel(Coefficients(jnp.asarray(w)), TASK),
            "per-e": RandomEffectModel(jnp.asarray(M), None, TASK),
        }
    )
    specs = {
        "fixed": CoordinateScoringSpec(shard="g"),
        "per-e": CoordinateScoringSpec(
            shard="re",
            random_effect_type="eid",
            entity_index={str(i): i for i in range(E)},
        ),
    }
    X = rng.normal(size=(n, D_FE)).astype(np.float32)
    Xe = rng.normal(size=(n, D_RE)).astype(np.float32)
    reqs = [
        ScoreRequest(
            features={"g": X[i], "re": Xe[i]},
            entity_ids={"eid": str((2 * i) % (E + 6))},
            uid=str(i),
        )
        for i in range(n)
    ]
    return model, specs, reqs


def _scores(results):
    return np.asarray([r.score for r in results], np.float64)


def _cold_scores(model, specs, reqs, mesh=None):
    with ServingEngine(
        ServingBundle.from_model(model, specs, TASK, mesh=mesh), max_batch=16
    ) as eng:
        return _scores(eng.score_batch(reqs))


# --------------------------------------------------------------- plan math


class TestReshardPlan:
    def test_shrink_plan_matches_brute_force_row_movement(self, rng):
        model, specs, _ = _fixture(rng)
        mesh8 = make_mesh()
        mesh4 = surviving_mesh(4)
        bundle = ServingBundle.from_model(model, specs, TASK, mesh=mesh8)
        plan = plan_reshard(bundle, mesh4)
        assert plan.old_shards == 8 and plan.new_shards == 4
        (cplan,) = plan.coordinates
        logical = E + 1
        assert cplan.logical_rows == logical
        assert cplan.padded_rows % 4 == 0
        # Brute force: a logical row moves iff its owning device changes.
        old_devs = list(np.asarray(mesh8.devices).flat)
        new_devs = list(np.asarray(mesh4.devices).flat)
        rows_per_old = bundle.coordinates["per-e"].shard_health.rows_per_shard
        rows_per_new = cplan.padded_rows // 4
        moved = sum(
            1
            for r in range(logical)
            if old_devs[r // rows_per_old] is not new_devs[r // rows_per_new]
        )
        assert cplan.moved_rows == moved > 0
        assert cplan.moved_bytes == moved * D_RE * 4
        assert plan.moved_rows == moved
        # Segments tile each new shard's block exactly.
        for k, segs in enumerate(cplan.segments):
            lo, hi = k * rows_per_new, (k + 1) * rows_per_new
            assert segs[0].row_lo == lo and segs[-1].row_hi == hi
            for a, b in zip(segs, segs[1:]):
                assert a.row_hi == b.row_lo

    def test_plan_requires_a_shard_tracked_coordinate(self, rng):
        model, specs, _ = _fixture(rng)
        bundle = ServingBundle.from_model(model, specs, TASK, hot_rows=4)
        try:
            # Drop the FE-only structure down to just the two-tier coord:
            # nothing left to mesh-reshard.
            with pytest.raises(ValueError, match="rebalance"):
                plan_reshard(
                    ServingBundle(
                        task=TASK,
                        coordinates={
                            "per-e": bundle.coordinates["per-e"]
                        },
                    ),
                    make_mesh(),
                )
        finally:
            bundle.release()

    def test_shard_loads_feed_the_plan(self, rng):
        """The engine records per-shard request load (cold starts
        excluded); the plan surfaces it so operators can see the
        overloaded shard."""
        model, specs, reqs = _fixture(rng)
        mesh8 = make_mesh()
        bundle = ServingBundle.from_model(model, specs, TASK, mesh=mesh8)
        with ServingEngine(bundle, max_batch=16) as eng:
            eng.score_batch(reqs)
            plan = plan_reshard(eng.bundle, surviving_mesh(4))
        (cplan,) = plan.coordinates
        known = sum(
            1 for r in reqs if int(r.entity_ids["eid"]) < E
        )
        assert sum(cplan.shard_loads) == known
        assert len(cplan.shard_loads) == 8


# --------------------------------------------------- live reshard (bitwise)


@pytest.mark.elastic
@pytest.mark.slow
class TestLiveReshard:
    """Multi-device reshard drills: slow+elastic, out of tier-1 (the
    plan/rollback/rebalance/mesh-loss contracts stay tier-1)."""

    def test_shrink_regrow_replicate_bitwise(self, rng):
        """8 -> 4 -> 8 -> replicated, each generation bitwise-equal to a
        cold start at that shape, zero hot-path recompiles after each
        pre-warm, and the generation counter advancing."""
        model, specs, reqs = _fixture(rng)
        ref = _cold_scores(model, specs, reqs)
        assert np.array_equal(
            ref, _cold_scores(model, specs, reqs, mesh=surviving_mesh(4))
        )
        bundle = ServingBundle.from_model(model, specs, TASK, mesh=make_mesh())
        with ServingEngine(bundle, max_batch=16) as eng:
            eng.warmup()
            orch = eng.reshard_orchestrator
            info = orch.reshard(surviving_mesh(4))
            assert info["version"] == 1 and info["old_released"]
            assert info["old_shards"] == 8 and info["new_shards"] == 4
            assert np.array_equal(_scores(eng.score_batch(reqs)), ref)
            assert eng.recompiles_after_warmup == 0  # pre-warm covered it
            info2 = orch.reshard(make_mesh())
            assert info2["new_shards"] == 8
            assert np.array_equal(_scores(eng.score_batch(reqs)), ref)
            info3 = orch.reshard(None)  # collapse to replicated
            assert info3["new_shards"] == 1
            assert np.array_equal(_scores(eng.score_batch(reqs)), ref)
            m = eng.metrics()
            assert m["bundle_reshards"] == 3
            assert m["bundle_version"] == 3
            assert m["sharding"]["entity_sharded"] is False
            # The load-time bundle HANDLE stays a live view of the
            # current generation across every flip — callers that encode
            # requests through it (the CLI's lazy replay stream) must
            # keep working, never hit a release()-gutted husk.
            assert not bundle.released
            rows, cold = bundle.coordinates["per-e"].lookup_rows(["3"])
            assert rows[0] == 3 and cold == 0
        assert faults.counters().get("reshard_rollbacks", 0) == 0

    @pytest.mark.slow
    def test_reshard_under_live_traffic_zero_failed(self, rng):
        """The acceptance drill: shrink 8->4 and regrow 4->8 while a
        closed-loop client scores continuously through the batcher —
        zero failed requests, every answer bitwise one of the two
        (identical) generations' answers, post-reshard probe bitwise a
        cold start at the new shape."""
        model, specs, reqs = _fixture(rng)
        ref = _cold_scores(model, specs, reqs)
        bundle = ServingBundle.from_model(model, specs, TASK, mesh=make_mesh())
        eng = ServingEngine(bundle, max_batch=16)
        eng.warmup()
        stop = threading.Event()
        failures: list = []
        answered = [0]

        def _traffic(b):
            j = 0
            while not stop.is_set():
                r = reqs[j % len(reqs)]
                try:
                    res = b.score(r)
                    if res.score != ref[j % len(reqs)]:
                        failures.append(
                            f"answer drift at {j}: {res.score}"
                        )
                    answered[0] += 1
                except Exception as exc:  # noqa: BLE001 - recorded
                    failures.append(repr(exc))
                j += 1

        with eng, eng.batcher(max_wait_ms=0.5) as batcher:
            th = threading.Thread(
                target=_traffic, args=(batcher,), name="elastic-traffic"
            )
            th.start()
            time.sleep(0.2)
            info = eng.reshard_orchestrator.reshard(surviving_mesh(4))
            time.sleep(0.2)
            info2 = eng.reshard_orchestrator.reshard(make_mesh())
            time.sleep(0.2)
            stop.set()
            th.join(timeout=60)
            assert not th.is_alive()
            probe = _scores(eng.score_batch(reqs))
        assert not failures, failures[:3]
        assert answered[0] > 0
        assert info["new_shards"] == 4 and info2["new_shards"] == 8
        assert np.array_equal(probe, ref)
        assert faults.counters().get("reshard_rollbacks", 0) == 0


# ------------------------------------------------------------ rollback drills


@pytest.mark.elastic
@pytest.mark.chaos
class TestReshardRollback:
    def test_stage_failure_rolls_back_and_keeps_serving(
        self, rng, monkeypatch
    ):
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        model, specs, reqs = _fixture(rng)
        ref = _cold_scores(model, specs, reqs)
        bundle = ServingBundle.from_model(model, specs, TASK, mesh=make_mesh())
        with ServingEngine(bundle, max_batch=16) as eng:
            eng.warmup()
            with faults.inject("reshard_stage:9999"):
                with pytest.raises(faults.InjectedFault):
                    eng.reshard_orchestrator.reshard(surviving_mesh(4))
                # Old generation NEVER stopped serving, bitwise intact.
                assert np.array_equal(_scores(eng.score_batch(reqs)), ref)
            c = faults.counters()
            assert c["reshard_rollbacks"] == 1
            assert c["reshard_retries"] > 0
            m = eng.metrics()
            assert m["bundle_version"] == 0
            assert m["bundle_reshards"] == 0
            assert m["bundle_reshard_rollbacks"] == 1
            # A later clean reshard still succeeds (no wedged state).
            info = eng.reshard_orchestrator.reshard(surviving_mesh(4))
            assert info["version"] == 1
            assert np.array_equal(_scores(eng.score_batch(reqs)), ref)

    def test_commit_failure_rolls_back(self, rng, monkeypatch):
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        model, specs, reqs = _fixture(rng)
        ref = _cold_scores(model, specs, reqs)
        bundle = ServingBundle.from_model(model, specs, TASK, mesh=make_mesh())
        with ServingEngine(bundle, max_batch=16) as eng:
            with faults.inject("reshard_commit:1"):
                with pytest.raises(faults.InjectedFault):
                    eng.reshard_orchestrator.reshard(surviving_mesh(4))
            assert np.array_equal(_scores(eng.score_batch(reqs)), ref)
            assert eng.bundle_version == 0
            assert faults.counters()["reshard_rollbacks"] == 1

    @pytest.mark.slow
    def test_rollback_under_live_traffic_zero_failed(
        self, rng, monkeypatch
    ):
        """An injected staging failure mid-traffic: every request keeps
        answering bitwise off the old generation while the reshard dies."""
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        model, specs, reqs = _fixture(rng)
        ref = _cold_scores(model, specs, reqs)
        bundle = ServingBundle.from_model(model, specs, TASK, mesh=make_mesh())
        eng = ServingEngine(bundle, max_batch=16)
        eng.warmup()
        stop = threading.Event()
        failures: list = []
        answered = [0]

        def _traffic(b):
            j = 0
            while not stop.is_set():
                try:
                    res = b.score(reqs[j % len(reqs)])
                    if res.score != ref[j % len(reqs)]:
                        failures.append(f"drift at {j}")
                    answered[0] += 1
                except Exception as exc:  # noqa: BLE001 - recorded
                    failures.append(repr(exc))
                j += 1

        with eng, eng.batcher(max_wait_ms=0.5) as batcher:
            th = threading.Thread(
                target=_traffic, args=(batcher,), name="elastic-rb-traffic"
            )
            th.start()
            time.sleep(0.1)
            with faults.inject("reshard_stage:9999"):
                with pytest.raises(faults.InjectedFault):
                    eng.reshard_orchestrator.reshard(surviving_mesh(4))
            time.sleep(0.1)
            stop.set()
            th.join(timeout=60)
            assert not th.is_alive()
        assert not failures, failures[:3]
        assert answered[0] > 0
        assert eng.bundle_version == 0

    @pytest.mark.slow
    def test_midstage_sigkill_leaves_old_generation_intact(self, tmp_path):
        """SIGKILL in the middle of the restage: the dying process had
        answered every request correctly up to the kill (zero failed in
        its log), and a restarted engine on the SAME model serves the old
        generation bitwise — a torn reshard leaves nothing behind."""
        script = _SIGKILL_CHILD_SCRIPT
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        env.pop("PALLAS_AXON_POOL_IPS", None)
        out = str(tmp_path)

        def _run(mode):
            return subprocess.Popen(
                [sys.executable, "-c", script, out, mode],
                env=env,
                cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )

        proc = _run("serve-and-reshard")
        marker = os.path.join(out, "staging")
        deadline = time.monotonic() + 120
        try:
            while not os.path.exists(marker):
                if proc.poll() is not None:
                    _, err = proc.communicate()
                    raise AssertionError(
                        f"child exited before staging: {err[-2000:]}"
                    )
                if time.monotonic() > deadline:
                    raise AssertionError("child never reached staging")
                time.sleep(0.05)
            time.sleep(0.1)  # inside the deliberately-slow restage
            os.kill(proc.pid, signal.SIGKILL)
        finally:
            proc.wait()
        log = json.load(open(os.path.join(out, "traffic.json")))
        assert log["failed"] == 0
        assert log["answered"] > 0
        # Restart: the old generation is fully intact — bitwise replay.
        proc2 = _run("restart-probe")
        _, err2 = proc2.communicate(timeout=300)
        assert proc2.returncode == 0, err2[-2000:]
        pre = np.load(os.path.join(out, "pre_scores.npy"))
        post = np.load(os.path.join(out, "post_scores.npy"))
        assert np.array_equal(pre, post)


_SIGKILL_CHILD_SCRIPT = r"""
import json, os, sys, threading, time
import numpy as np
import jax.numpy as jnp
from photon_ml_tpu.game.model import (
    Coefficients, FixedEffectModel, GameModel, RandomEffectModel,
)
from photon_ml_tpu.parallel.mesh import make_mesh, surviving_mesh
from photon_ml_tpu.serving import ScoreRequest, ServingBundle, ServingEngine
from photon_ml_tpu.serving.reshard import MeshReshardOrchestrator
from photon_ml_tpu.transformers.game_transformer import CoordinateScoringSpec
from photon_ml_tpu.types import TaskType

out, mode = sys.argv[1], sys.argv[2]
TASK = TaskType.LOGISTIC_REGRESSION
D_FE, D_RE, E = 7, 5, 24
rng = np.random.default_rng(7)
w = rng.normal(size=D_FE).astype(np.float32)
M = np.zeros((E + 1, D_RE), np.float32)
M[:E] = rng.normal(size=(E, D_RE))
model = GameModel({
    "fixed": FixedEffectModel(Coefficients(jnp.asarray(w)), TASK),
    "per-e": RandomEffectModel(jnp.asarray(M), None, TASK),
})
specs = {
    "fixed": CoordinateScoringSpec(shard="g"),
    "per-e": CoordinateScoringSpec(
        shard="re", random_effect_type="eid",
        entity_index={str(i): i for i in range(E)},
    ),
}
n = 16
X = rng.normal(size=(n, D_FE)).astype(np.float32)
Xe = rng.normal(size=(n, D_RE)).astype(np.float32)
reqs = [ScoreRequest(features={"g": X[i], "re": Xe[i]},
                     entity_ids={"eid": str(i % E)}) for i in range(n)]
bundle = ServingBundle.from_model(model, specs, TASK, mesh=make_mesh())
eng = ServingEngine(bundle, max_batch=16)
eng.warmup()
probe = np.asarray([r.score for r in eng.score_batch(reqs)], np.float64)

if mode == "restart-probe":
    np.save(os.path.join(out, "post_scores.npy"), probe)
    eng.close()
    sys.exit(0)

np.save(os.path.join(out, "pre_scores.npy"), probe)
log = {"answered": 0, "failed": 0}

def flush():
    tmp = os.path.join(out, ".traffic.json.tmp")
    with open(tmp, "w") as f:
        json.dump(log, f)
    os.replace(tmp, os.path.join(out, "traffic.json"))

stop = threading.Event()

def traffic(b):
    j = 0
    while not stop.is_set():
        try:
            res = b.score(reqs[j % n])
            if res.score != probe[j % n]:
                log["failed"] += 1
            else:
                log["answered"] += 1
        except Exception:
            log["failed"] += 1
        if j % 8 == 0:
            flush()
        j += 1

orig = MeshReshardOrchestrator._stage_resharded_params

def slow_stage(self, coord, cplan, new_mesh):
    open(os.path.join(out, "staging"), "w").close()
    time.sleep(60)  # the parent SIGKILLs us inside this window
    return orig(self, coord, cplan, new_mesh)

MeshReshardOrchestrator._stage_resharded_params = slow_stage
with eng, eng.batcher(max_wait_ms=0.5) as batcher:
    th = threading.Thread(target=traffic, args=(batcher,), name="t")
    th.start()
    time.sleep(0.2)
    flush()
    eng.reshard_orchestrator.reshard(surviving_mesh(4))
"""


# --------------------------------------------------------------- rebalance


class TestRebalance:
    def _hot_fixture(self, rng):
        """Requests hammering the tail entities (NOT the default preload
        prefix), so every pass pays cold-tier hits until a rebalance."""
        model, specs, _ = _fixture(rng)
        n = 16
        X = rng.normal(size=(n, D_FE)).astype(np.float32)
        Xe = rng.normal(size=(n, D_RE)).astype(np.float32)
        reqs = [
            ScoreRequest(
                features={"g": X[i], "re": Xe[i]},
                entity_ids={"eid": str(18 + (i % 6))},
            )
            for i in range(n)
        ]
        return model, specs, reqs

    def test_rebalance_preloads_observed_hot_rows_bitwise(self, rng):
        model, specs, reqs = self._hot_fixture(rng)
        ref = _cold_scores(model, specs, reqs)
        bundle = ServingBundle.from_model(model, specs, TASK, hot_rows=6)
        store = bundle.coordinates["per-e"].store
        with ServingEngine(bundle, max_batch=16) as eng:
            eng.warmup()
            for _ in range(2):
                assert np.array_equal(_scores(eng.score_batch(reqs)), ref)
                store.drain()
            hot = plan_rebalance(
                eng.bundle.coordinates["per-e"], min_promotions=1
            )
            assert set(hot) == set(range(18, 24))
            info = eng.reshard_orchestrator.rebalance(
                "per-e", min_promotions=1
            )
            assert info["rebalanced_rows"] == 6
            assert sorted(info["preloaded_rows"]) == list(range(18, 24))
            new_store = eng.bundle.coordinates["per-e"].store
            before = new_store.cold_hits
            assert np.array_equal(_scores(eng.score_batch(reqs)), ref)
            # The observed-hot rows now live in the hot tier: zero cold
            # hits on the replayed stream.
            assert new_store.cold_hits == before
            assert store._closed  # the replaced store joined its worker
            m = eng.metrics()
            assert m["bundle_rebalances"] == 1
        assert faults.counters()["rebalanced_rows"] == 6

    def test_rebalance_noop_below_min_promotions(self, rng):
        model, specs, reqs = self._hot_fixture(rng)
        bundle = ServingBundle.from_model(model, specs, TASK, hot_rows=6)
        store = bundle.coordinates["per-e"].store
        with ServingEngine(bundle, max_batch=16) as eng:
            eng.score_batch(reqs)
            store.drain()
            # Each hot entity promoted once; a floor of 100 means nothing
            # has earned a move — no generation flip.
            info = eng.reshard_orchestrator.rebalance(
                "per-e", min_promotions=100
            )
            assert info == {
                "rebalanced_rows": 0,
                "version": 0,
                "committed": False,
            }
            assert eng.bundle_version == 0
        bundle.release()

    def test_rebalance_stage_failure_rolls_back(self, rng, monkeypatch):
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        model, specs, reqs = self._hot_fixture(rng)
        ref = _cold_scores(model, specs, reqs)
        bundle = ServingBundle.from_model(model, specs, TASK, hot_rows=6)
        store = bundle.coordinates["per-e"].store
        with ServingEngine(bundle, max_batch=16) as eng:
            assert np.array_equal(_scores(eng.score_batch(reqs)), ref)
            store.drain()
            with faults.inject("reshard_stage:9999"):
                with pytest.raises(faults.InjectedFault):
                    eng.reshard_orchestrator.rebalance(
                        "per-e", min_promotions=1
                    )
            # Old store still live and serving bitwise.
            assert not store._closed
            assert np.array_equal(_scores(eng.score_batch(reqs)), ref)
            assert eng.bundle_version == 0
            assert faults.counters()["reshard_rollbacks"] == 1
        bundle.release()


# ------------------------------------------------------- journal coverage


class TestElasticJournal:
    def test_reshard_and_mesh_loss_events_validate(self, rng, tmp_path):
        """The new journal event types round-trip through a real run:
        reshard_start/commit on a live shrink, reshard_rollback on an
        injected failure — every line schema-valid."""
        path = str(tmp_path / "journal.jsonl")
        journal = telemetry.RunJournal(path)
        telemetry.install_journal(journal)
        try:
            model, specs, reqs = _fixture(rng)
            bundle = ServingBundle.from_model(
                model, specs, TASK, mesh=make_mesh()
            )
            with ServingEngine(bundle, max_batch=16) as eng:
                eng.reshard_orchestrator.reshard(surviving_mesh(4))
                with faults.inject("reshard_commit:1"):
                    with pytest.raises(faults.InjectedFault):
                        eng.reshard_orchestrator.reshard(make_mesh())
            telemetry.emit_event(
                "mesh_loss",
                iteration=1,
                coordinate="per-e",
                surviving_devices=4,
                source="memory",
            )
        finally:
            telemetry.uninstall_journal()
            journal.close()
        n_ok, errors = telemetry.validate_journal(path)
        assert not errors
        types = [
            json.loads(line)["type"] for line in open(path) if line.strip()
        ]
        for expected in (
            "reshard_start",
            "reshard_commit",
            "reshard_rollback",
            "mesh_loss",
        ):
            assert expected in types, (expected, types)


# --------------------------------------------------- mid-fit mesh-loss resume


@pytest.mark.chaos
@pytest.mark.elastic
class TestMeshLossResume:
    N_ENTITIES, ROWS_EACH, D = 40, 6, 5

    def _coords(self, mesh=None):
        from photon_ml_tpu.data.game_dataset import (
            GameDataset,
            RandomEffectDataConfig,
            build_random_effect_dataset,
        )
        from photon_ml_tpu.game.coordinate import RandomEffectCoordinate
        from photon_ml_tpu.optimize.config import (
            L2,
            CoordinateOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.parallel.mesh import (
            pad_game_dataset,
            shard_game_dataset,
            shard_random_effect_dataset,
        )

        cfg = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(max_iterations=8, tolerance=1e-7),
            regularization=L2,
            reg_weight=1.0,
        )
        re_cfg = RandomEffectDataConfig("entityId", "re", min_bucket=8)
        rng = np.random.default_rng(0)
        n = self.N_ENTITIES * self.ROWS_EACH
        Xe = rng.normal(size=(n, self.D)).astype(np.float32)
        ent = np.repeat(np.arange(self.N_ENTITIES), self.ROWS_EACH)
        y = (rng.uniform(size=n) > 0.5).astype(np.float32)
        ds = GameDataset.build(
            {"re": jnp.asarray(Xe)}, y, id_tags={"entityId": ent}
        )
        if mesh is not None:
            ds = shard_game_dataset(
                pad_game_dataset(ds, mesh.devices.size), mesh
            )
            red = shard_random_effect_dataset(
                build_random_effect_dataset(ds, re_cfg), mesh
            )
        else:
            red = build_random_effect_dataset(ds, re_cfg)
        return {"re": RandomEffectCoordinate(ds, red, cfg, TASK)}

    def _matrix(self, result):
        m = np.asarray(result.model.models["re"].coefficients_matrix)
        return m[: self.N_ENTITIES + 1]

    def test_injected_loss_costs_exactly_one_repeated_sweep(self):
        from photon_ml_tpu.game.coordinate_descent import (
            run_coordinate_descent,
        )

        clean = self._matrix(
            run_coordinate_descent(self._coords(make_mesh()), 2, seed=3)
        )
        with faults.inject("mesh_loss@2") as inj:
            res = run_coordinate_descent(
                self._coords(make_mesh()),
                2,
                seed=3,
                mesh_rebuilder=lambda: self._coords(surviving_mesh(4)),
            )
        assert inj.injected == {"mesh_loss": 1}
        assert res.mesh_losses == 1
        assert res.repeated_sweeps == 1
        np.testing.assert_array_equal(self._matrix(res), clean)
        assert faults.counters()["mesh_losses"] == 1

    def test_checkpoint_fallback_resumes_bitwise(self, tmp_path, monkeypatch):
        """The in-memory reassembly failing (the device blocks really are
        gone) falls back to the durable checkpoint — still bitwise."""
        import photon_ml_tpu.game.checkpoint as ckpt_mod
        from photon_ml_tpu.game.coordinate_descent import (
            run_coordinate_descent,
        )

        clean = self._matrix(
            run_coordinate_descent(self._coords(make_mesh()), 2, seed=3)
        )

        def unreachable(model):
            raise OSError("device blocks unreachable")

        monkeypatch.setattr(
            ckpt_mod, "reassemble_model_in_memory", unreachable
        )
        with faults.inject("mesh_loss@2"):
            res = run_coordinate_descent(
                self._coords(make_mesh()),
                2,
                seed=3,
                checkpoint_dir=str(tmp_path / "ck"),
                mesh_rebuilder=lambda: self._coords(surviving_mesh(4)),
            )
        assert res.mesh_losses == 1
        np.testing.assert_array_equal(self._matrix(res), clean)

    def test_no_recovery_source_reraises(self, monkeypatch):
        """In-memory reassembly broken AND no checkpoint configured: the
        MeshLoss surfaces instead of silently continuing on torn state."""
        import photon_ml_tpu.game.checkpoint as ckpt_mod
        from photon_ml_tpu.game.coordinate_descent import (
            run_coordinate_descent,
        )

        monkeypatch.setattr(
            ckpt_mod,
            "reassemble_model_in_memory",
            lambda m: (_ for _ in ()).throw(OSError("gone")),
        )
        with faults.inject("mesh_loss@2"):
            with pytest.raises(faults.MeshLoss):
                run_coordinate_descent(
                    self._coords(make_mesh()), 2, seed=3
                )

    def test_exhausted_losses_reraise(self):
        from photon_ml_tpu.game.coordinate_descent import (
            run_coordinate_descent,
        )

        with faults.inject("mesh_loss:9999"):
            with pytest.raises(faults.MeshLoss):
                run_coordinate_descent(
                    self._coords(make_mesh()),
                    2,
                    seed=3,
                    max_mesh_losses=1,
                    mesh_rebuilder=lambda: self._coords(surviving_mesh(4)),
                )
        assert faults.counters()["mesh_losses"] == 2

    def test_device_error_on_sharded_coordinate_escalates(self):
        """A device-shaped failure that escaped the coordinate's own
        failure domain (re-dispatch AND bucket-loop fallback both dead)
        on an entity-sharded coordinate becomes a MeshLoss recovery."""
        from photon_ml_tpu.game.coordinate_descent import (
            run_coordinate_descent,
        )

        clean = self._matrix(
            run_coordinate_descent(self._coords(make_mesh()), 2, seed=3)
        )
        coords = self._coords(make_mesh())
        orig = coords["re"].train
        calls = [0]

        def hang_once(*a, **k):
            calls[0] += 1
            if calls[0] == 1:
                raise faults.DeviceHang("dead shard group")
            return orig(*a, **k)

        coords["re"].train = hang_once
        res = run_coordinate_descent(
            coords,
            2,
            seed=3,
            mesh_rebuilder=lambda: self._coords(surviving_mesh(4)),
        )
        assert res.mesh_losses == 1
        np.testing.assert_array_equal(self._matrix(res), clean)

    def test_counters_roll_back_with_the_interrupted_sweep(self):
        """A divergence-guard rejection INSIDE the interrupted sweep
        replays deterministically after the rollback — it must be counted
        once, not twice (the sweep snapshot restores the counters too).

        Two coordinates so the rejection (coordinate a) can precede the
        loss (coordinate b) within one sweep. solve invocations: it0 a=1
        b=2; it1 a=3,4 (both armed -> rejected, +2) then b hits
        mesh_loss@4 (its 4th update) -> rollback; the replayed a update
        rejects again on invocations 5,6. With the counter rollback the
        run reports ONE logical rejection's worth (2 attempts)."""
        from photon_ml_tpu.data.game_dataset import (
            GameDataset,
            RandomEffectDataConfig,
            build_random_effect_dataset,
        )
        from photon_ml_tpu.game.coordinate import RandomEffectCoordinate
        from photon_ml_tpu.game.coordinate_descent import (
            run_coordinate_descent,
        )
        from photon_ml_tpu.optimize.config import (
            L2,
            CoordinateOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.parallel.mesh import (
            pad_game_dataset,
            shard_game_dataset,
            shard_random_effect_dataset,
        )

        cfg = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(max_iterations=6, tolerance=1e-7),
            regularization=L2,
            reg_weight=1.0,
        )
        rng = np.random.default_rng(0)
        n = self.N_ENTITIES * self.ROWS_EACH
        Xe = rng.normal(size=(n, self.D)).astype(np.float32)
        ent_a = np.repeat(np.arange(self.N_ENTITIES), self.ROWS_EACH)
        ent_b = np.tile(np.arange(self.ROWS_EACH), self.N_ENTITIES)
        y = (rng.uniform(size=n) > 0.5).astype(np.float32)

        def coords(mesh):
            ds = GameDataset.build(
                {"re": jnp.asarray(Xe)},
                y,
                id_tags={"a": ent_a, "b": ent_b},
            )
            if mesh is not None:
                ds = shard_game_dataset(
                    pad_game_dataset(ds, mesh.devices.size), mesh
                )
                build = lambda tag: shard_random_effect_dataset(
                    build_random_effect_dataset(
                        ds, RandomEffectDataConfig(tag, "re", min_bucket=8)
                    ),
                    mesh,
                )
            else:
                build = lambda tag: build_random_effect_dataset(
                    ds, RandomEffectDataConfig(tag, "re", min_bucket=8)
                )
            return {
                "a": RandomEffectCoordinate(ds, build("a"), cfg, TASK),
                "b": RandomEffectCoordinate(ds, build("b"), cfg, TASK),
            }

        with faults.inject("solve@3+4+5+6,mesh_loss@4"):
            res = run_coordinate_descent(
                coords(make_mesh()),
                2,
                seed=3,
                mesh_rebuilder=lambda: coords(surviving_mesh(4)),
            )
        assert res.mesh_losses == 1 and res.repeated_sweeps == 1
        assert res.diverged_steps == 2, res.diverged_steps

    def test_non_device_error_still_propagates(self):
        """A programming error must never be laundered into an elastic
        'recovery' — same discipline as the collective fallback."""
        from photon_ml_tpu.game.coordinate_descent import (
            run_coordinate_descent,
        )

        coords = self._coords(make_mesh())

        def boom(*a, **k):
            raise ValueError("a bug, not weather")

        coords["re"].train = boom
        with pytest.raises(ValueError, match="a bug"):
            run_coordinate_descent(
                coords,
                1,
                seed=3,
                mesh_rebuilder=lambda: self._coords(surviving_mesh(4)),
            )

"""Host data-plane pipeline tests.

Covers the PR-1 tentpole contracts:
  * a pipelined (threaded/overlapped) fit is BITWISE-identical to a
    forced-synchronous fit — the pipeline moves when host builds and
    device uploads happen, never what they compute;
  * `fit_timing` carries the per-stage prepare breakdown
    {re_build, projector, stats, pack, upload, compile} (+ `other`) and,
    in a synchronous run, the stages tile `prepare_s`;
  * the chunk-canonicalization compile cache shares random-effect solver
    programs across coordinates (jit cache entries do not grow when the
    second coordinate trains);
  * `begin_pack_async` defers to synchronous packing on a 1-effective-core
    host (the r05 e2e-vs-micro ingest gap);
  * ShardDict async prefetch materializes the same device arrays as the
    synchronous fault path.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data import pipeline as pl
from photon_ml_tpu.data.containers import SparseFeatures
from photon_ml_tpu.data.game_dataset import (
    FixedEffectDataConfig,
    GameDataset,
    HostCSR,
    RandomEffectDataConfig,
    ShardDict,
)
from photon_ml_tpu.estimators.game_estimator import (
    PREPARE_STAGES,
    GameEstimator,
)
from photon_ml_tpu.optimize.config import (
    L2,
    CoordinateOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils.observability import (
    TimingRegistry,
    record_stage,
    stage_scope,
    stage_timer,
)


def _glmix_dataset(seed=0, n=512, n_entities=16, d=6):
    """Small GLMix fixture: one dense shard feeding a fixed effect and two
    random effects whose entities all have IDENTICAL row counts — so the
    two coordinates produce identical canonical bucket shapes and must
    share compiled solver programs."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    # Exactly n / n_entities rows per entity for BOTH tags (a permutation
    # of a balanced assignment), so bucket capacities coincide.
    users = rng.permutation(np.repeat(np.arange(n_entities), n // n_entities))
    movies = rng.permutation(np.repeat(np.arange(n_entities), n // n_entities))
    w = rng.normal(size=d) * 0.5
    b_u = rng.normal(size=n_entities) * 0.7
    margins = X @ w + b_u[users]
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margins))).astype(np.float32)
    return GameDataset.build(
        {"g": jnp.asarray(X)},
        y,
        id_tags={"userId": users, "movieId": movies},
    )


DATA_CONFIGS = {
    "global": FixedEffectDataConfig("g"),
    "per-user": RandomEffectDataConfig("userId", "g", min_bucket=8),
    "per-movie": RandomEffectDataConfig("movieId", "g", min_bucket=8),
}


def _opt_configs():
    cfg = CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=15, tolerance=1e-7),
        regularization=L2,
        reg_weight=1.0,
    )
    return {cid: cfg for cid in DATA_CONFIGS}


def _fit(pipeline):
    est = GameEstimator(
        TaskType.LOGISTIC_REGRESSION,
        dict(DATA_CONFIGS),
        coordinate_descent_iterations=2,
        pipeline=pipeline,
    )
    results = est.fit(_glmix_dataset(), None, [_opt_configs()])
    return est, results[0].model


def _coeff_arrays(model):
    out = {}
    for cid in model.coordinate_ids:
        m = model[cid]
        if hasattr(m, "coefficients_matrix"):
            out[cid] = np.asarray(m.coefficients_matrix)
        else:
            out[cid] = np.asarray(m.coefficients.means)
    return out


class TestPipelineParity:
    def test_overlapped_fit_bitwise_identical_to_synchronous(self, monkeypatch):
        # Force the worker pool on even on a 1-core CI host: parity must
        # hold for the ACTUALLY-threaded path.
        monkeypatch.setenv("PHOTON_HOST_THREADS", "4")
        _, model_sync = _fit(pipeline=False)
        _, model_pipe = _fit(pipeline=True)
        sync, pipe = _coeff_arrays(model_sync), _coeff_arrays(model_pipe)
        assert set(sync) == set(pipe)
        for cid in sync:
            assert np.array_equal(sync[cid], pipe[cid]), (
                f"coordinate {cid}: pipelined fit diverged from synchronous"
            )

    def test_fit_timing_breakdown_tiles_prepare(self):
        from photon_ml_tpu.utils.contracts import FIT_TIMING_REQUIRED_KEYS

        est, _ = _fit(pipeline=False)
        for key in FIT_TIMING_REQUIRED_KEYS:
            assert key in est.fit_timing, f"fit_timing missing {key!r}"
        total = sum(est.fit_timing[k] for k in (*PREPARE_STAGES, "other"))
        prepare_s = est.fit_timing["prepare_s"]
        assert abs(total - prepare_s) <= 0.05 * max(prepare_s, 1e-9), (
            f"stage keys sum to {total:.4f}s but prepare_s={prepare_s:.4f}s"
        )
        # The dominant prepare stages must be non-trivially attributed.
        assert est.fit_timing["re_build"] > 0.0
        assert est.fit_timing["compile"] > 0.0
        # Pack placement split (r06 satellite): always present, even when
        # no bucketed pack engaged this fit — the bench e2e contract fails
        # loudly on their absence.
        assert "pack_device_s" in est.fit_timing
        assert "pack_host_s" in est.fit_timing
        assert est.fit_timing["pack_path"] in (
            "none",
            "device",
            "native-sharded",
            "native",
            "numpy",
        )


class TestCompileCacheSharing:
    def test_re_solver_programs_shared_across_coordinates(self):
        """Satellite: the power-of-two bucket canonicalization exists so the
        two RE coordinates share jitted solver programs — count jit cache
        entries before/after the second coordinate trains."""
        ds = _glmix_dataset(seed=3)
        est = GameEstimator(
            TaskType.LOGISTIC_REGRESSION,
            dict(DATA_CONFIGS),
            coordinate_descent_iterations=1,
        )
        prepared = est.prepare(ds)
        cfg = _opt_configs()["per-user"]
        c_user = est._coordinate_for(ds, "per-user", prepared["per-user"], cfg)
        c_movie = est._coordinate_for(ds, "per-movie", prepared["per-movie"], cfg)
        # Same static recipe + no normalization => the process-wide RE jit
        # cache must hand both coordinates the SAME jitted callables — the
        # per-bucket solver AND the scan-dispatched sweep program.
        assert c_user._train_bucket is c_movie._train_bucket
        assert c_user._train_scan is c_movie._train_scan
        from photon_ml_tpu.game.coordinate import sweep_scan_enabled

        solver = (
            c_user._train_scan if sweep_scan_enabled() else c_user._train_bucket
        )
        c_user.train(ds.offsets)
        counter = getattr(solver, "_cache_size", None)
        if counter is None:
            pytest.skip("jax version exposes no jit cache counter")
        entries_after_first = counter()
        assert entries_after_first >= 1
        c_movie.train(ds.offsets)
        assert counter() == entries_after_first, (
            "second RE coordinate compiled new solver programs — the "
            "canonical bucket shapes are not being shared"
        )


class TestPackDeferral:
    def _csr(self, n=64, k=4, dim=32):
        rng = np.random.default_rng(5)
        return HostCSR(
            np.arange(n + 1, dtype=np.int64) * k,
            rng.integers(0, dim, size=n * k).astype(np.int64),
            rng.normal(size=n * k).astype(np.float32),
            dim,
        )

    def test_defers_on_single_core(self, monkeypatch):
        from photon_ml_tpu.ops import pallas_sparse

        monkeypatch.setattr(
            pallas_sparse, "pack_worth_considering", lambda n: True
        )
        monkeypatch.setenv("PHOTON_HOST_THREADS", "1")
        csr = self._csr()
        pallas_sparse.begin_pack_async(csr, 64)
        assert csr.pack_future is None, (
            "1-core host must defer the background pack"
        )

    def test_defers_when_pipeline_forced_off(self, monkeypatch):
        from photon_ml_tpu.ops import pallas_sparse

        monkeypatch.setattr(
            pallas_sparse, "pack_worth_considering", lambda n: True
        )
        monkeypatch.setenv("PHOTON_HOST_THREADS", "8")
        monkeypatch.setenv("PHOTON_PIPELINE", "0")
        csr = self._csr()
        pallas_sparse.begin_pack_async(csr, 64)
        assert csr.pack_future is None, (
            "PHOTON_PIPELINE=0 must keep ingest thread-free"
        )

    def test_starts_thread_with_parallelism(self, monkeypatch):
        from photon_ml_tpu.ops import pallas_sparse

        monkeypatch.setattr(
            pallas_sparse, "pack_worth_considering", lambda n: True
        )
        monkeypatch.setenv("PHOTON_HOST_THREADS", "4")
        csr = self._csr()
        pallas_sparse.begin_pack_async(csr, 64)
        assert csr.pack_future is not None
        csr.pack_future.result(timeout=30)  # pack completes off-thread


class TestShardPrefetch:
    def _host_sparse(self):
        rng = np.random.default_rng(9)
        return SparseFeatures(
            rng.integers(0, 50, size=(40, 4)).astype(np.int32),
            rng.normal(size=(40, 4)).astype(np.float32),
            50,
        )

    def test_prefetch_matches_synchronous_fault(self):
        sp = self._host_sparse()
        d_pre = ShardDict({"s": sp})
        d_pre.prefetch("s")
        got_pre = d_pre["s"]
        d_sync = ShardDict({"s": dataclasses.replace(sp)})
        got_sync = d_sync["s"]
        assert isinstance(got_pre.indices, jax.Array)
        assert np.array_equal(np.asarray(got_pre.indices), np.asarray(got_sync.indices))
        assert np.array_equal(np.asarray(got_pre.values), np.asarray(got_sync.values))
        # The device copy is cached back: a second access returns it as-is.
        assert d_pre["s"] is got_pre

    def test_prefetch_noop_on_dense_and_device(self):
        dense = jnp.ones((4, 2))
        d = ShardDict({"x": dense})
        d.prefetch("x")  # no-op, no error
        assert d["x"] is dense
        d.prefetch("missing")  # absent key: silently ignored


class TestParallelismGates:
    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("PHOTON_HOST_THREADS", "7")
        assert pl.effective_host_parallelism() == 7
        monkeypatch.setenv("PHOTON_HOST_THREADS", "1")
        assert not pl.pipeline_enabled(None)
        # Explicit override beats the 1-core auto-gate; env beats auto.
        assert pl.pipeline_enabled(True)
        monkeypatch.setenv("PHOTON_PIPELINE", "0")
        monkeypatch.setenv("PHOTON_HOST_THREADS", "8")
        assert not pl.pipeline_enabled(None)
        monkeypatch.setenv("PHOTON_PIPELINE", "1")
        monkeypatch.setenv("PHOTON_HOST_THREADS", "1")
        assert pl.pipeline_enabled(None)

    def test_stage_scopes_are_thread_local_with_explicit_handoff(self):
        import threading

        reg = TimingRegistry()
        other = TimingRegistry()
        with stage_scope(reg):
            with stage_timer("stats"):
                pass
            # A bare worker thread does NOT inherit the scope (no silent
            # cross-fit attribution) ...
            t = threading.Thread(target=lambda: record_stage("upload", 0.25))
            t.start()
            t.join()
            assert "upload" not in reg.sections

            # ... the spawner hands its registry over explicitly instead.
            def _worker():
                with stage_scope(reg):
                    record_stage("upload", 0.25)

            t = threading.Thread(target=_worker)
            t.start()
            t.join()
            # A scope opened on another thread never leaks into this one.
            with stage_scope(other):
                pass
        record_stage("upload", 99.0)  # scope closed: no-op
        assert reg.get("upload") == 0.25
        assert "stats" in reg.sections
        assert other.sections == {}

    def test_uploader_records_into_submitters_registry(self):
        import time as _t

        reg = TimingRegistry()
        with stage_scope(reg):
            up = pl.AsyncUploader(stage="upload")
            fut = up.submit("k", lambda: 42)
        assert fut.result(timeout=30) == 42
        for _ in range(200):  # the stage record lands just after the result
            if "upload" in reg.sections:
                break
            _t.sleep(0.01)
        assert "upload" in reg.sections

"""Legacy staged GLM driver (Driver.scala stages, GLMSuite I/O surface)."""

import json
import os

import numpy as np
import pytest

from photon_ml_tpu.cli import glm_driver

REF_IN = "/root/reference/photon-client/src/integTest/resources/DriverIntegTest/input"
needs_ref = pytest.mark.skipif(
    not os.path.isdir(REF_IN), reason="reference fixtures not mounted"
)


@needs_ref
class TestLegacyDriver:
    def test_avro_staged_run_with_validation(self, tmp_path):
        """heart.avro through all four stages: metrics per weight, model
        selection, text + Avro model output, feature summarization."""
        out = str(tmp_path / "out")
        summary = glm_driver.run(glm_driver.build_parser().parse_args([
            "--training-data-directory", os.path.join(REF_IN, "heart.avro"),
            "--validate-data-directory", os.path.join(REF_IN, "heart_validation.avro"),
            "--output-directory", out,
            "--task", "LOGISTIC_REGRESSION",
            "--optimizer", "TRON",
            "--regularization-weights", "0.1,1,10",
            "--summarization-output-dir", str(tmp_path / "summary"),
        ]))
        assert summary["stages"] == ["INIT", "PREPROCESSED", "TRAINED", "VALIDATED"]
        assert set(summary["validation_metrics"]) == {"0.1", "1.0", "10.0"}
        m = summary["validation_metrics"][str(summary["best_regularization_weight"])]
        assert m["Area under ROC"] > 0.7
        assert "Peak F1 score" in m and "Per-datum log likelihood" in m
        # Text model format: name\tterm\tvalue\tregWeight, value-descending.
        lines = open(os.path.join(out, "learned-models-text", "model-10.0.txt")).read().splitlines()
        vals = [float(l.split("\t")[2]) for l in lines]
        assert vals == sorted(vals, reverse=True)
        assert all(l.split("\t")[3] == "10.0" for l in lines)
        # Avro model per weight reloads through the model store.
        from photon_ml_tpu.data.index_map import IndexMap
        from photon_ml_tpu.io import model_store

        imap = IndexMap.load(os.path.join(out, "feature-index.json"))
        art = model_store.load_game_model(os.path.join(out, "models", "10.0"), {"global": imap})
        assert np.all(np.isfinite(art.coordinates["global"].means))
        # Summarization Avro written.
        from photon_ml_tpu.io import avro as avro_io

        _, recs = avro_io.read_container(str(tmp_path / "summary" / "part-00000.avro"))
        assert len(recs) == imap.size - 1

    def test_libsvm_format_with_constraints(self, tmp_path):
        """heart.txt (the LibSVM twin of heart.avro) through the LIBSVM input
        format with an inline JSON constraint string."""
        out = str(tmp_path / "out")
        summary = glm_driver.run(glm_driver.build_parser().parse_args([
            "--training-data-directory", os.path.join(REF_IN, "heart.txt"),
            "--validate-data-directory", os.path.join(REF_IN, "heart_validation.txt"),
            "--output-directory", out,
            "--format", "LIBSVM",
            "--regularization-weights", "1",
            "--coefficient-constraints",
            json.dumps([{"name": "1", "term": "", "lowerBound": -0.01, "upperBound": 0.01}]),
        ]))
        assert summary["stages"][-1] == "VALIDATED"
        from photon_ml_tpu.data.index_map import IndexMap
        from photon_ml_tpu.io import model_store

        imap = IndexMap.load(os.path.join(out, "feature-index.json"))
        art = model_store.load_game_model(os.path.join(out, "models", "1.0"), {"global": imap})
        w1 = art.coordinates["global"].means[imap.get_index("1")]
        assert -0.01 - 1e-6 <= w1 <= 0.01 + 1e-6

    def test_stage_assertions(self, tmp_path):
        st = glm_driver._State()
        st.update(glm_driver.DriverStage.PREPROCESSED)
        with pytest.raises(RuntimeError, match="Expected driver stage INIT"):
            st.assert_stage(glm_driver.DriverStage.INIT)

    def test_output_dir_guard(self, tmp_path):
        out = tmp_path / "out"
        out.mkdir()
        with pytest.raises(FileExistsError):
            glm_driver.run(glm_driver.build_parser().parse_args([
                "--training-data-directory", os.path.join(REF_IN, "heart.avro"),
                "--output-directory", str(out),
            ]))

    def test_linear_regression_on_mg_fixtures(self, tmp_path):
        """mg_train/mg_test (the reference's linear-regression LibSVM pair):
        regression facet metrics + RMSE-minimizing selection, cross-checked
        against sklearn Ridge on identical data."""
        out = str(tmp_path / "out")
        summary = glm_driver.run(glm_driver.build_parser().parse_args([
            "--training-data-directory", os.path.join(REF_IN, "mg_train.txt"),
            "--validate-data-directory", os.path.join(REF_IN, "mg_test.txt"),
            "--output-directory", out,
            "--format", "LIBSVM",
            "--task", "LINEAR_REGRESSION",
            "--optimizer", "TRON",
            "--regularization-weights", "0.01,1,100",
        ]))
        best = str(summary["best_regularization_weight"])
        metrics = summary["validation_metrics"][best]
        assert {"Root mean square error", "Mean absolute error", "R-squared"} <= set(metrics)
        # Selection minimizes RMSE across the sweep.
        rmses = {w: m["Root mean square error"] for w, m in summary["validation_metrics"].items()}
        assert rmses[best] == min(rmses.values())

        from sklearn.linear_model import Ridge
        from sklearn.metrics import mean_squared_error

        from photon_ml_tpu.data.libsvm import read_libsvm

        tr = read_libsvm(os.path.join(REF_IN, "mg_train.txt"))
        te = read_libsvm(os.path.join(REF_IN, "mg_test.txt"), num_features=tr.dim - 1)
        # Our objective: sum-loss 0.5(z-y)^2 + rw/2 ||w||^2 == Ridge(alpha=rw)
        # up to Ridge's intercept handling; fit without intercept on the
        # same appended-intercept design matrix.
        clf = Ridge(alpha=float(best), fit_intercept=False)
        clf.fit(tr.to_dense(), tr.labels)
        sk_rmse = float(np.sqrt(mean_squared_error(te.labels, te.to_dense() @ clf.coef_)))
        assert rmses[best] == pytest.approx(sk_rmse, rel=0.02)

    def test_selected_features_whitelist(self, tmp_path):
        """--selected-features-file restricts training to the listed
        (name, term) features + intercept (GLMSuite selectedFeaturesFile)."""
        from photon_ml_tpu.io import avro as avro_io
        from photon_ml_tpu.io.avro_data import write_training_examples

        rng = np.random.default_rng(0)
        n = 200
        X = rng.normal(size=(n, 3))
        y = (X[:, 0] + 0.1 * rng.normal(size=n) > 0).astype(float)
        feats = [[("fa", float(X[i,0])), ("fb", float(X[i,1])), ("fc", float(X[i,2]))]
                 for i in range(n)]
        train = str(tmp_path / "train.avro")
        write_training_examples(train, feats, y.tolist())
        sel = str(tmp_path / "selected.avro")
        avro_io.write_container(sel, {
            "type": "record", "name": "FeatureNameTermAvro",
            "namespace": "com.linkedin.photon.avro.generated",
            "fields": [{"name": "name", "type": "string"},
                       {"name": "term", "type": "string"}],
        }, [{"name": "fa", "term": ""}])

        out = str(tmp_path / "out")
        glm_driver.run(glm_driver.build_parser().parse_args([
            "--training-data-directory", train,
            "--output-directory", out,
            "--regularization-weights", "1",
            "--selected-features-file", sel,
        ]))
        from photon_ml_tpu.data.index_map import IndexMap

        imap = IndexMap.load(os.path.join(out, "feature-index.json"))
        assert imap.size == 2  # fa + intercept only
        assert imap.get_index("fa") >= 0 and imap.get_index("fb") < 0

        with pytest.raises(IOError, match="Could not find"):
            glm_driver.run(glm_driver.build_parser().parse_args([
                "--training-data-directory", train,
                "--output-directory", str(tmp_path / "out2"),
                "--selected-features-file", str(tmp_path / "missing.avro"),
            ]))

"""Box-constraint maps (GLMSuite.createConstraintFeatureMap:190-265) and the
coordinate-cache structural key."""

import json
import os

import numpy as np
import pytest

from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.optimize.constraints import (
    bounds_arrays,
    create_constraint_feature_map,
)


def _imap():
    keys = [feature_key("age", ""), feature_key("f", "a"), feature_key("f", "b")]
    return IndexMap.from_feature_names(keys, add_intercept=True)


class TestConstraintMap:
    def test_explicit_feature(self):
        imap = _imap()
        s = json.dumps([{"name": "age", "term": "", "lowerBound": 0.0, "upperBound": 2.0}])
        cmap = create_constraint_feature_map(s, imap)
        idx = imap.get_index(feature_key("age", ""))
        assert cmap == {idx: (0.0, 2.0)}

    def test_missing_bound_defaults_to_inf(self):
        imap = _imap()
        s = json.dumps([{"name": "age", "term": "", "lowerBound": 0.0}])
        (bounds,) = create_constraint_feature_map(s, imap).values()
        assert bounds == (0.0, np.inf)

    def test_term_wildcard(self):
        imap = _imap()
        s = json.dumps([{"name": "f", "term": "*", "upperBound": 1.0}])
        cmap = create_constraint_feature_map(s, imap)
        assert set(cmap) == {
            imap.get_index(feature_key("f", "a")),
            imap.get_index(feature_key("f", "b")),
        }

    def test_all_wildcard_excludes_intercept(self):
        imap = _imap()
        s = json.dumps([{"name": "*", "term": "*", "lowerBound": -1.0, "upperBound": 1.0}])
        cmap = create_constraint_feature_map(s, imap)
        assert imap.intercept_index not in cmap
        assert len(cmap) == imap.size - 1

    def test_errors(self):
        imap = _imap()
        with pytest.raises(ValueError):  # no name/term
            create_constraint_feature_map(json.dumps([{"lowerBound": 1}]), imap)
        with pytest.raises(ValueError):  # both bounds infinite
            create_constraint_feature_map(json.dumps([{"name": "age", "term": ""}]), imap)
        with pytest.raises(ValueError):  # lb >= ub
            create_constraint_feature_map(
                json.dumps([{"name": "age", "term": "", "lowerBound": 2, "upperBound": 1}]),
                imap,
            )
        with pytest.raises(ValueError):  # name wildcard without term wildcard
            create_constraint_feature_map(
                json.dumps([{"name": "*", "term": "x", "upperBound": 1}]), imap
            )
        with pytest.raises(ValueError):  # overlap
            create_constraint_feature_map(
                json.dumps([
                    {"name": "f", "term": "a", "upperBound": 1},
                    {"name": "f", "term": "*", "upperBound": 2},
                ]),
                imap,
            )
        with pytest.raises(ValueError):  # wildcard plus anything else
            create_constraint_feature_map(
                json.dumps([
                    {"name": "f", "term": "a", "upperBound": 1},
                    {"name": "*", "term": "*", "upperBound": 2},
                ]),
                imap,
            )

    def test_bounds_arrays(self):
        imap = _imap()
        s = json.dumps([{"name": "age", "term": "", "lowerBound": 0.0, "upperBound": 2.0}])
        cmap = create_constraint_feature_map(s, imap)
        lower, upper = bounds_arrays(cmap, imap.size)
        idx = imap.get_index(feature_key("age", ""))
        assert lower[idx] == 0.0 and upper[idx] == 2.0
        others = [i for i in range(imap.size) if i != idx]
        assert np.all(np.isinf(lower[others])) and np.all(np.isinf(upper[others]))
        assert bounds_arrays(None, 4) is None


class TestCoordinateCacheKey:
    def test_distinct_box_constraints_do_not_collide(self):
        """Two configs differing only in constraint VALUES must map to
        different cache keys (the repr() key could truncate-collide)."""
        from photon_ml_tpu.estimators.game_estimator import _static_config_key
        from photon_ml_tpu.optimize.config import (
            CoordinateOptimizationConfig,
            OptimizerConfig,
        )

        d = 2000  # large enough that repr() would elide
        lo = np.full(d, -np.inf, np.float32)
        up1 = np.full(d, np.inf, np.float32)
        up2 = up1.copy()
        up2[d // 2] = 3.0  # differs in one elided element
        c1 = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(box_constraints=(lo, up1))
        )
        c2 = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(box_constraints=(lo, up2))
        )
        assert repr(c1) == repr(c2)  # the old key WOULD collide
        assert _static_config_key(c1) != _static_config_key(c2)
        # And identical configs still share a key (compile-cache hit).
        c3 = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(box_constraints=(lo.copy(), up1.copy()))
        )
        assert _static_config_key(c1) == _static_config_key(c3)


class TestConstrainedTrainingCLI:
    def test_cli_train_with_bounds(self, tmp_path):
        """End-to-end: constraints.file in the coordinate DSL produces a
        model whose coefficients respect the box."""
        from tests.test_cli import _write_glmix_avro
        from photon_ml_tpu.cli import train as train_cli
        from photon_ml_tpu.data.index_map import IndexMap
        from photon_ml_tpu.io import model_store

        train_avro = str(tmp_path / "train.avro")
        _write_glmix_avro(train_avro, 0, 300)
        constraints = tmp_path / "constraints.json"
        constraints.write_text(json.dumps([
            {"name": "f0", "term": "", "lowerBound": -0.05, "upperBound": 0.05},
            {"name": "f1", "term": "", "lowerBound": 0.0},
        ]))
        out = str(tmp_path / "out")
        train_cli.main([
            "--training-task", "LOGISTIC_REGRESSION",
            "--input-data-directories", train_avro,
            "--root-output-directory", out,
            "--feature-shard-configurations",
            "name=globalShard,feature.bags=features,intercept=true",
            "--coordinate-configurations",
            "name=global,feature.shard=globalShard,optimizer=LBFGS,"
            "tolerance=1e-7,max.iter=40,regularization=L2,reg.weights=0.01,"
            f"constraints.file={constraints}",
        ])
        best = os.path.join(out, "models", "best")
        imap = IndexMap.load(os.path.join(best, "feature-indexes", "globalShard.json"))
        art = model_store.load_game_model(best, {"globalShard": imap})
        w = art.coordinates["global"].means
        i0 = imap.get_index("f0")
        i1 = imap.get_index("f1")
        assert -0.05 - 1e-6 <= w[i0] <= 0.05 + 1e-6
        assert w[i1] >= -1e-6
        # The bound actually binds (unconstrained optimum exceeds it).
        out2 = str(tmp_path / "out2")
        train_cli.main([
            "--training-task", "LOGISTIC_REGRESSION",
            "--input-data-directories", train_avro,
            "--root-output-directory", out2,
            "--feature-shard-configurations",
            "name=globalShard,feature.bags=features,intercept=true",
            "--coordinate-configurations",
            "name=global,feature.shard=globalShard,optimizer=LBFGS,"
            "tolerance=1e-7,max.iter=40,regularization=L2,reg.weights=0.01",
        ])
        imap2 = IndexMap.load(os.path.join(out2, "models", "best", "feature-indexes", "globalShard.json"))
        art2 = model_store.load_game_model(os.path.join(out2, "models", "best"), {"globalShard": imap2})
        assert abs(art2.coordinates["global"].means[imap2.get_index("f0")]) > 0.05

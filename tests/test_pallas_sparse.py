"""Bucketed sparse layout + Pallas kernel tests.

Kernel bodies run in interpret mode on the CPU mesh (pallas_glm.FORCE_INTERPRET
pattern, as in test_pallas_glm.py); numerics are checked against float64
references built from the raw COO triplets.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from photon_ml_tpu.data import bucketed
from photon_ml_tpu.data.bucketed import (
    BucketedSparseFeatures,
    pack_bucketed,
    pack_from_ell,
    to_coo,
)
from photon_ml_tpu.data.containers import LabeledData, SparseFeatures
from photon_ml_tpu.ops import pallas_glm, pallas_sparse


def _random_coo(rng, n_rows, dim, nnz, hot_fraction=0.0):
    rows = rng.integers(0, n_rows, size=nnz).astype(np.int64)
    cols = rng.integers(0, dim, size=nnz).astype(np.int64)
    if hot_fraction:
        n_hot = int(nnz * hot_fraction)
        cols[:n_hot] = 3  # single hot feature -> hot bucket -> spill paths
    vals = rng.normal(size=nnz).astype(np.float32)
    return rows, cols, vals


def _dense(rows, cols, vals, n_rows, dim):
    M = np.zeros((n_rows, dim), np.float64)
    np.add.at(M, (rows, cols), vals.astype(np.float64))
    return M


class TestPacking:
    @pytest.mark.parametrize("row_aligned", [True, False])
    def test_roundtrip_preserves_every_entry(self, row_aligned):
        rng = np.random.default_rng(0)
        rows, cols, vals = _random_coo(rng, 5000, 300, 40000, hot_fraction=0.1)
        bf = pack_bucketed(rows, cols, vals, 5000, 300, row_aligned=row_aligned)
        assert bf.level1.row_aligned == row_aligned
        r2, c2, v2 = to_coo(bf)
        assert np.array_equal(
            _dense(rows, cols, vals, 5000, 300), _dense(r2, c2, v2, 5000, 300)
        )

    def test_hot_feature_spills_not_drops(self):
        rng = np.random.default_rng(1)
        rows, cols, vals = _random_coo(rng, 4096, 256, 30000, hot_fraction=0.5)
        bf = pack_bucketed(rows, cols, vals, 4096, 256)
        rep = bf.density_report()
        assert rep["level1_fraction"] < 1.0  # the hot bucket overflowed L1
        r2, c2, v2 = to_coo(bf)
        assert np.array_equal(
            _dense(rows, cols, vals, 4096, 256), _dense(r2, c2, v2, 4096, 256)
        )

    def test_pack_from_ell_drops_padding(self):
        sp = SparseFeatures(
            indices=jnp.asarray([[1, 2, 0], [4, 0, 0]], jnp.int32),
            values=jnp.asarray([[1.0, 2.0, 0.0], [3.0, 0.0, 0.0]], jnp.float32),
            dim=6,
        )
        bf = pack_from_ell(sp)
        r2, c2, v2 = to_coo(bf)
        M = _dense(r2, c2, v2, 2, 6)
        assert M[0, 1] == 1.0 and M[0, 2] == 2.0 and M[1, 4] == 3.0
        assert M.sum() == 6.0  # nothing extra (padding zeros dropped)

    def test_empty_matrix(self):
        bf = pack_bucketed(
            np.zeros(0, np.int64), np.zeros(0, np.int64), np.zeros(0, np.float32), 10, 7
        )
        z = pallas_sparse.matvec_xla(bf, jnp.ones(7))
        assert z.shape == (10,) and float(jnp.abs(z).max()) == 0.0


@pytest.fixture
def interpret_kernels():
    old = pallas_glm.FORCE_INTERPRET
    pallas_glm.FORCE_INTERPRET = True
    yield
    pallas_glm.FORCE_INTERPRET = old


class TestKernelParity:
    @pytest.mark.parametrize("row_aligned", [True, False])
    @pytest.mark.parametrize("shape", [(5000, 300, 35000), (9000, 700, 60000)])
    def test_matvec_rmatvec_match_f64(self, shape, row_aligned, interpret_kernels):
        n, d, nnz = shape
        rng = np.random.default_rng(2)
        rows, cols, vals = _random_coo(rng, n, d, nnz, hot_fraction=0.05)
        bf = pack_bucketed(rows, cols, vals, n, d, row_aligned=row_aligned)
        M = _dense(rows, cols, vals, n, d)
        w = rng.normal(size=d).astype(np.float32)
        u = rng.normal(size=n).astype(np.float32)

        z = np.asarray(pallas_sparse.matvec(bf, jnp.asarray(w), interpret=True))
        g = np.asarray(pallas_sparse.rmatvec(bf, jnp.asarray(u), interpret=True))
        gs = np.asarray(
            pallas_sparse.rmatvec(bf, jnp.asarray(u), interpret=True, square=True)
        )
        np.testing.assert_allclose(z, M @ w, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(g, M.T @ u, rtol=2e-5, atol=2e-5)
        gs_ref = np.zeros(d)
        np.add.at(gs_ref, cols, vals.astype(np.float64) ** 2 * u[rows])
        np.testing.assert_allclose(gs, gs_ref, rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("row_aligned", [True, False])
    def test_xla_reference_matches_f64(self, row_aligned):
        rng = np.random.default_rng(3)
        rows, cols, vals = _random_coo(rng, 3000, 500, 20000)
        bf = pack_bucketed(rows, cols, vals, 3000, 500, row_aligned=row_aligned)
        M = _dense(rows, cols, vals, 3000, 500)
        w = rng.normal(size=500).astype(np.float32)
        u = rng.normal(size=3000).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(pallas_sparse.matvec_xla(bf, jnp.asarray(w))), M @ w, rtol=2e-5, atol=2e-5
        )
        np.testing.assert_allclose(
            np.asarray(pallas_sparse.rmatvec_xla(bf, jnp.asarray(u))), M.T @ u, rtol=2e-5, atol=2e-5
        )

    def test_to_dense_xla(self):
        rng = np.random.default_rng(4)
        rows, cols, vals = _random_coo(rng, 600, 130, 4000, hot_fraction=0.3)
        bf = pack_bucketed(rows, cols, vals, 600, 130)
        np.testing.assert_allclose(
            np.asarray(pallas_sparse.to_dense_xla(bf)),
            _dense(rows, cols, vals, 600, 130),
            rtol=1e-6,
            atol=1e-6,
        )


def _assert_same_layout(a, b):
    """Bitwise equality of two BucketedSparseFeatures layouts."""
    assert a.level1.row_aligned == b.level1.row_aligned
    assert a.level1.spv == b.level1.spv
    np.testing.assert_array_equal(
        np.asarray(a.level1.packed), np.asarray(b.level1.packed)
    )
    np.testing.assert_array_equal(
        np.asarray(a.level1.values), np.asarray(b.level1.values)
    )
    assert (a.level2 is None) == (b.level2 is None)
    if a.level2 is not None:
        np.testing.assert_array_equal(
            np.asarray(a.level2.packed), np.asarray(b.level2.packed)
        )
        np.testing.assert_array_equal(
            np.asarray(a.level2.values), np.asarray(b.level2.values)
        )
    np.testing.assert_array_equal(
        np.asarray(a.overflow_rows), np.asarray(b.overflow_rows)
    )
    np.testing.assert_array_equal(
        np.asarray(a.overflow_cols), np.asarray(b.overflow_cols)
    )
    np.testing.assert_array_equal(
        np.asarray(a.overflow_vals), np.asarray(b.overflow_vals)
    )


class TestDevicePack:
    """The XLA counting-sort pack must place every entry exactly where the
    host counting sort does — the device path swaps WHERE the pack runs,
    never what it produces (tentpole acceptance: bitwise layout parity)."""

    def _both(self, rows, cols, vals, n, d, monkeypatch, **kw):
        monkeypatch.setenv("PHOTON_DEVICE_PACK", "0")
        host = pack_bucketed(rows, cols, vals, n, d, host_only=True, **kw)
        monkeypatch.setenv("PHOTON_DEVICE_PACK", "1")
        dev = pack_bucketed(rows, cols, vals, n, d, **kw)
        return host, dev

    @pytest.mark.parametrize("row_aligned", [True, False])
    def test_device_pack_matches_host_pack_bitwise(self, row_aligned, monkeypatch):
        rng = np.random.default_rng(12)
        rows, cols, vals = _random_coo(rng, 5000, 300, 40000, hot_fraction=0.1)
        host, dev = self._both(
            rows, cols, vals, 5000, 300, monkeypatch, row_aligned=row_aligned
        )
        _assert_same_layout(host, dev)

    def test_duplicate_columns_and_empty_rows(self, monkeypatch):
        """The edge cases a rank-assignment bug would corrupt: repeated
        (row, col) entries must keep their input order (both land, summing
        on decode), and rows with no entries must stay empty."""
        n, d = 4200, 260
        rng = np.random.default_rng(13)
        rows, cols, vals = _random_coo(rng, n, d, 20000)
        # Duplicate-column block: the same (row, col) pair many times, with
        # distinct values so placement order is observable.
        dup_rows = np.full(500, 7, np.int64)
        dup_cols = np.full(500, 33, np.int64)
        dup_vals = (np.arange(500, dtype=np.float32) + 1.0) * 1e-3
        rows = np.concatenate([rows, dup_rows])
        cols = np.concatenate([cols, dup_cols])
        vals = np.concatenate([vals, dup_vals])
        # Empty rows: everything below row 2048 moved out of [100, 2048).
        keep = ~((rows >= 100) & (rows < 2048))
        rows, cols, vals = rows[keep], cols[keep], vals[keep]
        host, dev = self._both(rows, cols, vals, n, d, monkeypatch)
        _assert_same_layout(host, dev)
        r2, c2, v2 = to_coo(dev)
        assert not (((r2 >= 100) & (r2 < 2048)).any())
        np.testing.assert_allclose(
            _dense(r2, c2, v2, n, d), _dense(rows, cols, vals, n, d)
        )

    def test_empty_matrix_device(self, monkeypatch):
        monkeypatch.setenv("PHOTON_DEVICE_PACK", "1")
        bf = pack_bucketed(
            np.zeros(0, np.int64),
            np.zeros(0, np.int64),
            np.zeros(0, np.float32),
            10,
            7,
        )
        z = pallas_sparse.matvec_xla(bf, jnp.ones(7))
        assert z.shape == (10,) and float(jnp.abs(z).max()) == 0.0

    def test_enabled_gate(self, monkeypatch):
        from photon_ml_tpu.data import device_pack

        monkeypatch.setenv("PHOTON_DEVICE_PACK", "0")
        assert not device_pack.enabled()
        monkeypatch.setenv("PHOTON_DEVICE_PACK", "1")
        assert device_pack.enabled()
        monkeypatch.delenv("PHOTON_DEVICE_PACK")
        # auto: on only with an accelerator attached
        assert device_pack.enabled() == (
            jax.default_backend() in ("tpu", "gpu")
        )


class TestLayoutPlanner:
    def test_env_forces_layout(self, monkeypatch):
        from photon_ml_tpu.data.bucketed import choose_layout

        monkeypatch.setenv("PHOTON_SPARSE_LAYOUT", "rowalign")
        assert choose_layout(10**6, 10**5, 4096)[0] is True
        monkeypatch.setenv("PHOTON_SPARSE_LAYOUT", "grouped")
        assert choose_layout(10**6, 10**5, 4096)[0] is False
        monkeypatch.delenv("PHOTON_SPARSE_LAYOUT")
        monkeypatch.setenv("PHOTON_SPARSE_ROWALIGN", "1")  # legacy knob
        assert choose_layout(10**6, 10**5, 4096)[0] is True

    def test_auto_declines_bench_shape(self, monkeypatch):
        """1M x 64 nnz into 16k dim: lane collisions force a ~2x aligned
        blowup (r05's measured 2.13), above the training threshold — auto
        must keep the grouped layout there."""
        from photon_ml_tpu.data.bucketed import choose_layout

        monkeypatch.delenv("PHOTON_SPARSE_LAYOUT", raising=False)
        aligned, _ = choose_layout(64 * 10**6, 10**6, 16384)
        assert aligned is False

    def test_auto_declines_when_lane_load_exceeds_capacity(self, monkeypatch):
        """Regression: lam >~ 746 underflowed exp(-lam) to 0 in the naive
        Poisson recurrence, so the planner saw ZERO spill on dense shapes
        whose per-lane load (~1562 here) dwarfs even MAX_SP capacity, and
        picked an aligned layout that spilled ~99% of entries to level 2.
        The log-space tail + the spill-fraction gate must decline."""
        from photon_ml_tpu.data.bucketed import (
            _poisson_excess_fraction,
            choose_layout,
        )

        monkeypatch.delenv("PHOTON_SPARSE_LAYOUT", raising=False)
        assert _poisson_excess_fraction(1562.5, 8) > 0.9
        aligned, _ = choose_layout(200_000, 2048, 128)
        assert aligned is False

    def test_auto_accepts_low_collision_shape(self, monkeypatch):
        """Dense-segment regime (high mean entries per lane): the adaptive
        width amortizes the 1024-slot granularity and alignment engages."""
        from photon_ml_tpu.data.bucketed import choose_layout

        monkeypatch.delenv("PHOTON_SPARSE_LAYOUT", raising=False)
        # mean1 = nnz / (T1 * B) = 64M / (16 * 1) = 4M>>MAX_SP; use a shape
        # with mean segment size ~6800: sp granularity is ~15% there.
        n_rows, dim = 32768, 128
        nnz = 16 * 1 * 6800
        aligned, sp1 = choose_layout(nnz, n_rows, dim)
        assert aligned is True and sp1 is not None and sp1 % 1024 == 0


class TestLayoutObjectiveParity:
    """Satellite: the fused sparse objective must agree across layouts —
    (value, gradient, sum_u) from the row-aligned pack vs the grouped pack
    of the SAME matrix, across level-1-only / level-2 / overflow mixes.
    (Exact bitwise equality across layouts is not defined — the two packs
    accumulate in different orders — so the contract is f32-tight
    agreement plus bitwise stability within each layout.)"""

    @pytest.mark.parametrize("hot_fraction", [0.0, 0.25, 0.6])
    def test_fused_objective_layout_parity(self, hot_fraction, interpret_kernels):
        from photon_ml_tpu.ops.losses import LOGISTIC

        rng = np.random.default_rng(21)
        n, d, nnz = 6000, 260, 48000
        rows, cols, vals = _random_coo(rng, n, d, nnz, hot_fraction=hot_fraction)
        bf_g = pack_bucketed(rows, cols, vals, n, d, row_aligned=False)
        bf_a = pack_bucketed(rows, cols, vals, n, d, row_aligned=True)
        if hot_fraction:
            # The hot bucket must actually exercise the spill levels.
            rep = bf_g.density_report()
            assert rep["level1_fraction"] < 1.0
        assert pallas_sparse.fused_feasible(bf_g)
        assert pallas_sparse.fused_feasible(bf_a)
        y = (rng.uniform(size=n) > 0.5).astype(np.float32)
        w = (rng.normal(size=d) * 0.1).astype(np.float32)
        offs = rng.normal(size=n).astype(np.float32) * 0.01
        wts = rng.uniform(0.5, 1.5, size=n).astype(np.float32)
        out = {}
        for name, bf in (("grouped", bf_g), ("aligned", bf_a)):
            val, grad, sum_u = pallas_sparse.fused_value_gradient_sums(
                LOGISTIC,
                jnp.asarray(w),
                jnp.zeros(()),
                bf,
                jnp.asarray(y),
                jnp.asarray(offs),
                jnp.asarray(wts),
                interpret=True,
            )
            out[name] = (float(val), np.asarray(grad), float(sum_u))
        np.testing.assert_allclose(out["grouped"][0], out["aligned"][0], rtol=1e-5)
        np.testing.assert_allclose(
            out["grouped"][1], out["aligned"][1], rtol=2e-4, atol=2e-4
        )
        np.testing.assert_allclose(out["grouped"][2], out["aligned"][2], rtol=1e-5)
        # f64 reference from the raw COO: both layouts must be RIGHT, not
        # merely mutually consistent.
        M = _dense(rows, cols, vals, n, d)
        z = M @ w.astype(np.float64) + offs
        p = 1.0 / (1.0 + np.exp(-z))
        val_ref = np.sum(
            wts * (np.log1p(np.exp(-np.abs(z))) + np.maximum(z, 0) - y * z)
        )
        u_ref = wts * (p - y)
        g_ref = M.T @ u_ref
        for name in ("grouped", "aligned"):
            np.testing.assert_allclose(out[name][0], val_ref, rtol=1e-4)
            np.testing.assert_allclose(
                out[name][1], g_ref, rtol=5e-4, atol=5e-4
            )
            np.testing.assert_allclose(out[name][2], u_ref.sum(), rtol=1e-4)


class TestMaybePack:
    def _ell(self, n, d, k, dtype=np.float32, seed=0):
        rng = np.random.default_rng(seed)
        idx = rng.integers(0, d, size=(n, k)).astype(np.int32)
        val = rng.normal(size=(n, k)).astype(dtype)
        return SparseFeatures(jnp.asarray(idx), jnp.asarray(val), d)

    def test_engages_on_worthwhile_shard(self, interpret_kernels):
        sp = self._ell(9000, 200, 8)
        assert pallas_sparse.maybe_pack(sp, 9000) is not None

    def test_declines_low_density(self, interpret_kernels):
        # 1 nnz/row into a wide dim: segment floor of 1024 slots would blow
        # padding up far past the ELL bytes.
        sp = self._ell(100_000, 16384, 1)
        assert pallas_sparse.maybe_pack(sp, 100_000) is None

    # (the f64 decline branch is untestable here: without jax_enable_x64,
    # jnp.asarray coerces f64 input to f32 before the gate ever sees it)

    def test_declines_small_problem(self, interpret_kernels):
        sp = self._ell(1000, 200, 8)
        assert pallas_sparse.maybe_pack(sp, 1000) is None

    def test_declines_when_disabled(self, interpret_kernels):
        sp = self._ell(9000, 200, 8)
        pallas_glm.set_enabled(False)
        try:
            assert pallas_sparse.maybe_pack(sp, 9000) is None
        finally:
            pallas_glm.set_enabled(True)


class TestObjectiveIntegration:
    def test_objective_with_bucketed_features(self, interpret_kernels):
        """value_and_gradient / hessian paths agree between ELL and bucketed."""
        from photon_ml_tpu.ops import objective
        from photon_ml_tpu.ops.losses import LOGISTIC

        rng = np.random.default_rng(5)
        n, d, k = 4000, 260, 9
        idx = rng.integers(0, d, size=(n, k)).astype(np.int32)
        val = rng.normal(size=(n, k)).astype(np.float32)
        sp = SparseFeatures(jnp.asarray(idx), jnp.asarray(val), d)
        bf = pack_from_ell(sp)
        y = (rng.uniform(size=n) > 0.5).astype(np.float32)
        w = (rng.normal(size=d) * 0.1).astype(np.float32)
        mk = lambda feats: LabeledData(
            feats, jnp.asarray(y), jnp.zeros(n), jnp.ones(n)
        )
        v1, g1 = objective.value_and_gradient(LOGISTIC, jnp.asarray(w), mk(sp), l2=0.5)
        v2, g2 = objective.value_and_gradient(LOGISTIC, jnp.asarray(w), mk(bf), l2=0.5)
        np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-4, atol=1e-4)

        hv1 = objective.hessian_vector(LOGISTIC, jnp.asarray(w), jnp.asarray(w), mk(sp), l2=0.5)
        hv2 = objective.hessian_vector(LOGISTIC, jnp.asarray(w), jnp.asarray(w), mk(bf), l2=0.5)
        np.testing.assert_allclose(np.asarray(hv1), np.asarray(hv2), rtol=1e-4, atol=1e-4)

        d1 = objective.hessian_diagonal(LOGISTIC, jnp.asarray(w), mk(sp), l2=0.5)
        d2 = objective.hessian_diagonal(LOGISTIC, jnp.asarray(w), mk(bf), l2=0.5)
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-4, atol=1e-4)

    def test_fixed_effect_coordinate_packs_and_trains(self, interpret_kernels):
        """A big-enough sparse shard repacks to bucketed and converges to the
        same optimum as the ELL/XLA path."""
        from photon_ml_tpu.data.game_dataset import GameDataset
        from photon_ml_tpu.game.coordinate import FixedEffectCoordinate
        from photon_ml_tpu.optimize.config import (
            L2,
            CoordinateOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.types import TaskType

        rng = np.random.default_rng(6)
        n, d, k = 9000, 200, 6
        idx = rng.integers(0, d, size=(n, k)).astype(np.int32)
        val = rng.normal(size=(n, k)).astype(np.float32)
        sp = SparseFeatures(jnp.asarray(idx), jnp.asarray(val), d)
        w_true = rng.normal(size=d) * 0.3
        M = _dense(np.repeat(np.arange(n), k), idx.reshape(-1), val.reshape(-1), n, d)
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-M @ w_true))).astype(np.float32)
        ds = GameDataset.build({"s": sp}, y)
        cfg = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(max_iterations=30, tolerance=1e-8),
            regularization=L2,
            reg_weight=1.0,
        )
        coord = FixedEffectCoordinate(ds, "s", cfg, TaskType.LOGISTIC_REGRESSION)
        assert isinstance(coord._features, BucketedSparseFeatures)
        model, res = coord.train(ds.offsets)

        pallas_glm.set_enabled(False)
        try:
            coord_ell = FixedEffectCoordinate(ds, "s", cfg, TaskType.LOGISTIC_REGRESSION)
            assert isinstance(coord_ell._features, SparseFeatures)
            model_ell, _ = coord_ell.train(ds.offsets)
        finally:
            pallas_glm.set_enabled(True)
        np.testing.assert_allclose(
            np.asarray(model.coefficients.means),
            np.asarray(model_ell.coefficients.means),
            rtol=5e-3,
            atol=5e-4,
        )
        # scoring path uses the bucketed features too
        s1 = np.asarray(coord.score(model))
        s2 = np.asarray(coord_ell.score(model))
        np.testing.assert_allclose(s1, s2, rtol=1e-4, atol=1e-4)


class TestHostCooPack:
    def test_coordinate_packs_from_host_csr(self, interpret_kernels, monkeypatch):
        """Ingest-stashed host CSR must feed the bucketed pack directly —
        the device-ELL pull-back (maybe_pack) must not run."""
        from photon_ml_tpu.data.game_dataset import GameDataset, HostCSR
        from photon_ml_tpu.game.coordinate import FixedEffectCoordinate
        from photon_ml_tpu.optimize.config import (
            L2,
            CoordinateOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.types import TaskType

        rng = np.random.default_rng(9)
        n, d, k = 9000, 200, 6
        idx = rng.integers(0, d, size=(n, k)).astype(np.int32)
        val = rng.normal(size=(n, k)).astype(np.float32)
        sp = SparseFeatures(jnp.asarray(idx), jnp.asarray(val), d)
        y = (rng.uniform(size=n) > 0.5).astype(np.float32)
        ds = GameDataset.build({"s": sp}, y)
        ds.host_csr = {
            "s": HostCSR(
                np.arange(n + 1, dtype=np.int64) * k,
                idx.reshape(-1).astype(np.int64),
                val.reshape(-1),
                d,
            )
        }
        monkeypatch.setattr(
            pallas_sparse,
            "maybe_pack",
            lambda *a, **k: pytest.fail("device-ELL pull-back ran"),
        )
        cfg = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(max_iterations=5, tolerance=1e-6),
            regularization=L2,
            reg_weight=1.0,
        )
        coord = FixedEffectCoordinate(ds, "s", cfg, TaskType.LOGISTIC_REGRESSION)
        assert isinstance(coord._features, BucketedSparseFeatures)
        assert coord._use_pallas is None

    def test_async_ingest_pack_joins_at_coordinate(
        self, interpret_kernels, monkeypatch
    ):
        """begin_pack_async at stash time -> the coordinate joins the
        background host pack (finish_pack) and the layout matches the
        synchronous pack exactly. The pipeline is forced on: the test is
        about join/pack parity, not the 1-core auto-off gate (which made
        it fail on single-core CI hosts), and the device pack is forced
        off so a background host thread exists to join at all."""
        monkeypatch.setenv("PHOTON_PIPELINE", "1")
        monkeypatch.setenv("PHOTON_DEVICE_PACK", "0")
        from photon_ml_tpu.data.game_dataset import GameDataset, HostCSR
        from photon_ml_tpu.game.coordinate import FixedEffectCoordinate
        from photon_ml_tpu.optimize.config import (
            L2,
            CoordinateOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.types import TaskType

        rng = np.random.default_rng(10)
        n, d, k = 9000, 200, 6
        idx = rng.integers(0, d, size=(n, k)).astype(np.int32)
        val = rng.normal(size=(n, k)).astype(np.float32)
        y = (rng.uniform(size=n) > 0.5).astype(np.float32)
        cols = idx.reshape(-1).astype(np.int64)
        vals = val.reshape(-1)
        indptr = np.arange(n + 1, dtype=np.int64) * k

        ds = GameDataset.build(
            {"s": SparseFeatures(jnp.asarray(idx), jnp.asarray(val), d)}, y
        )
        csr = HostCSR(indptr, cols, vals, d)
        ds.host_csr = {"s": csr}
        pallas_sparse.begin_pack_async(csr, n)
        assert csr.pack_future is not None
        cfg = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(max_iterations=5, tolerance=1e-6),
            regularization=L2,
            reg_weight=1.0,
        )
        coord = FixedEffectCoordinate(ds, "s", cfg, TaskType.LOGISTIC_REGRESSION)
        assert isinstance(coord._features, BucketedSparseFeatures)
        # Same layout as the synchronous data-plane pack.
        sync = pallas_sparse.maybe_pack_coo(
            np.repeat(np.arange(n, dtype=np.int64), k), cols, vals, n, d
        )
        np.testing.assert_array_equal(
            np.asarray(coord._features.level1.packed),
            np.asarray(sync.level1.packed),
        )
        np.testing.assert_array_equal(
            np.asarray(coord._features.level1.values),
            np.asarray(sync.level1.values),
        )
        model, res = coord.train(ds.offsets)
        assert np.isfinite(float(res.loss))

"""Precision-tier graceful degradation suite (ISSUE 20).

The load-bearing contracts of the f32 -> bf16 -> int8 -> host ladder:

  * quantized serving is CHARACTERIZED, not bitwise: bf16/int8 answers
    stay within the pinned TIER_TOLERANCES of the f32 reference, and
    every quantization's measured round-trip error lands in the
    per-tenant `tier_quant_error` histogram;
  * restore is BITWISE: every quantize step retains the original f32
    rows on the host, so walking back up to f32 (from any rung,
    including through the host tier with LRU-promoted hot rows)
    reproduces the pre-demotion answers exactly;
  * every ladder transition is a stage -> pre-warm -> commit -> drain
    generation flip: an injected `quantize_stage`/`tier_restore` fault
    (transient or terminal) never fails a request and a terminal one
    leaves the OLD generation serving bitwise — the in-process statement
    of the mid-quantize-SIGKILL contract (nothing commits before the
    flip);
  * the pressure valve and the autopilot's hbm rules are ladder-aware:
    quantize-in-place is tried before host-tier demotion, restore walks
    back up one rung at a time under the ceiling gate, and the
    post-action contract probe holds ladder actions to the pinned
    tolerances instead of bitwise.
"""

from __future__ import annotations

import json

import numpy as np
import jax.numpy as jnp
import pytest

from photon_ml_tpu.autopilot import Action, Autopilot, ControlRule
from photon_ml_tpu.autopilot.rules import hbm_demote_rule, hbm_restore_rule
from photon_ml_tpu.autopilot.sensors import SensorSnapshot, TenantSensors
from photon_ml_tpu.game.model import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.serving import ScoreRequest, ServingBundle, TenantRegistry
from photon_ml_tpu.serving.bundle import (
    PRECISION_LADDER,
    quantize_bundle_rows,
    restore_bundle_precision,
)
from photon_ml_tpu.serving.tenancy import TierErrorCeilingExceeded
from photon_ml_tpu.transformers.game_transformer import CoordinateScoringSpec
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import faults, telemetry
from photon_ml_tpu.utils.contracts import (
    JOURNAL_EVENT_SCHEMAS,
    TIER_BLOCK_KEYS,
    TIER_TOLERANCES,
)

pytestmark = pytest.mark.serving

TASK = TaskType.LOGISTIC_REGRESSION
D_FE, D_RE, E = 7, 5, 24


def _make_model(seed: int, n_entities: int = E):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=D_FE).astype(np.float32)
    M = np.zeros((n_entities + 1, D_RE), np.float32)
    M[:n_entities] = rng.normal(size=(n_entities, D_RE))
    model = GameModel(
        {
            "fixed": FixedEffectModel(Coefficients(jnp.asarray(w)), TASK),
            "per-e": RandomEffectModel(jnp.asarray(M), None, TASK),
        }
    )
    specs = {
        "fixed": CoordinateScoringSpec(shard="g"),
        "per-e": CoordinateScoringSpec(
            shard="re",
            random_effect_type="eid",
            entity_index={str(i): i for i in range(n_entities)},
        ),
    }
    return model, specs


def _bundle(seed: int, n_entities: int = E) -> ServingBundle:
    model, specs = _make_model(seed, n_entities)
    return ServingBundle.from_model(model, specs, TASK)


def _requests(seed: int, n: int, n_entities: int = E):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, D_FE)).astype(np.float32)
    Xe = rng.normal(size=(n, D_RE)).astype(np.float32)
    ids = rng.integers(0, n_entities + 6, size=n)  # trained + cold starts
    return [
        ScoreRequest(
            features={"g": X[i], "re": Xe[i]},
            entity_ids={"eid": str(int(ids[i]))},
            offset=float(i) * 0.125,
            uid=str(i),
        )
        for i in range(n)
    ]


def _scores(reg, name, reqs) -> np.ndarray:
    return np.asarray([reg.score(name, r).score for r in reqs], np.float64)


def _allclose(got, ref, tier) -> bool:
    tol = TIER_TOLERANCES[tier]
    return np.allclose(got, ref, rtol=tol["rtol"], atol=tol["atol"])


# =========================================================== quantize planes


class TestQuantizedPlanes:
    @pytest.mark.parametrize("tier", ["bf16", "int8"])
    def test_row_roundtrip_error_within_pinned_tolerance(self, tier):
        """Dequantizing the staged plane reproduces the original rows
        within the rung's pinned tolerance, and the builder's reported
        per-coordinate error is consistent with the measured one."""
        bundle = _bundle(1)
        re_cid = next(
            cid
            for cid, c in bundle.coordinates.items()
            if c.is_random_effect
        )
        original = np.asarray(bundle.coordinates[re_cid].params, np.float32)
        q, errors = quantize_bundle_rows(bundle, tier)
        c = q.coordinates[re_cid]
        assert c.tier == tier
        if tier == "int8":
            deq = np.asarray(c.params, np.float32) * np.asarray(
                c.scales, np.float32
            )[:, None]
        else:
            assert c.scales is None
            deq = np.asarray(c.params.astype(jnp.float32))
        assert _allclose(deq, original, tier)
        assert re_cid in errors and errors[re_cid] >= 0.0
        # The originals ride along on the host for the bitwise restore.
        assert np.array_equal(c.host_f32, original)
        r = restore_bundle_precision(q)
        assert np.array_equal(
            np.asarray(r.coordinates[re_cid].params), original
        )
        assert r.coordinates[re_cid].tier == "f32"
        r.release(close_stores=False)
        q.release(close_stores=False)
        bundle.release(close_stores=False)

    def test_quantized_plane_is_smaller(self):
        bundle = _bundle(2)
        re_cid = next(
            cid
            for cid, c in bundle.coordinates.items()
            if c.is_random_effect
        )
        f32 = bundle.coordinates[re_cid].device_nbytes()
        q16, _ = quantize_bundle_rows(bundle, "bf16")
        q8, _ = quantize_bundle_rows(bundle, "int8")
        assert q16.coordinates[re_cid].device_nbytes() < f32
        # int8 plane + f32 scale vector still beats the bf16 plane.
        assert (
            q8.coordinates[re_cid].device_nbytes()
            < q16.coordinates[re_cid].device_nbytes()
        )
        q8.release(close_stores=False)
        q16.release(close_stores=False)
        bundle.release(close_stores=False)

    def test_reshard_refuses_quantized_coordinate(self):
        """The reshard planner assumes f32 row planes; a quantized
        coordinate must be refused loudly, not silently moved."""
        from photon_ml_tpu.serving.reshard import plan_coordinate_reshard

        bundle = _bundle(3)
        q, _ = quantize_bundle_rows(bundle, "bf16")
        c = next(
            c for c in q.coordinates.values() if c.is_random_effect
        )
        with pytest.raises(ValueError, match="quantized"):
            plan_coordinate_reshard(c, None)
        q.release(close_stores=False)
        bundle.release(close_stores=False)


# ========================================================== serving parity


class TestServingParity:
    def test_ladder_down_characterized_and_restore_bitwise(self):
        """Walk a serving tenant down every rung and back: quantized
        answers within the pinned tolerances, restored answers bitwise
        (never-quantized FE rows and the quantized RE rows alike)."""
        reqs = _requests(7, 12)
        with TenantRegistry(max_batch=32, max_wait_ms=5.0) as reg:
            reg.admit("a", _bundle(1))
            t = reg.tenant("a")
            ref = _scores(reg, "a", reqs)
            for rung in PRECISION_LADDER[1:]:
                assert reg.demote_tier("a", reason="test") > 0
                assert t.tier == rung
                got = _scores(reg, "a", reqs)
                assert _allclose(got, ref, rung)
            # One more rung: the host tier (PR 15 demotion), built from
            # the retained originals — bitwise, with hot-row promotion.
            reg.demote_tier("a", reason="test")
            assert t.demoted
            assert np.array_equal(_scores(reg, "a", reqs), ref)
            # Back up: host -> f32 in one restore (the cold matrix IS
            # the original rows), answers bitwise vs pre-demotion self.
            assert reg.restore_tier("a", reason="test") > 0
            assert t.tier == "f32" and not t.demoted
            assert np.array_equal(_scores(reg, "a", reqs), ref)
            m = reg.metrics()
            block = m["tenants"]["a"]["tier"]
            assert set(block) == set(TIER_BLOCK_KEYS)
            assert block["demotions"] == 2  # bf16, int8 (host is PR 15's)
            assert block["quant_error_max"] is not None
            assert m["tenants"]["a"]["failed"] == 0
            reg.close(release_bundles=True)

    def test_direct_rung_restore_is_bitwise(self):
        """int8 -> f32 without passing the host tier: the restore builds
        from the retained originals, never by dequantizing the lossy
        plane."""
        reqs = _requests(9, 10)
        with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
            reg.admit("a", _bundle(4))
            ref = _scores(reg, "a", reqs)
            assert reg.demote_tier("a", to="int8", reason="test") > 0
            assert reg.tenant("a").tier == "int8"
            assert reg.restore_tier("a", reason="test") > 0
            assert reg.tenant("a").tier == "f32"
            assert np.array_equal(_scores(reg, "a", reqs), ref)
            assert faults.COUNTERS.get("tier_demotions") == 2
            assert faults.COUNTERS.get("tier_restores") >= 1
            reg.close(release_bundles=True)

    def test_int8_error_ceiling_refuses_the_rung(self, monkeypatch):
        """An int8 step whose measured round-trip error exceeds the
        knobbed ceiling raises BEFORE commit; the tenant keeps serving
        on its current rung."""
        monkeypatch.setenv("PHOTON_TIER_INT8_ERROR_CEILING", "1e-9")
        reqs = _requests(11, 8)
        with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
            reg.admit("a", _bundle(5))
            ref = _scores(reg, "a", reqs)
            reg.demote_tier("a", to="bf16", reason="test")
            with pytest.raises(TierErrorCeilingExceeded):
                reg.demote_tier("a", to="int8", reason="test")
            t = reg.tenant("a")
            assert t.tier == "bf16"
            assert t.tier_rollbacks == 1
            assert _allclose(_scores(reg, "a", reqs), ref, "bf16")
            # Walking PAST int8 to the host tier skips the refused rung:
            # pressure relief still lands on the bitwise host tier.
            reg.demote_tier("a", to="host", reason="test")
            assert t.demoted
            assert np.array_equal(_scores(reg, "a", reqs), ref)
            reg.close(release_bundles=True)

    def test_valve_quantizes_before_host_demotion(self, monkeypatch):
        """With the ladder opted in, HBM pressure at admission quantizes
        the coldest tenant in place instead of demoting it to the host
        tier."""
        monkeypatch.setenv("PHOTON_TIER_LADDER", "1")
        b0, b1, b2 = _bundle(10), _bundle(11), _bundle(12)
        per = b0.device_bytes_per_shard()
        with TenantRegistry(
            max_batch=16,
            max_wait_ms=2.0,
            hbm_budget_bytes=int(per * 3 - 100),
        ) as reg:
            reg.admit("cold", b0)
            reg.admit("warm", b1)
            reg.score("warm", _requests(62, 1)[0])  # cold is coldest
            reg.admit("new", b2)  # over budget -> quantize, don't demote
            m = reg.metrics()
            assert not m["tenants"]["cold"]["demoted"]
            assert m["tenants"]["cold"]["tier"]["tier"] != "f32"
            assert m["tenants"]["warm"]["tier"]["tier"] == "f32"
            assert m["tenants"]["new"]["tier"]["tier"] == "f32"
            reg.close(release_bundles=True)


# ======================================================== fault injection


@pytest.mark.chaos
class TestLadderFaults:
    def test_transient_quantize_fault_retries_and_commits(self):
        reqs = _requests(21, 8)
        with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
            reg.admit("a", _bundle(6))
            ref = _scores(reg, "a", reqs)
            with faults.inject("quantize_stage:1"):
                assert reg.demote_tier("a", reason="test") > 0
            t = reg.tenant("a")
            assert t.tier == "bf16"
            assert t.tier_rollbacks == 0
            assert _allclose(_scores(reg, "a", reqs), ref, "bf16")
            assert reg.metrics()["tenants"]["a"]["failed"] == 0
            reg.close(release_bundles=True)

    def test_terminal_quantize_fault_leaves_old_generation_bitwise(self):
        """Retry exhaustion mid-quantize: NOTHING commits before the
        generation flip, so the old f32 generation keeps serving bitwise
        with zero failed requests — the in-process statement of the
        mid-quantize-SIGKILL contract (a killed process never wrote a
        new generation either)."""
        reqs = _requests(23, 8)
        with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
            reg.admit("a", _bundle(7))
            t = reg.tenant("a")
            ref = _scores(reg, "a", reqs)
            version = t.engine._state.version
            with faults.inject("quantize_stage:99"):
                with pytest.raises(faults.InjectedFault):
                    reg.demote_tier("a", reason="test")
            assert t.tier == "f32"
            assert t.tier_rollbacks == 1
            assert t.engine._state.version == version  # no flip happened
            assert np.array_equal(_scores(reg, "a", reqs), ref)
            assert reg.metrics()["tenants"]["a"]["failed"] == 0
            assert faults.COUNTERS.get("tier_rollbacks") == 1
            assert faults.COUNTERS.get("tier_demotions") == 0
            reg.close(release_bundles=True)

    def test_terminal_restore_fault_keeps_quantized_generation(self):
        reqs = _requests(25, 8)
        with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
            reg.admit("a", _bundle(8))
            ref = _scores(reg, "a", reqs)
            reg.demote_tier("a", to="bf16", reason="test")
            with faults.inject("tier_restore:99"):
                with pytest.raises(faults.InjectedFault):
                    reg.restore_tier("a", reason="test")
            t = reg.tenant("a")
            assert t.tier == "bf16"  # the quantized generation survived
            assert _allclose(_scores(reg, "a", reqs), ref, "bf16")
            assert reg.metrics()["tenants"]["a"]["failed"] == 0
            # A later clean restore still lands bitwise.
            reg.restore_tier("a", reason="test")
            assert np.array_equal(_scores(reg, "a", reqs), ref)
            reg.close(release_bundles=True)

    def test_chaos_confined_to_the_transitioning_tenant(self):
        """A neighbor keeps answering bitwise, co-batched traffic and
        all, while another tenant's quantize step fails terminally."""
        req_a, req_b = _requests(27, 8), _requests(28, 8)
        with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
            reg.admit("chaos", _bundle(9))
            reg.admit("clean", _bundle(10))
            ref_clean = _scores(reg, "clean", req_b)
            with faults.inject("quantize_stage:99"):
                with pytest.raises(faults.InjectedFault):
                    reg.demote_tier("chaos", reason="test")
            assert np.array_equal(_scores(reg, "clean", req_b), ref_clean)
            assert np.array_equal(
                _scores(reg, "chaos", req_a),
                _scores(reg, "chaos", req_a),
            )
            m = reg.metrics()
            assert m["tenants"]["clean"]["failed"] == 0
            assert m["tenants"]["chaos"]["failed"] == 0
            reg.close(release_bundles=True)


# ==================================================== telemetry / journal


class TestLadderObservability:
    def test_transitions_journal_valid_and_histogram_labeled(self, tmp_path):
        path = str(tmp_path / "journal.jsonl")
        journal = telemetry.install_journal(telemetry.RunJournal(path))
        try:
            reqs = _requests(31, 6)
            with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
                reg.admit("a", _bundle(11))
                _scores(reg, "a", reqs)
                reg.demote_tier("a", to="int8", reason="test")
                reg.restore_tier("a", reason="test")
                reg.close(release_bundles=True)
        finally:
            telemetry.uninstall_journal()
            journal.close()
        n_ok, errors = telemetry.validate_journal(path)
        assert errors == []
        events = [json.loads(l) for l in open(path) if l.strip()]
        demotes = [e for e in events if e["type"] == "tier_demote"]
        restores = [e for e in events if e["type"] == "tier_restore"]
        assert [(e["from_tier"], e["to_tier"]) for e in demotes] == [
            ("f32", "bf16"),
            ("bf16", "int8"),
        ]
        assert restores and restores[-1]["to_tier"] == "f32"
        for e in demotes + restores:
            for key in JOURNAL_EVENT_SCHEMAS[e["type"]]:
                assert key in e, (e["type"], key)
        assert demotes[0]["evidence"]["quant_error_max"] >= 0.0
        # The per-tenant quantization-error histogram carries the
        # tenant label from the ambient metric scope.
        labeled = telemetry.METRICS.labeled_histograms("tier_quant_error")
        assert any(k == "tenant=a" for k in labeled)

    def test_obs_decisions_renders_tier_transitions(self, tmp_path, capsys):
        from photon_ml_tpu.cli.obs import cmd_decisions

        path = str(tmp_path / "journal.jsonl")
        journal = telemetry.install_journal(telemetry.RunJournal(path))
        try:
            with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
                reg.admit("a", _bundle(12))
                reg.demote_tier("a", to="bf16", reason="test")
                reg.restore_tier("a", reason="test")
                reg.close(release_bundles=True)
        finally:
            telemetry.uninstall_journal()
            journal.close()

        class _Args:
            pass

        args = _Args()
        args.path = path
        assert cmd_decisions(args) == 0
        out = capsys.readouterr().out
        assert "tier v" in out and "tier ^" in out
        assert "f32 -> bf16" in out and "bf16 -> f32" in out


# ========================================================== autopilot rules


def _tsensors(name, *, tier="f32", can_quantize=True, last_active=0.0,
              demoted=False, can_demote=True):
    return TenantSensors(
        name=name,
        demoted=demoted,
        can_demote=can_demote,
        last_active=last_active,
        completed=0,
        failed=0,
        in_flight=0,
        pending=0,
        device_bytes=1000,
        p95_ms=None,
        p99_ms=None,
        coords=(),
        tier=tier,
        can_quantize=can_quantize,
    )


def _snap(tenants, used=90, budget=100):
    return SensorSnapshot(
        tenants={t.name: t for t in tenants},
        hbm_budget=budget,
        hbm_used=used,
        latency_p95_ms=None,
        latency_p99_ms=None,
        queue_wait_p95_ms=None,
        batch_p50=None,
        failed_requests=0,
    )


class TestLadderRules:
    def test_demote_rule_prefers_quantize_when_ladder_on(self, monkeypatch):
        monkeypatch.setenv("PHOTON_TIER_LADDER", "1")
        rule = hbm_demote_rule()
        cur = _snap([_tsensors("a")], used=90)
        action = rule.decide(cur, None, 0.90)
        assert action.kind == "tier_demote"
        assert action.params["to"] == "bf16"
        assert action.evidence["from_tier"] == "f32"

    def test_demote_rule_int8_needs_the_higher_pressure(self, monkeypatch):
        monkeypatch.setenv("PHOTON_TIER_LADDER", "1")
        rule = hbm_demote_rule()
        cur = _snap([_tsensors("a", tier="bf16")], used=90)
        # Below the planned int8 pressure: the next rung is withheld and
        # the rule falls back to the host tier.
        action = rule.decide(cur, None, 0.90)
        assert action.kind == "demote"
        action = rule.decide(cur, None, 0.95)
        assert action.kind == "tier_demote"
        assert action.params["to"] == "int8"

    def test_demote_rule_host_tier_when_ladder_off(self):
        rule = hbm_demote_rule()
        cur = _snap([_tsensors("a")], used=90)
        action = rule.decide(cur, None, 0.90)
        assert action.kind == "demote"

    def test_restore_rule_walks_up_under_the_ceiling(self):
        rule = hbm_restore_rule()
        cur = _snap([_tsensors("a", tier="bf16")], used=40)
        action = rule.decide(cur, None, 0.6)
        assert action.kind == "tier_restore"
        assert action.params["to"] == "f32"
        cur = _snap([_tsensors("a", tier="int8")], used=40)
        assert rule.decide(cur, None, 0.6).params["to"] == "bf16"
        # Above the ceiling the restore is refused — walking straight
        # back into the demote band is the oscillation the gate avoids.
        over = _snap([_tsensors("a", tier="bf16")], used=85)
        assert rule.decide(over, None, 0.15) is None

    def test_restore_rule_signal_sees_quantized_tenants(self):
        rule = hbm_restore_rule()
        quantized = _snap([_tsensors("a", tier="int8")], used=40)
        assert rule.signal(quantized, None) == pytest.approx(0.6)
        healthy = _snap([_tsensors("a")], used=40)
        assert rule.signal(healthy, None) is None


class TestAutopilotLadderActuation:
    def _rule(self, kind, params, from_tier="f32"):
        return ControlRule(
            name=f"drive-{kind}",
            signal=lambda cur, prev: 12.0,
            fire_above=10.0,
            rearm_below=2.0,
            decide=lambda cur, prev, sig: Action(
                kind=kind,
                tenant="a",
                params=dict(params),
                # The built-in rules record the current rung; the probe
                # compares under the coarser of from/to.
                evidence={"from_tier": from_tier},
            ),
            cooldown_s=0.0,
        )

    def test_tier_actions_pass_the_characterized_probe(self):
        """A ladder step changes probe answers within tolerance — the
        loop must hold it to TIER_TOLERANCES, apply it, and the
        follow-up restore must land back on f32."""
        reqs = _requests(41, 4)
        with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
            reg.admit("a", _bundle(13))
            ref = _scores(reg, "a", reqs)
            down = Autopilot(
                reg,
                rules=[self._rule("tier_demote", {"to": "bf16"})],
                probe_requests={"a": reqs[0]},
                cooldown_s=0.0,
                max_actions=100,
                start=False,
            )
            down.tick()
            assert down.summary()["actions"] == 1
            assert down.summary()["rollbacks"] == 0
            assert reg.tenant("a").tier == "bf16"
            up = Autopilot(
                reg,
                rules=[
                    self._rule(
                        "tier_restore", {"to": "f32"}, from_tier="bf16"
                    )
                ],
                probe_requests={"a": reqs[0]},
                cooldown_s=0.0,
                max_actions=100,
                start=False,
            )
            up.tick()
            assert up.summary()["actions"] == 1
            assert reg.tenant("a").tier == "f32"
            assert np.array_equal(_scores(reg, "a", reqs), ref)
            assert reg.metrics()["tenants"]["a"]["failed"] == 0
            reg.close(release_bundles=True)

    @pytest.mark.chaos
    def test_actuation_fault_rolls_back_the_ladder_step(self):
        reqs = _requests(43, 4)
        with TenantRegistry(max_batch=16, max_wait_ms=2.0) as reg:
            reg.admit("a", _bundle(14))
            ref = _scores(reg, "a", reqs)
            pilot = Autopilot(
                reg,
                rules=[self._rule("tier_demote", {"to": "bf16"})],
                probe_requests={"a": reqs[0]},
                cooldown_s=0.0,
                max_actions=100,
                start=False,
            )
            with faults.inject("autopilot_act:1"):
                pilot.tick()
            s = pilot.summary()
            assert s["rollbacks"] == 1 and s["actions"] == 0
            assert reg.tenant("a").tier == "f32"
            assert np.array_equal(_scores(reg, "a", reqs), ref)
            assert reg.metrics()["tenants"]["a"]["failed"] == 0
            reg.close(release_bundles=True)

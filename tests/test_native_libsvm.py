"""Native LibSVM parser vs the pure-Python tokenizer.

Mirrors the index-store strategy: the Python implementation is the semantic
reference; the C++ engine must produce bit-identical CSR output on the same
input. Tests skip when no compiler is available (the framework falls back
to Python automatically).
"""

from __future__ import annotations

import numpy as np
import pytest

from photon_ml_tpu.data import libsvm
from photon_ml_tpu.native import libsvm_parser

TRICKY = (
    "+1 1:0.5 3:2.0\n"
    "\n"
    "-1 2:1e-3 7:-4.25   # trailing comment 9:9\n"
    "   # comment-only line\n"
    "3.5 1:+2.5 10:1E2\n"
    "-1 5:0.125"  # no trailing newline
)


@pytest.fixture
def tricky_file(tmp_path):
    p = tmp_path / "t.libsvm"
    p.write_text(TRICKY)
    return str(p)


def _python_parse(path, **kw):
    """Force the pure-Python tokenizer regardless of native availability."""
    import unittest.mock as mock

    with mock.patch.object(libsvm_parser, "parse_file", lambda *a, **k: None):
        return libsvm.read_libsvm(path, **kw)


def test_native_available_or_skipped():
    if not libsvm_parser.available():
        pytest.skip("no native toolchain in this environment")


def test_native_matches_python(tricky_file):
    if not libsvm_parser.available():
        pytest.skip("no native toolchain")
    for kw in (
        dict(),
        dict(add_intercept=False),
        dict(zero_based=True),
        dict(num_features=64),
        dict(binary_labels_to_01=False),
    ):
        native = libsvm.read_libsvm(tricky_file, **kw)
        ref = _python_parse(tricky_file, **kw)
        np.testing.assert_array_equal(native.indptr, ref.indptr)
        np.testing.assert_array_equal(native.indices, ref.indices)
        np.testing.assert_allclose(native.values, ref.values, rtol=1e-6)
        np.testing.assert_allclose(native.labels, ref.labels)
        assert native.dim == ref.dim


def test_native_raw_output(tricky_file):
    if not libsvm_parser.available():
        pytest.skip("no native toolchain")
    out = libsvm_parser.parse_file(tricky_file)
    assert out is not None
    labels, indptr, indices, values, max_idx = out
    np.testing.assert_allclose(labels, [1.0, -1.0, 3.5, -1.0])
    np.testing.assert_array_equal(indptr, [0, 2, 4, 6, 7])
    np.testing.assert_array_equal(indices, [0, 2, 1, 6, 0, 9, 4])
    np.testing.assert_allclose(
        values, [0.5, 2.0, 1e-3, -4.25, 2.5, 100.0, 0.125], rtol=1e-6
    )
    assert max_idx == 9


def test_empty_file(tmp_path):
    p = tmp_path / "e.libsvm"
    p.write_text("\n# only comments\n")
    ds = libsvm.read_libsvm(str(p), add_intercept=False, num_features=3)
    assert ds.num_rows == 0 and ds.dim == 3


def test_malformed_falls_back_to_python_error(tmp_path):
    p = tmp_path / "bad.libsvm"
    p.write_text("notanumber 1:2\n")
    with pytest.raises(ValueError):
        libsvm.read_libsvm(str(p))


def test_float64_precision_preserved(tmp_path):
    """dtype=float64 must not round-trip values through float32 natively."""
    if not libsvm_parser.available():
        pytest.skip("no native toolchain")
    p = tmp_path / "p.libsvm"
    p.write_text("1 1:0.1\n")
    ds = libsvm.read_libsvm(str(p), add_intercept=False, dtype=np.float64)
    assert ds.values[0] == 0.1  # exact f64 repr of the parsed literal


def test_hex_floats_rejected_consistently(tmp_path):
    """strtod accepts 0x10; Python float() does not. Native must decline so
    both engines agree on what a valid file is."""
    p = tmp_path / "h.libsvm"
    p.write_text("1 1:0x10\n")
    assert libsvm_parser.parse_file(str(p)) is None or not libsvm_parser.available()
    with pytest.raises(ValueError):
        libsvm.read_libsvm(str(p))


def test_huge_index_falls_back_loudly(tmp_path):
    p = tmp_path / "big.libsvm"
    p.write_text("1 3000000000:1.0\n")
    if libsvm_parser.available():
        assert libsvm_parser.parse_file(str(p)) is None
    with pytest.raises((ValueError, OverflowError)):
        libsvm.read_libsvm(str(p))


def test_no_trailing_newline_tail_token(tmp_path):
    """File ending mid-token without a newline must parse the final value
    exactly (guards the buffer-termination path)."""
    if not libsvm_parser.available():
        pytest.skip("no native toolchain")
    p = tmp_path / "t.libsvm"
    p.write_bytes(b"1 1:2.5 2:3")
    out = libsvm_parser.parse_file(str(p))
    assert out is not None
    _, _, indices, values, _ = out
    np.testing.assert_array_equal(indices, [0, 1])
    np.testing.assert_allclose(values, [2.5, 3.0])


def test_kill_switch_is_global(tmp_path, monkeypatch):
    """PHOTON_DISABLE_NATIVE must gate every native component through the one
    shared loader in native/build.py."""
    from photon_ml_tpu.native import build

    monkeypatch.setenv("PHOTON_DISABLE_NATIVE", "1")
    assert build.native_library_path() is None


def test_missing_value_after_colon_rejected(tmp_path):
    """'idx:' with no attached value must fail in both engines — the native
    parser must not consume the next line's label as the value."""
    for text in ("1 1:\n0 2:3\n", "1 1: 2\n"):
        p = tmp_path / "mv.libsvm"
        p.write_text(text)
        if libsvm_parser.available():
            assert libsvm_parser.parse_file(str(p)) is None
        with pytest.raises(ValueError):
            libsvm.read_libsvm(str(p))

"""Supervised GLM wrappers + legacy sweep workflow tests.

Counterpart of the reference's supervised integ tests (photon-api
src/integTest/.../supervised/BaseGLMIntegTest.scala with property
validators) and ModelTraining/ModelSelection behavior: link functions,
class prediction thresholds, warm-started reg-weight sweep, best-model
selection direction per task.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.containers import dense_data
from photon_ml_tpu.models import (
    LinearRegressionModel,
    LogisticRegressionModel,
    PoissonRegressionModel,
    create_model,
    select_best_model,
    train_glm_sweep,
)
from photon_ml_tpu.optimize.config import (
    L2,
    CoordinateOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.types import TaskType, VarianceComputationType


def _binary_problem(rng, n=400, d=6):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-(X @ w)))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    return X, y, w


def test_link_functions(rng):
    w = jnp.asarray(rng.normal(size=4).astype(np.float32))
    X = jnp.asarray(rng.normal(size=(10, 4)).astype(np.float32))
    z = X @ w

    logistic = create_model(TaskType.LOGISTIC_REGRESSION, w)
    assert isinstance(logistic, LogisticRegressionModel)
    np.testing.assert_allclose(
        logistic.compute_mean(X), 1.0 / (1.0 + np.exp(-np.asarray(z))), rtol=1e-5
    )

    linear = create_model(TaskType.LINEAR_REGRESSION, w)
    assert isinstance(linear, LinearRegressionModel)
    np.testing.assert_allclose(linear.compute_mean(X), np.asarray(z), rtol=1e-5)

    poisson = create_model(TaskType.POISSON_REGRESSION, w)
    assert isinstance(poisson, PoissonRegressionModel)
    np.testing.assert_allclose(poisson.compute_mean(X), np.exp(np.asarray(z)), rtol=1e-4)


def test_predict_class_threshold(rng):
    X, y, w = _binary_problem(rng)
    model = create_model(TaskType.LOGISTIC_REGRESSION, jnp.asarray(w))
    classes = np.asarray(model.predict_class(jnp.asarray(X)))
    assert set(np.unique(classes)).issubset({0.0, 1.0})
    # Threshold 0 -> everything positive.
    all_pos = np.asarray(model.predict_class(jnp.asarray(X), threshold=0.0))
    assert all_pos.min() == 1.0


def test_offsets_shift_margin(rng):
    w = jnp.asarray(rng.normal(size=3).astype(np.float32))
    X = jnp.asarray(rng.normal(size=(5, 3)).astype(np.float32))
    off = jnp.asarray(np.arange(5, dtype=np.float32))
    m = create_model(TaskType.LINEAR_REGRESSION, w)
    np.testing.assert_allclose(
        m.compute_score(X, off), np.asarray(X @ w) + np.arange(5), rtol=1e-5
    )


def test_sweep_warm_start_and_selection(rng):
    X, y, w_true = _binary_problem(rng, n=600)
    Xv, yv, _ = _binary_problem(rng, n=300)
    # Same generating coefficients for validation.
    pv = 1.0 / (1.0 + np.exp(-(Xv @ w_true)))
    yv = (rng.uniform(size=300) < pv).astype(np.float32)

    data = dense_data(X, y)
    val = dense_data(Xv, yv)
    cfg = CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=100, tolerance=1e-9),
        regularization=L2,
    )
    sweep = train_glm_sweep(
        data, TaskType.LOGISTIC_REGRESSION, cfg, [1000.0, 10.0, 0.1]
    )
    assert set(sweep.models) == {1000.0, 10.0, 0.1}
    # Heavier regularization shrinks the solution norm monotonically.
    norms = [
        float(jnp.linalg.norm(sweep.models[rw].coefficients.means))
        for rw in [1000.0, 10.0, 0.1]
    ]
    assert norms[0] < norms[1] < norms[2]

    rw, best, auc = select_best_model(sweep, val, TaskType.LOGISTIC_REGRESSION)
    assert rw in (10.0, 0.1)  # the absurd weight should lose
    assert auc > 0.7


def test_sweep_variances(rng):
    X, y, _ = _binary_problem(rng, n=200, d=4)
    data = dense_data(X, y)
    cfg = CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=50),
        regularization=L2,
        variance_computation=VarianceComputationType.SIMPLE,
    )
    sweep = train_glm_sweep(data, TaskType.LOGISTIC_REGRESSION, cfg, [1.0])
    coeffs = sweep.models[1.0].coefficients
    assert coeffs.variances is not None
    assert bool(jnp.all(coeffs.variances > 0.0))


def test_linear_regression_sweep_selection(rng):
    n, d = 500, 5
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d).astype(np.float32)
    y = (X @ w + 0.01 * rng.normal(size=n)).astype(np.float32)
    data = dense_data(X, y)
    cfg = CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=100), regularization=L2
    )
    sweep = train_glm_sweep(data, TaskType.LINEAR_REGRESSION, cfg, [100.0, 0.01])
    rw, model, rmse = select_best_model(sweep, data, TaskType.LINEAR_REGRESSION)
    assert rw == 0.01  # smaller-is-better direction for RMSE
    assert rmse < 0.1

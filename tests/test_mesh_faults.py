"""Pod-scale failure domains (ISSUE 10): mesh fault sites, elastic sharded
checkpoints, the hang watchdog, and serving shard-loss degradation.

The contracts, extending tests/test_faults.py to the distributed layers:

* an armed `collective` fault re-dispatches (bounded) and, exhausted,
  degrades THAT sweep group to the per-bucket loop — the trained model
  stays BITWISE-identical to the clean sharded fit either way;
* a checkpoint written from an entity-sharded fit lands as one npz per
  shard (per-shard crc32 in state.json) and resumes bitwise on a
  DIFFERENT mesh shape (replicated in-process; 1/2/8-device subprocesses
  in the slow kill-resume test in test_faults.py); a corrupt or armed
  (`resume_load`) shard read retries then refuses naming the shard;
* the watchdog converts an over-deadline dispatch into a typed
  `DeviceHang` — the sweep re-dispatches, serving degrades to FE-only
  answers + a DEGRADED health transition — and `watchdog_trips` counts
  what previously no counter observed;
* a LOST serving shard keeps the engine answering: exactly its entities
  get bitwise FE-only (pinned zero row) answers, per-shard health shows
  in metrics()["sharding"], and recovery restages ONLY the lost shard.
"""

import json
import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.game_dataset import (
    GameDataset,
    RandomEffectDataConfig,
    build_random_effect_dataset,
)
from photon_ml_tpu.game.checkpoint import (
    CheckpointIntegrityError,
    CoordinateDescentCheckpoint,
)
from photon_ml_tpu.game.coordinate import RandomEffectCoordinate
from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent
from photon_ml_tpu.optimize.config import (
    L2,
    CoordinateOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.parallel.mesh import (
    make_mesh,
    pad_game_dataset,
    shard_game_dataset,
    shard_random_effect_dataset,
)
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import faults
from photon_ml_tpu.utils.watchdog import Watchdog

pytestmark = pytest.mark.chaos

TASK = TaskType.LOGISTIC_REGRESSION
# 40 entities x 6 rows = 240 samples: divisible by 8, so the padded
# sharded dataset is IDENTICAL to the replicated one and the checkpoint
# config fingerprint matches across mesh shapes (elastic resume).
N_ENTITIES, ROWS_EACH, D_RE = 40, 6, 5


def _problem(seed=0):
    rng = np.random.default_rng(seed)
    n = N_ENTITIES * ROWS_EACH
    Xe = rng.normal(size=(n, D_RE)).astype(np.float32)
    ent = np.repeat(np.arange(N_ENTITIES), ROWS_EACH)
    y = (rng.uniform(size=n) > 0.5).astype(np.float32)
    return Xe, ent, y


_CFG = CoordinateOptimizationConfig(
    optimizer=OptimizerConfig(max_iterations=8, tolerance=1e-7),
    regularization=L2,
    reg_weight=1.0,
)
_RE_CFG = RandomEffectDataConfig("entityId", "re", min_bucket=8)


def _coords(sharded: bool, seed=0):
    Xe, ent, y = _problem(seed)
    ds = GameDataset.build(
        {"re": jnp.asarray(Xe)}, y, id_tags={"entityId": ent}
    )
    if sharded:
        mesh = make_mesh()
        ds = shard_game_dataset(pad_game_dataset(ds, mesh.devices.size), mesh)
        red = shard_random_effect_dataset(
            build_random_effect_dataset(ds, _RE_CFG), mesh
        )
    else:
        red = build_random_effect_dataset(ds, _RE_CFG)
    return {"re": RandomEffectCoordinate(ds, red, _CFG, TASK)}


def _matrix(result) -> np.ndarray:
    """Logical rows (E + 1) of the trained RE matrix — mesh padding rows
    are inert zeros and excluded from parity checks."""
    m = np.asarray(result.model.models["re"].coefficients_matrix)
    return m[: N_ENTITIES + 1]


# ------------------------------------------------------- collective faults


class TestCollectiveFaults:
    def test_sharded_scan_bitwise_equals_replicated(self):
        """Foundation for everything below: the entity-sharded scan sweep
        is BITWISE-equal to the single-device fit on logical rows."""
        a = _matrix(run_coordinate_descent(_coords(False), 2, seed=3))
        b = _matrix(run_coordinate_descent(_coords(True), 2, seed=3))
        np.testing.assert_array_equal(a, b)

    def test_collective_fault_redispatches_to_bitwise_parity(
        self, monkeypatch
    ):
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        clean = _matrix(run_coordinate_descent(_coords(True), 2, seed=3))
        with faults.inject("collective:1") as inj:
            faulted = _matrix(
                run_coordinate_descent(_coords(True), 2, seed=3)
            )
        assert inj.injected == {"collective": 1}
        assert faults.counters()["collective_retries"] == 1
        np.testing.assert_array_equal(clean, faulted)

    def test_exhausted_collective_degrades_to_bucket_loop(self, monkeypatch):
        """Retries exhausted on EVERY dispatch: each sweep group falls back
        to the per-bucket loop (collective site suppressed there) and the
        fit still lands bitwise on the clean sharded result."""
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        clean = _matrix(run_coordinate_descent(_coords(True), 2, seed=3))
        with faults.inject("collective:9999"):
            degraded = _matrix(
                run_coordinate_descent(_coords(True), 2, seed=3)
            )
        c = faults.counters()
        assert c["collective_fallbacks"] > 0
        assert c["collective_retries"] > 0
        np.testing.assert_array_equal(clean, degraded)

    def test_non_device_error_propagates(self):
        """The fallback tier is for device-shaped failures only — a
        programming error inside the sweep must surface, not be silently
        'degraded' around."""
        coords = _coords(True)
        coord = coords["re"]
        orig = coord._dispatch_scan_group

        def boom(*a, **k):
            raise ValueError("a bug, not weather")

        coord._dispatch_scan_group = boom
        with pytest.raises(ValueError, match="a bug"):
            run_coordinate_descent(coords, 1, seed=3)
        coord._dispatch_scan_group = orig


# ------------------------------------------------- elastic sharded ckpt


class TestElasticShardedCheckpoint:
    def test_sharded_layout_with_per_shard_checksums(self, tmp_path):
        ck = str(tmp_path / "ck")
        run_coordinate_descent(_coords(True), 1, seed=5, checkpoint_dir=ck)
        state = json.load(open(os.path.join(ck, "state.json")))
        rels = state["model_files"]["re"]
        assert isinstance(rels, list) and len(rels) == 8
        assert all(f".shard{k}of8.npz" in rels[k] for k in range(8))
        for rel in rels:
            assert state["checksums"][rel].startswith("crc32:")
            assert os.path.isfile(os.path.join(ck, rel))

    def test_resume_onto_other_mesh_shape_bitwise(self, tmp_path):
        """N-shard checkpoint -> replicated (1-device path) resume, and
        back: the reassembled matrix re-pads/re-shards onto the resuming
        layout and the final model is bitwise the uninterrupted one (the
        1/2/8-device SUBPROCESS matrix of this contract lives in
        test_faults.py::TestShardedKillResume)."""
        straight = _matrix(run_coordinate_descent(_coords(True), 2, seed=5))
        ck = str(tmp_path / "ck")

        class _Preempt:
            def __init__(self, inner, allowed):
                self.inner, self.allowed, self.calls = inner, allowed, 0

            def __getattr__(self, name):
                return getattr(self.inner, name)

            def train(self, *args, **kwargs):
                if self.calls >= self.allowed:
                    raise RuntimeError("simulated preemption")
                self.calls += 1
                return self.inner.train(*args, **kwargs)

        coords = _coords(True)
        coords["re"] = _Preempt(coords["re"], 1)  # step 1 commits, step 2 dies
        with pytest.raises(RuntimeError, match="preemption"):
            run_coordinate_descent(coords, 2, seed=5, checkpoint_dir=ck)
        # Resume on the REPLICATED layout (a 1-device mesh shape).
        resumed_repl = _matrix(
            run_coordinate_descent(_coords(False), 2, seed=5, checkpoint_dir=ck)
        )
        np.testing.assert_array_equal(straight, resumed_repl)
        # And the replicated run's (single-blob) checkpoint resumes back
        # onto the 8-device mesh bitwise too.
        resumed_sharded = _matrix(
            run_coordinate_descent(_coords(True), 2, seed=5, checkpoint_dir=ck)
        )
        np.testing.assert_array_equal(straight, resumed_sharded)

    def test_corrupt_shard_refused_naming_the_shard(self, tmp_path):
        ck = str(tmp_path / "ck")
        run_coordinate_descent(_coords(True), 1, seed=5, checkpoint_dir=ck)
        state = json.load(open(os.path.join(ck, "state.json")))
        rel = state["model_files"]["re"][3]
        path = os.path.join(ck, rel)
        data = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(data[: len(data) // 2])
        with pytest.raises(CheckpointIntegrityError, match="shard") as exc:
            CoordinateDescentCheckpoint(ck).load(TASK)
        assert rel in str(exc.value)

    def test_missing_shard_refused(self, tmp_path):
        ck = str(tmp_path / "ck")
        run_coordinate_descent(_coords(True), 1, seed=5, checkpoint_dir=ck)
        state = json.load(open(os.path.join(ck, "state.json")))
        os.remove(os.path.join(ck, state["model_files"]["re"][0]))
        with pytest.raises(
            CheckpointIntegrityError, match="missing shard file"
        ):
            CoordinateDescentCheckpoint(ck).load(TASK)

    def test_resume_load_fault_retried(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        ck = str(tmp_path / "ck")
        r1 = _matrix(
            run_coordinate_descent(_coords(True), 1, seed=5, checkpoint_dir=ck)
        )
        with faults.inject("resume_load:1") as inj:
            r2 = _matrix(
                run_coordinate_descent(
                    _coords(True), 1, seed=5, checkpoint_dir=ck
                )
            )
        assert inj.injected == {"resume_load": 1}
        assert faults.counters()["retries"] >= 1
        np.testing.assert_array_equal(r1, r2)


# -------------------------------------------------------------- watchdog


class TestWatchdog:
    def test_trip_raises_device_hang_and_counts(self):
        with Watchdog() as wd:
            with pytest.raises(faults.DeviceHang, match="watchdog deadline"):
                with wd.guard(5, "slow dispatch"):
                    time.sleep(0.08)
            assert wd.trips == 1
        assert faults.counters()["watchdog_trips"] == 1

    def test_fast_scope_is_free_and_disabled_is_noop(self):
        with Watchdog() as wd:
            with wd.guard(10_000, "fast"):
                pass
            with wd.guard(0, "disabled"):
                time.sleep(0.01)
            assert wd.trips == 0
        assert faults.counters().get("watchdog_trips", 0) == 0

    def test_on_trip_fires_while_still_stuck(self):
        """The callback must fire AT trip time (a hung-forever dispatch
        still flips health), not at scope exit."""
        seen = []
        with Watchdog(on_trip=seen.append) as wd:
            try:
                with wd.guard(5, "wedged"):
                    deadline = time.monotonic() + 2.0
                    while not seen and time.monotonic() < deadline:
                        time.sleep(0.005)
            except faults.DeviceHang:
                pass
        assert seen == ["wedged"]

    def test_close_joins_monitor(self):
        import threading

        wd = Watchdog()
        with wd.guard(10_000, "x"):
            pass
        wd.close()
        assert not any(
            t.name == "photon-watchdog" and t.is_alive()
            for t in threading.enumerate()
        )

    def test_sweep_converts_hang_to_redispatch(self, monkeypatch):
        """A scan-group dispatch that blows its deadline once re-dispatches
        and lands bitwise (the deterministic program reproduces itself)."""
        monkeypatch.setenv("PHOTON_RETRY_BASE_DELAY_S", "0.001")
        monkeypatch.setenv("PHOTON_WATCHDOG_MS", "50")
        clean = _matrix(run_coordinate_descent(_coords(True), 1, seed=3))

        coords = _coords(True)
        coord = coords["re"]
        real = coord._train_scan_sharded
        calls = {"n": 0}

        def slow_once(*args):
            out = real(*args)
            import jax

            jax.block_until_ready(out[0])
            calls["n"] += 1
            if calls["n"] == 1:
                time.sleep(0.2)  # first dispatch: simulated wedge
            return out

        coord._train_scan_sharded = slow_once
        hung = _matrix(run_coordinate_descent(coords, 1, seed=3))
        assert faults.counters()["watchdog_trips"] >= 1
        np.testing.assert_array_equal(clean, hung)

"""Fused Pallas GLM kernels vs the XLA objective path.

Runs the real kernel bodies in interpreter mode on the CPU backend (the
same stand-in strategy the conftest uses for the device mesh), asserting
numerical agreement with ops.objective's XLA expressions — which are
themselves tested against finite differences in test_objective.py.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.containers import LabeledData
from photon_ml_tpu.ops import objective, pallas_glm
from photon_ml_tpu.ops.losses import LOGISTIC, POISSON, SMOOTHED_HINGE, SQUARED
from photon_ml_tpu.ops.normalization import NormalizationContext

LOSSES = [LOGISTIC, SQUARED, POISSON, SMOOTHED_HINGE]


def _problem(rng, n, d, poisson_scale=False):
    X = rng.normal(size=(n, d)).astype(np.float32)
    if poisson_scale:
        X *= 0.1
    y = (rng.uniform(size=n) > 0.5).astype(np.float32)
    offsets = rng.normal(size=n).astype(np.float32) * 0.1
    weights = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    w = (rng.normal(size=d) * 0.1).astype(np.float32)
    return (
        jnp.asarray(X),
        jnp.asarray(y),
        jnp.asarray(offsets),
        jnp.asarray(weights),
        jnp.asarray(w),
    )


@pytest.mark.parametrize("loss", LOSSES, ids=lambda l: l.name)
@pytest.mark.parametrize("n", [1024, 1100])  # exact tile fit + ragged remainder
def test_value_gradient_sums_match_xla(rng, loss, n):
    d = 64
    X, y, off, wt, w = _problem(rng, n, d, poisson_scale=loss is POISSON)
    data = LabeledData(features=X, labels=y, offsets=off, weights=wt)

    val_ref, g_ref = objective.value_and_gradient(loss, w, data)
    shift = jnp.zeros(())
    val, g, sum_u = pallas_glm.value_gradient_sums(
        loss, w, shift, X, y, off, wt, interpret=True
    )
    np.testing.assert_allclose(float(val), float(val_ref), rtol=2e-5)
    # Scale-relative bound: hilo's 2-pass decomposition carries ~2^-16
    # representation error of the LARGEST magnitudes, so tiny elements of a
    # mixed-magnitude gradient can miss a per-element rtol while the result
    # is accurate to ~1e-5 of the vector's scale.
    g_scale = float(np.max(np.abs(np.asarray(g_ref)))) + 1e-6
    assert float(np.max(np.abs(np.asarray(g) - np.asarray(g_ref)))) < 3e-5 * g_scale
    u = wt * loss.d1(X @ w + off, y)
    np.testing.assert_allclose(float(sum_u), float(jnp.sum(u)), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("loss", [LOGISTIC, SQUARED, POISSON], ids=lambda l: l.name)
def test_hessian_vector_sums_match_xla(rng, loss):
    n, d = 1100, 64
    X, y, off, wt, w = _problem(rng, n, d, poisson_scale=loss is POISSON)
    v = jnp.asarray((rng.normal(size=d)).astype(np.float32))
    data = LabeledData(features=X, labels=y, offsets=off, weights=wt)

    hv_ref = objective.hessian_vector(loss, w, v, data)
    hv, sum_r = pallas_glm.hessian_vector_sums(
        loss, w, jnp.zeros(()), v, jnp.zeros(()), X, y, off, wt, interpret=True
    )
    hv_scale = float(np.max(np.abs(np.asarray(hv_ref)))) + 1e-6
    assert float(np.max(np.abs(np.asarray(hv) - np.asarray(hv_ref)))) < 3e-5 * hv_scale
    z = X @ w + off
    r = wt * loss.d2(z, y) * (X @ v)
    np.testing.assert_allclose(float(sum_r), float(jnp.sum(r)), rtol=2e-4, atol=2e-4)


def test_objective_dispatch_with_normalization(rng, monkeypatch):
    """The objective-layer dispatch must apply the shift/factor algebra to the
    kernel's raw sums identically to the XLA branch."""
    n, d = 2048, 128  # above the should_use size floor
    X, y, off, wt, w = _problem(rng, n, d)
    data = LabeledData(features=X, labels=y, offsets=off, weights=wt)
    norm = NormalizationContext(
        factors=jnp.asarray(rng.uniform(0.5, 2.0, size=d).astype(np.float32)),
        shifts=jnp.asarray((rng.normal(size=d) * 0.1).astype(np.float32)),
    )

    val_ref, g_ref = objective.value_and_gradient(LOGISTIC, w, data, norm, l2=0.3)
    v = jnp.asarray(rng.normal(size=d).astype(np.float32))
    hv_ref = objective.hessian_vector(LOGISTIC, w, v, data, norm, l2=0.3)

    monkeypatch.setattr(pallas_glm, "FORCE_INTERPRET", True)
    assert pallas_glm.should_use(data.features, w)
    val, g = objective.value_and_gradient(LOGISTIC, w, data, norm, l2=0.3)
    hv = objective.hessian_vector(LOGISTIC, w, v, data, norm, l2=0.3)

    np.testing.assert_allclose(float(val), float(val_ref), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(hv), np.asarray(hv_ref), rtol=2e-4, atol=2e-4)


def test_should_use_policy(rng):
    big = jnp.zeros((4096, 256), jnp.float32)
    w_big = jnp.zeros((256,), jnp.float32)
    small = jnp.zeros((128, 16), jnp.float32)
    w_small = jnp.zeros((16,), jnp.float32)
    wide = jnp.zeros((4096, 32768), jnp.float32)
    w_wide = jnp.zeros((32768,), jnp.float32)

    # CPU backend without the test hook: always off.
    assert not pallas_glm.should_use(big, w_big)
    try:
        pallas_glm.FORCE_INTERPRET = True
        assert pallas_glm.should_use(big, w_big)
        # Small (vmapped per-entity) problems and very wide ones stay on XLA.
        assert not pallas_glm.should_use(small, w_small)
        assert not pallas_glm.should_use(wide, w_wide)
        # Sparse containers are not dense arrays.
        from photon_ml_tpu.data.containers import SparseFeatures

        sf = SparseFeatures(
            indices=jnp.zeros((4096, 8), jnp.int32),
            values=jnp.zeros((4096, 8), jnp.float32),
            dim=256,
        )
        assert not pallas_glm.should_use(sf, w_big)
    finally:
        pallas_glm.FORCE_INTERPRET = False


def test_health_probe_gates_dispatch(rng, monkeypatch):
    """A kernel that crashes or miscomputes on this backend must disable
    dispatch instead of taking down training."""
    big = jnp.zeros((4096, 256), jnp.float32)
    w = jnp.zeros((256,), jnp.float32)
    monkeypatch.setattr(pallas_glm, "FORCE_INTERPRET", True)

    # Healthy: probe passes and is cached.
    monkeypatch.setattr(pallas_glm, "_HEALTHY", None)
    assert pallas_glm.should_use(big, w)
    assert pallas_glm._HEALTHY is True

    # Crashing kernel: falls back.
    monkeypatch.setattr(pallas_glm, "_HEALTHY", None)
    def boom(*a, **k):
        raise RuntimeError("mosaic says no")
    monkeypatch.setattr(pallas_glm, "value_gradient_sums", boom)
    assert not pallas_glm.should_use(big, w)
    assert pallas_glm._HEALTHY is False


def test_health_probe_checks_numerics(rng, monkeypatch):
    big = jnp.zeros((4096, 256), jnp.float32)
    w = jnp.zeros((256,), jnp.float32)
    monkeypatch.setattr(pallas_glm, "FORCE_INTERPRET", True)
    monkeypatch.setattr(pallas_glm, "_HEALTHY", None)

    real = pallas_glm.value_gradient_sums
    def wrong(*a, **k):
        val, g, su = real(*a, **k)
        return val + 100.0, g, su  # silently wrong value
    monkeypatch.setattr(pallas_glm, "value_gradient_sums", wrong)
    assert not pallas_glm.should_use(big, w)

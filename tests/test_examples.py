"""The examples must stay runnable end to end (reference parity: the tutorial
flow of README.md:307-345 is exercised by the driver integ tests)."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "examples"))

from photon_ml_tpu.cli import libsvm_to_avro, score as score_cli, train as train_cli
from photon_ml_tpu.io.avro_data import FeatureShardConfig, read_game_dataset


def test_libsvm_converter_roundtrip(tmp_path):
    src = tmp_path / "t.libsvm"
    src.write_text(
        "+1 1:0.5 3:2.0 # memberId=m1\n"
        "-1 2:1.0  # memberId=m2,country=us\n"
        "\n"
        "+1 1:1.5\n"
    )
    out = str(tmp_path / "t.avro")
    n = libsvm_to_avro.convert(str(src), out, tag_comments=True)
    assert n == 3
    ds, maps = read_game_dataset(
        out,
        {"g": FeatureShardConfig(has_intercept=False)},
        id_tag_fields=["memberId", "country"],
        response_field="label",
    )
    assert ds.num_samples == 3
    np.testing.assert_allclose(np.asarray(ds.labels), [1.0, 0.0, 1.0])
    assert list(ds.id_tags["memberId"]) == ["m1", "m2", ""]
    assert list(ds.id_tags["country"]) == ["", "us", ""]
    dense = np.asarray(ds.shards["g"].to_dense())
    assert dense[0, maps["g"].get_index("0")] == 0.5
    assert dense[0, maps["g"].get_index("2")] == 2.0


def test_generator_is_deterministic(tmp_path):
    import generate_dataset

    p1 = tmp_path / "a.libsvm"
    p2 = tmp_path / "b.libsvm"
    generate_dataset.generate(str(p1), 50, seed=0, entities=4)
    generate_dataset.generate(str(p2), 50, seed=0, entities=4)
    assert p1.read_text() == p2.read_text()
    assert "# memberId=m" in p1.read_text()


def test_fixed_effect_example_flow(tmp_path):
    """The run_game_training.sh stages, driven in-process at reduced size."""
    import generate_dataset

    data = tmp_path / "data"
    data.mkdir()
    generate_dataset.generate(str(data / "train.libsvm"), 600, seed=0)
    generate_dataset.generate(str(data / "test.libsvm"), 300, seed=1)
    libsvm_to_avro.main([str(data / "train.libsvm"), str(data / "train.avro")])
    libsvm_to_avro.main([str(data / "test.libsvm"), str(data / "test.avro")])

    out = str(tmp_path / "results")
    train_cli.main([
        "--training-task", "LOGISTIC_REGRESSION",
        "--input-data-directories", str(data / "train.avro"),
        "--validation-data-directories", str(data / "test.avro"),
        "--root-output-directory", out,
        "--feature-shard-configurations",
        "name=globalShard,feature.bags=features,intercept=true",
        "--coordinate-configurations",
        "name=global,feature.shard=globalShard,optimizer=LBFGS,"
        "tolerance=1.0E-7,max.iter=50,regularization=L2,reg.weights=0.1|1|10",
        "--validation-evaluators", "AUC",
        "--output-mode", "BEST",
    ])
    summary = json.load(open(os.path.join(out, "training-summary.json")))
    assert summary["best_evaluation"]["AUC"] > 0.75

    scores = str(tmp_path / "scores")
    score_cli.main([
        "--input-data-directories", str(data / "test.avro"),
        "--model-input-directory", os.path.join(out, "models", "best"),
        "--root-output-directory", scores,
        "--feature-shard-configurations",
        "name=globalShard,feature.bags=features,intercept=true",
        "--evaluators", "AUC",
    ])
    ssum = json.load(open(os.path.join(scores, "scoring-summary.json")))
    assert abs(ssum["evaluation"]["AUC"] - summary["best_evaluation"]["AUC"]) < 5e-3


def test_example_shell_scripts_are_wellformed():
    """Guard the scripts against referencing CLIs/flags that do not exist."""
    for script in ("run_game_training.sh", "run_glmix.sh"):
        text = open(os.path.join(REPO, "examples", script)).read()
        assert "set -euo pipefail" in text
        for mod in ("cli.libsvm_to_avro", "cli.train", "cli.score"):
            assert mod in text
    # Flags used by the scripts must parse.
    parser = train_cli.build_parser()
    known = {a for action in parser._actions for a in action.option_strings}
    for script in ("run_game_training.sh", "run_glmix.sh"):
        text = open(os.path.join(REPO, "examples", script)).read()
        in_train = False
        for line in text.splitlines():
            line = line.strip().rstrip("\\").strip()
            if "cli.train" in line:
                in_train = True
                continue
            if in_train:
                if line.startswith("--"):
                    flag = line.split()[0]
                    assert flag in known, f"{script}: unknown train flag {flag}"
                elif not line.startswith('"') and not line.startswith("'"):
                    in_train = False


def test_converter_label_mapping_is_whole_file(tmp_path):
    """Regression files containing some ±1 labels must pass through unmapped,
    matching read_libsvm's whole-file rule."""
    src = tmp_path / "r.libsvm"
    src.write_text("2.5 1:1\n-1 1:1\n")
    out = str(tmp_path / "r.avro")
    libsvm_to_avro.convert(str(src), out)
    ds, _ = read_game_dataset(
        out, {"g": FeatureShardConfig(has_intercept=False)}, response_field="label"
    )
    np.testing.assert_allclose(np.asarray(ds.labels), [2.5, -1.0])

"""PalDB v1 store reader vs the reference's OWN prebuilt index partitions
(PalDBIndexMap.scala / PalDBIndexMapBuilder.scala fixtures)."""

import os

import numpy as np
import pytest

from photon_ml_tpu.data.index_map import DELIMITER, INTERCEPT_KEY, feature_key
from photon_ml_tpu.io import paldb

REF = "/root/reference/photon-client/src/integTest/resources"
PALDB_HEART = os.path.join(REF, "PalDBIndexMapTest")
GAME_IN = os.path.join(REF, "GameIntegTest", "input")

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference fixtures not mounted"
)


class TestPalDBReader:
    def test_heart_two_partition_store(self):
        """paldb_offheapmap_for_heart: 13 heart features hash-split over two
        partitions, global ids offset per partition (PalDBIndexMap.load)."""
        m = paldb.load_index_map(
            os.path.join(PALDB_HEART, "paldb_offheapmap_for_heart"), "global"
        )
        assert set(m) == {str(i) for i in range(1, 14)}
        assert sorted(m[k] for k in m) == list(range(13))

    def test_heart_store_with_intercept(self):
        m = paldb.load_index_map(
            os.path.join(PALDB_HEART, "paldb_offheapmap_for_heart_with_intercept"),
            "global",
        )
        assert set(m) == {str(i) for i in range(1, 14)} | {INTERCEPT_KEY}
        assert m.intercept_index is not None
        assert sorted(m[k] for k in m) == list(range(14))

    @pytest.mark.parametrize(
        "store,shard,size",
        [
            ("feature-indexes", "shard1", 15045),
            ("feature-indexes", "shard2", 15015),
            ("feature-indexes", "shard3", 31),
            ("test-with-uid-feature-indexes", "globalShard", 7234),
            ("test-with-uid-feature-indexes", "userShard", 7204),
            ("test-with-uid-feature-indexes", "songShard", 7204),
        ],
    )
    def test_game_integ_stores_decode_fully(self, store, shard, size):
        """Every GameIntegTest store decodes completely (the reader refuses
        partial decodes), ids are dense 0..size-1, intercepts present —
        covering every int width (single-byte, raw-byte, varint) and
        thousands of name/term strings."""
        m = paldb.load_index_map(os.path.join(GAME_IN, store), shard)
        assert m.size == size
        assert sorted(m[k] for k in m) == list(range(size))
        assert m.intercept_index is not None

    def test_shard3_covers_song_feature_list(self):
        """shard3's keys include every (name, term) the reference's
        songFeatures list names."""
        m = paldb.load_index_map(os.path.join(GAME_IN, "feature-indexes"), "shard3")
        lists = open(os.path.join(GAME_IN, "feature-lists", "songFeatures")).read()
        for line in lists.splitlines():
            if line.strip():
                name, term = (line.split("\t") + [""])[:2]
                assert m.get_index(feature_key(name, term)) >= 0

    def test_rejects_non_paldb(self, tmp_path):
        p = tmp_path / "paldb-partition-x-0.dat"
        p.write_bytes(b"\x00\x08NOTPALDB" + b"\x00" * 50)
        with pytest.raises(ValueError, match="PALDB_V1"):
            paldb.read_store(str(p))


class TestTrainWithReferenceIndexStore:
    def test_cli_trains_against_reference_paldb_index(self, tmp_path):
        """End to end: the training driver consumes the reference's OWN
        PalDB index partitions via --offheap-indexmap-dir and trains on the
        yahoo-music records with those feature ids."""
        from photon_ml_tpu.cli import train as train_cli
        import json

        out = str(tmp_path / "out")
        train_cli.main([
            "--training-task", "LINEAR_REGRESSION",
            "--input-data-directories",
            os.path.join(GAME_IN, "duplicateFeatures", "yahoo-music-train.avro"),
            "--root-output-directory", out,
            "--offheap-indexmap-dir",
            os.path.join(GAME_IN, "test-with-uid-feature-indexes"),
            "--feature-shard-configurations",
            "name=globalShard,feature.bags=features|userFeatures|songFeatures,intercept=true",
            "--coordinate-configurations",
            "name=global,feature.shard=globalShard,optimizer=TRON,"
            "max.iter=10,regularization=L2,reg.weights=10",
        ])
        summary = json.load(open(os.path.join(out, "training-summary.json")))
        assert summary["num_samples"] == 6
        # The saved model's coefficient ids live in the REFERENCE's index
        # space (size 7234), not a data-derived map.
        from photon_ml_tpu.data.index_map import IndexMap
        from photon_ml_tpu.io import model_store

        imap = IndexMap.load(
            os.path.join(out, "models", "best", "feature-indexes", "globalShard.json")
        )
        assert imap.size == 7234
        art = model_store.load_game_model(
            os.path.join(out, "models", "best"), {"globalShard": imap}
        )
        assert np.isfinite(art.coordinates["global"].means).all()


def test_partition_files_exact_shard_match(tmp_path):
    """Shard 'global' must not swallow 'global-v2' partitions or stray
    non-numeric .dat files."""
    for name in (
        "paldb-partition-global-0.dat",
        "paldb-partition-global-1.dat",
        "paldb-partition-global-v2-0.dat",
        "paldb-partition-global-meta.dat",
    ):
        (tmp_path / name).write_bytes(b"x")
    got = [os.path.basename(p) for p in paldb.partition_files(str(tmp_path), "global")]
    assert got == ["paldb-partition-global-0.dat", "paldb-partition-global-1.dat"]
    got2 = [os.path.basename(p) for p in paldb.partition_files(str(tmp_path), "global-v2")]
    assert got2 == ["paldb-partition-global-v2-0.dat"]
    assert paldb.partition_files(str(tmp_path / "missing"), "x") == []


class TestScoreWithReferencePalDBIndex:
    def test_cli_scores_reference_model_with_paldb_index(self, tmp_path):
        """GameScoringDriverIntegTest flow: the scoring driver loads the
        reference's pre-trained model THROUGH the reference's PalDB index
        store and scores yahoo-music records; CLI scores must equal the
        library path's scores under the same maps."""
        import json

        from photon_ml_tpu.cli import score as score_cli
        from photon_ml_tpu.io import model_store
        from photon_ml_tpu.io.avro_data import FeatureShardConfig, read_game_dataset
        from photon_ml_tpu.io.model_bridge import game_model_from_artifact
        from photon_ml_tpu.io.score_store import load_scores
        from photon_ml_tpu.transformers.game_transformer import GameTransformer

        mdir = os.path.join(REF, "GameIntegTest", "fixedEffectOnlyGAMEModel")
        store = os.path.join(GAME_IN, "test-with-uid-feature-indexes")
        data = os.path.join(GAME_IN, "duplicateFeatures", "yahoo-music-train.avro")
        out = str(tmp_path / "scores")
        score_cli.main([
            "--input-data-directories", data,
            "--model-input-directory", mdir,
            "--offheap-indexmap-dir", store,
            "--root-output-directory", out,
            "--feature-shard-configurations",
            "name=globalShard,feature.bags=features|userFeatures|songFeatures,intercept=true",
        ])
        ssum = json.load(open(os.path.join(out, "scoring-summary.json")))
        assert ssum["num_scored"] == 6
        items = load_scores(os.path.join(out, "scores"))
        cli_scores = np.asarray([it.prediction_score for it in items])

        imap = paldb.load_index_map(store, "globalShard")
        art = model_store.load_game_model(mdir, {"globalShard": imap})
        model, specs = game_model_from_artifact(art)
        ds, _ = read_game_dataset(
            data,
            {"globalShard": FeatureShardConfig(
                ("features", "userFeatures", "songFeatures"), True)},
            index_maps={"globalShard": imap},
        )
        lib_scores = np.asarray(
            GameTransformer(model, specs, art.task).transform(ds).scores
        )
        np.testing.assert_allclose(cli_scores, lib_scores, rtol=1e-5)
        assert np.isfinite(cli_scores).all()


class TestPalDBWriter:
    """Write side: stores this framework emits must be loadable by the
    reference's PalDBIndexMap. Format fidelity is proven two ways: exact
    byte reproduction of the reference's own fixture stores, and a
    simulated paldb StorageReader lookup (hash -> slot -> probe -> value)
    resolving every key."""

    FIXTURES = [
        os.path.join(
            REF, "PalDBIndexMapTest/paldb_offheapmap_for_heart/"
            "paldb-partition-global-0.dat"),
        os.path.join(
            REF, "PalDBIndexMapTest/paldb_offheapmap_for_heart/"
            "paldb-partition-global-1.dat"),
        os.path.join(
            REF, "PalDBIndexMapTest/paldb_offheapmap_for_heart_with_intercept/"
            "paldb-partition-global-0.dat"),
        os.path.join(
            REF, "GameIntegTest/input/feature-indexes/"
            "paldb-partition-shard1-0.dat"),
    ]

    def test_byte_identical_fixture_roundtrip(self, tmp_path):
        import struct

        for p in self.FIXTURES:
            raw = open(p, "rb").read()
            store = paldb.read_store(p)
            names = {k: v for k, v in store.items() if isinstance(k, str)}
            entries = []
            for name, i in sorted(names.items(), key=lambda kv: kv[1]):
                entries.append((name, i))
                entries.append((i, name))
            ulen = struct.unpack(">H", raw[:2])[0]
            ts = struct.unpack(">q", raw[2 + ulen : 2 + ulen + 8])[0]
            out = str(tmp_path / "rt.dat")
            paldb.write_store(out, entries, timestamp_ms=ts)
            assert open(out, "rb").read() == raw, os.path.basename(p)

    def test_simulated_paldb_lookup_resolves_every_key(self, tmp_path):
        out = str(tmp_path / "s.dat")
        keys = [f"feat{i}\x01term{i % 7}" for i in range(500)] + ["(INTERCEPT)\x01"]
        entries = []
        for i, k in enumerate(keys):
            entries.append((k, i))
            entries.append((i, k))
        paldb.write_store(out, entries)
        b = open(out, "rb").read()
        for i, k in enumerate(keys):
            assert paldb.lookup(b, k) == i, k
            assert paldb.lookup(b, i) == k, i
        assert paldb.lookup(b, "absent\x01") is None
        assert paldb.lookup(b, 10**6) is None

    def test_write_index_map_reader_roundtrip(self, tmp_path):
        """Our own reader (validated against the reference's stores) loads
        what write_index_map emits, with identical global ids."""
        store_dir = str(tmp_path / "store")
        feats = [f"f{i}" for i in range(97)] + ["name\x01term", "(INTERCEPT)"]
        mapping = paldb.write_index_map(store_dir, "myShard", feats, num_partitions=3)
        assert len(paldb.partition_files(store_dir, "myShard")) == 3
        imap = paldb.load_index_map(store_dir, "myShard")
        assert len(mapping) == len(feats)
        for k, v in mapping.items():
            assert imap.get_index(k) == v, k
        # partition routing must follow java hashCode mod n
        for k in feats:
            stored = k if "\x01" in k else k + "\x01"
            pid = paldb.java_partition(stored, 3)
            files = paldb.partition_files(store_dir, "myShard")
            assert paldb.lookup(open(files[pid], "rb").read(), stored) is not None

    def test_cli_paldb_output(self, tmp_path):
        """cli/build_index --output-format paldb emits PalDB partitions the
        (validated) reader + the heart training path can consume."""
        from photon_ml_tpu.cli import build_index as bi_cli

        data = os.path.join(REF, "DriverIntegTest/input/heart.avro")
        out = str(tmp_path / "index")
        bi_cli.main([
            "--input-data-directories", data,
            "--feature-shard-configurations",
            "name=global,feature.bags=features,intercept=true",
            "--num-partitions", "2",
            "--output-dir", out,
            "--output-format", "paldb",
        ])
        files = paldb.partition_files(out, "global")
        assert len(files) == 2
        imap = paldb.load_index_map(out, "global")
        assert imap.get_index("(INTERCEPT)") >= 0
        assert imap.size == 14  # 13 heart features + intercept

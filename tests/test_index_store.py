"""Native persistent index store + indexing drivers.

Covers the PalDB-equivalent stack (reference PalDBIndexMap.scala,
PalDBIndexMapBuilder.scala, FeatureIndexingDriver.scala,
NameAndTermFeatureBagsDriver.scala): on-disk format roundtrip through both
engines (C++ via ctypes and the pure-Python fallback), cross-engine
compatibility, the partitioned global-index/offset scheme, and the two CLI
drivers end-to-end.
"""

import json
import os

import pytest

from photon_ml_tpu.cli import build_index, name_and_term
from photon_ml_tpu.data.index_map import INTERCEPT_KEY, feature_key
from photon_ml_tpu.io.avro_data import write_training_examples
from photon_ml_tpu.native import index_store as ist


KEYS = [feature_key(f"f{i}", f"t{i % 3}") for i in range(100)] + ["plain", INTERCEPT_KEY]

ENGINES = [True]  # force_python
if ist.native_available():
    ENGINES.append(False)


def test_native_library_builds():
    """The image ships g++; the native engine must actually be available."""
    assert ist.native_available(), "C++ index store failed to build"


@pytest.mark.parametrize("force_python", ENGINES)
def test_partition_roundtrip(tmp_path, force_python):
    path = str(tmp_path / "part.bin")
    ist.build_partition(path, KEYS, force_python=force_python)
    part = ist.open_partition(path, force_python=force_python)
    assert part.size == len(KEYS)
    for i, key in enumerate(KEYS):
        assert part.get(key.encode()) == i
        assert part.name(i) == key
    assert part.get(b"missing") == -1
    assert part.name(len(KEYS)) is None
    part.close()


@pytest.mark.parametrize("builder_python,reader_python", [(True, False), (False, True)])
def test_cross_engine_format_compat(tmp_path, builder_python, reader_python):
    if not ist.native_available():
        pytest.skip("native engine unavailable")
    path = str(tmp_path / "part.bin")
    ist.build_partition(path, KEYS, force_python=builder_python)
    part = ist.open_partition(path, force_python=reader_python)
    for i, key in enumerate(KEYS):
        assert part.get(key.encode()) == i
        assert part.name(i) == key
    part.close()


def test_empty_partition(tmp_path):
    path = str(tmp_path / "empty.bin")
    ist.build_partition(path, [], force_python=True)
    for force in (True, False) if ist.native_available() else (True,):
        part = ist.open_partition(path, force_python=force)
        assert part.size == 0
        assert part.get(b"x") == -1
        part.close()


@pytest.mark.parametrize("force_python", ENGINES)
def test_partitioned_store_global_indices(tmp_path, force_python):
    """Global idx = local + offset, unique and dense over all partitions
    (PalDBIndexMap.scala:36-44 offset-array semantics)."""
    store_dir = str(tmp_path / "store")
    total = ist.build_partitioned_store(
        store_dir, KEYS, num_partitions=4, namespace="shardA", force_python=force_python
    )
    assert total == len(KEYS)
    with ist.PartitionedIndexStore(
        store_dir, "shardA", force_python=force_python
    ) as store:
        assert store.num_partitions == 4
        assert store.size == len(KEYS)
        seen = {}
        for key in KEYS:
            idx = store.get_index(key)
            assert 0 <= idx < store.size
            assert idx not in seen
            seen[idx] = key
            # reverse lookup is the exact inverse
            assert store.get_feature_name(idx) == key
        assert sorted(seen) == list(range(len(KEYS)))
        assert store.get_index("nope") == -1
        assert store.get_feature_name(-1) is None
        assert store.get_feature_name(store.size) is None
        assert store.intercept_index == store.get_index(INTERCEPT_KEY)
        assert INTERCEPT_KEY in store
        assert dict(store.items()) == {v: k for k, v in seen.items()}


def test_rebuild_removes_stale_partitions(tmp_path):
    """Rebuilding with fewer partitions must not leave old files the loader
    would silently mix in."""
    store_dir = str(tmp_path / "store")
    ist.build_partitioned_store(store_dir, KEYS, num_partitions=4)
    ist.build_partitioned_store(store_dir, KEYS, num_partitions=2)
    assert not os.path.exists(os.path.join(store_dir, ist.partition_filename(2)))
    assert not os.path.exists(os.path.join(store_dir, ist.partition_filename(3)))
    with ist.PartitionedIndexStore(store_dir) as store:
        assert store.num_partitions == 2
        assert store.size == len(KEYS)
        assert all(store.get_index(k) >= 0 for k in KEYS)


def test_metadata_partition_count_mismatch(tmp_path):
    """A deleted partition file must fail loudly, not truncate the store."""
    import json as _json

    store_dir = str(tmp_path / "store")
    ist.build_partitioned_store(store_dir, KEYS, num_partitions=3)
    with open(os.path.join(store_dir, "_index_metadata.json"), "w") as f:
        _json.dump({"num_partitions": 3}, f)
    os.remove(os.path.join(store_dir, ist.partition_filename(2)))
    with pytest.raises(OSError, match="metadata"):
        ist.PartitionedIndexStore(store_dir)


def test_corrupt_partition_rejected(tmp_path):
    """Truncated / zero-slot files must be refused by both engines, not
    crash the process."""
    path = str(tmp_path / "bad.bin")
    ist.build_partition(path, KEYS[:10])
    blob = bytearray(open(path, "rb").read())
    # zero out num_slots
    blob[16:24] = b"\x00" * 8
    open(path, "wb").write(bytes(blob))
    for force in ENGINES:
        with pytest.raises(OSError):
            ist.open_partition(path, force_python=force)
    # truncated file
    ist.build_partition(path, KEYS[:10])
    data = open(path, "rb").read()
    open(path, "wb").write(data[:-4])
    for force in ENGINES:
        with pytest.raises(OSError):
            ist.open_partition(path, force_python=force)


def test_name_and_term_rejects_delimiters(tmp_path):
    from photon_ml_tpu.cli.name_and_term import write_name_and_term_file

    with pytest.raises(ValueError, match="tab/newline"):
        write_name_and_term_file(str(tmp_path / "f"), {("a\tb", "t")})
    with pytest.raises(ValueError, match="tab/newline"):
        write_name_and_term_file(str(tmp_path / "f"), {("a", "t\nx")})


def test_partition_routing_matches_hash(tmp_path):
    """Keys must live in the partition fnv1a64(key) % P selects."""
    store_dir = str(tmp_path / "store")
    ist.build_partitioned_store(store_dir, KEYS, num_partitions=3)
    for key in KEYS:
        p = ist.partition_for_key(key, 3)
        part = ist.open_partition(
            os.path.join(store_dir, ist.partition_filename(p))
        )
        assert part.get(key.encode()) >= 0
        part.close()


def _write_sample_data(path, n=40):
    feats = []
    for i in range(n):
        row = [(feature_key("age"), float(i)), (feature_key(f"genre", f"g{i % 5}"), 1.0)]
        if i % 2:
            row.append((feature_key("songs", f"s{i % 7}"), 2.0))
        feats.append(row)
    write_training_examples(path, feats, [float(i % 2) for i in range(n)])


def test_name_and_term_driver(tmp_path):
    data = str(tmp_path / "data.avro")
    _write_sample_data(data)
    out = str(tmp_path / "nat")
    assert (
        name_and_term.main(
            [
                "--input-data-directories",
                data,
                "--feature-bags-keys",
                "features",
                "--output-dir",
                out,
            ]
        )
        == 0
    )
    pairs = name_and_term.read_name_and_term_file(os.path.join(out, "features"))
    assert ("age", "") in pairs
    assert ("genre", "g0") in pairs
    assert len(pairs) == len(set(pairs))


def test_build_index_driver_from_raw_data(tmp_path):
    data = str(tmp_path / "data.avro")
    _write_sample_data(data)
    out = str(tmp_path / "index")
    assert (
        build_index.main(
            [
                "--input-data-directories",
                data,
                "--feature-shard-configurations",
                "name=globalShard,feature.bags=features",
                "--num-partitions",
                "2",
                "--output-dir",
                out,
            ]
        )
        == 0
    )
    meta = json.load(open(os.path.join(out, build_index.METADATA_FILE)))
    assert meta["num_partitions"] == 2
    with ist.PartitionedIndexStore(out, "globalShard") as store:
        assert store.get_index(feature_key("age")) >= 0
        assert store.get_index(feature_key("genre", "g3")) >= 0
        assert store.intercept_index is not None
        assert store.size == meta["shards"]["globalShard"]["num_features"]


def test_build_index_driver_from_name_and_term(tmp_path):
    data = str(tmp_path / "data.avro")
    _write_sample_data(data)
    nat = str(tmp_path / "nat")
    name_and_term.main(
        [
            "--input-data-directories",
            data,
            "--feature-bags-keys",
            "features",
            "--output-dir",
            nat,
        ]
    )
    out = str(tmp_path / "index")
    assert (
        build_index.main(
            [
                "--name-and-term-directory",
                nat,
                "--feature-shard-configurations",
                "name=globalShard,feature.bags=features,intercept=false",
                "--num-partitions",
                "1",
                "--output-dir",
                out,
            ]
        )
        == 0
    )
    with ist.PartitionedIndexStore(out, "globalShard") as store:
        assert store.get_index(feature_key("genre", "g1")) >= 0
        assert store.intercept_index is None


def test_train_with_offheap_index(tmp_path):
    """Training against a prebuilt off-heap index dir reaches the same model
    quality as in-memory maps (GameDriver.prepareFeatureMaps parity)."""
    from photon_ml_tpu.cli import train as train_cli
    from tests.test_cli import _write_glmix_avro

    train_avro = str(tmp_path / "train.avro")
    _write_glmix_avro(train_avro, 0, 300)
    idx_dir = str(tmp_path / "index")
    build_index.main(
        [
            "--input-data-directories",
            train_avro,
            "--feature-shard-configurations",
            "name=globalShard,feature.bags=features,intercept=true",
            "--num-partitions",
            "2",
            "--output-dir",
            idx_dir,
        ]
    )
    out = str(tmp_path / "out")
    train_cli.main(
        [
            "--training-task",
            "LOGISTIC_REGRESSION",
            "--input-data-directories",
            train_avro,
            "--validation-data-directories",
            train_avro,
            "--root-output-directory",
            out,
            "--offheap-indexmap-dir",
            idx_dir,
            "--feature-shard-configurations",
            "name=globalShard,feature.bags=features,intercept=true",
            "--coordinate-configurations",
            "name=global,feature.shard=globalShard,max.iter=30,"
            "regularization=L2,reg.weights=1",
            "--validation-evaluators",
            "AUC",
        ]
    )
    summary = json.load(open(os.path.join(out, "training-summary.json")))
    assert summary["best_evaluation"]["AUC"] > 0.6
    # The exported per-shard JSON map must agree with the off-heap store.
    exported = json.load(
        open(os.path.join(out, "models", "best", "feature-indexes", "globalShard.json"))
    )
    with ist.PartitionedIndexStore(idx_dir, "globalShard") as store:
        assert exported == dict(store.items())

"""CLI tests: config DSL round-trips, sweep expansion, end-to-end
train -> score drivers (reference: ScoptParserHelpers / GameTrainingDriver /
GameScoringDriver behavior)."""

import json
import os

import numpy as np
import pytest

from photon_ml_tpu.cli import score as score_cli
from photon_ml_tpu.cli import train as train_cli
from photon_ml_tpu.cli.config import (
    coordinate_config_to_string,
    expand_game_opt_configs,
    parse_coordinate_config,
    parse_feature_shard_config,
)
from photon_ml_tpu.data.game_dataset import RandomEffectDataConfig
from photon_ml_tpu.io.avro_data import write_training_examples
from photon_ml_tpu.types import OptimizerType, ProjectorType, RegularizationType


class TestConfigDSL:
    def test_feature_shard_parse(self):
        name, cfg = parse_feature_shard_config(
            "name=globalShard,feature.bags=features|context,intercept=true"
        )
        assert name == "globalShard"
        assert cfg.feature_bags == ("features", "context")
        assert cfg.has_intercept

    def test_feature_shard_defaults_and_errors(self):
        name, cfg = parse_feature_shard_config("name=s")
        assert cfg.feature_bags == ("features",) and cfg.has_intercept
        with pytest.raises(ValueError):
            parse_feature_shard_config("feature.bags=f1")
        with pytest.raises(ValueError):
            parse_feature_shard_config("name=s,bogus.key=1")

    def test_coordinate_parse_readme_example(self):
        # The README.md:283-292 example string parses verbatim.
        cfg = parse_coordinate_config(
            "name=global,feature.shard=globalShard,min.partitions=4,"
            "optimizer=LBFGS,tolerance=1.0E-6,max.iter=50,"
            "regularization=L2,reg.weights=0.1|1|10|100"
        )
        assert cfg.name == "global"
        assert cfg.data_config.feature_shard == "globalShard"
        assert cfg.opt_config.optimizer.optimizer_type == OptimizerType.LBFGS
        assert cfg.opt_config.optimizer.tolerance == 1e-6
        assert cfg.opt_config.optimizer.max_iterations == 50
        assert cfg.opt_config.regularization.reg_type == RegularizationType.L2
        assert set(cfg.reg_weights) == {0.1, 1.0, 10.0, 100.0}
        # Descending expansion (CoordinateConfiguration.scala:71-77).
        assert [c.reg_weight for c in cfg.expand()] == [100.0, 10.0, 1.0, 0.1]

    def test_random_effect_coordinate_parse(self):
        cfg = parse_coordinate_config(
            "name=per-member,random.effect.type=memberId,feature.shard=memberShard,"
            "active.data.lower.bound=2,active.data.upper.bound=100,"
            "optimizer=TRON,regularization=L2,reg.weights=1,projector=RANDOM,"
            "projected.dim=16,min.bucket=4"
        )
        dc = cfg.data_config
        assert isinstance(dc, RandomEffectDataConfig)
        assert dc.random_effect_type == "memberId"
        assert dc.active_lower_bound == 2 and dc.active_upper_bound == 100
        assert dc.projector_type == ProjectorType.RANDOM and dc.projected_dim == 16
        assert dc.min_bucket == 4

    def test_round_trip(self):
        for s in [
            "name=global,feature.shard=g,optimizer=OWLQN,tolerance=0.001,"
            "max.iter=20,regularization=L1,reg.weights=0.5|2.0",
            "name=re,random.effect.type=uid,feature.shard=s,optimizer=LBFGS,"
            "tolerance=1e-07,max.iter=100,regularization=NONE",
        ]:
            cfg = parse_coordinate_config(s)
            printed = coordinate_config_to_string(cfg)
            cfg2 = parse_coordinate_config(printed)
            assert cfg2.name == cfg.name
            assert cfg2.reg_weights == cfg.reg_weights
            assert cfg2.opt_config == cfg.opt_config
            assert cfg2.data_config == cfg.data_config

    def test_expand_cross_product(self):
        a = parse_coordinate_config(
            "name=a,feature.shard=s,regularization=L2,reg.weights=1|10"
        )
        b = parse_coordinate_config(
            "name=b,feature.shard=s,regularization=L2,reg.weights=0.5"
        )
        combos = expand_game_opt_configs({"a": a, "b": b})
        assert len(combos) == 2
        assert [c["a"].reg_weight for c in combos] == [10.0, 1.0]
        assert all(c["b"].reg_weight == 0.5 for c in combos)

    def test_errors(self):
        with pytest.raises(ValueError):
            parse_coordinate_config("feature.shard=s")  # no name
        with pytest.raises(ValueError):
            parse_coordinate_config("name=a,feature.shard=s,regularization=L2")
        with pytest.raises(ValueError):
            parse_coordinate_config("name=a,feature.shard=s,nope=1")


def _write_glmix_avro(path, seed, n, n_entities=8):
    rng = np.random.default_rng(seed)
    w_true = np.random.default_rng(99).normal(size=4)
    b_true = np.random.default_rng(98).normal(size=(20, 2))
    X = rng.normal(size=(n, 4))
    entity = rng.integers(0, n_entities, size=n)
    margins = X @ w_true + np.einsum("nd,nd->n", X[:, :2], b_true[entity])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margins))).astype(np.float32)
    feats = [
        [(f"f{j}", float(X[i, j])) for j in range(4)] for i in range(n)
    ]
    write_training_examples(
        path,
        feats,
        y.tolist(),
        uids=[f"uid{i}" for i in range(n)],
        id_tags={"memberId": [f"m{e}" for e in entity]},
    )


class TestDriversEndToEnd:
    def test_train_then_score(self, tmp_path, monkeypatch):
        train_avro = str(tmp_path / "train.avro")
        val_avro = str(tmp_path / "val.avro")
        _write_glmix_avro(train_avro, 0, 400)
        _write_glmix_avro(val_avro, 1, 200)
        out = str(tmp_path / "out")

        train_cli.main([
            "--training-task", "LOGISTIC_REGRESSION",
            "--input-data-directories", train_avro,
            "--validation-data-directories", val_avro,
            "--root-output-directory", out,
            "--feature-shard-configurations",
            "name=globalShard,feature.bags=features,intercept=true",
            "--coordinate-configurations",
            "name=global,feature.shard=globalShard,optimizer=LBFGS,"
            "tolerance=1e-7,max.iter=40,regularization=L2,reg.weights=0.1|10",
            "name=per-member,random.effect.type=memberId,feature.shard=globalShard,"
            "optimizer=LBFGS,max.iter=30,regularization=L2,reg.weights=1,min.bucket=4",
            "--validation-evaluators", "AUC",
            "--output-mode", "ALL",
            "--data-summary-directory", str(tmp_path / "summary"),
        ])

        # Feature-shard summary Avro (writeBasicStatistics hook,
        # GameTrainingDriver.scala:582).
        from photon_ml_tpu.io import avro as avro_io
        _, srecs = avro_io.read_container(
            str(tmp_path / "summary" / "globalShard" / "part-00000.avro")
        )
        assert {r["featureName"] for r in srecs} == {"f0", "f1", "f2", "f3"}
        assert set(srecs[0]["metrics"]) == {
            "max", "min", "mean", "normL1", "normL2", "numNonzeros", "variance"
        }

        # Model layout (ModelProcessingUtils.scala:77-141).
        best = os.path.join(out, "models", "best")
        assert os.path.isfile(os.path.join(best, "model-metadata.json"))
        assert os.path.isdir(os.path.join(best, "fixed-effect", "global"))
        assert os.path.isdir(os.path.join(best, "random-effect", "per-member"))
        assert os.path.isdir(os.path.join(out, "models", "explicit-1"))
        summary = json.load(open(os.path.join(out, "training-summary.json")))
        assert summary["num_explicit"] == 2
        assert summary["best_evaluation"]["AUC"] > 0.6
        # Job log file (PhotonLogger) written under the output root.
        job_log = open(os.path.join(out, "photon-ml-tpu.log")).read()
        assert "training 2 explicit configuration(s)" in job_log
        assert "read data" in job_log  # Timed sections

        # Score with the trained model.
        score_out = str(tmp_path / "scores")
        score_cli.main([
            "--input-data-directories", val_avro,
            "--model-input-directory", best,
            "--root-output-directory", score_out,
            "--feature-shard-configurations",
            "name=globalShard,feature.bags=features,intercept=true",
            "--evaluators", "AUC",
        ])
        ssum = json.load(open(os.path.join(score_out, "scoring-summary.json")))
        assert ssum["num_scored"] == 200
        # Scoring-side AUC must match the training driver's validation AUC
        # (same model, same data, original-space scoring path).
        assert abs(ssum["evaluation"]["AUC"] - summary["best_evaluation"]["AUC"]) < 5e-3

        from photon_ml_tpu.io.score_store import load_scores
        items = load_scores(os.path.join(score_out, "scores"))
        assert len(items) == 200 and items[0].uid.startswith("uid")

        # Replay the same records through the ONLINE serving driver: same
        # model, same feature DSL — per-uid scores must agree with the
        # offline driver (approx, not bitwise: offline ingest scores the
        # ELL sparse layout, the engine densifies request rows, so the
        # per-row reduction ranges differ).
        from photon_ml_tpu.cli import serve as serve_cli
        serve_out = str(tmp_path / "served")
        # Small replay windows force the MULTI-window path, so the
        # --reshard-to drill below runs on its background worker WHILE
        # later windows stream — the generation flips mid-replay and the
        # lazily-encoding request iterator must keep working across it
        # (the retired bundle handle stays a live view of the new
        # generation).
        monkeypatch.setattr(serve_cli, "REPLAY_WINDOW", 32)
        serve_cli.main([
            "--model-input-directory", best,
            "--requests", val_avro,
            "--root-output-directory", serve_out,
            "--feature-shard-configurations",
            "name=globalShard,feature.bags=features,intercept=true",
            "--max-batch", "32",
            "--max-wait-ms", "1",
            "--reshard-to", "4",  # live elasticity drill mid-replay
        ])
        served = {
            it.uid: it.prediction_score
            for it in load_scores(os.path.join(serve_out, "scores"))
        }
        offline = {it.uid: it.prediction_score for it in items}
        assert set(served) == set(offline)
        for uid, s in served.items():
            assert s == pytest.approx(offline[uid], rel=1e-4, abs=1e-5)
        ssummary = json.load(
            open(os.path.join(serve_out, "serving-summary.json"))
        )
        assert ssummary["num_requests"] == 200
        m = ssummary["serving"]
        assert m["completed"] == 200
        assert m["recompiles_after_warmup"] == 0
        assert m["degraded_batches"] == 0
        # Validation entities were all seen at training time: no cold starts.
        assert m["cold_start_fraction"] == 0.0
        # The --reshard-to drill committed (replicated -> 4 entity shards)
        # with zero failed requests — every per-uid score above already
        # matched the offline driver across the generation flip.
        assert ssummary["reshard"]["committed"] is True
        assert ssummary["reshard"]["new_shards"] == 4
        assert ssummary["failed_requests"] == 0
        # The whole stream was encoded and scored ACROSS the flip — no
        # record silently dropped as malformed by a gutted encoder handle.
        assert ssummary["malformed_records"] == 0

        # JSON-lines replay: named features resolved through the model's
        # index maps.
        jsonl = str(tmp_path / "requests.jsonl")
        with open(jsonl, "w") as f:
            f.write(json.dumps({
                "uid": "j0",
                "ids": {"memberId": "m1"},
                "features": {"globalShard": {"f0": 1.0, "(INTERCEPT)": 1.0}},
            }) + "\n")
            f.write(json.dumps({
                "uid": "j1",
                "ids": {"memberId": "never-seen"},
                "features": {"globalShard": {"f1": -1.0, "(INTERCEPT)": 1.0}},
            }) + "\n")
        serve_out2 = str(tmp_path / "served-jsonl")
        serve_cli.main([
            "--model-input-directory", best,
            "--requests", jsonl,
            "--root-output-directory", serve_out2,
            "--max-batch", "4",
        ])
        jm = json.load(open(os.path.join(serve_out2, "serving-summary.json")))
        assert jm["num_requests"] == 2
        assert jm["serving"]["cold_start_lookups"] == 1
        # Unplanned replays always carry an INACTIVE plan block (the
        # SERVING_SUMMARY_KEYS contract: absence must be loud, "planner
        # off" must be explicit).
        assert jm["plan"]["active"] is False

        # Planned replay (ISSUE 14): the first replay's persisted serve
        # profile plans this one — bucket ceiling and micro-batch wait
        # resolve from the plan, the summary's plan block is active and
        # carries the full decision audit, and every summary contract
        # key is present.
        from photon_ml_tpu.utils.contracts import (
            PLAN_BLOCK_KEYS,
            SERVING_SUMMARY_KEYS,
        )

        serve_out3 = str(tmp_path / "served-planned")
        serve_cli.main([
            "--model-input-directory", best,
            "--requests", jsonl,
            "--root-output-directory", serve_out3,
            "--profile", os.path.join(serve_out, "profile.json"),
        ])
        pm = json.load(open(os.path.join(serve_out3, "serving-summary.json")))
        missing = [k for k in SERVING_SUMMARY_KEYS if k not in pm]
        assert not missing, missing
        block = pm["plan"]
        assert tuple(block) == PLAN_BLOCK_KEYS
        assert block["active"] is True
        assert block["source"] == "profile"
        assert {d["decision"] for d in block["decisions"]} == {
            "serving_max_batch",
            "serving_max_wait_ms",
        }
        assert pm["failed_requests"] == 0 and pm["num_requests"] == 2
        # The planned run's own profile re-reads loudly WITH its block.
        from photon_ml_tpu.utils import telemetry as _tel

        back = _tel.read_profile(
            os.path.join(serve_out3, "profile.json"), kind="serve"
        )
        assert back["plan"] == block

        # Multi-tenant replay (ISSUE 15): the same model serves as two
        # named tenants on one fleet through the TenantRegistry; replay
        # records assign round-robin, scores land per tenant, and the
        # summary carries one TENANT_BLOCK_KEYS dict per tenant.
        from photon_ml_tpu.utils.contracts import TENANT_BLOCK_KEYS

        serve_out4 = str(tmp_path / "served-tenants")
        serve_cli.main([
            "--tenant", f"alpha={best}",
            "--tenant", f"beta={best}",
            "--requests", jsonl,
            "--root-output-directory", serve_out4,
            "--max-batch", "4",
        ])
        tm = json.load(open(os.path.join(serve_out4, "serving-summary.json")))
        missing_t = [k for k in SERVING_SUMMARY_KEYS if k not in tm]
        assert not missing_t, missing_t
        assert tm["num_requests"] == 2 and tm["failed_requests"] == 0
        assert set(tm["tenants"]) == {"alpha", "beta"}
        for name, tblock in tm["tenants"].items():
            assert set(tblock) == set(TENANT_BLOCK_KEYS), name
            assert tblock["completed"] == 1 and tblock["failed"] == 0
        # Round-robin wrote each tenant's scores under its own subdir.
        alpha_scores = load_scores(
            os.path.join(serve_out4, "scores", "alpha")
        )
        beta_scores = load_scores(os.path.join(serve_out4, "scores", "beta"))
        assert {it.uid for it in alpha_scores} == {"j0"}
        assert {it.uid for it in beta_scores} == {"j1"}
        # Same model, same records: the tenant-path scores agree with the
        # single-tenant replay of the same stream bitwise.
        single = {
            it.uid: it.prediction_score
            for it in load_scores(os.path.join(serve_out2, "scores"))
        }
        for it in list(alpha_scores) + list(beta_scores):
            assert it.prediction_score == single[it.uid]

    def test_warm_start_and_partial_retrain(self, tmp_path):
        train_avro = str(tmp_path / "train.avro")
        _write_glmix_avro(train_avro, 0, 300)
        out1 = str(tmp_path / "out1")
        common = [
            "--training-task", "LOGISTIC_REGRESSION",
            "--input-data-directories", train_avro,
            "--feature-shard-configurations",
            "name=globalShard,feature.bags=features,intercept=true",
        ]
        train_cli.main(common + [
            "--root-output-directory", out1,
            "--coordinate-configurations",
            "name=global,feature.shard=globalShard,max.iter=30,"
            "regularization=L2,reg.weights=1",
            "name=per-member,random.effect.type=memberId,feature.shard=globalShard,"
            "max.iter=20,regularization=L2,reg.weights=1,min.bucket=4",
        ])
        # Partial retrain: lock the fixed effect, retrain only the RE.
        out2 = str(tmp_path / "out2")
        train_cli.main(common + [
            "--root-output-directory", out2,
            "--model-input-directory", os.path.join(out1, "models", "best"),
            "--partial-retrain-locked-coordinates", "global",
            "--coordinate-configurations",
            "name=global,feature.shard=globalShard,max.iter=30,"
            "regularization=L2,reg.weights=1",
            "name=per-member,random.effect.type=memberId,feature.shard=globalShard,"
            "max.iter=20,regularization=L2,reg.weights=0.1,min.bucket=4",
        ])
        assert os.path.isdir(os.path.join(out2, "models", "best", "fixed-effect"))


class TestValidators:
    def test_validation_catches_bad_rows(self, tmp_path):
        import jax.numpy as jnp

        from photon_ml_tpu.data.game_dataset import GameDataset
        from photon_ml_tpu.data.validators import (
            DataValidationError,
            validate_game_dataset,
        )
        from photon_ml_tpu.types import DataValidationType, TaskType

        ds = GameDataset.build(
            {"s": jnp.asarray([[1.0], [np.nan]])},
            [1.0, 3.0],
            weights=[1.0, -1.0],
        )
        with pytest.raises(DataValidationError) as exc:
            validate_game_dataset(ds, TaskType.LOGISTIC_REGRESSION, DataValidationType.VALIDATE_FULL)
        names = [f[0] for f in exc.value.failures]
        assert "positive weight" in names
        assert "binary label" in names
        assert any("finite features" in n for n in names)
        # Disabled mode never raises.
        validate_game_dataset(ds, TaskType.LOGISTIC_REGRESSION, DataValidationType.VALIDATE_DISABLED)


def test_features_to_samples_ratio_dsl_roundtrip():
    from photon_ml_tpu.cli.config import (
        coordinate_config_to_string,
        parse_coordinate_config,
    )

    cfg = parse_coordinate_config(
        "name=per-user,random.effect.type=userId,feature.shard=s,"
        "features.to.samples.ratio=0.5,optimizer=LBFGS,reg.weights=1"
    )
    assert cfg.data_config.num_features_to_samples_ratio_upper_bound == 0.5
    rendered = coordinate_config_to_string(cfg)
    assert "features.to.samples.ratio=0.5" in rendered
    assert (
        parse_coordinate_config(rendered).data_config.num_features_to_samples_ratio_upper_bound
        == 0.5
    )


class TestDateRangeAndMultiDirInput:
    def test_train_on_daily_dirs_and_multiple_inputs(self, tmp_path):
        """N input directories + date-range expansion feed one training run
        (GameDriver.pathsForDateRange:248; AvroDataReader.readMerged paths)."""
        # Daily layout: base/2016/01/{01,02}/part.avro + a second plain dir.
        base = tmp_path / "daily"
        d1 = base / "2016" / "01" / "01"
        d2 = base / "2016" / "01" / "02"
        d1.mkdir(parents=True)
        d2.mkdir(parents=True)
        extra = tmp_path / "extra"
        extra.mkdir()
        _write_glmix_avro(str(d1 / "part-00000.avro"), 0, 150)
        _write_glmix_avro(str(d2 / "part-00000.avro"), 1, 150)
        _write_glmix_avro(str(extra / "part-00000.avro"), 2, 100)
        out = str(tmp_path / "out")

        # Date-ranged read of the daily tree only.
        train_cli.main([
            "--training-task", "LOGISTIC_REGRESSION",
            "--input-data-directories", str(base),
            "--input-data-date-range", "20160101-20160131",
            "--root-output-directory", out,
            "--feature-shard-configurations",
            "name=globalShard,feature.bags=features,intercept=true",
            "--coordinate-configurations",
            "name=global,feature.shard=globalShard,optimizer=LBFGS,"
            "tolerance=1e-7,max.iter=20,regularization=L2,reg.weights=1",
        ])
        summary = json.load(open(os.path.join(out, "training-summary.json")))
        assert summary["num_samples"] == 300  # both daily dirs, not extra

        # Multiple plain input directories concatenate.
        out2 = str(tmp_path / "out2")
        train_cli.main([
            "--training-task", "LOGISTIC_REGRESSION",
            "--input-data-directories", str(d1), str(extra),
            "--root-output-directory", out2,
            "--feature-shard-configurations",
            "name=globalShard,feature.bags=features,intercept=true",
            "--coordinate-configurations",
            "name=global,feature.shard=globalShard,optimizer=LBFGS,"
            "tolerance=1e-7,max.iter=20,regularization=L2,reg.weights=1",
        ])
        summary2 = json.load(open(os.path.join(out2, "training-summary.json")))
        assert summary2["num_samples"] == 250

        # Scoring accepts multiple dirs + ranges too (cli/score.py).
        score_out = str(tmp_path / "scores")
        score_cli.main([
            "--input-data-directories", str(base),
            "--input-data-date-range", "20160101-20160102",
            "--model-input-directory", os.path.join(out, "models", "best"),
            "--root-output-directory", score_out,
            "--feature-shard-configurations",
            "name=globalShard,feature.bags=features,intercept=true",
        ])
        ssum = json.load(open(os.path.join(score_out, "scoring-summary.json")))
        assert ssum["num_scored"] == 300


class TestHyperparameterTuningCLI:
    def test_bayesian_tuning_end_to_end(self, tmp_path):
        """--hyper-parameter-tuning BAYESIAN runs GP trials after the
        explicit sweep, writes tuned-<i> model dirs, and the selected best
        model comes from the union (GameTrainingDriver.runHyperparameterTuning
        -> AtlasTuner -> GaussianProcessSearch)."""
        train_avro = str(tmp_path / "train.avro")
        val_avro = str(tmp_path / "val.avro")
        _write_glmix_avro(train_avro, 0, 300)
        _write_glmix_avro(val_avro, 1, 150)
        out = str(tmp_path / "out")

        train_cli.main([
            "--training-task", "LOGISTIC_REGRESSION",
            "--input-data-directories", train_avro,
            "--validation-data-directories", val_avro,
            "--root-output-directory", out,
            "--feature-shard-configurations",
            "name=globalShard,feature.bags=features,intercept=true",
            "--coordinate-configurations",
            "name=global,feature.shard=globalShard,optimizer=LBFGS,"
            "tolerance=1e-7,max.iter=25,regularization=L2,reg.weights=1",
            "--validation-evaluators", "AUC",
            "--hyper-parameter-tuning", "BAYESIAN",
            "--hyper-parameter-tuning-iter", "4",
            "--output-mode", "ALL",
        ])
        summary = json.load(open(os.path.join(out, "training-summary.json")))
        assert summary["num_tuned"] == 4
        # Tuned model dirs persisted alongside explicit ones.
        for i in range(4):
            assert os.path.isfile(
                os.path.join(out, "models", f"tuned-{i}", "model-metadata.json")
            )
        assert summary["best_evaluation"]["AUC"] > 0.6
        # Each trial carries its own sampled reg weight in the metadata.
        weights = set()
        for i in range(4):
            meta = json.load(open(os.path.join(out, "models", f"tuned-{i}", "model-metadata.json")))
            weights.add(json.dumps(meta.get("optimizationConfigurations", {}), sort_keys=True))
        assert len(weights) > 1  # the search explored, not repeated, configs


class TestTuneDriver:
    def test_tune_end_to_end(self, tmp_path):
        """cli/tune.py: the pod-parallel sweep driver — batched Bayesian
        rounds through the stacked executor, winner model saved in the
        standard layout, tuning-summary written, and trial_start/
        trial_finish journal lines validating against their schemas."""
        from photon_ml_tpu.cli import tune as tune_cli
        from photon_ml_tpu.utils import telemetry

        train_avro = str(tmp_path / "train.avro")
        val_avro = str(tmp_path / "val.avro")
        _write_glmix_avro(train_avro, 0, 300)
        _write_glmix_avro(val_avro, 1, 150)
        out = str(tmp_path / "out")
        tune_cli.main([
            "--training-task", "LOGISTIC_REGRESSION",
            "--input-data-directories", train_avro,
            "--validation-data-directories", val_avro,
            "--root-output-directory", out,
            "--feature-shard-configurations",
            "name=globalShard,feature.bags=features,intercept=true",
            "--coordinate-configurations",
            "name=global,feature.shard=globalShard,optimizer=LBFGS,"
            "tolerance=1e-7,max.iter=15,regularization=L2,reg.weights=1",
            "name=per-member,random.effect.type=memberId,"
            "feature.shard=globalShard,optimizer=LBFGS,max.iter=10,"
            "regularization=L2,reg.weights=1,min.bucket=4",
            "--validation-evaluators", "AUC",
            "--tuning-iter", "4",
            "--tuning-batch-size", "2",
            "--logging-level", "WARNING",
        ])
        summary = json.load(open(os.path.join(out, "tuning-summary.json")))
        assert len(summary["trials"]) == 4 and summary["rounds"] == 2
        assert summary["modes"] == ["stacked"]
        assert summary["tuned_coordinates"] == ["global", "per-member"]
        assert np.isfinite(summary["winner_value"])
        assert len(summary["best_point"]) == 2
        # Winner model in the standard layout, loadable with its indexes.
        best = os.path.join(out, "models", "tuned-best")
        assert os.path.isfile(os.path.join(best, "model-metadata.json"))
        assert os.path.isdir(os.path.join(best, "fixed-effect", "global"))
        assert os.path.isdir(os.path.join(best, "random-effect", "per-member"))
        assert os.path.isfile(
            os.path.join(best, "feature-indexes", "globalShard.json")
        )
        meta = json.load(open(os.path.join(best, "model-metadata.json")))
        tuned_rw = meta["optimizationConfigurations"]["global"]["reg_weight"]
        assert tuned_rw == summary["best_point"][0]
        # Journal: every line valid, one start + one finish per trial.
        n_ok, errors = telemetry.validate_journal(
            os.path.join(out, "journal.jsonl")
        )
        assert errors == [] and n_ok == 8

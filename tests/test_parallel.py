"""Multi-device tests on the 8-device virtual CPU mesh.

Counterpart of the reference's Spark local-cluster integ tests
(SparkTestUtils.scala): the sharded code paths (GSPMD-partitioned optimizer
loops, entity-sharded vmapped solves, cross-shard residual gathers) run for
real with 8 devices, and must agree numerically with single-device runs.
"""

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from photon_ml_tpu.data.game_dataset import (
    GameDataset,
    RandomEffectDataConfig,
    build_random_effect_dataset,
)
from photon_ml_tpu.game.coordinate import FixedEffectCoordinate, RandomEffectCoordinate
from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent
from photon_ml_tpu.optimize.config import L2, CoordinateOptimizationConfig, OptimizerConfig
from photon_ml_tpu.parallel.mesh import (
    make_mesh,
    pad_game_dataset,
    shard_game_dataset,
    shard_random_effect_dataset,
)
from photon_ml_tpu.types import TaskType


def _dataset(rng, n=203, d=5, n_entities=11, d_re=3):
    Xf = rng.normal(size=(n, d)).astype(np.float32)
    Xf[:, -1] = 1.0
    Xe = rng.normal(size=(n, d_re)).astype(np.float32)
    entity = rng.integers(0, n_entities, size=n)
    w = rng.normal(size=d)
    u = rng.normal(size=(n_entities, d_re))
    m = Xf @ w + np.einsum("nd,nd->n", Xe, u[entity])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-m))).astype(np.float32)
    return GameDataset.build(
        {"global": jnp.asarray(Xf), "per_entity": jnp.asarray(Xe)},
        y,
        id_tags={"entityId": entity},
    )


def _cfg(w=0.1):
    return CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=50, tolerance=1e-7),
        regularization=L2,
        reg_weight=w,
    )


def test_mesh_has_8_devices():
    mesh = make_mesh()
    assert mesh.devices.size == 8


def test_pad_dataset_row_count_and_inertness(rng):
    ds = _dataset(rng, n=203)
    padded = pad_game_dataset(ds, 8)
    assert padded.num_samples == 208
    assert float(padded.weights[203:].sum()) == 0.0
    np.testing.assert_array_equal(np.asarray(padded.labels[:203]), np.asarray(ds.labels))


def test_sharded_fixed_effect_matches_single_device(rng):
    ds = _dataset(rng)
    mesh = make_mesh()
    sharded = shard_game_dataset(ds, mesh)

    single = FixedEffectCoordinate(ds, "global", _cfg(), TaskType.LOGISTIC_REGRESSION)
    multi = FixedEffectCoordinate(sharded, "global", _cfg(), TaskType.LOGISTIC_REGRESSION)

    m1, r1 = single.train(ds.offsets)
    m2, r2 = multi.train(sharded.offsets)
    # f32 reduction order differs across shards; parity is to ~1e-4 absolute.
    np.testing.assert_allclose(
        m1.coefficients.means, m2.coefficients.means, rtol=5e-3, atol=2e-4
    )
    # The sharded input really is distributed over 8 devices.
    assert len(sharded.labels.sharding.device_set) == 8


class TestRingCollectives:
    """ring_gather_rows / ring_scatter_rows: exact row movement over the mesh
    (no arithmetic), so results must be bit-identical to local indexing."""

    def test_ring_gather_matches_local_gather(self, rng):
        from photon_ml_tpu.parallel.mesh import (
            batch_sharding,
            make_mesh,
            matrix_row_sharding,
            ring_gather_rows,
        )

        mesh = make_mesh()
        ndev = mesh.devices.size
        R, D, S = 4 * ndev, 6, 5 * ndev
        M = jnp.asarray(rng.normal(size=(R, D)).astype(np.float32))
        rows = jnp.asarray(rng.integers(0, R, size=S).astype(np.int32))
        Ms = jax.device_put(M, matrix_row_sharding(mesh))
        rows_s = jax.device_put(rows, batch_sharding(mesh, 1))
        got = np.asarray(ring_gather_rows(Ms, rows_s, mesh))
        assert np.array_equal(got, np.asarray(M)[np.asarray(rows)])

    def test_ring_gather_2d_rows(self, rng):
        from photon_ml_tpu.parallel.mesh import (
            batch_sharding,
            make_mesh,
            matrix_row_sharding,
            ring_gather_rows,
        )

        mesh = make_mesh()
        ndev = mesh.devices.size
        R, D = 2 * ndev, 4
        M = jnp.asarray(rng.normal(size=(R, D)).astype(np.float32))
        rows = jnp.asarray(rng.integers(0, R, size=(2 * ndev, 3)).astype(np.int32))
        got = np.asarray(
            ring_gather_rows(
                jax.device_put(M, matrix_row_sharding(mesh)),
                jax.device_put(rows, batch_sharding(mesh, 2)),
                mesh,
            )
        )
        assert np.array_equal(got, np.asarray(M)[np.asarray(rows)])

    def test_ring_scatter_matches_local_set(self, rng):
        from photon_ml_tpu.parallel.mesh import (
            batch_sharding,
            make_mesh,
            matrix_row_sharding,
            ring_scatter_rows,
        )

        mesh = make_mesh()
        ndev = mesh.devices.size
        R, D, S = 4 * ndev, 6, 2 * ndev
        M = jnp.asarray(rng.normal(size=(R, D)).astype(np.float32))
        # unique target rows (the coordinate's contract within a bucket)
        rows = jnp.asarray(
            rng.choice(R, size=S, replace=False).astype(np.int32)
        )
        vals = jnp.asarray(rng.normal(size=(S, D)).astype(np.float32))
        got = np.asarray(
            ring_scatter_rows(
                jax.device_put(M, matrix_row_sharding(mesh)),
                jax.device_put(rows, batch_sharding(mesh, 1)),
                jax.device_put(vals, batch_sharding(mesh, 2)),
                mesh,
            )
        )
        want = np.asarray(M).copy()
        want[np.asarray(rows)] = np.asarray(vals)
        assert np.array_equal(got, want)

    def test_trained_re_matrix_is_row_sharded(self, rng):
        ds = _dataset(rng)
        mesh = make_mesh()
        padded = pad_game_dataset(ds, mesh.devices.size)
        sharded = shard_game_dataset(padded, mesh)
        red = shard_random_effect_dataset(
            build_random_effect_dataset(
                sharded, RandomEffectDataConfig("entityId", "per_entity")
            ),
            mesh,
        )
        rand = RandomEffectCoordinate(sharded, red, _cfg(1.0), TaskType.LOGISTIC_REGRESSION)
        assert rand._entity_mesh is not None
        model, _ = rand.train(sharded.offsets)
        m = model.coefficients_matrix
        shard_bytes = [s.data.nbytes for s in m.addressable_shards]
        assert len(shard_bytes) == mesh.devices.size
        assert max(shard_bytes) <= m.nbytes // mesh.devices.size
        # sharded scoring matches the replicated gather
        s_sharded = np.asarray(rand.score(model))
        from photon_ml_tpu.game.model import random_effect_margins

        s_repl = np.asarray(
            random_effect_margins(
                sharded.shards["per_entity"],
                red.sample_entity_rows,
                jax.device_put(m, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())),
                None,
            )
        )
        np.testing.assert_allclose(s_sharded, s_repl, rtol=1e-6, atol=1e-6)

    def test_sharded_margins_match_replicated_with_norm(self, rng):
        """Guards the deliberate duplication between random_effect_margins and
        its sharded twin: norm algebra must stay numerically identical."""
        from photon_ml_tpu.game.model import (
            random_effect_margins,
            random_effect_margins_sharded,
        )
        from photon_ml_tpu.ops.normalization import NormalizationContext
        from photon_ml_tpu.parallel.mesh import (
            batch_sharding,
            make_mesh,
            matrix_row_sharding,
        )

        mesh = make_mesh()
        ndev = mesh.devices.size
        R, D, N = 4 * ndev, 6, 3 * ndev + 1  # N deliberately not divisible
        M = jnp.asarray(rng.normal(size=(R, D)).astype(np.float32))
        rows = jnp.asarray(rng.integers(0, R, size=N).astype(np.int32))
        X = jnp.asarray(rng.normal(size=(N, D)).astype(np.float32))
        norm = NormalizationContext(
            factors=jnp.asarray(rng.uniform(0.5, 2.0, size=D).astype(np.float32)),
            shifts=jnp.asarray(rng.normal(size=D).astype(np.float32) * 0.1),
        )
        want = np.asarray(random_effect_margins(X, rows, M, norm))
        got = np.asarray(
            random_effect_margins_sharded(
                X, rows, jax.device_put(M, matrix_row_sharding(mesh)), norm, mesh
            )
        )
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_sharded_game_training_matches_single_device(rng):
    ds = _dataset(rng)
    cfg_re = RandomEffectDataConfig("entityId", "per_entity")

    # Single-device path.
    red_s = build_random_effect_dataset(ds, cfg_re)
    fixed_s = FixedEffectCoordinate(ds, "global", _cfg(), TaskType.LOGISTIC_REGRESSION)
    rand_s = RandomEffectCoordinate(ds, red_s, _cfg(1.0), TaskType.LOGISTIC_REGRESSION)
    res_s = run_coordinate_descent({"f": fixed_s, "r": rand_s}, 2)

    # Sharded path: pad + shard samples, shard entity blocks.
    mesh = make_mesh()
    padded = pad_game_dataset(ds, mesh.devices.size)
    sharded = shard_game_dataset(padded, mesh)
    red_m = shard_random_effect_dataset(build_random_effect_dataset(sharded, cfg_re), mesh)
    fixed_m = FixedEffectCoordinate(sharded, "global", _cfg(), TaskType.LOGISTIC_REGRESSION)
    rand_m = RandomEffectCoordinate(sharded, red_m, _cfg(1.0), TaskType.LOGISTIC_REGRESSION)
    res_m = run_coordinate_descent({"f": fixed_m, "r": rand_m}, 2)

    np.testing.assert_allclose(
        res_s.model["f"].coefficients.means,
        res_m.model["f"].coefficients.means,
        rtol=5e-3,
        atol=5e-4,
    )
    # Entity rows may be ordered differently only if id sets differ — they
    # don't here (same build logic); padded dataset adds one sentinel entity.
    W_s = np.asarray(res_s.model["r"].coefficients_matrix)
    W_m = np.asarray(res_m.model["r"].coefficients_matrix)
    for ent, row_s in red_s.entity_index.items():
        row_m = red_m.entity_index[ent]
        np.testing.assert_allclose(
            W_s[row_s], W_m[row_m], rtol=5e-3, atol=5e-4,
        )


def test_entity_blocks_sharded_over_devices(rng):
    ds = _dataset(rng)
    mesh = make_mesh()
    padded = pad_game_dataset(ds, mesh.devices.size)
    red = shard_random_effect_dataset(
        build_random_effect_dataset(padded, RandomEffectDataConfig("entityId", "per_entity")),
        mesh,
    )
    for b in red.buckets:
        assert b.gather.shape[0] % 8 == 0
        assert len(b.gather.sharding.device_set) == 8


class TestShardedFusedObjective:
    """The distributed fused Pallas objective: per-device kernel + psum
    (ValueAndGradientAggregator.scala:248-252 as one ICI all-reduce). The
    fused path must engage on batch-sharded data and match XLA numerics."""

    @pytest.fixture
    def interpret_kernels(self, monkeypatch):
        from photon_ml_tpu.ops import pallas_glm

        monkeypatch.setattr(pallas_glm, "FORCE_INTERPRET", True)
        monkeypatch.setattr(pallas_glm, "_HEALTHY", None)
        return pallas_glm

    @pytest.fixture
    def big_sharded(self, rng):
        # Sizes chosen to clear the per-device row threshold (2048) on 8 devs.
        from photon_ml_tpu.ops import pallas_glm

        n, d = 8 * pallas_glm._MIN_ROWS, 128
        Xf = rng.normal(size=(n, d)).astype(np.float32)
        Xf[:, -1] = 1.0
        w = rng.normal(size=d) * 0.2
        m = Xf @ w
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-m))).astype(np.float32)
        ds = GameDataset.build({"global": jnp.asarray(Xf)}, y)
        return shard_game_dataset(ds, make_mesh())

    def test_dispatch_returns_sharded_mode(self, interpret_kernels, big_sharded):
        pallas_glm = interpret_kernels
        feats = big_sharded.shards["global"]
        mode = pallas_glm.dispatch(
            feats, jnp.zeros((feats.shape[-1],), feats.dtype)
        )
        assert isinstance(mode, pallas_glm.ShardedDispatch)
        assert mode.mesh.devices.size == 8
        # Boolean view stays False for multi-device (it cannot carry a mesh).
        assert pallas_glm.should_use(feats, jnp.zeros((feats.shape[-1],))) is False

    def test_sharded_fused_sums_match_xla(self, interpret_kernels, big_sharded, rng):
        pallas_glm = interpret_kernels
        from photon_ml_tpu.ops.losses import LOGISTIC

        ds = big_sharded
        feats = ds.shards["global"]
        d = feats.shape[-1]
        w = jnp.asarray(rng.normal(size=d).astype(np.float32) * 0.1)
        mode = pallas_glm.dispatch(feats, w)
        val, g, sum_u = pallas_glm.sharded_value_gradient_sums(
            LOGISTIC, w, jnp.zeros(()), feats, ds.labels, ds.offsets,
            ds.weights, mesh=mode.mesh, axis=mode.axis, interpret=True,
        )
        X = np.asarray(feats)
        z = X @ np.asarray(w) + np.asarray(ds.offsets)
        u = np.asarray(ds.weights) * np.asarray(LOGISTIC.d1(jnp.asarray(z), ds.labels))
        val_ref = float(np.sum(np.asarray(ds.weights) * np.asarray(LOGISTIC.loss(jnp.asarray(z), ds.labels))))
        np.testing.assert_allclose(float(val), val_ref, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g), u @ X, rtol=1e-3, atol=1e-2)
        np.testing.assert_allclose(float(sum_u), float(u.sum()), rtol=1e-3, atol=1e-3)

        hv, sum_r = pallas_glm.sharded_hessian_vector_sums(
            LOGISTIC, w, jnp.zeros(()), w, jnp.zeros(()), feats, ds.labels,
            ds.offsets, ds.weights, mesh=mode.mesh, axis=mode.axis,
            interpret=True,
        )
        r = np.asarray(ds.weights) * np.asarray(LOGISTIC.d2(jnp.asarray(z), ds.labels)) * (X @ np.asarray(w))
        np.testing.assert_allclose(np.asarray(hv), r @ X, rtol=1e-3, atol=1e-2)

    def test_fixed_effect_trains_through_sharded_fused_path(
        self, interpret_kernels, big_sharded
    ):
        """End-to-end: FixedEffectCoordinate on batch-sharded data engages
        the sharded fused objective and lands on the XLA path's optimum."""
        pallas_glm = interpret_kernels
        ds = big_sharded
        cfg = CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(max_iterations=5, tolerance=1e-7),
            regularization=L2,
            reg_weight=1.0,
        )
        fused = FixedEffectCoordinate(ds, "global", cfg, TaskType.LOGISTIC_REGRESSION)
        assert isinstance(fused._use_pallas, pallas_glm.ShardedDispatch)
        m_fused, _ = fused.train(ds.offsets)

        pallas_glm.set_enabled(False)
        try:
            xla = FixedEffectCoordinate(ds, "global", cfg, TaskType.LOGISTIC_REGRESSION)
            assert xla._use_pallas is False
            m_xla, _ = xla.train(ds.offsets)
        finally:
            pallas_glm.set_enabled(True)
        np.testing.assert_allclose(
            np.asarray(m_fused.coefficients.means),
            np.asarray(m_xla.coefficients.means),
            rtol=5e-3,
            atol=5e-4,
        )


@pytest.mark.slow
@pytest.mark.multihost
def test_multihost_two_process_dryrun():
    """TWO OS PROCESSES form a jax.distributed cluster (coordinator +
    worker) and train a sample-sharded GLM whose gradient all-reduces cross
    process boundaries, PLUS the entity-sharded random-effect variant
    (coefficient rows sharded over the cross-process mesh, ring collectives
    over DCN, per-process row parity) — the mesh.py multi-host claim,
    executed (parallel/multihost.py; reference analog: Spark local-cluster
    tests, SparkTestUtils.scala:61-75, one level stronger: real processes).
    Out of tier-1 (slow + multihost): OS-process jax.distributed needs a
    jaxlib with cross-process CPU collectives; the single-process 8-device
    sharded-sweep parity below is the tier-1 certificate."""
    from photon_ml_tpu.parallel.multihost import dryrun_multihost

    dryrun_multihost(2, 2, timeout_s=300)


def test_bcast_gather_rows_exact(rng):
    """The psum broadcast-gather (serving's sharded dispatch) is exact row
    movement: one shard contributes each requested row, the others exact
    zeros — bitwise equal to local indexing."""
    from photon_ml_tpu.parallel.mesh import (
        bcast_gather_rows,
        make_mesh,
        matrix_row_sharding,
    )

    mesh = make_mesh()
    ndev = mesh.devices.size
    R, D, S = 4 * ndev, 6, 13  # S deliberately not a mesh multiple
    M = jnp.asarray(rng.normal(size=(R, D)).astype(np.float32))
    rows = jnp.asarray(rng.integers(0, R, size=S).astype(np.int32))
    got = np.asarray(
        bcast_gather_rows(jax.device_put(M, matrix_row_sharding(mesh)), rows, mesh)
    )
    assert np.array_equal(got, np.asarray(M)[np.asarray(rows)])


def test_sharded_scan_sweep_matches_bucket_loop(rng, monkeypatch):
    """Tier-1 pod-scale certificate on the 8-virtual-device mesh: the
    entity-sharded scan sweep (ring gather -> vmapped shard-local solves ->
    ring scatter, all inside ONE lax.scan program per block shape) is
    BITWISE equal to the sharded per-bucket loop, keeps the coefficient
    store row-sharded, and reports its collective bytes."""
    mesh = make_mesh()
    cfg_re = RandomEffectDataConfig("entityId", "per_entity", min_bucket=4)

    def build():
        # Fresh identical dataset per path: neither may warm the other's
        # device residency or pack caches.
        ds = shard_game_dataset(
            pad_game_dataset(_dataset(np.random.default_rng(7)), mesh.devices.size),
            mesh,
        )
        red = shard_random_effect_dataset(
            build_random_effect_dataset(ds, cfg_re), mesh
        )
        return ds, red

    ds_a, red_a = build()
    scan_coord = RandomEffectCoordinate(
        ds_a, red_a, _cfg(1.0), TaskType.LOGISTIC_REGRESSION
    )
    assert scan_coord._entity_mesh is not None
    assert scan_coord._train_scan_sharded is not None
    m_scan, _ = scan_coord.train(ds_a.offsets)

    monkeypatch.setenv("PHOTON_SWEEP_SCAN", "0")
    ds_b, red_b = build()
    loop_coord = RandomEffectCoordinate(
        ds_b, red_b, _cfg(1.0), TaskType.LOGISTIC_REGRESSION
    )
    m_loop, _ = loop_coord.train(ds_b.offsets)

    W_scan = np.asarray(m_scan.coefficients_matrix)
    W_loop = np.asarray(m_loop.coefficients_matrix)
    assert np.array_equal(W_scan, W_loop)  # bitwise: dispatch never rounds

    # The coefficient store stayed row-sharded through the scan.
    shard_bytes = [
        s.data.nbytes for s in m_scan.coefficients_matrix.addressable_shards
    ]
    assert len(shard_bytes) == mesh.devices.size
    assert max(shard_bytes) <= m_scan.coefficients_matrix.nbytes // mesh.devices.size

    # Sharding decision + analytic wire accounting surface as proper keys.
    info = scan_coord.sharding_info()
    assert info["entity_sharded"] is True
    assert info["axis_size"] == mesh.devices.size
    assert info["collective_bytes_per_sweep"] > 0
    assert scan_coord.last_train_collective_bytes == info[
        "collective_bytes_per_sweep"
    ]


def test_feature_sharded_wide_fe_matches_replicated(rng):
    """Wide-FE option (SURVEY §2.6 TP row): X columns + coefficient vector
    sharded over the mesh; GSPMD partitions the XLA objective (forward
    all-reduce, local gradient) and the unmodified L-BFGS solver runs on
    sharded vector state. Must land on the replicated path's optimum."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.containers import LabeledData
    from photon_ml_tpu.optimize import problem
    from photon_ml_tpu.optimize.config import (
        L2,
        CoordinateOptimizationConfig,
        OptimizerConfig,
    )
    from photon_ml_tpu.ops.losses import LOGISTIC
    from photon_ml_tpu.parallel.mesh import (
        feature_sharding,
        feature_vector_sharding,
        make_mesh,
    )

    mesh = make_mesh()
    n, d = 512, 1024  # wide: D >> N is the regime feature sharding exists for
    X_np = rng.normal(size=(n, d)).astype(np.float32)
    w_true = (rng.normal(size=d) * 0.2).astype(np.float32)
    y_np = (rng.uniform(size=n) < 1 / (1 + np.exp(-X_np @ w_true))).astype(
        np.float32
    )
    cfg = CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=15, tolerance=1e-7),
        regularization=L2,
        reg_weight=1.0,
    )

    def solve(X, y, w0):
        return problem.solve(
            LOGISTIC,
            LabeledData(X, y, jnp.zeros(n), jnp.ones(n)),
            cfg,
            w0,
            None,
            use_pallas=False,
        )

    res_rep = jax.jit(solve)(
        jnp.asarray(X_np), jnp.asarray(y_np), jnp.zeros(d, jnp.float32)
    )

    Xs = jax.device_put(jnp.asarray(X_np), feature_sharding(mesh))
    w0s = jax.device_put(jnp.zeros(d, jnp.float32), feature_vector_sharding(mesh))
    res_sh = jax.jit(solve)(Xs, jnp.asarray(y_np), w0s)

    # Coefficient state stays feature-sharded through the whole solve.
    shards = res_sh.coefficients.addressable_shards
    assert len(shards) == mesh.devices.size
    assert max(s.data.size for s in shards) <= d // mesh.devices.size

    np.testing.assert_allclose(
        np.asarray(res_sh.coefficients),
        np.asarray(res_rep.coefficients),
        rtol=2e-3,
        atol=2e-4,
    )
    assert int(np.asarray(res_sh.iterations)) > 0

"""Multi-device tests on the 8-device virtual CPU mesh.

Counterpart of the reference's Spark local-cluster integ tests
(SparkTestUtils.scala): the sharded code paths (GSPMD-partitioned optimizer
loops, entity-sharded vmapped solves, cross-shard residual gathers) run for
real with 8 devices, and must agree numerically with single-device runs.
"""

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from photon_ml_tpu.data.game_dataset import (
    GameDataset,
    RandomEffectDataConfig,
    build_random_effect_dataset,
)
from photon_ml_tpu.game.coordinate import FixedEffectCoordinate, RandomEffectCoordinate
from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent
from photon_ml_tpu.optimize.config import L2, CoordinateOptimizationConfig, OptimizerConfig
from photon_ml_tpu.parallel.mesh import (
    make_mesh,
    pad_game_dataset,
    shard_game_dataset,
    shard_random_effect_dataset,
)
from photon_ml_tpu.types import TaskType


def _dataset(rng, n=203, d=5, n_entities=11, d_re=3):
    Xf = rng.normal(size=(n, d)).astype(np.float32)
    Xf[:, -1] = 1.0
    Xe = rng.normal(size=(n, d_re)).astype(np.float32)
    entity = rng.integers(0, n_entities, size=n)
    w = rng.normal(size=d)
    u = rng.normal(size=(n_entities, d_re))
    m = Xf @ w + np.einsum("nd,nd->n", Xe, u[entity])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-m))).astype(np.float32)
    return GameDataset.build(
        {"global": jnp.asarray(Xf), "per_entity": jnp.asarray(Xe)},
        y,
        id_tags={"entityId": entity},
    )


def _cfg(w=0.1):
    return CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=50, tolerance=1e-7),
        regularization=L2,
        reg_weight=w,
    )


def test_mesh_has_8_devices():
    mesh = make_mesh()
    assert mesh.devices.size == 8


def test_pad_dataset_row_count_and_inertness(rng):
    ds = _dataset(rng, n=203)
    padded = pad_game_dataset(ds, 8)
    assert padded.num_samples == 208
    assert float(padded.weights[203:].sum()) == 0.0
    np.testing.assert_array_equal(np.asarray(padded.labels[:203]), np.asarray(ds.labels))


def test_sharded_fixed_effect_matches_single_device(rng):
    ds = _dataset(rng)
    mesh = make_mesh()
    sharded = shard_game_dataset(ds, mesh)

    single = FixedEffectCoordinate(ds, "global", _cfg(), TaskType.LOGISTIC_REGRESSION)
    multi = FixedEffectCoordinate(sharded, "global", _cfg(), TaskType.LOGISTIC_REGRESSION)

    m1, r1 = single.train(ds.offsets)
    m2, r2 = multi.train(sharded.offsets)
    # f32 reduction order differs across shards; parity is to ~1e-4 absolute.
    np.testing.assert_allclose(
        m1.coefficients.means, m2.coefficients.means, rtol=5e-3, atol=2e-4
    )
    # The sharded input really is distributed over 8 devices.
    assert len(sharded.labels.sharding.device_set) == 8


def test_sharded_game_training_matches_single_device(rng):
    ds = _dataset(rng)
    cfg_re = RandomEffectDataConfig("entityId", "per_entity")

    # Single-device path.
    red_s = build_random_effect_dataset(ds, cfg_re)
    fixed_s = FixedEffectCoordinate(ds, "global", _cfg(), TaskType.LOGISTIC_REGRESSION)
    rand_s = RandomEffectCoordinate(ds, red_s, _cfg(1.0), TaskType.LOGISTIC_REGRESSION)
    res_s = run_coordinate_descent({"f": fixed_s, "r": rand_s}, 2)

    # Sharded path: pad + shard samples, shard entity blocks.
    mesh = make_mesh()
    padded = pad_game_dataset(ds, mesh.devices.size)
    sharded = shard_game_dataset(padded, mesh)
    red_m = shard_random_effect_dataset(build_random_effect_dataset(sharded, cfg_re), mesh)
    fixed_m = FixedEffectCoordinate(sharded, "global", _cfg(), TaskType.LOGISTIC_REGRESSION)
    rand_m = RandomEffectCoordinate(sharded, red_m, _cfg(1.0), TaskType.LOGISTIC_REGRESSION)
    res_m = run_coordinate_descent({"f": fixed_m, "r": rand_m}, 2)

    np.testing.assert_allclose(
        res_s.model["f"].coefficients.means,
        res_m.model["f"].coefficients.means,
        rtol=5e-3,
        atol=5e-4,
    )
    # Entity rows may be ordered differently only if id sets differ — they
    # don't here (same build logic); padded dataset adds one sentinel entity.
    W_s = np.asarray(res_s.model["r"].coefficients_matrix)
    W_m = np.asarray(res_m.model["r"].coefficients_matrix)
    for ent, row_s in red_s.entity_index.items():
        row_m = red_m.entity_index[ent]
        np.testing.assert_allclose(
            W_s[row_s], W_m[row_m], rtol=5e-3, atol=5e-4,
        )


def test_entity_blocks_sharded_over_devices(rng):
    ds = _dataset(rng)
    mesh = make_mesh()
    padded = pad_game_dataset(ds, mesh.devices.size)
    red = shard_random_effect_dataset(
        build_random_effect_dataset(padded, RandomEffectDataConfig("entityId", "per_entity")),
        mesh,
    )
    for b in red.buckets:
        assert b.gather.shape[0] % 8 == 0
        assert len(b.gather.sharding.device_set) == 8

"""Native Avro block decoder + native bucketed packer: parity vs the pure
Python implementations on generated data and the reference's own fixtures
(DriverIntegTest heart.avro, GameIntegTest yahoo-music-train.avro)."""

import os

import numpy as np
import pytest

import photon_ml_tpu.io.avro_data as ad
from photon_ml_tpu.io import avro_fast
from photon_ml_tpu.native.build import load_native

REF = "/root/reference/photon-client/src/integTest/resources"
DRIVER_IN = os.path.join(REF, "DriverIntegTest/input")
GAME_IN = os.path.join(REF, "GameIntegTest/input")

needs_native = pytest.mark.skipif(
    load_native() is None, reason="native library unavailable"
)
# The reference's own integration fixtures (heart.avro,
# yahoo-music-train.avro) ship with a photon-ml checkout, not with this
# repo — on hosts without one the parity suite must SKIP with a reason,
# not fail red forever (TestGeneratedParity covers the same decode paths
# on generated data everywhere).
needs_reference_fixtures = pytest.mark.skipif(
    not os.path.isdir(DRIVER_IN),
    reason=f"reference fixture tree not present at {REF} "
    "(clone photon-ml to run the reference-parity suite)",
)


def _dense(ds, shard, size):
    sp = ds.shards[shard]
    n = ds.num_samples
    M = np.zeros((n, size))
    idx, val = np.asarray(sp.indices), np.asarray(sp.values)
    np.add.at(M, (np.repeat(np.arange(n), idx.shape[1]), idx.ravel()), val.ravel())
    return M


def _assert_parity(path, cfgs, tags=()):
    cols = ad.InputColumnNames()
    fast = avro_fast.try_read_native([path], cfgs, None, list(tags), cols, ad.LABEL)
    assert fast is not None, "native decoder fell back on a supported fixture"
    ds_n, maps_n = fast
    os.environ["PHOTON_DISABLE_NATIVE"] = "1"
    try:
        ds_p, maps_p = ad.read_game_dataset(path, cfgs, id_tag_fields=list(tags))
    finally:
        del os.environ["PHOTON_DISABLE_NATIVE"]
    assert ds_n.num_samples == ds_p.num_samples
    for k in ("labels", "offsets", "weights"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ds_n, k)), np.asarray(getattr(ds_p, k)), err_msg=k
        )
    assert set(ds_n.id_tags) == set(ds_p.id_tags)
    for t in ds_p.id_tags:
        assert np.array_equal(ds_n.id_tags[t], ds_p.id_tags[t]), t
    for shard in cfgs:
        assert maps_n[shard].size == maps_p[shard].size
        np.testing.assert_allclose(
            _dense(ds_n, shard, maps_n[shard].size),
            _dense(ds_p, shard, maps_p[shard].size),
        )


@needs_native
@needs_reference_fixtures
class TestReferenceFixtureParity:
    def test_heart(self):
        _assert_parity(
            os.path.join(DRIVER_IN, "heart.avro"),
            {"g": ad.FeatureShardConfig(("features",), True)},
        )

    def test_heart_validation(self):
        _assert_parity(
            os.path.join(DRIVER_IN, "heart_validation.avro"),
            {"g": ad.FeatureShardConfig(("features",), True)},
        )

    def test_yahoo_music_multi_shard_with_tags(self):
        import glob

        ym = glob.glob(GAME_IN + "/**/yahoo-music-train.avro", recursive=True)
        assert ym
        _assert_parity(
            ym[0],
            {
                "g": ad.FeatureShardConfig(("features",), True),
                "s": ad.FeatureShardConfig(("songFeatures",), True),
                "u": ad.FeatureShardConfig(("userFeatures",), False),
            },
            tags=("userId", "songId"),
        )


@needs_native
class TestGeneratedParity:
    def test_roundtrip_with_tags_offsets_weights(self, tmp_path):
        rng = np.random.default_rng(0)
        n, d = 700, 80
        feats = [
            [(f"f{j}", float(rng.normal())) for j in rng.choice(d, size=6, replace=False)]
            for _ in range(n)
        ]
        labels = (rng.uniform(size=n) > 0.5).astype(float)
        p = str(tmp_path / "t.avro")
        ad.write_training_examples(
            p,
            feats,
            labels,
            offsets=rng.normal(size=n) * 0.1,
            weights=rng.uniform(0.5, 1.5, size=n),
            uids=[f"u{i}" for i in range(n)],
            id_tags={"entityId": rng.integers(0, 9, size=n)},
        )
        _assert_parity(
            p, {"g": ad.FeatureShardConfig(("features",), True)}, tags=("entityId",)
        )

    def test_supplied_index_map_drops_unseen(self, tmp_path):
        from photon_ml_tpu.data.index_map import IndexMap

        rng = np.random.default_rng(1)
        n = 100
        feats = [[(f"f{i % 7}", 1.0), (f"g{i % 5}", 2.0)] for i in range(n)]
        p = str(tmp_path / "t.avro")
        ad.write_training_examples(p, feats, np.zeros(n))
        imap = IndexMap.from_feature_names({f"f{i}" for i in range(7)}, add_intercept=True)
        cfgs = {"g": ad.FeatureShardConfig(("features",), True)}
        cols = ad.InputColumnNames()
        fast = avro_fast.try_read_native([p], cfgs, {"g": imap}, [], cols, ad.LABEL)
        assert fast is not None
        ds_n, maps_n = fast
        os.environ["PHOTON_DISABLE_NATIVE"] = "1"
        try:
            ds_p, maps_p = ad.read_game_dataset(p, cfgs, index_maps={"g": imap})
        finally:
            del os.environ["PHOTON_DISABLE_NATIVE"]
        np.testing.assert_allclose(
            _dense(ds_n, "g", imap.size), _dense(ds_p, "g", imap.size)
        )

    def test_falls_back_on_dotted_tags(self, tmp_path):
        rng = np.random.default_rng(2)
        n = 20
        p = str(tmp_path / "t.avro")
        ad.write_training_examples(p, [[("f0", 1.0)]] * n, np.zeros(n))
        cols = ad.InputColumnNames()
        cfgs = {"g": ad.FeatureShardConfig(("features",), True)}
        assert (
            avro_fast.try_read_native([p], cfgs, None, ["ids.member"], cols, ad.LABEL)
            is None
        )


@needs_native
class TestNativePacker:
    def test_bit_identical_to_numpy(self):
        from photon_ml_tpu.data.bucketed import pack_bucketed, to_coo

        rng = np.random.default_rng(3)
        nnz = 300_000
        rows = np.repeat(np.arange(nnz // 10, dtype=np.int64), 10)
        cols = rng.integers(0, 3000, size=nnz)
        cols[: nnz // 20] = 7  # hot feature: exercise spill
        vals = rng.normal(size=nnz).astype(np.float32)
        bf_n = pack_bucketed(rows, cols, vals, nnz // 10, 3000)
        os.environ["PHOTON_DISABLE_NATIVE"] = "1"
        try:
            bf_p = pack_bucketed(rows, cols, vals, nnz // 10, 3000)
        finally:
            del os.environ["PHOTON_DISABLE_NATIVE"]
        for a, b in zip(to_coo(bf_n), to_coo(bf_p)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(
            np.asarray(bf_n.level1.packed), np.asarray(bf_p.level1.packed)
        )


@needs_native
class TestThreadedDecode:
    """Block-parallel decode must be bit-identical to sequential (merge
    preserves interned-id first-encounter order; avro_reader.cc run_job)."""

    def _decode(self, path, n_threads, tags=("e",)):
        from photon_ml_tpu.data.index_map import DELIMITER
        from photon_ml_tpu.io import avro as avro_io
        from photon_ml_tpu.native import avro_reader

        cols = ad.InputColumnNames()
        with open(path, "rb") as f:
            data = f.read()
        schema, codec, sync, body = avro_io.read_header(data, path)
        prog = avro_reader.compile_program(
            schema,
            response=cols.response,
            fallback_label=ad.LABEL,
            offset=cols.offset,
            weight=cols.weight,
            uid=cols.uid,
            metadata_map=cols.metadata_map,
            bag_names=["features"],
            tag_fields=tuple(tags),
        )
        assert prog is not None
        return avro_reader.decode_file_native(
            data, body, codec, sync, prog, DELIMITER, n_threads=n_threads
        )

    def test_thread_count_invariance(self, tmp_path):
        rng = np.random.default_rng(11)
        n = 30_000  # enough records for several container blocks
        feats = [
            [
                (f"f{j}", float(v))
                for j, v in zip(
                    rng.choice(400, size=8, replace=False), rng.normal(size=8)
                )
            ]
            for _ in range(n)
        ]
        p = str(tmp_path / "t.avro")
        ad.write_training_examples(
            p, feats, rng.uniform(size=n),
            id_tags={"e": rng.integers(0, 40, size=n)},
        )
        a = self._decode(p, 1)
        for w in (2, 5):
            b = self._decode(p, w)
            assert a.keys == b.keys
            assert a.tag_values == b.tag_values
            assert a.bag_has_dups == b.bag_has_dups
            np.testing.assert_array_equal(a.labels, b.labels)
            np.testing.assert_array_equal(a.tag_ids, b.tag_ids)
            for x, y in zip(
                (a.bag_indptr[0], a.bag_keys[0], a.bag_vals[0]),
                (b.bag_indptr[0], b.bag_keys[0], b.bag_vals[0]),
            ):
                np.testing.assert_array_equal(x, y)

    def test_dup_flag(self, tmp_path):
        p1 = str(tmp_path / "clean.avro")
        ad.write_training_examples(p1, [[("a", 1.0), ("b", 2.0)]] * 5, np.zeros(5))
        assert self._decode(p1, 1, tags=()).bag_has_dups == [False]
        p2 = str(tmp_path / "dups.avro")
        ad.write_training_examples(
            p2, [[("a", 1.0), ("b", 2.0), ("a", 3.0)]] * 5, np.zeros(5)
        )
        d = self._decode(p2, 1, tags=())
        assert d.bag_has_dups == [True]

    def test_dup_records_still_match_python_path(self, tmp_path):
        # In-record duplicates are accumulated at decode time; results must
        # equal the pure-Python codec's accumulate-duplicates semantics.
        p = str(tmp_path / "dups.avro")
        feats = [[("a", 1.0), ("b", 2.0), ("a", 3.0)], [("b", 1.0)]] * 40
        ad.write_training_examples(p, feats, np.zeros(80))
        _assert_parity(p, {"g": ad.FeatureShardConfig(("features",), True)})

    def test_triple_dup_accumulates_in_float64(self, tmp_path):
        # Catastrophic-cancellation probe: [a:1e8, a:1, a:-1e8] must sum to
        # exactly 1.0 (float64 accumulation, one final float32 cast) on BOTH
        # readers — a float32 running sum would silently produce 0.0.
        p = str(tmp_path / "cancel.avro")
        feats = [[("a", 1e8), ("a", 1.0), ("a", -1e8)], [("b", 2.0)]] * 20
        ad.write_training_examples(p, feats, np.zeros(40))
        cfgs = {"g": ad.FeatureShardConfig(("features",), True)}
        ds_n, maps_n = ad.read_game_dataset(p, cfgs)
        assert float(np.asarray(ds_n.shards["g"].values).max()) == 2.0
        assert 1.0 in np.asarray(ds_n.shards["g"].values)
        _assert_parity(p, cfgs)

    def test_wide_record_dedup_matches(self, tmp_path):
        # Wide records (>=64 entries) take the sort-based dedup path in the
        # decoder; parity with the Python codec must hold there too.
        p = str(tmp_path / "wide.avro")
        feats = [[(f"f{i % 500}", float(i)) for i in range(2000)]] * 3
        ad.write_training_examples(p, feats, np.zeros(3))
        _assert_parity(p, {"g": ad.FeatureShardConfig(("features",), True)})


@needs_native
class TestHostCooStash:
    def test_small_or_ineligible_not_stashed(self, tmp_path):
        # Below the pack size gate (or on a non-kernel backend) the COO
        # stash would pin host RAM with no consumer — it must stay empty.
        n = 500
        p = str(tmp_path / "t.avro")
        ad.write_training_examples(p, [[("a", 1.0)]] * n, np.zeros(n))
        cfgs = {"g": ad.FeatureShardConfig(("features",), True)}
        cols = ad.InputColumnNames()
        ds, _ = avro_fast.try_read_native([p], cfgs, None, [], cols, ad.LABEL)
        assert ds.host_csr == {}

    def test_ingest_stashes_host_csr(self, tmp_path):
        from photon_ml_tpu.ops import pallas_glm

        rng = np.random.default_rng(12)
        n = 9000  # >= the pack size gate (4 * L1_TILE_ROWS)
        feats = [
            [(f"f{j}", float(rng.normal())) for j in rng.choice(50, size=4, replace=False)]
            for _ in range(n)
        ]
        p = str(tmp_path / "t.avro")
        ad.write_training_examples(p, feats, np.zeros(n))
        cfgs = {"g": ad.FeatureShardConfig(("features",), True)}
        cols = ad.InputColumnNames()
        old = pallas_glm.FORCE_INTERPRET
        pallas_glm.FORCE_INTERPRET = True  # make kernels_eligible() true on CPU
        try:
            ds, maps = avro_fast.try_read_native([p], cfgs, None, [], cols, ad.LABEL)
        finally:
            pallas_glm.FORCE_INTERPRET = old
        assert "g" in ds.host_csr
        rows, cols_, vals, dim = ds.host_csr["g"].to_coo()
        assert dim == maps["g"].size
        # host COO must reproduce the device ELL contents exactly
        M_coo = np.zeros((n, dim))
        np.add.at(M_coo, (np.asarray(rows), np.asarray(cols_)), np.asarray(vals))
        np.testing.assert_allclose(M_coo, _dense(ds, "g", dim))


@needs_native
class TestColumnarWriter:
    def test_native_and_python_writers_agree(self, tmp_path):
        from photon_ml_tpu.native import avro_writer as aw

        rng = np.random.default_rng(21)
        n, k, d = 800, 5, 60
        indptr = np.arange(n + 1, dtype=np.int64) * k
        ids = rng.integers(0, d, size=n * k).astype(np.int32)
        vals = rng.normal(size=n * k)
        names = [f"f{i}" for i in range(d)]
        labels = (rng.uniform(size=n) > 0.5).astype(np.float64)
        offs = rng.normal(size=n) * 0.1
        wts = rng.uniform(0.5, 1.5, size=n)
        tags = rng.integers(0, 9, size=n).astype(str)

        p_nat = str(tmp_path / "nat.avro")
        aw.write_training_examples_columnar(
            p_nat, labels, indptr, ids, vals, names,
            offsets=offs, weights=wts, tag_key="entityId", tag_values=tags,
        )
        p_py = str(tmp_path / "py.avro")
        aw._python_fallback(
            p_py, labels, indptr, ids, vals, names,
            offsets=offs, weights=wts, tag_key="entityId", tag_values=tags,
        )
        cfgs = {"g": ad.FeatureShardConfig(("features",), True)}
        ds_n, m_n = ad.read_game_dataset(p_nat, cfgs, id_tag_fields=["entityId"])
        ds_p, m_p = ad.read_game_dataset(p_py, cfgs, id_tag_fields=["entityId"])
        for attr in ("labels", "offsets", "weights"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ds_n, attr)), np.asarray(getattr(ds_p, attr))
            )
        assert np.array_equal(ds_n.id_tags["entityId"], ds_p.id_tags["entityId"])
        assert m_n["g"].size == m_p["g"].size
        np.testing.assert_allclose(
            _dense(ds_n, "g", m_n["g"].size), _dense(ds_p, "g", m_p["g"].size)
        )

    def test_empty_rows_and_no_tags(self, tmp_path):
        from photon_ml_tpu.native import avro_writer as aw

        indptr = np.array([0, 2, 2, 3], np.int64)  # middle record empty
        ids = np.array([0, 1, 0], np.int32)
        vals = np.array([1.0, 2.0, 3.0])
        p = str(tmp_path / "t.avro")
        aw.write_training_examples_columnar(
            p, np.array([1.0, 0.0, 1.0]), indptr, ids, vals, ["a", "b"]
        )
        cfgs = {"g": ad.FeatureShardConfig(("features",), False)}
        ds, maps = ad.read_game_dataset(p, cfgs)
        M = _dense(ds, "g", maps["g"].size)
        assert M[1].sum() == 0  # empty record round-trips empty
        assert ds.num_samples == 3

    def test_bad_name_id_fails_cleanly(self, tmp_path):
        from photon_ml_tpu.native import avro_writer as aw

        indptr = np.array([0, 1], np.int64)
        p = str(tmp_path / "t.avro")
        with pytest.raises(OSError):
            aw.write_training_examples_columnar(
                p, np.array([1.0]), indptr, np.array([5], np.int32),
                np.array([1.0]), ["only"],  # id 5 out of range
            )

"""Native Avro block decoder + native bucketed packer: parity vs the pure
Python implementations on generated data and the reference's own fixtures
(DriverIntegTest heart.avro, GameIntegTest yahoo-music-train.avro)."""

import os

import numpy as np
import pytest

import photon_ml_tpu.io.avro_data as ad
from photon_ml_tpu.io import avro_fast
from photon_ml_tpu.native.build import load_native

REF = "/root/reference/photon-client/src/integTest/resources"
DRIVER_IN = os.path.join(REF, "DriverIntegTest/input")
GAME_IN = os.path.join(REF, "GameIntegTest/input")

needs_native = pytest.mark.skipif(
    load_native() is None, reason="native library unavailable"
)


def _dense(ds, shard, size):
    sp = ds.shards[shard]
    n = ds.num_samples
    M = np.zeros((n, size))
    idx, val = np.asarray(sp.indices), np.asarray(sp.values)
    np.add.at(M, (np.repeat(np.arange(n), idx.shape[1]), idx.ravel()), val.ravel())
    return M


def _assert_parity(path, cfgs, tags=()):
    cols = ad.InputColumnNames()
    fast = avro_fast.try_read_native([path], cfgs, None, list(tags), cols, ad.LABEL)
    assert fast is not None, "native decoder fell back on a supported fixture"
    ds_n, maps_n = fast
    os.environ["PHOTON_DISABLE_NATIVE"] = "1"
    try:
        ds_p, maps_p = ad.read_game_dataset(path, cfgs, id_tag_fields=list(tags))
    finally:
        del os.environ["PHOTON_DISABLE_NATIVE"]
    assert ds_n.num_samples == ds_p.num_samples
    for k in ("labels", "offsets", "weights"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ds_n, k)), np.asarray(getattr(ds_p, k)), err_msg=k
        )
    assert set(ds_n.id_tags) == set(ds_p.id_tags)
    for t in ds_p.id_tags:
        assert np.array_equal(ds_n.id_tags[t], ds_p.id_tags[t]), t
    for shard in cfgs:
        assert maps_n[shard].size == maps_p[shard].size
        np.testing.assert_allclose(
            _dense(ds_n, shard, maps_n[shard].size),
            _dense(ds_p, shard, maps_p[shard].size),
        )


@needs_native
class TestReferenceFixtureParity:
    def test_heart(self):
        _assert_parity(
            os.path.join(DRIVER_IN, "heart.avro"),
            {"g": ad.FeatureShardConfig(("features",), True)},
        )

    def test_heart_validation(self):
        _assert_parity(
            os.path.join(DRIVER_IN, "heart_validation.avro"),
            {"g": ad.FeatureShardConfig(("features",), True)},
        )

    def test_yahoo_music_multi_shard_with_tags(self):
        import glob

        ym = glob.glob(GAME_IN + "/**/yahoo-music-train.avro", recursive=True)
        assert ym
        _assert_parity(
            ym[0],
            {
                "g": ad.FeatureShardConfig(("features",), True),
                "s": ad.FeatureShardConfig(("songFeatures",), True),
                "u": ad.FeatureShardConfig(("userFeatures",), False),
            },
            tags=("userId", "songId"),
        )


@needs_native
class TestGeneratedParity:
    def test_roundtrip_with_tags_offsets_weights(self, tmp_path):
        rng = np.random.default_rng(0)
        n, d = 700, 80
        feats = [
            [(f"f{j}", float(rng.normal())) for j in rng.choice(d, size=6, replace=False)]
            for _ in range(n)
        ]
        labels = (rng.uniform(size=n) > 0.5).astype(float)
        p = str(tmp_path / "t.avro")
        ad.write_training_examples(
            p,
            feats,
            labels,
            offsets=rng.normal(size=n) * 0.1,
            weights=rng.uniform(0.5, 1.5, size=n),
            uids=[f"u{i}" for i in range(n)],
            id_tags={"entityId": rng.integers(0, 9, size=n)},
        )
        _assert_parity(
            p, {"g": ad.FeatureShardConfig(("features",), True)}, tags=("entityId",)
        )

    def test_supplied_index_map_drops_unseen(self, tmp_path):
        from photon_ml_tpu.data.index_map import IndexMap

        rng = np.random.default_rng(1)
        n = 100
        feats = [[(f"f{i % 7}", 1.0), (f"g{i % 5}", 2.0)] for i in range(n)]
        p = str(tmp_path / "t.avro")
        ad.write_training_examples(p, feats, np.zeros(n))
        imap = IndexMap.from_feature_names({f"f{i}" for i in range(7)}, add_intercept=True)
        cfgs = {"g": ad.FeatureShardConfig(("features",), True)}
        cols = ad.InputColumnNames()
        fast = avro_fast.try_read_native([p], cfgs, {"g": imap}, [], cols, ad.LABEL)
        assert fast is not None
        ds_n, maps_n = fast
        os.environ["PHOTON_DISABLE_NATIVE"] = "1"
        try:
            ds_p, maps_p = ad.read_game_dataset(p, cfgs, index_maps={"g": imap})
        finally:
            del os.environ["PHOTON_DISABLE_NATIVE"]
        np.testing.assert_allclose(
            _dense(ds_n, "g", imap.size), _dense(ds_p, "g", imap.size)
        )

    def test_falls_back_on_dotted_tags(self, tmp_path):
        rng = np.random.default_rng(2)
        n = 20
        p = str(tmp_path / "t.avro")
        ad.write_training_examples(p, [[("f0", 1.0)]] * n, np.zeros(n))
        cols = ad.InputColumnNames()
        cfgs = {"g": ad.FeatureShardConfig(("features",), True)}
        assert (
            avro_fast.try_read_native([p], cfgs, None, ["ids.member"], cols, ad.LABEL)
            is None
        )


@needs_native
class TestNativePacker:
    def test_bit_identical_to_numpy(self):
        from photon_ml_tpu.data.bucketed import pack_bucketed, to_coo

        rng = np.random.default_rng(3)
        nnz = 300_000
        rows = np.repeat(np.arange(nnz // 10, dtype=np.int64), 10)
        cols = rng.integers(0, 3000, size=nnz)
        cols[: nnz // 20] = 7  # hot feature: exercise spill
        vals = rng.normal(size=nnz).astype(np.float32)
        bf_n = pack_bucketed(rows, cols, vals, nnz // 10, 3000)
        os.environ["PHOTON_DISABLE_NATIVE"] = "1"
        try:
            bf_p = pack_bucketed(rows, cols, vals, nnz // 10, 3000)
        finally:
            del os.environ["PHOTON_DISABLE_NATIVE"]
        for a, b in zip(to_coo(bf_n), to_coo(bf_p)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(
            np.asarray(bf_n.level1.packed), np.asarray(bf_p.level1.packed)
        )

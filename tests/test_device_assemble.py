"""Device-resident RE assembly & index-map projection: bitwise parity vs
the host path (r09). Stable sorts are uniquely determined permutations and
every scatter destination is unique, so PHOTON_DEVICE_ASSEMBLY=1 must
reproduce the host arrays bit for bit — gather blocks, masks, entity rows,
slot tables, projected planes, and the whole trained model."""

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu.data import device_assemble
from photon_ml_tpu.data import game_dataset as gd
from photon_ml_tpu.data.containers import SparseFeatures
from photon_ml_tpu.data.stats import summarize
from photon_ml_tpu.game import projector as pj
from photon_ml_tpu.types import ProjectorType


def _dataset(seed=1, n=4000, d=48, k=4, n_entities=250, skew=True):
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, d, size=(n, k)).astype(np.int32)
    val = rng.normal(size=(n, k)).astype(np.float32)
    val[rng.uniform(size=val.shape) < 0.15] = 0.0
    ents = rng.integers(0, n_entities, size=n).astype(str)
    if skew:  # one very frequent entity exercises the reservoir
        ents[: n // 4] = "0"
    ds = gd.GameDataset.build(
        {"g": SparseFeatures(jnp.asarray(idx), jnp.asarray(val), d)},
        rng.normal(size=n).astype(np.float32),
        id_tags={"e": ents},
    )
    ds.host_ell["g"] = (idx, val)
    return ds


def _build_both(monkeypatch, cfg, **ds_kw):
    out = []
    for flag in ("0", "1"):
        monkeypatch.setenv("PHOTON_DEVICE_ASSEMBLY", flag)
        out.append(_dataset(**ds_kw))
    ds_h, ds_d = out
    monkeypatch.setenv("PHOTON_DEVICE_ASSEMBLY", "0")
    red_h = gd._build_random_effect_dataset(ds_h, cfg)
    monkeypatch.setenv("PHOTON_DEVICE_ASSEMBLY", "1")
    red_d = gd._build_random_effect_dataset(ds_d, cfg)
    return (ds_h, red_h), (ds_d, red_d)


def _assert_blocks_equal(red_h, red_d):
    assert len(red_h.buckets) == len(red_d.buckets)
    for i, (bh, bd) in enumerate(zip(red_h.buckets, red_d.buckets)):
        np.testing.assert_array_equal(
            np.asarray(bh.gather), np.asarray(bd.gather), err_msg=f"gather {i}"
        )
        np.testing.assert_array_equal(
            np.asarray(bh.mask), np.asarray(bd.mask), err_msg=f"mask {i}"
        )
        np.testing.assert_array_equal(
            np.asarray(bh.entity_rows),
            np.asarray(bd.entity_rows),
            err_msg=f"entity_rows {i}",
        )
    np.testing.assert_array_equal(
        np.asarray(red_h.sample_entity_rows),
        np.asarray(red_d.sample_entity_rows),
    )
    assert red_h.num_active_samples == red_d.num_active_samples
    assert red_h.entity_index == red_d.entity_index


class TestEntityBlockParity:
    @pytest.mark.parametrize(
        "cfg_kw",
        [
            dict(),  # no caps: every row active
            dict(active_upper_bound=16),  # reservoir engages
            dict(active_lower_bound=5),  # small entities dropped
            dict(active_upper_bound=16, active_lower_bound=3),
            dict(active_upper_bound=8, max_block_cells=1 << 9),  # chunking
        ],
    )
    def test_bitwise(self, monkeypatch, cfg_kw):
        cfg = gd.RandomEffectDataConfig("e", "g", min_bucket=8, **cfg_kw)
        (_, red_h), (_, red_d) = _build_both(monkeypatch, cfg)
        _assert_blocks_equal(red_h, red_d)

    def test_single_entity(self, monkeypatch):
        cfg = gd.RandomEffectDataConfig("e", "g", active_upper_bound=32)
        (_, red_h), (_, red_d) = _build_both(
            monkeypatch, cfg, n=600, n_entities=1, skew=False
        )
        _assert_blocks_equal(red_h, red_d)

    def test_auto_gate_off_on_cpu(self, monkeypatch):
        """Auto mode mirrors device_pack: off on the CPU backend, forced
        by PHOTON_DEVICE_ASSEMBLY=1 (the path tier-1 exercises)."""
        monkeypatch.delenv("PHOTON_DEVICE_ASSEMBLY", raising=False)
        import jax

        expected = jax.default_backend() in ("tpu", "gpu")
        assert device_assemble.enabled() is expected
        monkeypatch.setenv("PHOTON_DEVICE_ASSEMBLY", "1")
        assert device_assemble.enabled() is True
        monkeypatch.setenv("PHOTON_DEVICE_ASSEMBLY", "0")
        assert device_assemble.enabled() is False

    def test_pearson_keeps_host_path(self, monkeypatch):
        """Pearson feature selection needs host per-entity row lists; the
        device gate must step aside rather than break it."""
        monkeypatch.setenv("PHOTON_DEVICE_ASSEMBLY", "1")
        ds = _dataset()
        cfg = gd.RandomEffectDataConfig(
            "e", "g", num_features_to_samples_ratio_upper_bound=0.5
        )
        red = gd._build_random_effect_dataset(ds, cfg)
        assert red.feature_mask is not None


class TestProjectorParity:
    def _both(self, monkeypatch, want_stats=False):
        reds = []
        for flag in ("0", "1"):
            monkeypatch.setenv("PHOTON_DEVICE_ASSEMBLY", flag)
            ds = _dataset(seed=2)
            cfg = gd.RandomEffectDataConfig("e", "g", min_bucket=8)
            red = gd._build_random_effect_dataset(ds, cfg)
            ps = pj.project_shard(
                ds, red, ProjectorType.INDEX_MAP, want_stats=want_stats
            )
            reds.append((ds, red, ps))
        return reds

    def test_slot_tables_and_planes_bitwise(self, monkeypatch):
        (ds_h, _, ps_h), (ds_d, _, ps_d) = self._both(monkeypatch)
        np.testing.assert_array_equal(
            ps_h.projector.slot_tables, ps_d.projector.slot_tables
        )
        assert ps_h.projector.projected_dim == ps_d.projector.projected_dim
        sh = ds_h.peek_shard(ps_h.shard_name)
        sd = ds_d.peek_shard(ps_d.shard_name)
        assert sh.ell_axis == sd.ell_axis == -2
        np.testing.assert_array_equal(
            np.asarray(sh.indices), np.asarray(sd.indices)
        )
        np.testing.assert_array_equal(
            np.asarray(sh.values), np.asarray(sd.values)
        )
        assert np.asarray(sd.indices).dtype == np.asarray(sh.indices).dtype

    def test_project_features_unseen_entities(self, monkeypatch):
        """Scoring-time projection (validation data) routes unseen
        entities to all-zero rows on both paths, bitwise."""
        (_, red_h, ps_h), (_, red_d, ps_d) = self._both(monkeypatch)
        rng = np.random.default_rng(9)
        m, k = 400, 4
        idx = rng.integers(0, 48, size=(m, k)).astype(np.int32)
        val = rng.normal(size=(m, k)).astype(np.float32)
        ents = rng.integers(0, red_h.num_entities + 1, size=m)  # incl unseen
        feats = SparseFeatures(jnp.asarray(idx), jnp.asarray(val), 48)
        monkeypatch.setenv("PHOTON_DEVICE_ASSEMBLY", "0")
        out_h = ps_h.projector.project_features(feats, ents, (idx, val))
        monkeypatch.setenv("PHOTON_DEVICE_ASSEMBLY", "1")
        out_d = ps_d.projector.project_features(feats, ents, (idx, val))
        np.testing.assert_array_equal(
            np.asarray(out_h.indices), np.asarray(out_d.indices)
        )
        np.testing.assert_array_equal(
            np.asarray(out_h.values), np.asarray(out_d.values)
        )

    def test_fused_stats_bitwise_vs_summarize(self, monkeypatch):
        """The fused auxiliary pass: want_stats folds the feature summary
        into the projector build's sweep; the result must be BITWISE what
        a standalone summarize() of the original shard computes."""
        (_, _, ps_h), (ds_d, _, ps_d) = self._both(monkeypatch, want_stats=True)
        assert ps_h.projector.original_stats is None  # host path: no fusion
        st = ps_d.projector.original_stats
        assert st is not None
        idx, val = ds_d.host_ell["g"]
        ref = summarize(SparseFeatures(jnp.asarray(idx), jnp.asarray(val), 48))
        for f in (
            "count",
            "mean",
            "variance",
            "num_nonzeros",
            "max",
            "min",
            "norm_l1",
            "norm_l2",
            "mean_abs",
        ):
            np.testing.assert_array_equal(
                np.asarray(getattr(st, f)),
                np.asarray(getattr(ref, f)),
                err_msg=f,
            )

    def test_unsupported_key_space_falls_back(self, monkeypatch):
        monkeypatch.setenv("PHOTON_DEVICE_ASSEMBLY", "1")
        assert not device_assemble.projector_supported(2**16, 2**16)
        assert device_assemble.projector_supported(140_000, 200)


class TestEndToEndFitParity:
    def test_trained_model_bitwise(self, monkeypatch):
        """The whole point: a fit under PHOTON_DEVICE_ASSEMBLY=1 trains a
        model bitwise-equal to the host data plane's."""
        from photon_ml_tpu.estimators.game_estimator import GameEstimator
        from photon_ml_tpu.optimize.config import (
            CoordinateOptimizationConfig,
            OptimizerConfig,
        )
        from photon_ml_tpu.types import TaskType

        models = []
        for flag in ("0", "1"):
            monkeypatch.setenv("PHOTON_DEVICE_ASSEMBLY", flag)
            ds = _dataset(seed=4, n=2500, n_entities=80)
            est = GameEstimator(
                TaskType.LOGISTIC_REGRESSION,
                {
                    "fe": gd.FixedEffectDataConfig("g"),
                    "re": gd.RandomEffectDataConfig(
                        "e", "g", active_upper_bound=24
                    ),
                },
            )
            cfg = {
                "fe": CoordinateOptimizationConfig(
                    optimizer=OptimizerConfig(max_iterations=3)
                ),
                "re": CoordinateOptimizationConfig(
                    optimizer=OptimizerConfig(max_iterations=3)
                ),
            }
            res = est.fit(ds, None, [cfg])
            models.append((res[0].model, dict(est.fit_timing)))
        (m_h, t_h), (m_d, t_d) = models
        assert t_h["re_path"] == "host" and t_d["re_path"] == "device"
        assert t_d["re_device_s"] > 0.0 and t_h["re_host_s"] > 0.0
        np.testing.assert_array_equal(
            np.asarray(m_h.models["fe"].coefficients.means),
            np.asarray(m_d.models["fe"].coefficients.means),
        )
        np.testing.assert_array_equal(
            np.asarray(m_h.models["re"].coefficients_matrix),
            np.asarray(m_d.models["re"].coefficients_matrix),
        )

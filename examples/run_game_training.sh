#!/bin/bash
# Fixed-effect logistic regression end to end — the TPU-native counterpart of
# the reference tutorial flow (README.md:307-345: a1a LibSVM -> Avro ->
# training driver -> model dir) and of examples/run_photon_ml_driver.sh.
#
# Usage: ./run_game_training.sh [working_root]
#
# Layout produced under working_root (default ./photon-demo):
#     data/       train.libsvm test.libsvm + Avro conversions
#     results/    trained models (models/best, models/explicit-*)
#     scores/     scored test set + scoring-summary.json
set -euo pipefail

ROOT="${1:-./photon-demo}"
REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)"
export PYTHONPATH="$REPO_DIR${PYTHONPATH:+:$PYTHONPATH}"
DATA="$ROOT/data"
mkdir -p "$DATA"

# Prefer the REAL adult-income dataset when the reference's fixtures are
# mounted (DriverIntegTest ships a9a/a9a.t — the same family as the
# tutorial's a1a); fall back to the deterministic synthetic stand-in.
REF_A9A="${REF_A9A:-/root/reference/photon-client/src/integTest/resources/DriverIntegTest/input}"
if [[ -f "$REF_A9A/a9a" && -f "$REF_A9A/a9a.t" ]]; then
    echo "== 1/4 use the reference's a9a LibSVM fixtures =="
    cp "$REF_A9A/a9a" "$DATA/train.libsvm"
    cp "$REF_A9A/a9a.t" "$DATA/test.libsvm"
else
    echo "== 1/4 generate a1a-like dataset (reference fixtures not mounted) =="
    python "$REPO_DIR/examples/generate_dataset.py" "$DATA" --train 1600 --test 800
fi

echo "== 2/4 convert LibSVM -> TrainingExample Avro =="
python -m photon_ml_tpu.cli.libsvm_to_avro "$DATA/train.libsvm" "$DATA/train.avro"
python -m photon_ml_tpu.cli.libsvm_to_avro "$DATA/test.libsvm" "$DATA/test.avro"

echo "== 3/4 train: logistic regression, L2 sweep 0.1|1|10|100 =="
python -m photon_ml_tpu.cli.train \
    --training-task LOGISTIC_REGRESSION \
    --input-data-directories "$DATA/train.avro" \
    --validation-data-directories "$DATA/test.avro" \
    --root-output-directory "$ROOT/results" \
    --override-output-directory \
    --feature-shard-configurations \
        "name=globalShard,feature.bags=features,intercept=true" \
    --coordinate-configurations \
        "name=global,feature.shard=globalShard,optimizer=LBFGS,tolerance=1.0E-7,max.iter=50,regularization=L2,reg.weights=0.1|1|10|100" \
    --validation-evaluators AUC \
    --output-mode ALL

echo "== 4/4 score the held-out split with the selected model =="
python -m photon_ml_tpu.cli.score \
    --input-data-directories "$DATA/test.avro" \
    --model-input-directory "$ROOT/results/models/best" \
    --root-output-directory "$ROOT/scores" \
    --feature-shard-configurations \
        "name=globalShard,feature.bags=features,intercept=true" \
    --evaluators AUC

echo
echo "model dir:      $ROOT/results/models/best"
echo "train summary:  $ROOT/results/training-summary.json"
echo "score summary:  $ROOT/scores/scoring-summary.json"

#!/bin/bash
# GLMix (GAME) end to end: a fixed-effect coordinate plus a per-member
# random-effect coordinate trained by coordinate descent — the per-entity
# model structure from the GLMix paper (reference README.md:58-64), driven
# through the same CLI surface as the reference's GameTrainingDriver
# (coordinate mini-DSL per README.md:283-292).
#
# Usage: ./run_glmix.sh [working_root]
set -euo pipefail

ROOT="${1:-./photon-glmix-demo}"
REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)"
export PYTHONPATH="$REPO_DIR${PYTHONPATH:+:$PYTHONPATH}"
DATA="$ROOT/data"
mkdir -p "$DATA"

echo "== 1/3 generate dataset with 24 member entities =="
python "$REPO_DIR/examples/generate_dataset.py" "$DATA" --train 2400 --test 800 --entities 24
python -m photon_ml_tpu.cli.libsvm_to_avro --tag-comments "$DATA/train.libsvm" "$DATA/train.avro"
python -m photon_ml_tpu.cli.libsvm_to_avro --tag-comments "$DATA/test.libsvm" "$DATA/test.avro"

echo "== 2/3 train GAME: fixed effect + per-member random effect =="
python -m photon_ml_tpu.cli.train \
    --training-task LOGISTIC_REGRESSION \
    --input-data-directories "$DATA/train.avro" \
    --validation-data-directories "$DATA/test.avro" \
    --root-output-directory "$ROOT/results" \
    --override-output-directory \
    --feature-shard-configurations \
        "name=globalShard,feature.bags=features,intercept=true" \
    --coordinate-configurations \
        "name=global,feature.shard=globalShard,optimizer=LBFGS,tolerance=1.0E-7,max.iter=50,regularization=L2,reg.weights=1" \
        "name=per-member,random.effect.type=memberId,feature.shard=globalShard,optimizer=LBFGS,max.iter=30,regularization=L2,reg.weights=10,min.bucket=8" \
    --coordinate-descent-iterations 2 \
    --validation-evaluators AUC \
    --output-mode BEST

echo "== 3/3 score =="
python -m photon_ml_tpu.cli.score \
    --input-data-directories "$DATA/test.avro" \
    --model-input-directory "$ROOT/results/models/best" \
    --root-output-directory "$ROOT/scores" \
    --feature-shard-configurations \
        "name=globalShard,feature.bags=features,intercept=true" \
    --evaluators AUC

echo
echo "per-member models: $ROOT/results/models/best/random-effect/per-member"
echo "score summary:     $ROOT/scores/scoring-summary.json"

"""Generate a1a-like synthetic LibSVM datasets for the examples.

The reference's tutorial (README.md:307-345) downloads the `a1a` adult-income
dataset from the LibSVM site and pushes it through the drivers. This
environment has no network egress, so the examples generate a statistically
similar stand-in: 123 binary indicator features, ~14 active per row, a sparse
ground-truth weight vector, logistic response — same shape and sparsity as
a1a, fully deterministic.

Usage:
    python examples/generate_dataset.py OUTDIR [--train N] [--test N] [--entities K]

Writes OUTDIR/train.libsvm and OUTDIR/test.libsvm (labels in {-1,+1}, 1-based
indices, LibSVM text). With --entities > 0, rows also get a trailing
`# memberId=mK` comment consumed by the GLMix example's converter step.
"""

from __future__ import annotations

import argparse
import os

import numpy as np

DIM = 123  # a1a's feature count
ACTIVE_PER_ROW = 14  # a1a rows average ~13.9 active indicators


def generate(
    path: str, n: int, seed: int, entities: int = 0
) -> None:
    rng = np.random.default_rng(seed)
    w_rng = np.random.default_rng(12345)  # shared truth across splits
    w_true = np.where(
        w_rng.uniform(size=DIM) < 0.3, w_rng.normal(size=DIM) * 1.5, 0.0
    )
    bias = -0.5
    b_true = w_rng.normal(size=(max(entities, 1), 8)) * 1.0

    with open(path, "w") as f:
        for i in range(n):
            k = max(1, rng.poisson(ACTIVE_PER_ROW))
            cols = np.sort(rng.choice(DIM, size=min(k, DIM), replace=False))
            margin = w_true[cols].sum() + bias
            ent = int(rng.integers(0, entities)) if entities else -1
            if ent >= 0:
                re_cols = cols[cols < 8]
                margin += b_true[ent, re_cols].sum()
            p = 1.0 / (1.0 + np.exp(-margin))
            label = 1 if rng.uniform() < p else -1
            toks = " ".join(f"{c + 1}:1" for c in cols)
            tag = f" # memberId=m{ent}" if ent >= 0 else ""
            f.write(f"{label} {toks}{tag}\n")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("outdir")
    ap.add_argument("--train", type=int, default=1600)
    ap.add_argument("--test", type=int, default=800)
    ap.add_argument("--entities", type=int, default=0)
    args = ap.parse_args()
    os.makedirs(args.outdir, exist_ok=True)
    generate(os.path.join(args.outdir, "train.libsvm"), args.train, 0, args.entities)
    generate(os.path.join(args.outdir, "test.libsvm"), args.test, 1, args.entities)
    print(f"wrote {args.train}+{args.test} rows to {args.outdir}")


if __name__ == "__main__":
    main()

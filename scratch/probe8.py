"""Two-level kernels at bench scale on v5e: pack time + scan-timed passes."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from photon_ml_tpu.data.bucketed import pack_bucketed
from photon_ml_tpu.ops import pallas_sparse as ps

N, K, D = 1 << 20, 64, 16384
REPS = 8
rng = np.random.default_rng(0)
idx = rng.integers(0, D, size=(N, K)).astype(np.int64)
val = rng.normal(size=(N, K)).astype(np.float32)
u_np = rng.normal(size=N).astype(np.float32)
w_np = (rng.normal(size=D) * 0.1).astype(np.float32)

t0 = time.perf_counter()
rows = np.repeat(np.arange(N, dtype=np.int64), K)
bf = pack_bucketed(rows, idx.reshape(-1), val.reshape(-1), N, D)
print(f"pack: {time.perf_counter()-t0:.1f}s  {bf.density_report()}")

w = jnp.asarray(w_np); u = jnp.asarray(u_np)
jax.block_until_ready((bf.level1.packed, bf.level1.values))

def scan_time(name, call, vec):
    @jax.jit
    def f(x):
        def one(c, i):
            return c + jnp.sum(call(x * (1.0 + i * 1e-4))), None
        tot, _ = jax.lax.scan(one, 0.0, jnp.arange(REPS, dtype=jnp.float32))
        return tot
    try:
        float(f(vec))
    except Exception as e:
        print(f"{name}: FAIL {type(e).__name__} {str(e)[:200]}")
        return
    ent = np.random.default_rng()
    ts = []
    for r in range(3):
        t0 = time.perf_counter()
        float(f(vec * (1.0 + float(ent.uniform(1e-4, 1e-2)))))
        ts.append((time.perf_counter() - t0) / REPS)
    print(f"{name}: {min(ts)*1e3:.1f} ms/eval  (all {[f'{x*1e3:.1f}' for x in ts]})")

scan_time("matvec ", lambda x: ps.matvec(bf, x), w)
scan_time("rmatvec", lambda x: ps.rmatvec(bf, x), u)

# correctness on chip
ent = np.random.default_rng()
m = 1.0 + float(ent.uniform(1e-4, 1e-2))
z_k = np.asarray(ps.matvec(bf, w * m))
g_k = np.asarray(ps.rmatvec(bf, u * m))
z_ref = np.einsum("nk,nk->n", w_np[idx].astype(np.float64), val) * m
g_ref = np.zeros(D); np.add.at(g_ref, idx.reshape(-1), (val.astype(np.float64) * u_np[:, None]).reshape(-1))
g_ref *= m
print("z rel err:", np.abs(z_k - z_ref).max() / np.abs(z_ref).max())
print("g rel err:", np.abs(g_k - g_ref).max() / np.abs(g_ref).max())
print("done")

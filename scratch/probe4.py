"""Bisect: (a) is F1 really sub-ms (full-output checksum + grid scaling)?
(b) which construct crashes the Mosaic remote compiler?"""
import time
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N, K, D = 1 << 20, 64, 16384
HI, LO = D // 128, 128
TN = 128
E = K * TN

rng = np.random.default_rng(0)
idx_nk = rng.integers(0, D, size=(N, K)).astype(np.int32)
val_nk = rng.normal(size=(N, K)).astype(np.float32)
w_np = (rng.normal(size=(D,)) * 0.1).astype(np.float32)
idxT = jnp.asarray(idx_nk.T.copy())
valT = jnp.asarray(val_nk.T.copy())
w = jnp.asarray(w_np)
z_ref = np.einsum("nk,nk->n", w_np[idx_nk].astype(np.float64), val_nk)


def f1_kernel(idx_ref, val_ref, w2_ref, z_ref):
    idx = idx_ref[:]
    hi = jax.lax.shift_right_logical(idx, 7)
    lo = jax.lax.bitwise_and(idx, 127)
    acc = jnp.zeros((K, TN), jnp.float32)
    w2 = w2_ref[:]
    for j in range(HI):
        wrow = jax.lax.broadcast_in_dim(w2[j, :], (K, TN), (1,))
        g = jnp.take_along_axis(wrow, lo, axis=1)
        acc = acc + jnp.where(hi == j, g, 0.0)
    z_ref[:] = jnp.sum(acc * val_ref[:], axis=0, keepdims=True)


def make_f1(n_rows):
    @jax.jit
    def f1(idxT, valT, w):
        z = pl.pallas_call(
            f1_kernel,
            grid=(n_rows // TN,),
            in_specs=[
                pl.BlockSpec((K, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
                pl.BlockSpec((K, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
                pl.BlockSpec((HI, LO), lambda i: (0, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((1, n_rows), jnp.float32),
        )(idxT[:, :n_rows], valT[:, :n_rows], w.reshape(HI, LO))
        return jnp.sum(z), z[0, :5]

    return f1


for n_rows in (N // 8, N):
    f1 = make_f1(n_rows)
    jax.block_until_ready(f1(idxT, valT, w))
    ts = []
    for r in (1, 2, 3):
        wr = w * (1.0 + r * 1e-3)
        t0 = time.perf_counter()
        s, head = jax.block_until_ready(f1(idxT, valT, wr))
        ts.append(time.perf_counter() - t0)
    want = z_ref[:n_rows].sum() * (1.0 + 3 * 1e-3)
    print(
        f"F1 rows={n_rows}: {min(ts)*1e3:.2f} ms  checksum rel err "
        f"{abs(float(s) - want)/abs(want):.2e}  head err "
        f"{np.max(np.abs(np.asarray(head) - z_ref[:5]*(1+3e-3))):.2e}"
    )

# ---------------- construct bisection ----------------
def try_kernel(name, kernel, in_specs, out_spec, out_shape, args):
    try:
        out = pl.pallas_call(
            kernel, grid=(4,), in_specs=in_specs, out_specs=out_spec,
            out_shape=out_shape,
        )(*args)
        jax.block_until_ready(out)
        print(f"{name}: ok")
    except Exception as e:
        print(f"{name}: FAIL {type(e).__name__}: {str(e)[:160]}")


A8 = jnp.asarray(rng.normal(size=(32, 128)).astype(np.float32))
I8 = jnp.asarray(rng.integers(0, 128, size=(32, 128)).astype(np.int32))

spec = lambda s: pl.BlockSpec(s, lambda i: (0, 0), memory_space=pltpu.VMEM)

# t1: reshape (8,128)->(1024,1)
def t1(a_ref, o_ref):
    r = a_ref[:].reshape(1024, 1)
    o_ref[:] = jnp.sum(r) + jnp.zeros((1, 1))
try_kernel("t1 reshape (8,128)->(1024,1)", t1, [spec((8, 128))], spec((1, 1)), jax.ShapeDtypeStruct((1, 1), jnp.float32), (A8[:8],))

# t2: iota (1024,128) cmp col
def t2(i_ref, o_ref):
    col = i_ref[:].reshape(1024, 1)
    oh = (jax.lax.broadcasted_iota(jnp.int32, (1024, 128), 1) == col).astype(jnp.float32)
    o_ref[:] = jnp.sum(oh) + jnp.zeros((1, 1))
try_kernel("t2 iota cmp colvec (1024,128)", t2, [spec((8, 128))], spec((1, 1)), jax.ShapeDtypeStruct((1, 1), jnp.float32), (I8[:8],))

# t2b: iota cmp with (S,128)-shaped hi (no reshape to column)
def t2b(i_ref, o_ref):
    hi = i_ref[:]
    oh = (jax.lax.broadcasted_iota(jnp.int32, (32, 128), 1) == hi).astype(jnp.float32)
    o_ref[:] = jnp.sum(oh) + jnp.zeros((1, 1))
try_kernel("t2b iota cmp same-shape (32,128)", t2b, [spec((32, 128))], spec((1, 1)), jax.ShapeDtypeStruct((1, 1), jnp.float32), (I8,))

# t3: dot_general contracting dim 0
def t3(a_ref, b_ref, o_ref):
    o_ref[:] = jax.lax.dot_general(
        a_ref[:], b_ref[:], dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
try_kernel("t3 dotT (32,128)x(32,128)", t3, [spec((32, 128)), spec((32, 128))], spec((128, 128)), jax.ShapeDtypeStruct((128, 128), jnp.float32), (A8, A8))

# t4: plain dot (128,32)@(32,128)
def t4(a_ref, b_ref, o_ref):
    o_ref[:] = jnp.dot(a_ref[:].T, b_ref[:], preferred_element_type=jnp.float32)
try_kernel("t4 a.T@b", t4, [spec((32, 128)), spec((32, 128))], spec((128, 128)), jax.ShapeDtypeStruct((128, 128), jnp.float32), (A8, A8))

# t5: take_along_axis with broadcast_in_dim indices
def t5(a_ref, i_ref, o_ref):
    lob = jax.lax.broadcast_in_dim(i_ref[:][:, 0], (32, 128), (0,))
    g = jnp.take_along_axis(a_ref[:], lob, axis=1)
    o_ref[:] = jnp.sum(g) + jnp.zeros((1, 1))
try_kernel("t5 take broadcast idx", t5, [spec((32, 128)), spec((32, 128))], spec((1, 1)), jax.ShapeDtypeStruct((1, 1), jnp.float32), (A8, I8))

# t6: dot with one-hot f32 built from iota (the F2/B1 core)
def t6(i_ref, a_ref, o_ref):
    oh = (jax.lax.broadcasted_iota(jnp.int32, (32, 128), 1) == i_ref[:]).astype(jnp.float32)
    o_ref[:] = jax.lax.dot_general(
        oh, a_ref[:], dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
try_kernel("t6 onehot dotT", t6, [spec((32, 128)), spec((32, 128))], spec((128, 128)), jax.ShapeDtypeStruct((128, 128), jnp.float32), (I8, A8))

# t7: accumulate output across grid with pl.when
def t7(a_ref, o_ref):
    i = pl.program_id(0)
    @pl.when(i == 0)
    def _():
        o_ref[:] = a_ref[:]
    @pl.when(i > 0)
    def _():
        o_ref[:] += a_ref[:]
try_kernel("t7 grid accum", t7, [spec((32, 128))], spec((32, 128)), jax.ShapeDtypeStruct((32, 128), jnp.float32), (A8,))
print("done")

import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp
from photon_ml_tpu.data.bucketed import pack_bucketed, BucketedSparseFeatures
from photon_ml_tpu.ops import pallas_sparse as ps

N, K, D = 1 << 20, 64, 16384
rng = np.random.default_rng(0)
idx = rng.integers(0, D, size=(N, K)).astype(np.int64)
val = rng.normal(size=(N, K)).astype(np.float32)
w_np = (rng.normal(size=D) * 0.1).astype(np.float32)
t0 = time.perf_counter()
rows = np.repeat(np.arange(N, dtype=np.int64), K)
bf = pack_bucketed(rows, idx.reshape(-1), val.reshape(-1), N, D)
print(f"pack: {time.perf_counter()-t0:.1f}s  {bf.density_report()}", flush=True)
w = jnp.asarray(w_np)

empty = bf.overflow_vals[:0]
bf1 = BucketedSparseFeatures(level1=bf.level1, level2=None,
    overflow_rows=bf.overflow_rows[:0], overflow_cols=bf.overflow_cols[:0],
    overflow_vals=empty, n_rows=N, dim=D)
t0 = time.perf_counter()
z = float(jnp.sum(ps.matvec(bf1, w)))
print(f"L1 matvec compile+run: {time.perf_counter()-t0:.1f}s", flush=True)

bf2 = BucketedSparseFeatures(level1=bf.level2, level2=None,
    overflow_rows=bf.overflow_rows[:0], overflow_cols=bf.overflow_cols[:0],
    overflow_vals=empty, n_rows=N, dim=D)
t0 = time.perf_counter()
z2 = float(jnp.sum(ps.matvec(bf2, w)))
print(f"L2 matvec compile+run: {time.perf_counter()-t0:.1f}s", flush=True)
print("done", flush=True)

"""RTT-amortized timing: scan 8 perturbed kernel evals inside one jit.
Variants: V0 full G=1, V5 no-scatter G=1, V4 G=8 full, V4 G=8 no-scatter,
plus rmatvec G=8. Also measures bare RTT."""
import sys, time, functools
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from photon_ml_tpu.data.bucketed import pack_bucketed

N, K, D = 1 << 20, 64, 16384
RT = 16
REPS = 8
rng = np.random.default_rng(0)
idx = rng.integers(0, D, size=(N, K)).astype(np.int64)
val = rng.normal(size=(N, K)).astype(np.float32)
rows = np.repeat(np.arange(N, dtype=np.int64), K)
bf = pack_bucketed(rows, idx.reshape(-1), val.reshape(-1), N, D)
T, B, spv = bf.num_tiles, bf.num_buckets, bf.spv
w = jnp.asarray((rng.normal(size=D) * 0.1).astype(np.float32))
u = jnp.asarray(rng.normal(size=N).astype(np.float32))
PREC = jax.lax.Precision.DEFAULT

# bare RTT
fid = jax.jit(lambda x: x + 1.0)
float(fid(1.0))
t0 = time.perf_counter(); [float(fid(float(i))) for i in range(5)]
print(f"RTT per tiny call: {(time.perf_counter()-t0)/5*1e3:.1f} ms")

def bcast(row, s):
    return jax.lax.broadcast_in_dim(row[0, :], (s, 128), (1,))

def fwd_call(G, scatter):
    def kern(pk_ref, val_ref, w_ref, z_ref):
        bg = pl.program_id(1)
        zc = jnp.zeros((RT, 128), jnp.float32)
        for gi in range(G):
            pk = pk_ref[pl.ds(gi * spv, spv), :] if G > 1 else pk_ref[:]
            vv = val_ref[pl.ds(gi * spv, spv), :] if G > 1 else val_ref[:]
            rl = jax.lax.shift_right_logical(pk, 7)
            lane = jax.lax.bitwise_and(pk, 127)
            wb = bcast(w_ref[pl.ds(bg * G + gi, 1), :], spv)
            p = jnp.take_along_axis(wb, lane, axis=1) * vv
            if not scatter:
                zc = zc + jnp.sum(p) * jnp.float32(1e-9)
                continue
            for s in range(spv):
                rl_row = rl[s : s + 1, :]
                rhi = jax.lax.shift_right_logical(rl_row, 7)
                rlo = jax.lax.bitwise_and(rl_row, 127)
                orh = jax.lax.broadcasted_iota(jnp.int32, (RT, 128), 0) == bcast(rhi, RT)
                p1 = jnp.where(orh, bcast(p[s : s + 1, :], RT), 0.0)
                orlt = (
                    jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0) == bcast(rlo, 128)
                ).astype(jnp.float32)
                zc = zc + jax.lax.dot_general(
                    p1, orlt, dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32, precision=PREC)
        @pl.when(bg == 0)
        def _():
            z_ref[:] = zc
        @pl.when(bg > 0)
        def _():
            z_ref[:] += zc

    return pl.pallas_call(
        kern,
        grid=(T, B // G),
        in_specs=[
            pl.BlockSpec((G * spv, 128), lambda t, bg: (t * (B // G) + bg, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((G * spv, 128), lambda t, bg: (t * (B // G) + bg, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((B, 128), lambda t, bg: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((RT, 128), lambda t, bg: (t, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((T * RT, 128), jnp.float32),
    )

def bwd_call(G):
    def kern(pk_ref, val_ref, u_ref, g_ref):
        bg = pl.program_id(0)
        t = pl.program_id(1)
        u2 = u_ref[:]
        for gi in range(G):
            pk = pk_ref[pl.ds(gi * spv, spv), :] if G > 1 else pk_ref[:]
            vv = val_ref[pl.ds(gi * spv, spv), :] if G > 1 else val_ref[:]
            rl = jax.lax.shift_right_logical(pk, 7)
            lane = jax.lax.bitwise_and(pk, 127)
            gc = jnp.zeros((1, 128), jnp.float32)
            for s in range(spv):
                rl_row = rl[s : s + 1, :]
                rhi = jax.lax.shift_right_logical(rl_row, 7)
                rlo = jax.lax.bitwise_and(rl_row, 127)
                tu = jnp.take_along_axis(u2, bcast(rlo, RT), axis=1)
                orh = jax.lax.broadcasted_iota(jnp.int32, (RT, 128), 0) == bcast(rhi, RT)
                u_sel = jnp.sum(jnp.where(orh, tu, 0.0), axis=0, keepdims=True)
                a = u_sel * vv[s : s + 1, :]
                olt = (
                    jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0) == bcast(rlo * 0 + jax.lax.bitwise_and(pk[s:s+1,:], 127), 128)
                ).astype(jnp.float32)
                gc = gc + jax.lax.dot_general(
                    a, olt, dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32, precision=PREC)
            bidx = bg * G + gi
            @pl.when(t == 0)
            def _():
                g_ref[pl.ds(bidx, 1), :] = gc
            @pl.when(t > 0)
            def _():
                g_ref[pl.ds(bidx, 1), :] += gc

    return pl.pallas_call(
        kern,
        grid=(B // G, T),
        in_specs=[
            pl.BlockSpec((G * spv, 128), lambda bg, t: (t * (B // G) + bg, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((G * spv, 128), lambda bg, t: (t * (B // G) + bg, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((RT, 128), lambda bg, t: (t, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((B, 128), lambda bg, t: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, 128), jnp.float32),
    )

def scan_time(name, call, vec, transform):
    """call(pk, val, x) -> array; scan REPS with x perturbed per rep."""
    @jax.jit
    def f(pk, v, x):
        def one(c, i):
            return c + jnp.sum(call(pk, v, transform(x * (1.0 + i * 1e-4)))), None
        tot, _ = jax.lax.scan(one, 0.0, jnp.arange(REPS, dtype=jnp.float32))
        return tot
    try:
        float(f(bf.packed, bf.values, vec))
    except Exception as e:
        print(f"{name}: FAIL {str(e)[:200]}")
        return
    ent = np.random.default_rng()
    ts = []
    for r in range(3):
        xr = vec * (1.0 + float(ent.uniform(1e-4, 1e-2)))
        t0 = time.perf_counter()
        float(f(bf.packed, bf.values, xr))
        ts.append((time.perf_counter() - t0) / REPS)
    print(f"{name}: {min(ts)*1e3:.1f} ms/eval  (all {[f'{x*1e3:.1f}' for x in ts]})")

scan_time("fwd G=1 full      ", lambda pk, v, w2: fwd_call(1, True)(pk, v, w2), w, lambda x: x.reshape(B, 128))
scan_time("fwd G=1 no-scatter", lambda pk, v, w2: fwd_call(1, False)(pk, v, w2), w, lambda x: x.reshape(B, 128))
scan_time("fwd G=8 full      ", lambda pk, v, w2: fwd_call(8, True)(pk, v, w2), w, lambda x: x.reshape(B, 128))
scan_time("fwd G=8 no-scatter", lambda pk, v, w2: fwd_call(8, False)(pk, v, w2), w, lambda x: x.reshape(B, 128))
scan_time("fwd G=32 full     ", lambda pk, v, w2: fwd_call(32, True)(pk, v, w2), w, lambda x: x.reshape(B, 128))
scan_time("bwd G=8 full      ", lambda pk, v, u2: bwd_call(8)(pk, v, u2), u, lambda x: jnp.pad(x, (0, T * 2048 - N)).reshape(T * RT, 128))
print("done")

"""TPU probe: sparse kernels with row-aligned vs legacy feature-lane layout.

Within-run comparison (tunnel variance up to 4x between runs): same COO,
both layouts packed, matvec / rmatvec / fused objective timed per pass with
the bench protocol (combining-scalar fetch, rtt subtracted, perturbed
inputs per rep).
"""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

from photon_ml_tpu.data.bucketed import pack_bucketed
from photon_ml_tpu.ops import pallas_sparse
from photon_ml_tpu.ops.losses import LOGISTIC

t0 = time.perf_counter()
def mark(m):
    sys.stderr.write(f"+{time.perf_counter()-t0:.1f}s {m}\n"); sys.stderr.flush()

mark(f"backend {jax.devices()[0].platform}")
n, d, k = 1 << 20, 16384, 64
rng = np.random.default_rng(7)
rows = np.repeat(np.arange(n, dtype=np.int64), k)
cols = rng.integers(0, d, size=n * k).astype(np.int64)
vals = rng.normal(size=n * k).astype(np.float32)
y = (rng.uniform(size=n) > 0.5).astype(np.float32)

@jax.jit
def _force_sum(parts):
    return sum(parts[1:], parts[0])

def _force(out):
    leaves = [x for x in jax.tree_util.tree_leaves(out) if hasattr(x, "dtype")]
    return float(_force_sum(tuple(jnp.sum(x.astype(jnp.float32)) for x in leaves)))

_force(jnp.ones(2))
ts = [0.0] * 5
for i in range(5):
    tt = time.perf_counter(); _force(jnp.ones(4) * (i + 1)); ts[i] = time.perf_counter() - tt
rtt = min(ts)
mark(f"rtt {rtt*1e3:.0f} ms")

y_d = jnp.asarray(y)
zeros = jnp.zeros(n, jnp.float32)
ones = jnp.ones(n, jnp.float32)

w_fix = jnp.asarray(rng.normal(size=d).astype(np.float32) * 0.1)
u_fix = jnp.asarray(rng.normal(size=n).astype(np.float32))


def run(row_aligned):
    bf = pack_bucketed(rows, cols, vals, n, d, row_aligned=row_aligned)
    rep = bf.density_report()
    mark(f"aligned={row_aligned} packed: {rep}")
    w, u = w_fix, u_fix
    out = {}
    REPS = 8
    for name, fn in [
        ("matvec", lambda i: pallas_sparse.matvec(bf, w + i * 1e-6)),
        ("rmatvec", lambda i: pallas_sparse.rmatvec(bf, u + i * 1e-6)),
        ("fused", lambda i: pallas_sparse.fused_value_gradient_sums(
            LOGISTIC, w + i * 1e-6, jnp.zeros(()), bf, y_d, zeros, ones)),
    ]:
        _force(fn(-1))  # compile
        walls = []
        for r in range(3):
            tt = time.perf_counter()
            for i in range(REPS):
                o = fn(r * REPS + i)
            _force(o)
            walls.append(max((time.perf_counter() - tt - rtt) / REPS, 1e-9))
        out[name] = min(walls)
        mark(f"aligned={row_aligned} {name}: {out[name]*1e3:.1f} ms/pass")
    # numeric check vs f64 host
    z = np.asarray(pallas_sparse.matvec(bf, w))
    g = np.asarray(pallas_sparse.rmatvec(bf, u))
    return out, rep, z, g

res_new, rep_new, z_new, g_new = run(True)
res_old, rep_old, z_old, g_old = run(False)
print("within-run ratios (legacy / row-aligned):")
for kk in res_new:
    print(f"  {kk}: {res_old[kk]/res_new[kk]:.2f}x  ({res_old[kk]*1e3:.1f} -> {res_new[kk]*1e3:.1f} ms)")
print(f"pad blowup: legacy {rep_old['pad_blowup']:.3f} vs aligned {rep_new['pad_blowup']:.3f}")
print(f"matvec agreement: {np.max(np.abs(z_new - z_old)):.2e}; rmatvec: {np.max(np.abs(g_new - g_old)):.2e}")

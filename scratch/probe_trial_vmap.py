"""Probe: is vmap over a trial axis (reg_weight, w0, offsets) bitwise-equal
per-trial to the unbatched solve? And same question for lax.scan over trials.
Run: JAX_PLATFORMS=cpu python scratch/probe_trial_vmap.py
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np
import jax
import jax.numpy as jnp

from photon_ml_tpu.data.containers import LabeledData
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.optimize import problem
from photon_ml_tpu.optimize.config import (
    L2,
    CoordinateOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.types import TaskType
import dataclasses

rng = np.random.default_rng(0)
n, d = 512, 12
X = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
y = jnp.asarray((rng.uniform(size=n) > 0.5).astype(np.float32))
wts = jnp.ones((n,), jnp.float32)
loss = loss_for_task(TaskType.LOGISTIC_REGRESSION)
cfg = CoordinateOptimizationConfig(
    optimizer=OptimizerConfig(max_iterations=25, tolerance=1e-7),
    regularization=L2,
    reg_weight=0.0,
)

def traced_cfg(rw):
    return dataclasses.replace(cfg, reg_weight=rw)


@jax.jit
def solve_one(offsets, w0, rw):
    data = LabeledData(X, y, offsets, wts)
    return problem.solve(loss, data, traced_cfg(rw), w0, None, use_pallas=False)


@jax.jit
def solve_vmap(offsets_k, w0_k, rw_k):
    def one(o, w0, rw):
        data = LabeledData(X, y, o, wts)
        return problem.solve(loss, data, traced_cfg(rw), w0, None, use_pallas=False)

    return jax.vmap(one)(offsets_k, w0_k, rw_k)


@jax.jit
def solve_scan(offsets_k, w0_k, rw_k):
    def step(carry, xs):
        o, w0, rw = xs
        data = LabeledData(X, y, o, wts)
        res = problem.solve(loss, data, traced_cfg(rw), w0, None, use_pallas=False)
        return carry, res

    _, res = jax.lax.scan(step, 0, (offsets_k, w0_k, rw_k))
    return res


k = 5
rws = jnp.asarray([0.1, 1.0, 10.0, 100.0, 3.0], jnp.float32)
offs = jnp.asarray(rng.normal(size=(k, n)).astype(np.float32) * 0.1)
w0s = jnp.zeros((k, d), jnp.float32)

serial = [solve_one(offs[i], w0s[i], rws[i]) for i in range(k)]
vm = solve_vmap(offs, w0s, rws)
sc = solve_scan(offs, w0s, rws)

for name, batched in [("vmap", vm), ("scan", sc)]:
    eq_c = all(
        np.array_equal(np.asarray(serial[i].coefficients), np.asarray(batched.coefficients[i]))
        for i in range(k)
    )
    eq_i = all(
        np.array_equal(np.asarray(serial[i].iterations), np.asarray(batched.iterations[i]))
        for i in range(k)
    )
    md = max(
        float(np.abs(np.asarray(serial[i].coefficients) - np.asarray(batched.coefficients[i])).max())
        for i in range(k)
    )
    print(f"{name}: coeff_bitwise={eq_c} iters_equal={eq_i} maxdiff={md:.3e}")

# Also: nested vmap (trial x entity) vs single vmap (entity) — the RE case.
E, S = 6, 32
Xe = jnp.asarray(rng.normal(size=(E, S, d)).astype(np.float32))
ye = jnp.asarray((rng.uniform(size=(E, S)) > 0.5).astype(np.float32))
we = jnp.ones((E, S), jnp.float32)


@jax.jit
def re_one(offs_e, w0_e, rw):
    def one(Xi, yi, oi, wi, w0i):
        data = LabeledData(Xi, yi, oi, wi)
        return problem.solve(loss, data, traced_cfg(rw), w0i, None, use_pallas=False)

    return jax.vmap(one)(Xe, ye, offs_e, we, w0_e)


@jax.jit
def re_trials(offs_ke, w0_ke, rw_k):
    return jax.vmap(re_one)(offs_ke, w0_ke, rw_k)


@jax.jit
def re_trials_scan(offs_ke, w0_ke, rw_k):
    def step(carry, xs):
        o, w0, rw = xs
        return carry, re_one(o, w0, rw)

    _, res = jax.lax.scan(step, 0, (offs_ke, w0_ke, rw_k))
    return res


offs_ke = jnp.asarray(rng.normal(size=(k, E, S)).astype(np.float32) * 0.1)
w0_ke = jnp.zeros((k, E, d), jnp.float32)
serial_re = [re_one(offs_ke[i], w0_ke[i], rws[i]) for i in range(k)]
vm_re = re_trials(offs_ke, w0_ke, rws)
sc_re = re_trials_scan(offs_ke, w0_ke, rws)
for name, batched in [("re_vmap", vm_re), ("re_scan", sc_re)]:
    eq_c = all(
        np.array_equal(np.asarray(serial_re[i].coefficients), np.asarray(batched.coefficients[i]))
        for i in range(k)
    )
    md = max(
        float(np.abs(np.asarray(serial_re[i].coefficients) - np.asarray(batched.coefficients[i])).max())
        for i in range(k)
    )
    print(f"{name}: coeff_bitwise={eq_c} maxdiff={md:.3e}")

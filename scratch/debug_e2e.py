"""Find the e2e OOM stage on the TPU at reduced scale."""
import os
import sys
import tempfile
import time

sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp

import photon_ml_tpu.io.avro_data as ad
from photon_ml_tpu.data.game_dataset import FixedEffectDataConfig, RandomEffectDataConfig
from photon_ml_tpu.estimators.game_estimator import GameEstimator
from photon_ml_tpu.evaluation.suite import EvaluationSuite, EvaluatorType
from photon_ml_tpu.native.avro_writer import write_training_examples_columnar as wcol
from photon_ml_tpu.transformers.game_transformer import GameTransformer
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.optimize.config import L2, CoordinateOptimizationConfig, OptimizerConfig

rows = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
t00 = time.perf_counter()
def mark(m):
    print(f"+{time.perf_counter()-t00:.1f}s {m}", flush=True)

n_users, n_movies, k, d = max(200, rows // 145), max(50, rows // 740), 8, 200
rng = np.random.default_rng(23)
users = rng.integers(0, n_users, size=rows)
movies = rng.integers(0, n_movies, size=rows)
indptr = np.arange(rows + 1, dtype=np.int64) * k
ids = rng.integers(0, d, size=rows * k).astype(np.int32)
vals = rng.normal(size=rows * k)
w_true = rng.normal(size=d) * 0.3
margin = (vals * w_true[ids]).reshape(rows, k).sum(1) + rng.normal(size=n_users)[users] * 0.7 + rng.normal(size=n_movies)[movies] * 0.7
labels = (rng.uniform(size=rows) < 1 / (1 + np.exp(-margin))).astype(np.float64)
tags = np.char.add(np.char.add(users.astype(str), ":"), movies.astype(str))
td = tempfile.mkdtemp()
wcol(os.path.join(td, "p0.avro"), labels, indptr, ids, vals, [f"f{i}" for i in range(d)], tag_key="umId", tag_values=tags)
mark("written")
ds, _ = ad.read_game_dataset(td, {"g": ad.FeatureShardConfig(("features",), True)}, id_tag_fields=["umId"])
mark(f"ingested {ds.num_samples}")
um = np.char.partition(ds.id_tags["umId"].astype(str), ":")
ds.id_tags["userId"] = um[:, 0]
ds.id_tags["movieId"] = um[:, 2]
mark("tags split")
est = GameEstimator(
    TaskType.LOGISTIC_REGRESSION,
    {
        "global": FixedEffectDataConfig("g"),
        "per-user": RandomEffectDataConfig("userId", "g", active_upper_bound=256, min_bucket=8),
        "per-movie": RandomEffectDataConfig("movieId", "g", active_upper_bound=512, min_bucket=8),
    },
    coordinate_descent_iterations=1,
)
cfg = lambda it, w: CoordinateOptimizationConfig(optimizer=OptimizerConfig(max_iterations=it, tolerance=1e-6), regularization=L2, reg_weight=w)
results = est.fit(ds, None, [{"global": cfg(10, 1.0), "per-user": cfg(5, 10.0), "per-movie": cfg(5, 10.0)}])
mark("trained")
scores = GameTransformer(results[0].model, est.scoring_specs(), est.task).transform(ds)
suite = EvaluationSuite([EvaluatorType("AUC")], jnp.asarray(labels.astype(np.float32)))
res = suite.evaluate(scores.scores)
mark(f"AUC {float(res.primary_value):.4f}")

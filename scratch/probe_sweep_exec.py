"""Probe: SweepExecutor stacked vs serial bitwise parity on a small GLMix
problem (FE + RE coordinates), cold and warm-started rounds.
Run: JAX_PLATFORMS=cpu python scratch/probe_sweep_exec.py
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np
import jax.numpy as jnp

from photon_ml_tpu.data.game_dataset import (
    GameDataset,
    RandomEffectDataConfig,
    build_random_effect_dataset,
)
from photon_ml_tpu.evaluation.suite import EvaluationSuite, EvaluatorType
from photon_ml_tpu.game.coordinate import (
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.hyperparameter.sweep import SweepExecutor
from photon_ml_tpu.optimize.config import (
    L2,
    CoordinateOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.transformers.game_transformer import _fe_margins, _re_margins
from photon_ml_tpu.types import TaskType, VarianceComputationType

rng = np.random.default_rng(0)


def make_data(n, n_entities, d_fixed=5, d_re=3, seed=0):
    r = np.random.default_rng(seed)
    entity = r.integers(0, n_entities, size=n)
    Xf = r.normal(size=(n, d_fixed)).astype(np.float32)
    Xe = r.normal(size=(n, d_re)).astype(np.float32)
    w = r.normal(size=d_fixed).astype(np.float32)
    u = r.normal(size=(n_entities, d_re)).astype(np.float32)
    margin = Xf @ w + np.einsum("nd,nd->n", Xe, u[entity])
    y = (r.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(np.float32)
    return GameDataset.build(
        {"global": jnp.asarray(Xf), "per_entity": jnp.asarray(Xe)},
        y,
        id_tags={"entityId": entity},
    ), entity


def cfg(variance=VarianceComputationType.NONE):
    return CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=30, tolerance=1e-7),
        regularization=L2,
        reg_weight=0.0,
        variance_computation=variance,
    )


ds, entity = make_data(256, 10, seed=1)
val, val_entity = make_data(128, 10, seed=2)
red = build_random_effect_dataset(
    ds, RandomEffectDataConfig("entityId", "per_entity", min_bucket=8)
)
task = TaskType.LOGISTIC_REGRESSION
fixed = FixedEffectCoordinate(ds, "global", cfg(), task)
rand = RandomEffectCoordinate(ds, red, cfg(), task)
coords = {"fixed": fixed, "re": rand}

suite = EvaluationSuite([EvaluatorType("AUC")], val.labels)
val_rows = np.asarray(
    [red.entity_index.get(e, red.num_entities) for e in val_entity], np.int32
)
val_rows = jnp.asarray(val_rows)
val_Xf = val.shards["global"]
val_Xe = val.shards["per_entity"]

scorers = {
    "fixed": lambda a: _fe_margins(val_Xf, a["w"], None),
    "re": lambda a: _re_margins(val_Xe, val_rows, a["m"], None),
}


def make_exec(mode, warm_start=True):
    return SweepExecutor(
        coords,
        ["fixed", "re"],
        num_iterations=2,
        task=task,
        base_reg_weights={"fixed": 1.0, "re": 1.0},
        validation_suite=suite,
        validation_offsets=val.offsets,
        num_validation_samples=val.num_samples,
        trial_scorers=scorers,
        maximize=True,
        seed=3,
        mode=mode,
        warm_start=warm_start,
    )


points = np.array([[0.1, 0.5], [1.0, 2.0], [10.0, 0.01]])
points2 = np.array([[0.5, 0.5], [3.0, 0.3]])

for ws in (False, True):
    ex_serial = make_exec("serial", ws)
    ex_stacked = make_exec("stacked", ws)
    vs1 = ex_serial.evaluate_batch(points)
    vt1 = ex_stacked.evaluate_batch(points)
    ms1 = ex_serial.last_trial_models
    mt1 = ex_stacked.last_trial_models
    vs2 = ex_serial.evaluate_batch(points2)
    vt2 = ex_stacked.evaluate_batch(points2)
    ms2 = ex_serial.last_trial_models
    mt2 = ex_stacked.last_trial_models

    def cmp(ms, mt, tag):
        ok = True
        for i, (a, b) in enumerate(zip(ms, mt)):
            for cid in a:
                for name in a[cid]:
                    x, z = a[cid][name], b[cid][name]
                    if x is None and z is None:
                        continue
                    same = np.array_equal(np.asarray(x), np.asarray(z))
                    if not same:
                        md = float(
                            np.abs(np.asarray(x) - np.asarray(z)).max()
                        )
                        print(f"  {tag} trial{i} {cid}/{name}: MISMATCH maxdiff={md:.3e}")
                        ok = False
        return ok

    print(f"warm_start={ws}")
    print("  round1 models bitwise:", cmp(ms1, mt1, "r1"))
    print("  round1 values:", vs1, vt1, "equal:", vs1 == vt1)
    print("  round2 models bitwise:", cmp(ms2, mt2, "r2"))
    print("  round2 values:", vs2, vt2, "equal:", vs2 == vt2)

"""fn_evals stability across tile sizes/seeds for the dense LBFGS solve."""
import os
import sys
import time

sys.path.insert(0, "/root/repo")
tile = sys.argv[1] if len(sys.argv) > 1 else "512"
os.environ["PHOTON_PALLAS_TILE"] = tile

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.containers import LabeledData
from photon_ml_tpu.optimize import problem
from photon_ml_tpu.optimize.config import L2, CoordinateOptimizationConfig, OptimizerConfig
from photon_ml_tpu.ops.losses import LOGISTIC

n, d = 1 << 20, 512
cfg = CoordinateOptimizationConfig(
    optimizer=OptimizerConfig(max_iterations=20, tolerance=1e-7),
    regularization=L2,
    reg_weight=10.0,
)

@jax.jit
def solve(X, y, off, wt, w0):
    return problem.solve(
        LOGISTIC, LabeledData(X, y, off, wt), cfg, w0, None, use_pallas=True
    )

for seed in (0, 1, 2):
    key = jax.random.PRNGKey(seed)
    k1, k2, k3 = jax.random.split(key, 3)
    w_true = jax.random.normal(k1, (d,), jnp.float32) * 0.2
    X = jax.random.normal(k2, (n, d), jnp.float32)
    margin = X @ w_true
    y = (jax.random.uniform(k3, (n,)) < jax.nn.sigmoid(margin)).astype(jnp.float32)
    off = jnp.zeros(n); wt = jnp.ones(n); w0 = jnp.zeros(d)
    jax.block_until_ready(X)
    t0 = time.perf_counter()
    res = solve(X, y, off, wt, w0)
    it = int(np.asarray(res.iterations)); fe = int(np.asarray(res.fn_evals))
    loss = float(np.asarray(res.loss)); rsn = int(np.asarray(res.reason))
    wall = time.perf_counter() - t0
    print(f"tile={tile} seed={seed}: iters={it} fn_evals={fe} loss={loss:.6f} reason={rsn} wall={wall:.2f}s", flush=True)

"""Round-4 ADVICE-fix drive: fused-sparse gate, avro UB hardening, zlib fallback."""
import json
import os
import struct
import sys

import numpy as np

# ---------------------------------------------------------------- part 1
# Fused sparse objective engages in production coordinate training.
import jax.numpy as jnp

from photon_ml_tpu.data.containers import SparseFeatures
from photon_ml_tpu.data.bucketed import BucketedSparseFeatures
from photon_ml_tpu.data.game_dataset import GameDataset
from photon_ml_tpu.ops import pallas_glm, pallas_sparse
from photon_ml_tpu.optimize.config import L2, CoordinateOptimizationConfig, OptimizerConfig
from photon_ml_tpu.types import TaskType

pallas_glm.FORCE_INTERPRET = True

calls = {"fused": 0}
_orig = pallas_sparse.fused_value_gradient_sums


def _counting(*a, **k):
    calls["fused"] += 1
    return _orig(*a, **k)


pallas_sparse.fused_value_gradient_sums = _counting
# objective.py imported pallas_sparse as a module, so the monkeypatch is seen.

from photon_ml_tpu.game.coordinate import FixedEffectCoordinate

rng = np.random.default_rng(0)
n, d, k = 9000, 200, 6
idx = rng.integers(0, d, size=(n, k)).astype(np.int32)
val = rng.normal(size=(n, k)).astype(np.float32)
sp = SparseFeatures(jnp.asarray(idx), jnp.asarray(val), d)
w_true = rng.normal(size=d) * 0.3
M = np.zeros((n, d))
np.add.at(M, (np.repeat(np.arange(n), k), idx.ravel()), val.ravel())
y = (rng.uniform(size=n) < 1 / (1 + np.exp(-M @ w_true))).astype(np.float32)
ds = GameDataset.build({"s": sp}, y)
cfg = CoordinateOptimizationConfig(
    optimizer=OptimizerConfig(max_iterations=20, tolerance=1e-8),
    regularization=L2,
    reg_weight=1.0,
)
coord = FixedEffectCoordinate(ds, "s", cfg, TaskType.LOGISTIC_REGRESSION)
assert isinstance(coord._features, BucketedSparseFeatures), type(coord._features)
assert coord._use_pallas is None, f"gate still {coord._use_pallas!r}"
model, res = coord.train(ds.offsets)
assert calls["fused"] > 0, "fused kernel never traced in coordinate training"
print(f"PART1 OK: _use_pallas=None, fused traced {calls['fused']}x, loss={float(res.loss):.5f}")

# cross-check vs ELL/XLA path
pallas_glm.set_enabled(False)
coord_ell = FixedEffectCoordinate(ds, "s", cfg, TaskType.LOGISTIC_REGRESSION)
model_ell, _ = coord_ell.train(ds.offsets)
pallas_glm.set_enabled(True)
np.testing.assert_allclose(
    np.asarray(model.coefficients.means),
    np.asarray(model_ell.coefficients.means),
    rtol=5e-3, atol=5e-4,
)
print("PART1 OK: fused-path optimum matches ELL path")

# ---------------------------------------------------------------- part 2
# Native decoder: INT64_MIN / oversized block counts reject gracefully.
from photon_ml_tpu.io import avro_fast
import photon_ml_tpu.io.avro_data as ad
from photon_ml_tpu.native.build import load_native

assert load_native() is not None, "native lib must be available for this drive"


def zz(v):  # zigzag varint
    u = (v << 1) ^ (v >> 63) if v >= 0 else ((-v) << 1) - 1
    u &= (1 << 64) - 1
    out = b""
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out += bytes([b | 0x80])
        else:
            out += bytes([b])
            return out


def avro_str(s):
    b = s.encode()
    return zz(len(b)) + b


SCHEMA = json.dumps({
    "type": "record", "name": "T", "fields": [
        {"name": "label", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": {
            "type": "record", "name": "F", "fields": [
                {"name": "name", "type": "string"},
                {"name": "term", "type": "string"},
                {"name": "value", "type": "double"}]}}},
    ]})
SYNC = bytes(range(16))


def container(body, count=1):
    hdr = b"Obj\x01"
    hdr += zz(2)
    hdr += avro_str("avro.schema") + zz(len(SCHEMA)) + SCHEMA.encode()
    hdr += avro_str("avro.codec") + zz(len(b"null")) + b"null"
    hdr += zz(0)  # end metadata map
    hdr += SYNC
    return hdr + zz(count) + zz(len(body)) + body + SYNC


def feat(name, v):
    return avro_str(name) + avro_str("") + struct.pack("<d", v)


good_body = struct.pack("<d", 1.0) + zz(2) + feat("a", 1.0) + feat("b", 2.0) + zz(0)
tmp = "/tmp/drive_avro"
os.makedirs(tmp, exist_ok=True)
good = os.path.join(tmp, "good.avro")
with open(good, "wb") as f:
    f.write(container(good_body))

cfgs = {"g": ad.FeatureShardConfig(("features",), False)}
cols = ad.InputColumnNames()
ok = avro_fast.try_read_native([good], cfgs, None, [], cols, ad.LABEL)
assert ok is not None, "valid hand-built file must decode natively"
dsg, mapsg = ok
assert dsg.num_samples == 1 and mapsg["g"].size == 2
print("PART2 OK: valid hand-built container decodes natively")

# INT64_MIN feature-array block count (zigzag = 2^64-1): previously UB negation
int64min_varint = zz(-(2**63))
assert len(int64min_varint) == 10
bad_body = struct.pack("<d", 1.0) + int64min_varint + zz(4) + b"\x00" * 4 + zz(0)
bad = os.path.join(tmp, "bad_int64min.avro")
with open(bad, "wb") as f:
    f.write(container(bad_body))
r = avro_fast.try_read_native([bad], cfgs, None, [], cols, ad.LABEL)
assert r is None, "INT64_MIN block count must reject to the fallback"
print("PART2 OK: INT64_MIN block count -> graceful native fallback (no crash)")

# absurd positive count (structurally impossible: count > remaining bytes)
huge_body = struct.pack("<d", 1.0) + zz(2**40) + feat("a", 1.0) + zz(0)
huge = os.path.join(tmp, "bad_huge.avro")
with open(huge, "wb") as f:
    f.write(container(huge_body))
r = avro_fast.try_read_native([huge], cfgs, None, [], cols, ad.LABEL)
assert r is None, "oversized block count must reject to the fallback"
print("PART2 OK: 2^40 block count -> graceful native fallback")

# negative (spec-legal) block count still decodes
neg_body = (
    struct.pack("<d", 1.0)
    + zz(-2) + zz(len(feat("a", 1.0) + feat("b", 2.0)))
    + feat("a", 1.0) + feat("b", 2.0) + zz(0)
)
neg = os.path.join(tmp, "neg_count.avro")
with open(neg, "wb") as f:
    f.write(container(neg_body))
r = avro_fast.try_read_native([neg], cfgs, None, [], cols, ad.LABEL)
assert r is not None, "spec-legal negative block count must still decode"
assert r[0].num_samples == 1 and r[1]["g"].size == 2
print("PART2 OK: spec-legal negative block count still decodes")

print("ALL PARTS 1-2 PASS")

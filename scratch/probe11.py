import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp
from photon_ml_tpu.data.bucketed import pack_bucketed, BucketedSparseFeatures
from photon_ml_tpu.ops import pallas_sparse as ps

N, K, D = 1 << 20, 64, 16384
REPS = 8
rng = np.random.default_rng(0)
idx = rng.integers(0, D, size=(N, K)).astype(np.int64)
val = rng.normal(size=(N, K)).astype(np.float32)
w_np = (rng.normal(size=D) * 0.1).astype(np.float32)
rows = np.repeat(np.arange(N, dtype=np.int64), K)
bf = pack_bucketed(rows, idx.reshape(-1), val.reshape(-1), N, D)
print("packed", flush=True)
w = jnp.asarray(w_np)
empty = bf.overflow_vals[:0]
bf1 = BucketedSparseFeatures(level1=bf.level1, level2=None,
    overflow_rows=bf.overflow_rows[:0], overflow_cols=bf.overflow_cols[:0],
    overflow_vals=empty, n_rows=N, dim=D)
bf2 = BucketedSparseFeatures(level1=bf.level2, level2=None,
    overflow_rows=bf.overflow_rows[:0], overflow_cols=bf.overflow_cols[:0],
    overflow_vals=empty, n_rows=N, dim=D)

def scan_probe(name, b):
    @jax.jit
    def f(x):
        def one(c, i):
            return c + jnp.sum(ps.matvec(b, x * (1.0 + i * 1e-4))), None
        tot, _ = jax.lax.scan(one, 0.0, jnp.arange(REPS, dtype=jnp.float32))
        return tot
    t0 = time.perf_counter()
    float(f(w))
    print(f"{name} scan compile+run: {time.perf_counter()-t0:.1f}s", flush=True)
    ent = np.random.default_rng()
    ts = []
    for r in range(3):
        t0 = time.perf_counter()
        float(f(w * (1.0 + float(ent.uniform(1e-4, 1e-2)))))
        ts.append((time.perf_counter() - t0) / REPS)
    print(f"{name} scan: {min(ts)*1e3:.1f} ms/eval", flush=True)

scan_probe("L1-only", bf1)
scan_probe("L2-only", bf2)
print("done", flush=True)

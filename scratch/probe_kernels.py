"""Measure candidate sparse-ELL kernel formulations end-to-end on v5e.

Layout: transposed ELL (K, N) so ELL rows are lanes. Per grid step, a
(K=64, TN=128) tile = 8192 entries; row-locality is the lane index (static).
w lives in VMEM as (128, 128) [d = hi*128 + lo].

  F1 fwd: 128-iter masked lane-gather loop (VPU, f32 exact)
  F2 fwd: one-hot(hi) @ w2 MXU + lane-gather of the result row
  B1 bwd: grad[j,l] = A^T @ O with A = a*onehot(hi), O = onehot(lo)  (MXU)
  FUSED: F2-style fwd + B1 bwd sharing the tile loads

Timing: one jit per variant, lax.scan over REPS perturbing w/u, so the axon
execution cache cannot serve repeats. Numerics checked vs numpy on the first
rep's parameters.
"""
import functools, time
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

REPS = 8
N, K, D = 1 << 20, 64, 16384
HI, LO = D // 128, 128
TN = 128  # ELL rows per tile (lanes)
GRID = N // TN

rng = np.random.default_rng(0)
idx_nk = rng.integers(0, D, size=(N, K)).astype(np.int32)
val_nk = rng.normal(size=(N, K)).astype(np.float32)
u_np = rng.normal(size=(N,)).astype(np.float32)
w_np = (rng.normal(size=(D,)) * 0.1).astype(np.float32)

# transposed ELL: (K, N)
idxT = jnp.asarray(idx_nk.T.copy())
valT = jnp.asarray(val_nk.T.copy())
u = jnp.asarray(u_np)
w = jnp.asarray(w_np)

z_ref_np = np.einsum("nk,nk->n", w_np[idx_nk], val_nk)
g_ref_np = np.zeros(D, np.float32)
np.add.at(g_ref_np, idx_nk.reshape(-1), (val_nk * u_np[:, None]).reshape(-1))


def timeit(name, fn, args, check=None):
    try:
        out = jax.block_until_ready(fn(*args))
    except Exception as e:
        print(f"{name}: FAIL {type(e).__name__}: {str(e)[:250]}")
        return
    t0 = time.perf_counter()
    out = jax.block_until_ready(fn(*args))
    dt = (time.perf_counter() - t0) / REPS
    msg = f"{name}: {dt*1e3:.1f} ms/eval"
    if check is not None:
        msg += f"   [{check(out)}]"
    print(msg)


# ---------------- F1: select-loop fwd ----------------
def f1_kernel(idx_ref, val_ref, w2_ref, z_ref):
    idx = idx_ref[:]
    hi = jax.lax.shift_right_logical(idx, 7)
    lo = jax.lax.bitwise_and(idx, 127)
    acc = jnp.zeros((K, TN), jnp.float32)
    w2 = w2_ref[:]
    for j in range(HI):
        wrow = jax.lax.broadcast_in_dim(w2[j, :], (K, TN), (1,))
        g = jnp.take_along_axis(wrow, lo, axis=1)
        acc = acc + jnp.where(hi == j, g, 0.0)
    z_ref[:] = jnp.sum(acc * val_ref[:], axis=0, keepdims=True)


@jax.jit
def f1(idxT, valT, w):
    w2 = w.reshape(HI, LO)

    def call(w2):
        return pl.pallas_call(
            f1_kernel,
            grid=(GRID,),
            in_specs=[
                pl.BlockSpec((K, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
                pl.BlockSpec((K, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
                pl.BlockSpec((HI, LO), lambda i: (0, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((1, N), jnp.float32),
        )(idxT, valT, w2)

    def one(c, i):
        return c + call(w2 * (1.0 + i * 1e-6))[0, :7], None

    tot, _ = jax.lax.scan(one, jnp.zeros(7), jnp.arange(REPS, dtype=jnp.float32))
    return tot


# ---------------- F2: MXU one-hot fwd ----------------
def f2_kernel(idx_ref, val_ref, w2_ref, z_ref):
    idx = idx_ref[:].reshape(K * TN // 128, 128)  # entries as (S,128)
    hi = jax.lax.shift_right_logical(idx, 7)
    lo = jax.lax.bitwise_and(idx, 127)
    S = K * TN // 128
    # one-hot(hi): (S*128, HI) ... build as (S,128)->? need (E,HI) 2D.
    # Reshape entries to (E, 1)? E=8192 sublanes. Build one-hot via iota cmp:
    hi_col = hi.reshape(K * TN, 1)
    oh = (jax.lax.broadcasted_iota(jnp.int32, (K * TN, HI), 1) == hi_col).astype(
        jnp.float32
    )
    t = jax.lax.dot_general(
        oh, w2_ref[:], dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (E, 128)
    lo_e = lo.reshape(K * TN, 1)
    g = jnp.take_along_axis(t, jax.lax.broadcast_in_dim(lo_e[:, 0], (K * TN, 128), (0,)), axis=1)[:, :1]
    g2 = g.reshape(K, TN)
    z_ref[:] = jnp.sum(g2 * val_ref[:], axis=0, keepdims=True)


@jax.jit
def f2(idxT, valT, w):
    w2 = w.reshape(HI, LO)

    def call(w2):
        return pl.pallas_call(
            f2_kernel,
            grid=(GRID,),
            in_specs=[
                pl.BlockSpec((K, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
                pl.BlockSpec((K, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
                pl.BlockSpec((HI, LO), lambda i: (0, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((1, N), jnp.float32),
        )(idxT, valT, w2)

    def one(c, i):
        return c + call(w2 * (1.0 + i * 1e-6))[0, :7], None

    tot, _ = jax.lax.scan(one, jnp.zeros(7), jnp.arange(REPS, dtype=jnp.float32))
    return tot


# ---------------- B1: MXU one-hot bwd ----------------
def b1_kernel(idx_ref, val_ref, u_ref, g_ref):
    i = pl.program_id(0)
    idx = idx_ref[:]
    a = val_ref[:] * jax.lax.broadcast_in_dim(u_ref[0, :], (K, TN), (1,))
    E = K * TN
    hi = jax.lax.shift_right_logical(idx, 7).reshape(E, 1)
    lo = jax.lax.bitwise_and(idx, 127).reshape(E, 1)
    af = a.reshape(E, 1)
    A = jnp.where(
        jax.lax.broadcasted_iota(jnp.int32, (E, HI), 1) == hi, af, 0.0
    )  # (E, HI) f32
    O = (jax.lax.broadcasted_iota(jnp.int32, (E, LO), 1) == lo).astype(jnp.float32)
    contrib = jax.lax.dot_general(
        A, O, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (HI, LO)

    @pl.when(i == 0)
    def _():
        g_ref[:] = contrib

    @pl.when(i > 0)
    def _():
        g_ref[:] += contrib


@jax.jit
def b1(idxT, valT, u):
    def call(u):
        return pl.pallas_call(
            b1_kernel,
            grid=(GRID,),
            in_specs=[
                pl.BlockSpec((K, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
                pl.BlockSpec((K, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((HI, LO), lambda i: (0, 0), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((HI, LO), jnp.float32),
        )(idxT, valT, u.reshape(1, N))

    def one(c, i):
        return c + call(u * (1.0 + i * 1e-6)).reshape(-1)[:7], None

    tot, _ = jax.lax.scan(one, jnp.zeros(7), jnp.arange(REPS, dtype=jnp.float32))
    return tot


def chk_z(out):
    got = np.asarray(out)
    want = sum(z_ref_np[:7] * (1.0 + i * 1e-6) for i in range(REPS))
    return f"max err {np.max(np.abs(got - want)):.2e}"


def chk_g(out):
    got = np.asarray(out)
    want = sum(g_ref_np[:7] * (1.0 + i * 1e-6) for i in range(REPS))
    return f"max err {np.max(np.abs(got - want)):.2e}"


timeit("F1 fwd select-loop ", f1, (idxT, valT, w), chk_z)
timeit("F2 fwd MXU one-hot ", f2, (idxT, valT, w), chk_z)
timeit("B1 bwd MXU one-hot ", b1, (idxT, valT, u), chk_g)

# honest XLA baselines with same scan-perturb protocol
idx2 = jnp.asarray(idx_nk)
val2 = jnp.asarray(val_nk)

@jax.jit
def xla_fwd(idx, val, w):
    def one(c, i):
        z = jnp.einsum("nk,nk->n", jnp.take(w * (1.0 + i * 1e-6), idx, axis=-1), val)
        return c + z[:7], None
    tot, _ = jax.lax.scan(one, jnp.zeros(7), jnp.arange(REPS, dtype=jnp.float32))
    return tot

@jax.jit
def xla_bwd(idx, val, u):
    def one(c, i):
        fv = (val * (u * (1.0 + i * 1e-6))[:, None]).reshape(-1)
        g = jnp.zeros((D,), jnp.float32).at[idx.reshape(-1)].add(fv)
        return c + g[:7], None
    tot, _ = jax.lax.scan(one, jnp.zeros(7), jnp.arange(REPS, dtype=jnp.float32))
    return tot

timeit("XLA fwd gather     ", xla_fwd, (idx2, val2, w), chk_z)
timeit("XLA bwd scatter    ", xla_bwd, (idx2, val2, u), chk_g)
print("done")

import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp
from photon_ml_tpu.data.bucketed import pack_bucketed
from photon_ml_tpu.ops import pallas_sparse as ps

N, K, D = 1 << 20, 64, 16384
REPS = 8
rng = np.random.default_rng(0)
idx = rng.integers(0, D, size=(N, K)).astype(np.int64)
val = rng.normal(size=(N, K)).astype(np.float32)
u_np = rng.normal(size=N).astype(np.float32)
w_np = (rng.normal(size=D) * 0.1).astype(np.float32)
rows = np.repeat(np.arange(N, dtype=np.int64), K)
t0 = time.perf_counter()
bf = pack_bucketed(rows, idx.reshape(-1), val.reshape(-1), N, D)
print(f"pack {time.perf_counter()-t0:.1f}s", flush=True)
w = jnp.asarray(w_np); u = jnp.asarray(u_np)

def scan_probe(name, call, vec):
    @jax.jit
    def f(b, x):  # bf as ARGUMENT: no giant constants in the HLO
        def one(c, i):
            return c + jnp.sum(call(b, x * (1.0 + i * 1e-4))), None
        tot, _ = jax.lax.scan(one, 0.0, jnp.arange(REPS, dtype=jnp.float32))
        return tot
    t0 = time.perf_counter()
    float(f(bf, vec))
    print(f"{name} compile+run: {time.perf_counter()-t0:.1f}s", flush=True)
    ent = np.random.default_rng()
    ts = []
    for r in range(3):
        t0 = time.perf_counter()
        float(f(bf, vec * (1.0 + float(ent.uniform(1e-4, 1e-2)))))
        ts.append((time.perf_counter() - t0) / REPS)
    print(f"{name}: {min(ts)*1e3:.1f} ms/eval  (all {[f'{x*1e3:.1f}' for x in ts]})", flush=True)

scan_probe("matvec ", lambda b, x: ps.matvec(b, x), w)
scan_probe("rmatvec", lambda b, x: ps.rmatvec(b, x), u)
m = 1.0 + float(np.random.default_rng().uniform(1e-4, 1e-2))
z_k = np.asarray(ps.matvec(bf, w * m)); g_k = np.asarray(ps.rmatvec(bf, u * m))
z_ref = np.einsum("nk,nk->n", w_np[idx].astype(np.float64), val) * m
g_ref = np.zeros(D); np.add.at(g_ref, idx.reshape(-1), (val.astype(np.float64) * u_np[:, None]).reshape(-1)); g_ref *= m
print("z rel err:", np.abs(z_k - z_ref).max() / np.abs(z_ref).max(), flush=True)
print("g rel err:", np.abs(g_k - g_ref).max() / np.abs(g_ref).max(), flush=True)
print("done", flush=True)

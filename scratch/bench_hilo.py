"""Honest hilo-vs-highest dense kernel timing on the remote TPU backend:
perturbed inputs per rep (defeats result caching) + scalar force-fetch
(block_until_ready is unreliable over the tunnel), rtt-subtracted."""
import os
import sys
import time

sys.path.insert(0, "/root/repo")
mode = sys.argv[1] if len(sys.argv) > 1 else "hilo"
os.environ["PHOTON_PALLAS_PRECISION"] = mode

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.ops import pallas_glm
from photon_ml_tpu.ops.losses import LOGISTIC

print("backend:", jax.default_backend(), "mode:", pallas_glm._PREC_MODE, flush=True)
n, d = 1 << 20, 512
rng = np.random.default_rng(0)
X = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
y = jnp.asarray((rng.uniform(size=n) > 0.5).astype(np.float32))
off = jnp.zeros(n)
wt = jnp.ones(n)
w0 = jnp.asarray(rng.normal(size=d).astype(np.float32) * 0.1)
zero = jnp.zeros(())


def force(out):
    return float(sum(float(jnp.sum(x)) for x in out))


# rtt of a scalar fetch
_ = force((jnp.ones(2),))
rtt = min(
    (lambda t0: (force((jnp.ones(4) * (i + 1),)), time.perf_counter() - t0)[1])(
        time.perf_counter()
    )
    for i in range(5)
)
print(f"rtt {rtt*1e3:.0f} ms", flush=True)

t0 = time.perf_counter()
val, g, su = pallas_glm.value_gradient_sums(LOGISTIC, w0, zero, X, y, off, wt)
force((val, g))
print(f"compile+first: {time.perf_counter()-t0:.1f}s", flush=True)

reps = 8
walls = []
for i in range(reps):
    w = w0 * (1.0 + 1e-4 * (i + 1))  # perturbed input per rep
    t0 = time.perf_counter()
    val, g, su = pallas_glm.value_gradient_sums(LOGISTIC, w, zero, X, y, off, wt)
    force((val, g))
    walls.append(time.perf_counter() - t0 - rtt)
per = min(walls)
print(f"value+grad [{mode}]: {per*1e3:.2f} ms/pass  {n*d*4/per/1e9:.1f} GB/s", flush=True)

t0 = time.perf_counter()
hv, sr = pallas_glm.hessian_vector_sums(LOGISTIC, w0, zero, w0, zero, X, y, off, wt)
force((hv,))
print(f"hvp compile+first: {time.perf_counter()-t0:.1f}s", flush=True)
walls = []
for i in range(reps):
    w = w0 * (1.0 + 1e-4 * (i + 1))
    t0 = time.perf_counter()
    hv, sr = pallas_glm.hessian_vector_sums(LOGISTIC, w, zero, w, zero, X, y, off, wt)
    force((hv,))
    walls.append(time.perf_counter() - t0 - rtt)
per = min(walls)
print(f"hvp        [{mode}]: {per*1e3:.2f} ms/pass  {n*d*4/per/1e9:.1f} GB/s", flush=True)

# numerics: kernel gradient vs f32 XLA reference on-device (cheap, no host f64)
from photon_ml_tpu.ops import objective
from photon_ml_tpu.data.containers import LabeledData

val_x, g_x = objective.value_and_gradient(
    LOGISTIC, w0, LabeledData(X, y, off, wt), use_pallas=False
)
num = float(jnp.max(jnp.abs(g - g_x)) / (jnp.max(jnp.abs(g_x)) + 1e-9))
print(f"grad vs XLA-f32 scale-relative err: {num:.2e}", flush=True)

"""Round-4 ingest drive: CLI train end-to-end from Avro on disk through the
parallel native decoder + data-plane pack, with a GLMix (fixed + random
effect) config, on the virtual CPU mesh."""
import json
import os
import subprocess
import sys
import tempfile

import numpy as np

sys.path.insert(0, "/root/repo")
import photon_ml_tpu.io.avro_data as ad

td = tempfile.mkdtemp()
rng = np.random.default_rng(5)
n, d, n_ent = 6000, 60, 40
w_true = rng.normal(size=d) * 0.5
ent_eff = rng.normal(size=n_ent) * 1.0
ent = rng.integers(0, n_ent, size=n)
feats = []
margins = np.zeros(n)
for i in range(n):
    js = rng.choice(d, size=6, replace=False)
    vs = rng.normal(size=6)
    feats.append([(f"f{j}", float(v)) for j, v in zip(js, vs)])
    margins[i] = vs @ w_true[js] + ent_eff[ent[i]]
labels = (rng.uniform(size=n) < 1 / (1 + np.exp(-margins))).astype(float)

train_dir = os.path.join(td, "train")
os.makedirs(train_dir)
ad.write_training_examples(
    os.path.join(train_dir, "part-0.avro"),
    feats[: n // 2],
    labels[: n // 2],
    id_tags={"entityId": ent[: n // 2]},
)
ad.write_training_examples(
    os.path.join(train_dir, "part-1.avro"),
    feats[n // 2 :],
    labels[n // 2 :],
    id_tags={"entityId": ent[n // 2 :]},
    codec="null",  # mixed codecs across files must work
)
out_dir = os.path.join(td, "out")

cmd = [
    sys.executable,
    "-m",
    "photon_ml_tpu.cli.train",
    "--training-task", "LOGISTIC_REGRESSION",
    "--input-data-directories", train_dir,
    "--root-output-directory", out_dir,
    "--feature-shard-configurations",
    "name=globalShard,feature.bags=features,intercept=true",
    "--coordinate-configurations",
    "name=global,feature.shard=globalShard,min.partitions=1,optimizer=LBFGS,"
    "tolerance=1.0E-7,max.iter=30,regularization=L2,reg.weights=1.0",
    "name=perEntity,random.effect.type=entityId,feature.shard=globalShard,"
    "min.partitions=1,optimizer=LBFGS,tolerance=1.0E-7,max.iter=20,"
    "regularization=L2,reg.weights=10.0,active.data.lower.bound=1",
    "--coordinate-update-sequence", "global,perEntity",
    "--coordinate-descent-iterations", "2",
    "--validation-evaluators", "AUC",
]
env = dict(os.environ)
env.pop("PALLAS_AXON_POOL_IPS", None)
env["JAX_PLATFORMS"] = "cpu"
env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
env["PYTHONPATH"] = "/root/repo"
r = subprocess.run(cmd, capture_output=True, text=True, timeout=420, env=env)
print(r.stdout[-3000:])
if r.returncode != 0:
    print(r.stderr[-4000:])
    sys.exit(1)

# model artifacts written?
found = []
for root, dirs, fs in os.walk(out_dir):
    for f in fs:
        found.append(os.path.relpath(os.path.join(root, f), out_dir))
print("artifacts:", sorted(found)[:12])
assert any("fixed-effect" in f for f in found), "no fixed-effect model written"
assert any("random-effect" in f for f in found), "no random-effect model written"
print("CLI E2E DRIVE OK")

"""Fused sparse objective on v5e at bench scale: scan-timed per-eval wall."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp
from photon_ml_tpu.data.bucketed import pack_bucketed
from photon_ml_tpu.ops import pallas_sparse as ps
from photon_ml_tpu.ops.losses import LOGISTIC

N, K, D = 1 << 20, 64, 16384
REPS = 8
rng = np.random.default_rng(0)
idx = rng.integers(0, D, size=(N, K)).astype(np.int64)
val = rng.normal(size=(N, K)).astype(np.float32)
y_np = (rng.uniform(size=N) > 0.5).astype(np.float32)
w_np = (rng.normal(size=D) * 0.1).astype(np.float32)
rows = np.repeat(np.arange(N, dtype=np.int64), K)
t0 = time.perf_counter()
bf = pack_bucketed(rows, idx.reshape(-1), val.reshape(-1), N, D)
print(f"pack {time.perf_counter()-t0:.1f}s {bf.density_report()}", flush=True)
w = jnp.asarray(w_np); y = jnp.asarray(y_np)
off = jnp.zeros(N); wt = jnp.ones(N)

@jax.jit
def f(b, x, yy, oo, ww):
    def one(c, i):
        v, g, su = ps.fused_value_gradient_sums(
            LOGISTIC, x * (1.0 + i * 1e-4), jnp.zeros(()), b, yy, oo, ww)
        return c + v + jnp.sum(g) + su, None
    tot, _ = jax.lax.scan(one, 0.0, jnp.arange(REPS, dtype=jnp.float32))
    return tot

t0 = time.perf_counter()
float(f(bf, w, y, off, wt))
print(f"fused compile+run: {time.perf_counter()-t0:.1f}s", flush=True)
ent = np.random.default_rng()
ts = []
for r in range(3):
    t0 = time.perf_counter()
    float(f(bf, w * (1.0 + float(ent.uniform(1e-4, 1e-2))), y, off, wt))
    ts.append((time.perf_counter() - t0) / REPS)
print(f"fused: {min(ts)*1e3:.1f} ms/eval  (all {[f'{x*1e3:.1f}' for x in ts]})", flush=True)

# numerics on chip
m = 1.0 + float(ent.uniform(1e-4, 1e-2))
v_k, g_k, su_k = ps.fused_value_gradient_sums(LOGISTIC, w * m, jnp.zeros(()), bf, y, off, wt)
wm = w_np * m
z = np.einsum("nk,nk->n", wm[idx].astype(np.float64), val)
sig = 1/(1+np.exp(-z))
val_ref = np.sum(np.log1p(np.exp(-np.abs(z))) + np.maximum(z,0) - y_np*z)
u_ref = sig - y_np
g_ref = np.zeros(D); np.add.at(g_ref, idx.reshape(-1), (val.astype(np.float64) * u_ref[:, None]).reshape(-1))
print("val rel err:", abs(float(v_k) - val_ref)/abs(val_ref), flush=True)
print("g rel err:", np.abs(np.asarray(g_k) - g_ref).max()/np.abs(g_ref).max(), flush=True)
print("done", flush=True)

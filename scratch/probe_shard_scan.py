"""Probe: (1) ring shard_map collectives inside lax.scan inside jit on the
8-virtual-device CPU mesh; (2) bitwise-ness of sharded RE training vs the
single-device path; (3) psum-based bcast gather exactness."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from photon_ml_tpu.data.game_dataset import (
    GameDataset,
    RandomEffectDataConfig,
    build_random_effect_dataset,
)
from photon_ml_tpu.game.coordinate import RandomEffectCoordinate
from photon_ml_tpu.optimize.config import L2, CoordinateOptimizationConfig, OptimizerConfig
from photon_ml_tpu.parallel.mesh import (
    make_mesh,
    matrix_row_sharding,
    pad_game_dataset,
    ring_gather_rows,
    ring_scatter_rows,
    shard_game_dataset,
    shard_random_effect_dataset,
)
from photon_ml_tpu.types import TaskType

mesh = make_mesh()
ndev = mesh.devices.size
axis = mesh.axis_names[0]
print("devices:", ndev)

# ---- (1) ring collectives inside scan inside jit -------------------------
rng = np.random.default_rng(0)
R, D, E, K = 4 * ndev, 6, 2 * ndev, 3
M = jnp.asarray(rng.normal(size=(R, D)).astype(np.float32))
Ms = jax.device_put(M, matrix_row_sharding(mesh))
rows_k = rng.integers(0, R, size=(K, E)).astype(np.int32)
# unique rows per step (scatter contract)
for k in range(K):
    rows_k[k] = rng.choice(R, size=E, replace=False)
rows_s = jax.device_put(
    jnp.asarray(rows_k), NamedSharding(mesh, P(None, axis))
)


@jax.jit
def scan_ring(m, rows_all):
    def step(m, rows):
        w = ring_gather_rows(m, rows, mesh)
        m = ring_scatter_rows(m, rows, w * 2.0, mesh)
        return m, jnp.sum(w)

    return jax.lax.scan(step, m, rows_all)


m_out, sums = scan_ring(Ms, rows_s)
m_ref = np.array(M)
for k in range(K):
    m_ref[rows_k[k]] = m_ref[rows_k[k]] * 2.0
print("scan-ring exact:", np.array_equal(np.asarray(m_out), m_ref))

# ---- (2) sharded RE training bitwise vs single device --------------------
def _dataset(n=256, d_re=4, n_entities=24):
    Xe = rng.normal(size=(n, d_re)).astype(np.float32)
    entity = rng.integers(0, n_entities, size=n)
    y = (rng.uniform(size=n) > 0.5).astype(np.float32)
    return GameDataset.build(
        {"per_entity": jnp.asarray(Xe)}, y, id_tags={"entityId": entity}
    )


cfg = CoordinateOptimizationConfig(
    optimizer=OptimizerConfig(max_iterations=20, tolerance=1e-7),
    regularization=L2,
    reg_weight=1.0,
)
ds = _dataset()
red = build_random_effect_dataset(
    ds, RandomEffectDataConfig("entityId", "per_entity", min_bucket=4)
)
single = RandomEffectCoordinate(ds, red, cfg, TaskType.LOGISTIC_REGRESSION)
m_single, _ = single.train(ds.offsets)

ds2 = _dataset.__wrapped__() if hasattr(_dataset, "__wrapped__") else None
# rebuild identically (fresh rng state differs; rebuild from same arrays)
ds_pad = pad_game_dataset(
    GameDataset.build(
        {"per_entity": ds.shards["per_entity"]},
        np.asarray(ds.labels),
        id_tags={"entityId": ds.id_tags["entityId"]},
    ),
    ndev,
)
sharded = shard_game_dataset(ds_pad, mesh)
red_m = shard_random_effect_dataset(
    build_random_effect_dataset(
        sharded, RandomEffectDataConfig("entityId", "per_entity", min_bucket=4)
    ),
    mesh,
)
multi = RandomEffectCoordinate(sharded, red_m, cfg, TaskType.LOGISTIC_REGRESSION)
m_multi, _ = multi.train(sharded.offsets)
W_s = np.asarray(m_single.coefficients_matrix)
W_m = np.asarray(m_multi.coefficients_matrix)
rows_cmp = [red_m.entity_index[e] for e in red.entity_index]
same = np.array_equal(W_s[[red.entity_index[e] for e in red.entity_index]], W_m[rows_cmp])
print("sharded-vs-single RE train bitwise:", same)
if not same:
    d = np.abs(
        W_s[[red.entity_index[e] for e in red.entity_index]] - W_m[rows_cmp]
    ).max()
    print("  maxdiff:", d)

# ---- (3) psum bcast gather -----------------------------------------------
import functools


@functools.lru_cache(maxsize=8)
def _bcast_fn(mesh, rows_ndim):
    axis = mesh.axis_names[0]

    def per_device(m_loc, rows):
        my = jax.lax.axis_index(axis)
        chunk = m_loc.shape[0]
        base = my * chunk
        mask = (rows >= base) & (rows < base + chunk)
        local = jnp.clip(rows - base, 0, chunk - 1)
        part = jnp.where(mask[..., None], m_loc[local], 0.0)
        return jax.lax.psum(part, axis)

    from photon_ml_tpu.parallel.mesh import shard_map_compat

    return jax.jit(
        shard_map_compat(
            per_device,
            mesh=mesh,
            in_specs=(P(axis, None), P()),
            out_specs=P(),
        )
    )


rows_q = jnp.asarray(rng.integers(0, R, size=13).astype(np.int32))
got = np.asarray(_bcast_fn(mesh, 1)(Ms, rows_q))
print("bcast gather exact:", np.array_equal(got, np.asarray(M)[np.asarray(rows_q)]))

"""Round r07 runner: produce BENCH_r07.json + MULTICHIP_r07.json in the
same committed shape as prior rounds (r05/r06)."""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_bench() -> dict:
    cmd = "if [ -f bench.py ]; then python bench.py; else exit 0; fi"
    out = subprocess.run(
        ["bash", "-c", cmd],
        capture_output=True,
        text=True,
        cwd=ROOT,
        timeout=3600,
    )
    parsed = None
    for line in out.stdout.splitlines():
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except ValueError:
                pass
    return {
        "n": 7,
        "cmd": cmd,
        "rc": out.returncode,
        "tail": (out.stdout or "")[-6000:],
        "parsed": parsed,
    }


def run_multichip() -> dict:
    env = dict(os.environ, DRYRUN_DEVICES="8", JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "__graft_entry__.py")],
        capture_output=True,
        text=True,
        cwd=ROOT,
        env=env,
        timeout=1800,
    )
    tail_lines = [
        l for l in (out.stdout + out.stderr).splitlines() if "dryrun_multichip" in l
    ]
    tail = (tail_lines[-1] + "\n") if tail_lines else (out.stderr or "")[-2000:]
    return {
        "n_devices": 8,
        "rc": out.returncode,
        "ok": out.returncode == 0 and "dryrun_multichip OK" in tail,
        "skipped": False,
        "tail": tail,
    }


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    if which in ("both", "bench"):
        rec = run_bench()
        with open(os.path.join(ROOT, "BENCH_r07.json"), "w") as f:
            json.dump(rec, f, indent=1)
        print("BENCH_r07.json rc=", rec["rc"], "parsed=", rec["parsed"] is not None)
    if which in ("both", "multichip"):
        rec = run_multichip()
        with open(os.path.join(ROOT, "MULTICHIP_r07.json"), "w") as f:
            json.dump(rec, f, indent=1)
        print("MULTICHIP_r07.json ok=", rec["ok"])

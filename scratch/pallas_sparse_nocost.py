"""Pallas TPU kernels for bucketed sparse matvec / rmatvec.

The sparse GLM hot loop — margins `z = X @ w` and gradient `g = X^T u` over a
bag-of-features design matrix — is the reference's native workload
(photon-lib function/glm/ValueAndGradientAggregator.scala:137-161 streams
sparse LabeledPoint entries; photon-lib data/LabeledPoint.scala:33). Expressed
as XLA gather/scatter the two passes serialize (~0.59 s forward / ~0.47 s
backward at 1M x 64nnz, dim 16k — measured on v5e); these kernels run the
same passes out of VMEM with the only fast data-dependent primitive the
hardware has — the within-vreg 128-lane `dynamic_gather` — plus small one-hot
contractions on the MXU.

Layout contract (see data/bucketed.py): entries grouped by (row-tile,
feature-bucket of 128) into fixed-width segments; per entry one packed int32
`row_local << 7 | lane` and one f32 value; two levels (fine tiles + a coarse
spill level) and a COO tail handled by XLA.

Forward, per (row-tile, bucket-group) grid step, per segment:
    w_b       = 128-wide bucket slice of w, broadcast over sublanes
    p         = dynamic_gather(w_b, lane) * value    # 1024 entries / vreg-op
    z_tile   += sum_e p_e . onehot(row_local_e)      # MXU contraction
The z-scatter runs on the MXU: per 128-entry sublane row, a one-hot
(rhi x rlo) contraction accumulates into the tile's (tile_rows/128, 128)
z block, VMEM-resident across the whole bucket loop.

Backward mirrors it: per entry u[row_local] is a lane-gather of the u-tile
followed by a sublane one-hot select, and the 128-wide bucket gradient is a
one-hot contraction. Each kernel streams `packed`+`values` exactly once per
pass — the sparse counterpart of the dense fused kernel's single-X-read
property (ops/pallas_glm.py).

Precision: the one-hot operand is exact in bf16; the value-carrying operand
is split hi/lo into two bf16 MXU passes, which matches f32 accumulation to
~3e-6 relative (measured) at a fraction of HIGHEST's six passes. Set
PHOTON_SPARSE_PRECISION=default for single-pass bf16 (~1.7e-3 relative) when
raw speed matters more than line-search quality.

Measured on v5e at 1M x 64 nnz, dim 16384 (uniform): forward ~16 ms, backward
~21 ms per pass at hi/lo precision vs 592 / 465 ms for the XLA path — see
BENCH_r03.json for the bench-protocol numbers.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pragma: no cover - absent only on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

from photon_ml_tpu.data.bucketed import (
    BUCKET,
    BucketedLevel,
    BucketedSparseFeatures,
    _ROW_SHIFT,
)
from photon_ml_tpu.ops import pallas_glm

Array = jax.Array

# Value-carrying MXU operand precision: "hilo" (two bf16 passes ~= f32) or a
# jax.lax.Precision name. Validated leniently like the dense kernel's knobs.
_SPARSE_PREC = os.environ.get("PHOTON_SPARSE_PRECISION", "hilo").strip().lower()
if _SPARSE_PREC not in ("hilo", "default", "highest"):
    import logging

    logging.getLogger(__name__).warning(
        "PHOTON_SPARSE_PRECISION=%r: expected hilo|default|highest; using hilo",
        _SPARSE_PREC,
    )
    _SPARSE_PREC = "hilo"

# Static-unroll budget: segments wider than this fall back to XLA (the
# kernels unroll spv iterations per segment).
MAX_SPV = 64
# Bucket-group size: segments fused per grid step to amortize per-step
# overhead (measured ~2x at 1M x 64nnz). Chosen per call to divide B.
_GROUP = 32


def _bcast_row(row: Array, sublanes: int) -> Array:
    return jax.lax.broadcast_in_dim(row[0, :], (sublanes, 128), (1,))


def _onehot_contract(values_row: Array, onehot: Array) -> Array:
    """dot(values, onehot^T) with the configured value-operand precision."""
    dn = (((1,), (1,)), ((), ()))
    if _SPARSE_PREC == "hilo":
        hi = values_row.astype(jnp.bfloat16).astype(jnp.float32)
        lo = values_row - hi
        return jax.lax.dot_general(
            hi, onehot, dimension_numbers=dn, preferred_element_type=jnp.float32
        ) + jax.lax.dot_general(
            lo, onehot, dimension_numbers=dn, preferred_element_type=jnp.float32
        )
    prec = (
        jax.lax.Precision.HIGHEST
        if _SPARSE_PREC == "highest"
        else jax.lax.Precision.DEFAULT
    )
    return jax.lax.dot_general(
        values_row,
        onehot,
        dimension_numbers=dn,
        preferred_element_type=jnp.float32,
        precision=prec,
    )


def _matvec_kernel(spv: int, rt: int, group: int, pk_ref, val_ref, w_ref, z_ref):
    bg = pl.program_id(1)
    zc = jnp.zeros((rt, 128), jnp.float32)
    for gi in range(group):
        pk = pk_ref[pl.ds(gi * spv, spv), :]
        vv = val_ref[pl.ds(gi * spv, spv), :]
        rl = jax.lax.shift_right_logical(pk, _ROW_SHIFT)
        lane = jax.lax.bitwise_and(pk, BUCKET - 1)
        wb = _bcast_row(w_ref[pl.ds(bg * group + gi, 1), :], spv)
        p = jnp.take_along_axis(wb, lane, axis=1) * vv
        for s in range(spv):
            rl_row = rl[s : s + 1, :]
            rhi = jax.lax.shift_right_logical(rl_row, 7)
            rlo = jax.lax.bitwise_and(rl_row, 127)
            orh = jax.lax.broadcasted_iota(jnp.int32, (rt, 128), 0) == _bcast_row(
                rhi, rt
            )
            p1 = jnp.where(orh, _bcast_row(p[s : s + 1, :], rt), 0.0)
            orlt = (
                jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0)
                == _bcast_row(rlo, 128)
            ).astype(jnp.float32)
            zc = zc + _onehot_contract(p1, orlt)

    @pl.when(bg == 0)
    def _():
        z_ref[:] = zc

    @pl.when(bg > 0)
    def _():
        z_ref[:] += zc


def _rmatvec_kernel(
    spv: int, rt: int, group: int, square: bool, pk_ref, val_ref, u_ref, g_ref
):
    bg = pl.program_id(0)
    t = pl.program_id(1)
    u2 = u_ref[:]
    for gi in range(group):
        pk = pk_ref[pl.ds(gi * spv, spv), :]
        vv = val_ref[pl.ds(gi * spv, spv), :]
        if square:
            vv = vv * vv
        rl = jax.lax.shift_right_logical(pk, _ROW_SHIFT)
        lane = jax.lax.bitwise_and(pk, BUCKET - 1)
        gc = jnp.zeros((1, 128), jnp.float32)
        for s in range(spv):
            rl_row = rl[s : s + 1, :]
            rhi = jax.lax.shift_right_logical(rl_row, 7)
            rlo = jax.lax.bitwise_and(rl_row, 127)
            tu = jnp.take_along_axis(u2, _bcast_row(rlo, rt), axis=1)
            orh = jax.lax.broadcasted_iota(jnp.int32, (rt, 128), 0) == _bcast_row(
                rhi, rt
            )
            u_sel = jnp.sum(jnp.where(orh, tu, 0.0), axis=0, keepdims=True)
            a = u_sel * vv[s : s + 1, :]
            olt = (
                jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0)
                == _bcast_row(lane[s : s + 1, :], 128)
            ).astype(jnp.float32)
            gc = gc + _onehot_contract(a, olt)
        bidx = bg * group + gi

        @pl.when(t == 0)
        def _():
            g_ref[pl.ds(bidx, 1), :] = gc

        @pl.when(t > 0)
        def _():
            g_ref[pl.ds(bidx, 1), :] += gc


def _pick_group(B: int) -> int:
    for g in (_GROUP, 16, 8, 4, 2, 1):
        if B % g == 0:
            return g
    return 1


def _level_matvec(
    level: BucketedLevel, n_rows: int, dim: int, w_pad2: Array, interpret: bool
) -> Array:
    B = w_pad2.shape[0]
    T = level.num_tiles(n_rows)
    rt = level.tile_rows // 128
    spv = level.spv
    G = _pick_group(B)
    z2 = pl.pallas_call(
        functools.partial(_matvec_kernel, spv, rt, G),
        grid=(T, B // G),
        in_specs=[
            pl.BlockSpec(
                (G * spv, 128), lambda t, bg: (t * (B // G) + bg, 0), memory_space=_VMEM
            ),
            pl.BlockSpec(
                (G * spv, 128), lambda t, bg: (t * (B // G) + bg, 0), memory_space=_VMEM
            ),
            pl.BlockSpec((B, 128), lambda t, bg: (0, 0), memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((rt, 128), lambda t, bg: (t, 0), memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((T * rt, 128), jnp.float32),
                interpret=interpret,
    )(level.packed, level.values, w_pad2)
    return z2.reshape(-1)[: n_rows]


def _level_rmatvec(
    level: BucketedLevel,
    n_rows: int,
    B: int,
    u_pad: Array,
    square: bool,
    interpret: bool,
) -> Array:
    T = level.num_tiles(n_rows)
    rt = level.tile_rows // 128
    spv = level.spv
    G = _pick_group(B)
    u2 = jnp.pad(u_pad, (0, T * level.tile_rows - u_pad.shape[0])).reshape(T * rt, 128)
    g2 = pl.pallas_call(
        functools.partial(_rmatvec_kernel, spv, rt, G, square),
        grid=(B // G, T),
        in_specs=[
            pl.BlockSpec(
                (G * spv, 128), lambda bg, t: (t * (B // G) + bg, 0), memory_space=_VMEM
            ),
            pl.BlockSpec(
                (G * spv, 128), lambda bg, t: (t * (B // G) + bg, 0), memory_space=_VMEM
            ),
            pl.BlockSpec((rt, 128), lambda bg, t: (t, 0), memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((B, 128), lambda bg, t: (0, 0), memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((B, 128), jnp.float32),
                interpret=interpret,
    )(level.packed, level.values, u2)
    return g2.reshape(-1)


def should_use(bf: BucketedSparseFeatures) -> bool:
    """Kernel dispatch gate: TPU backend (or forced interpret for tests),
    sane segment widths, enough work to amortize."""
    if not pallas_glm.is_enabled():
        return False
    if jax.default_backend() != "tpu" and not pallas_glm.FORCE_INTERPRET:
        return False
    if bf.level1.spv > MAX_SPV:
        return False
    if bf.level2 is not None and bf.level2.spv > MAX_SPV:
        return False
    return True


@functools.partial(jax.jit, static_argnames=("interpret",))
def matvec(bf: BucketedSparseFeatures, w: Array, *, interpret: bool = False) -> Array:
    """z = X @ w over the bucketed layout (kernels + XLA overflow)."""
    B = bf.num_buckets
    w_pad2 = jnp.pad(w.astype(jnp.float32), (0, B * BUCKET - bf.dim)).reshape(B, BUCKET)
    z = _level_matvec(bf.level1, bf.n_rows, bf.dim, w_pad2, interpret)
    if bf.level2 is not None:
        z = z + _level_matvec(bf.level2, bf.n_rows, bf.dim, w_pad2, interpret)
    if bf.overflow_vals.shape[0]:
        z = z.at[bf.overflow_rows].add(
            bf.overflow_vals * jnp.take(w_pad2.reshape(-1), bf.overflow_cols)
        )
    return z


@functools.partial(jax.jit, static_argnames=("interpret", "square"))
def rmatvec(
    bf: BucketedSparseFeatures,
    u: Array,
    *,
    interpret: bool = False,
    square: bool = False,
) -> Array:
    """g = X^T u (or (X.^2)^T u with square=True, for Hessian diagonals)."""
    B = bf.num_buckets
    u_f = u.astype(jnp.float32)
    g = _level_rmatvec(bf.level1, bf.n_rows, B, u_f, square, interpret)
    if bf.level2 is not None:
        g = g + _level_rmatvec(bf.level2, bf.n_rows, B, u_f, square, interpret)
    g = g[: bf.dim]
    if bf.overflow_vals.shape[0]:
        ov = bf.overflow_vals
        if square:
            ov = ov * ov
        g = g.at[bf.overflow_cols].add(ov * jnp.take(u_f, bf.overflow_rows))
    return g


# ------------------------------------------------------------- XLA reference


def _level_coo(level: BucketedLevel, B: int):
    rl = jax.lax.shift_right_logical(level.packed, _ROW_SHIFT)
    lane = jax.lax.bitwise_and(level.packed, BUCKET - 1)
    seg = jnp.arange(level.packed.shape[0]) // level.spv
    bucket = (seg % B)[:, None]
    tile = (seg // B)[:, None]
    rows = tile * level.tile_rows + rl
    cols = bucket * BUCKET + lane
    return rows, cols


def matvec_xla(bf: BucketedSparseFeatures, w: Array) -> Array:
    """Same contraction via XLA gather/scatter (fallback + test oracle)."""
    B = bf.num_buckets
    w_pad = jnp.pad(w.astype(jnp.float32), (0, B * BUCKET - bf.dim))
    z = jnp.zeros(bf.n_rows, jnp.float32)
    for level in (bf.level1, bf.level2):
        if level is None:
            continue
        rows, cols = _level_coo(level, B)
        p = jnp.take(w_pad, cols) * level.values
        pad_rows = level.num_tiles(bf.n_rows) * level.tile_rows
        zl = jnp.zeros(pad_rows, jnp.float32).at[rows.reshape(-1)].add(p.reshape(-1))
        z = z + zl[: bf.n_rows]
    if bf.overflow_vals.shape[0]:
        z = z.at[bf.overflow_rows].add(
            bf.overflow_vals * jnp.take(w_pad, bf.overflow_cols)
        )
    return z


def to_dense_xla(bf: BucketedSparseFeatures) -> Array:
    """Densify inside jit (FULL-variance Hessian path; modest dims only)."""
    B = bf.num_buckets
    M = jnp.zeros((bf.n_rows, B * BUCKET), jnp.float32)
    for level in (bf.level1, bf.level2):
        if level is None:
            continue
        rows, cols = _level_coo(level, B)
        valid = rows < bf.n_rows  # padding entries have value 0 anyway
        M = M.at[
            jnp.where(valid, rows, 0).reshape(-1), cols.reshape(-1)
        ].add(jnp.where(valid, level.values, 0.0).reshape(-1))
    if bf.overflow_vals.shape[0]:
        M = M.at[bf.overflow_rows, bf.overflow_cols].add(bf.overflow_vals)
    return M[:, : bf.dim]


def rmatvec_xla(bf: BucketedSparseFeatures, u: Array, *, square: bool = False) -> Array:
    B = bf.num_buckets
    g = jnp.zeros(B * BUCKET, jnp.float32)
    u_f = u.astype(jnp.float32)
    for level in (bf.level1, bf.level2):
        if level is None:
            continue
        rows, cols = _level_coo(level, B)
        pad_rows = level.num_tiles(bf.n_rows) * level.tile_rows
        u_pad = jnp.pad(u_f, (0, pad_rows - bf.n_rows))
        val = level.values
        if square:
            val = val * val
        a = jnp.take(u_pad, rows) * val
        g = g.at[cols.reshape(-1)].add(a.reshape(-1))
    g = g[: bf.dim]
    if bf.overflow_vals.shape[0]:
        ov = bf.overflow_vals
        if square:
            ov = ov * ov
        g = g.at[bf.overflow_cols].add(ov * jnp.take(u_f, bf.overflow_rows))
    return g

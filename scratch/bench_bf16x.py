"""TPU probe: dense fused-kernel wall with f32-stored vs bf16-stored X.

Within-run comparison only (the tunnel shows up to 4x run-to-run variance).
Protocol from bench.py: jitted combining-scalar fetch, rtt subtracted,
perturbed warm-up inputs.
"""
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

from photon_ml_tpu.data.game_dataset import GameDataset
from photon_ml_tpu.game.coordinate import FixedEffectCoordinate
from photon_ml_tpu.optimize.config import L2, CoordinateOptimizationConfig, OptimizerConfig
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.ops import pallas_glm

t0 = time.perf_counter()
def mark(m):
    sys.stderr.write(f"+{time.perf_counter()-t0:.1f}s {m}\n"); sys.stderr.flush()

platform = jax.devices()[0].platform
mark(f"backend {platform}")
n, d = 1 << 20, 512
key = jax.random.PRNGKey(0)
kx, kw, kl = jax.random.split(key, 3)
X = jax.random.normal(kx, (n, d), jnp.float32)
w_true = jax.random.normal(kw, (d,)) * 0.1
y = (jax.random.uniform(kl, (n,)) < jax.nn.sigmoid(X @ w_true)).astype(jnp.float32)
jax.block_until_ready(y)
mark("data on device")

@jax.jit
def _force_sum(parts):
    return sum(parts[1:], parts[0])

def _force(out):
    leaves = [x for x in jax.tree_util.tree_leaves(out) if hasattr(x, "dtype")]
    return float(_force_sum(tuple(jnp.sum(x.astype(jnp.float32)) for x in leaves)))

_force(jnp.ones(2))
ts = []
for i in range(5):
    tt = time.perf_counter(); _force(jnp.ones(4) * (i + 1)); ts.append(time.perf_counter() - tt)
rtt = min(ts)
mark(f"rtt {rtt*1e3:.0f} ms")

cfg = CoordinateOptimizationConfig(
    optimizer=OptimizerConfig(max_iterations=40, tolerance=1e-8),
    regularization=L2, reg_weight=1.0,
)

def run(mode_env):
    os.environ["PHOTON_DENSE_BF16X"] = mode_env
    ds = GameDataset.build({"g": X}, y)
    coord = FixedEffectCoordinate(ds, "g", cfg, TaskType.LOGISTIC_REGRESSION)
    xdt = coord._features.dtype
    warm_off = ds.offsets + jnp.float32(1e-3)
    tc = time.perf_counter()
    _force(coord.train(warm_off)[1])  # compile + warm
    mark(f"bf16x={mode_env} (X dtype {xdt}, dispatch {coord._use_pallas!r}) warm {time.perf_counter()-tc:.1f}s")
    walls, evals = [], None
    for rep in range(3):
        off = ds.offsets + jnp.float32(1e-6 * (rep + 1))
        tt = time.perf_counter()
        _, res = coord.train(off)
        _force(res)
        walls.append(max(time.perf_counter() - tt - rtt, 1e-9))
        evals = int(np.asarray(res.fn_evals))
    wall = min(walls)
    per_pass_bytes = n * d * 4  # f32-normalized, bench formula
    eff = evals * per_pass_bytes / wall / 1e9
    print(f"bf16x={mode_env}: wall={wall:.3f}s fn_evals={evals} eff={eff:.0f} GB/s (f32-normalized) walls={['%.3f'%w for w in walls]}")
    return wall, evals, res

w_f32, e_f32, res_f32 = run("0")
w_bf16, e_bf16, res_bf16 = run("1")
print(f"speedup: {w_f32 / w_bf16:.2f}x  fn_evals {e_f32} -> {e_bf16}")
d_coef = float(jnp.max(jnp.abs(res_f32.coefficients - res_bf16.coefficients)))
scale = float(jnp.max(jnp.abs(res_f32.coefficients)))
print(f"coef diff {d_coef:.2e} (scale {scale:.2e})")

"""Ablate the fwd kernel to find where the 120ms goes.
V0 full | V1 constant ORLT (no one-hot build) | V2 no matmul | V3 loop empty
V4 full but grid batched over 8 buckets per step | V5 only gather+mult, no loop
"""
import sys, time, functools
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from photon_ml_tpu.data.bucketed import pack_bucketed

N, K, D = 1 << 20, 64, 16384
RT = 16
rng = np.random.default_rng(0)
idx = rng.integers(0, D, size=(N, K)).astype(np.int64)
val = rng.normal(size=(N, K)).astype(np.float32)
rows = np.repeat(np.arange(N, dtype=np.int64), K)
bf = pack_bucketed(rows, idx.reshape(-1), val.reshape(-1), N, D)
T, B, spv = bf.num_tiles, bf.num_buckets, bf.spv
print("T,B,spv:", T, B, spv)
w_np = (rng.normal(size=D) * 0.1).astype(np.float32)
w = jnp.asarray(w_np)

PREC = jax.lax.Precision.DEFAULT

def bcast(row, s):
    return jax.lax.broadcast_in_dim(row[0, :], (s, 128), (1,))

def mk_kernel(variant):
    def kern(pk_ref, val_ref, w_ref, z_ref):
        b = pl.program_id(1)
        pk = pk_ref[:]
        rl = jax.lax.shift_right_logical(pk, 7)
        lane = jax.lax.bitwise_and(pk, 127)
        wb = bcast(w_ref[pl.ds(b, 1), :], spv)
        p = jnp.take_along_axis(wb, lane, axis=1) * val_ref[:]
        zc = jnp.zeros((RT, 128), jnp.float32)
        if variant != "V5":
            for s in range(spv):
                rl_row = rl[s : s + 1, :]
                rhi = jax.lax.shift_right_logical(rl_row, 7)
                rlo = jax.lax.bitwise_and(rl_row, 127)
                if variant == "V3":
                    zc = zc + jnp.float32(1e-9) * bcast(rlo.astype(jnp.float32), RT)
                    continue
                orh = jax.lax.broadcasted_iota(jnp.int32, (RT, 128), 0) == bcast(rhi, RT)
                p1 = jnp.where(orh, bcast(p[s : s + 1, :], RT), 0.0)
                if variant == "V2":
                    zc = zc + p1
                    continue
                if variant == "V1":
                    orlt = jnp.broadcast_to(jnp.float32(1.0), (128, 128)) * 0.5
                else:
                    orlt = (
                        jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0) == bcast(rlo, 128)
                    ).astype(jnp.float32)
                zc = zc + jax.lax.dot_general(
                    p1, orlt, dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32, precision=PREC)
        else:
            zc = zc + jnp.sum(p) * jnp.ones((RT, 128), jnp.float32) * 1e-9
        @pl.when(b == 0)
        def _():
            z_ref[:] = zc
        @pl.when(b > 0)
        def _():
            z_ref[:] += zc
    return kern

def run(variant):
    fn = pl.pallas_call(
        mk_kernel(variant),
        grid=(T, B),
        in_specs=[
            pl.BlockSpec((spv, 128), lambda t, b: (t * B + b, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((spv, 128), lambda t, b: (t * B + b, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((B, 128), lambda t, b: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((RT, 128), lambda t, b: (t, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((T * RT, 128), jnp.float32),
    )
    f = jax.jit(lambda pk, v, w2: jnp.sum(fn(pk, v, w2)))
    w2 = w.reshape(B, 128)
    try:
        float(f(bf.packed, bf.values, w2))
    except Exception as e:
        print(f"{variant}: FAIL {str(e)[:150]}")
        return
    ent = np.random.default_rng()  # OS entropy: unique args every run
    ts = []
    for r in range(3):
        w2r = w2 * (1.0 + float(ent.uniform(1e-4, 1e-2)))
        t0 = time.perf_counter()
        float(f(bf.packed, bf.values, w2r))  # scalar fetch forces sync
        ts.append(time.perf_counter() - t0)
    print(f"{variant}: {min(ts)*1e3:.1f} ms  (all {[f'{x*1e3:.1f}' for x in ts]})")

# V4: batch G buckets per grid step
def run_v4(G):
    def kern(pk_ref, val_ref, w_ref, z_ref):
        bg = pl.program_id(1)
        zc = jnp.zeros((RT, 128), jnp.float32)
        for gi in range(G):
            pk = pk_ref[pl.ds(gi * spv, spv), :]
            vv = val_ref[pl.ds(gi * spv, spv), :]
            rl = jax.lax.shift_right_logical(pk, 7)
            lane = jax.lax.bitwise_and(pk, 127)
            wb = bcast(w_ref[pl.ds(bg * G + gi, 1), :], spv)
            p = jnp.take_along_axis(wb, lane, axis=1) * vv
            for s in range(spv):
                rl_row = rl[s : s + 1, :]
                rhi = jax.lax.shift_right_logical(rl_row, 7)
                rlo = jax.lax.bitwise_and(rl_row, 127)
                orh = jax.lax.broadcasted_iota(jnp.int32, (RT, 128), 0) == bcast(rhi, RT)
                p1 = jnp.where(orh, bcast(p[s : s + 1, :], RT), 0.0)
                orlt = (
                    jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0) == bcast(rlo, 128)
                ).astype(jnp.float32)
                zc = zc + jax.lax.dot_general(
                    p1, orlt, dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32, precision=PREC)
        @pl.when(bg == 0)
        def _():
            z_ref[:] = zc
        @pl.when(bg > 0)
        def _():
            z_ref[:] += zc

    fn = pl.pallas_call(
        kern,
        grid=(T, B // G),
        in_specs=[
            pl.BlockSpec((G * spv, 128), lambda t, bg: (t * (B // G) + bg, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((G * spv, 128), lambda t, bg: (t * (B // G) + bg, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((B, 128), lambda t, bg: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((RT, 128), lambda t, bg: (t, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((T * RT, 128), jnp.float32),
    )
    f = jax.jit(lambda pk, v, w2: fn(pk, v, w2))
    fsum = jax.jit(lambda pk, v, w2: jnp.sum(fn(pk, v, w2)))
    w2 = w.reshape(B, 128)
    try:
        out = jax.block_until_ready(f(bf.packed, bf.values, w2))
        float(fsum(bf.packed, bf.values, w2))
    except Exception as e:
        print(f"V4 G={G}: FAIL {str(e)[:200]}")
        return
    ent = np.random.default_rng()
    ts = []
    for r in range(3):
        m = 1.0 + float(ent.uniform(1e-4, 1e-2))
        w2r = w2 * m
        t0 = time.perf_counter()
        float(fsum(bf.packed, bf.values, w2r))
        ts.append(time.perf_counter() - t0)
    out = f(bf.packed, bf.values, w2 * m)
    z_ref = np.einsum("nk,nk->n", w_np[idx].astype(np.float64), val) * m
    got = np.asarray(out).reshape(-1)[: N]
    print(f"V4 G={G}: {min(ts)*1e3:.1f} ms  (all {[f'{x*1e3:.1f}' for x in ts]})  err {np.abs(got - z_ref).max()/np.abs(z_ref).max():.1e}")

for v in ("V5", "V3", "V2", "V1", "V0"):
    run(v)
run_v4(8)
run_v4(16)
print("done")

"""Round 2 of kernel probes: fixed timing (outer calls vary args), chunked
MXU one-hot builds, plus a fused value+grad candidate."""
import functools, time
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N, K, D = 1 << 20, 64, 16384
HI, LO = D // 128, 128
TN = 128
GRID = N // TN
E = K * TN  # entries per tile = 8192
CH = 1024   # one-hot chunk (rows of the E axis)

rng = np.random.default_rng(0)
idx_nk = rng.integers(0, D, size=(N, K)).astype(np.int32)
val_nk = rng.normal(size=(N, K)).astype(np.float32)
u_np = rng.normal(size=(N,)).astype(np.float32)
w_np = (rng.normal(size=(D,)) * 0.1).astype(np.float32)

idxT = jnp.asarray(idx_nk.T.copy())
valT = jnp.asarray(val_nk.T.copy())
u = jnp.asarray(u_np)
w = jnp.asarray(w_np)

z_ref_np = np.einsum("nk,nk->n", w_np[idx_nk].astype(np.float64), val_nk).astype(np.float64)
g_ref_np = np.zeros(D, np.float64)
np.add.at(g_ref_np, idx_nk.reshape(-1), (val_nk.astype(np.float64) * u_np[:, None]).reshape(-1))


def timeit(name, fn, argmaker, check=None):
    """argmaker(r) -> args; r=0 compiles, r=1.. timed with different args."""
    try:
        out = jax.block_until_ready(fn(*argmaker(0)))
    except Exception as e:
        print(f"{name}: FAIL {type(e).__name__}: {str(e)[:250]}")
        return
    ts = []
    for r in (1, 2, 3):
        a = argmaker(r)
        t0 = time.perf_counter()
        out = jax.block_until_ready(fn(*a))
        ts.append(time.perf_counter() - t0)
    msg = f"{name}: {min(ts)*1e3:.1f} ms/eval"
    if check is not None:
        msg += f"   [{check(out, 1 + ts.index(min(ts)))}]"  # scale of last... use r of min
    print(msg)


def wargs(r):
    return idxT, valT, w * (1.0 + r * 1e-3)


def uargs(r):
    return idxT, valT, u * (1.0 + r * 1e-3)


def chk_z(out, r):
    got = np.asarray(out, np.float64)
    want = z_ref_np[:7] * (1.0 + r * 1e-3)
    return f"err {np.max(np.abs(got - want)):.2e}"


def chk_g(out, r):
    got = np.asarray(out, np.float64).reshape(-1)[:7]
    want = g_ref_np[:7] * (1.0 + r * 1e-3)
    return f"err {np.max(np.abs(got - want)):.2e}"


# ---------------- F1: select-loop fwd ----------------
def f1_kernel(idx_ref, val_ref, w2_ref, z_ref):
    idx = idx_ref[:]
    hi = jax.lax.shift_right_logical(idx, 7)
    lo = jax.lax.bitwise_and(idx, 127)
    acc = jnp.zeros((K, TN), jnp.float32)
    w2 = w2_ref[:]
    for j in range(HI):
        wrow = jax.lax.broadcast_in_dim(w2[j, :], (K, TN), (1,))
        g = jnp.take_along_axis(wrow, lo, axis=1)
        acc = acc + jnp.where(hi == j, g, 0.0)
    z_ref[:] = jnp.sum(acc * val_ref[:], axis=0, keepdims=True)


@jax.jit
def f1(idxT, valT, w):
    return pl.pallas_call(
        f1_kernel,
        grid=(GRID,),
        in_specs=[
            pl.BlockSpec((K, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((K, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((HI, LO), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, N), jnp.float32),
    )(idxT, valT, w.reshape(HI, LO))[0, :7]


# ---------------- F2c: chunked MXU one-hot fwd ----------------
def f2_kernel(idx_ref, val_ref, w2_ref, z_ref):
    idx = idx_ref[:].reshape(E // 128, 128)
    hi = jax.lax.shift_right_logical(idx, 7)
    lo = jax.lax.bitwise_and(idx, 127)
    w2 = w2_ref[:]
    gs = []
    for c in range(E // CH):
        hic = hi[c * (CH // 128):(c + 1) * (CH // 128)].reshape(CH, 1)
        oh = (jax.lax.broadcasted_iota(jnp.int32, (CH, HI), 1) == hic).astype(jnp.float32)
        t = jax.lax.dot_general(
            oh, w2, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (CH, 128)
        loc = lo[c * (CH // 128):(c + 1) * (CH // 128)].reshape(CH, 1)
        lob = jax.lax.broadcast_in_dim(loc[:, 0], (CH, 128), (0,))
        g = jnp.take_along_axis(t, lob, axis=1)[:, :1]  # (CH, 1)
        gs.append(g)
    g_all = jnp.concatenate(gs, axis=0).reshape(K, TN)
    z_ref[:] = jnp.sum(g_all * val_ref[:], axis=0, keepdims=True)


@jax.jit
def f2(idxT, valT, w):
    return pl.pallas_call(
        f2_kernel,
        grid=(GRID,),
        in_specs=[
            pl.BlockSpec((K, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((K, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((HI, LO), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, N), jnp.float32),
    )(idxT, valT, w.reshape(HI, LO))[0, :7]


# ---------------- B1c: chunked MXU one-hot bwd ----------------
def b1_kernel(idx_ref, val_ref, u_ref, g_ref):
    i = pl.program_id(0)
    idx = idx_ref[:]
    a = val_ref[:] * jax.lax.broadcast_in_dim(u_ref[0, :], (K, TN), (1,))
    hi = jax.lax.shift_right_logical(idx, 7).reshape(E // 128, 128)
    lo = jax.lax.bitwise_and(idx, 127).reshape(E // 128, 128)
    af = a.reshape(E // 128, 128)
    contrib = jnp.zeros((HI, LO), jnp.float32)
    for c in range(E // CH):
        sl = slice(c * (CH // 128), (c + 1) * (CH // 128))
        hic = hi[sl].reshape(CH, 1)
        loc = lo[sl].reshape(CH, 1)
        ac = af[sl].reshape(CH, 1)
        A = jnp.where(jax.lax.broadcasted_iota(jnp.int32, (CH, HI), 1) == hic, ac, 0.0)
        O = (jax.lax.broadcasted_iota(jnp.int32, (CH, LO), 1) == loc).astype(jnp.float32)
        contrib += jax.lax.dot_general(
            A, O, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(i == 0)
    def _():
        g_ref[:] = contrib

    @pl.when(i > 0)
    def _():
        g_ref[:] += contrib


@jax.jit
def b1(idxT, valT, u):
    return pl.pallas_call(
        b1_kernel,
        grid=(GRID,),
        in_specs=[
            pl.BlockSpec((K, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((K, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TN), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((HI, LO), lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((HI, LO), jnp.float32),
    )(idxT, valT, u.reshape(1, N))


def b1_head(idxT, valT, u):
    return b1(idxT, valT, u).reshape(-1)[:7]


timeit("F1 fwd select-loop  ", f1, wargs, chk_z)
timeit("F2c fwd MXU chunked ", f2, wargs, chk_z)
timeit("B1c bwd MXU chunked ", jax.jit(b1_head), uargs, chk_g)
print("done")

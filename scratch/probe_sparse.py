"""Probe: which sparse gather/scatter formulations compile + their speed on v5e.

Candidates for the sparse-ELL objective kernel:
  A. XLA status quo: gather-matvec + scatter-add rmatvec  (the 840 ms/eval path)
  B. XLA CSC-transpose: grad via gather of u (static pattern, transpose once)
  C. Pallas: jnp.take(w, idx) gather inside kernel (does Mosaic lower it?)
  D. Pallas: one-hot matmul for both directions
"""
import functools, time, sys
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N, K, D = 1 << 20, 64, 16384
rng = np.random.default_rng(0)
idx = jnp.asarray(rng.integers(0, D, size=(N, K)).astype(np.int32))
val = jnp.asarray(rng.normal(size=(N, K)).astype(np.float32))
u = jnp.asarray(rng.normal(size=(N,)).astype(np.float32))
w = jnp.asarray(rng.normal(size=(D,)).astype(np.float32))


def timed(name, fn, *args):
    try:
        out = jax.block_until_ready(fn(*args))
    except Exception as e:
        print(f"{name}: FAIL {type(e).__name__}: {str(e)[:300]}")
        return None
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    print(f"{name}: {min(ts)*1e3:.1f} ms")
    return out


# ---- A: XLA baselines ----
@jax.jit
def xla_matvec(idx, val, w):
    return jnp.einsum("nk,nk->n", jnp.take(w, idx, axis=-1), val)

@jax.jit
def xla_rmatvec(idx, val, u):
    flat_idx = idx.reshape(-1)
    flat_val = (val * u[:, None]).reshape(-1)
    return jnp.zeros((D,), jnp.float32).at[flat_idx].add(flat_val)

z_ref = timed("A fwd xla gather-matvec", xla_matvec, idx, val, w)
g_ref = timed("A bwd xla scatter-add  ", xla_rmatvec, idx, val, u)

# ---- B: CSC transpose (host, one-time) + XLA gather ----
t0 = time.perf_counter()
flat_i = np.asarray(idx).reshape(-1)
order = np.argsort(flat_i, kind="stable")
rowT = (order // K).astype(np.int32)
colT = flat_i[order]
valT = np.asarray(val).reshape(-1)[order]
counts = np.bincount(colT, minlength=D)
KT = int(counts.max())
print(f"B transpose host: {time.perf_counter()-t0:.1f}s, max col len {KT}, mean {counts.mean():.0f}")
# pad to ELL-T (D, KT) -- KT ~ N*K/D * smallish factor
offs = np.zeros(D + 1, np.int64); np.cumsum(counts, out=offs[1:])
rT = np.zeros((D, KT), np.int32); vT = np.zeros((D, KT), np.float32)
for d in range(D):
    lo, hi = offs[d], offs[d + 1]
    rT[d, : hi - lo] = rowT[lo:hi]
    vT[d, : hi - lo] = valT[lo:hi]
rT = jnp.asarray(rT); vT = jnp.asarray(vT)

@jax.jit
def xla_csc_grad(rT, vT, u):
    return jnp.einsum("dk,dk->d", jnp.take(u, rT, axis=-1), vT)

g_b = timed("B bwd xla csc-gather   ", xla_csc_grad, rT, vT, u)
if g_b is not None:
    print("  B vs A max err:", float(jnp.max(jnp.abs(g_b - g_ref))))

# ---- C: Pallas gather kernel ----
TILE = 1024

def c_fwd_kernel(idx_ref, val_ref, w_ref, z_ref):
    g = jnp.take(w_ref[:], idx_ref[:], axis=0)  # (TILE,K) gather from (D,)
    z_ref[:] = jnp.sum(g * val_ref[:], axis=1, keepdims=True)

@jax.jit
def pallas_fwd(idx, val, w):
    return pl.pallas_call(
        c_fwd_kernel,
        grid=(N // TILE,),
        in_specs=[
            pl.BlockSpec((TILE, K), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE, K), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((D,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((TILE, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((N, 1), jnp.float32),
    )(idx, val, w)

z_c = timed("C fwd pallas take      ", pallas_fwd, idx, val, w)
if z_c is not None and z_ref is not None:
    print("  C vs A max err:", float(jnp.max(jnp.abs(z_c[:, 0] - z_ref))))

# C2: gather from 2D w (D,1) via take_along_axis style
def c2_fwd_kernel(idx_ref, val_ref, w_ref, z_ref):
    w = w_ref[:]  # (1, D)
    g = jnp.take(w[0], idx_ref[:], axis=0)
    z_ref[:] = jnp.sum(g * val_ref[:], axis=1, keepdims=True)

@jax.jit
def pallas_fwd2(idx, val, w2):
    return pl.pallas_call(
        c2_fwd_kernel,
        grid=(N // TILE,),
        in_specs=[
            pl.BlockSpec((TILE, K), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE, K), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, D), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((TILE, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((N, 1), jnp.float32),
    )(idx, val, w.reshape(1, D))

z_c2 = timed("C2 fwd pallas take 2d  ", pallas_fwd2, idx, val, w)
if z_c2 is not None and z_ref is not None:
    print("  C2 vs A max err:", float(jnp.max(jnp.abs(z_c2[:, 0] - z_ref))))

# ---- C3: Pallas CSC gather for gradient (u in VMEM: N*4B = 4MB) ----
TD = 512  # dim tile

def c3_kernel(rT_ref, vT_ref, u_ref, g_ref):
    g = jnp.take(u_ref[0], rT_ref[:], axis=0)  # (TD, KT) gather from (N,)
    g_ref[:] = jnp.sum(g * vT_ref[:], axis=1, keepdims=True)

@jax.jit
def pallas_csc_grad(rT, vT, u2):
    return pl.pallas_call(
        c3_kernel,
        grid=(D // TD,),
        in_specs=[
            pl.BlockSpec((TD, KT), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((TD, KT), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, N), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((TD, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((D, 1), jnp.float32),
    )(rT, vT, u.reshape(1, N))

g_c3 = timed("C3 bwd pallas csc take ", pallas_csc_grad, rT, vT, u)
if g_c3 is not None:
    print("  C3 vs A max err:", float(jnp.max(jnp.abs(g_c3[:, 0] - g_ref))))

# ---- D: Pallas one-hot matmul bwd (dim-blocked) ----
DB = 2048
TN = 512

def d_kernel(idx_ref, a_ref, g_ref):
    j = pl.program_id(1)
    base = j * DB
    idxf = idx_ref[:].reshape(TN * K)  # entries
    af = a_ref[:].reshape(TN * K, 1)
    lanes = jax.lax.broadcasted_iota(jnp.int32, (TN * K, DB), 1) + base
    onehot = (lanes == idxf[:, None]).astype(jnp.float32)
    contrib = jax.lax.dot_general(
        onehot, af, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (DB, 1)
    @pl.when(pl.program_id(0) == 0)
    def _():
        g_ref[:] = contrib
    @pl.when(pl.program_id(0) > 0)
    def _():
        g_ref[:] += contrib

@jax.jit
def pallas_onehot_grad(idx, a):
    return pl.pallas_call(
        d_kernel,
        grid=(N // TN, D // DB),
        in_specs=[
            pl.BlockSpec((TN, K), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((TN, K), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((DB, 1), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((D, 1), jnp.float32),
    )(idx, a)

a = val * u[:, None]
g_d = timed("D bwd pallas onehot    ", pallas_onehot_grad, idx, a)
if g_d is not None:
    print("  D vs A max err:", float(jnp.max(jnp.abs(g_d[:, 0] - g_ref))))
print("done")

"""Measure bucketed kernels at bench scale on v5e: pack time, matvec, rmatvec."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from photon_ml_tpu.data.bucketed import pack_bucketed
from photon_ml_tpu.ops import pallas_sparse as ps

N, K, D = 1 << 20, 64, 16384
rng = np.random.default_rng(0)
idx = rng.integers(0, D, size=(N, K)).astype(np.int32)
val = rng.normal(size=(N, K)).astype(np.float32)
u_np = rng.normal(size=N).astype(np.float32)
w_np = (rng.normal(size=D) * 0.1).astype(np.float32)

t0 = time.perf_counter()
rows = np.repeat(np.arange(N, dtype=np.int64), K)
bf = pack_bucketed(rows, idx.reshape(-1).astype(np.int64), val.reshape(-1), N, D)
print(f"pack: {time.perf_counter()-t0:.1f}s  {bf.density_report()}")

w = jnp.asarray(w_np); u = jnp.asarray(u_np)
jax.block_until_ready((bf.packed, bf.values))

def timed(name, fn, mk):
    jax.block_until_ready(fn(mk(0)))
    ts = []
    for r in (1, 2, 3):
        a = mk(r)
        jax.block_until_ready(a)
        t0 = time.perf_counter()
        out = jax.block_until_ready(fn(a))
        ts.append(time.perf_counter() - t0)
    print(f"{name}: {min(ts)*1e3:.1f} ms  (all {[f'{t*1e3:.1f}' for t in ts]})")
    return out

z_k = timed("matvec  kernel", lambda a: ps.matvec(bf, a), lambda r: w * (1.0 + r * 1e-3))
g_k = timed("rmatvec kernel", lambda a: ps.rmatvec(bf, a), lambda r: u * (1.0 + r * 1e-3))

# correctness vs f64 host
z_ref = np.einsum("nk,nk->n", w_np[idx].astype(np.float64), val) * (1 + 3e-3)
g_ref = np.zeros(D); np.add.at(g_ref, idx.reshape(-1), (val * u_np[:, None]).reshape(-1))
g_ref = g_ref * (1 + 3e-3)
print("z rel err:", np.abs(np.asarray(z_k) - z_ref).max() / np.abs(z_ref).max())
print("g rel err:", np.abs(np.asarray(g_k) - g_ref).max() / np.abs(g_ref).max())
print("done")

"""Optimization round: RT=8 tiles, sublane-gather u-select, identity-gather
one-hot, dimension_semantics, hi/lo precision. All scan-timed (RTT-amortized).
Also correctness-checked against f64."""
import sys, time, functools
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N, K, D = 1 << 20, 64, 16384
REPS = 8
rng = np.random.default_rng(0)
idx = rng.integers(0, D, size=(N, K)).astype(np.int64)
val = rng.normal(size=(N, K)).astype(np.float32)
u_np = rng.normal(size=N).astype(np.float32)
w_np = (rng.normal(size=D) * 0.1).astype(np.float32)

# --- quick capability check: sublane gather with S=16 ---
def cap_kernel(a_ref, i_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(a_ref[:], i_ref[:], axis=0)
try:
    a16 = jnp.asarray(rng.normal(size=(16, 128)).astype(np.float32))
    i16 = jnp.asarray(rng.integers(0, 16, size=(16, 128)).astype(np.int32))
    out = pl.pallas_call(
        cap_kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 2,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
    )(a16, i16)
    ref = np.take_along_axis(np.asarray(a16), np.asarray(i16), axis=0)
    print("sublane gather S=16: ok, err", np.abs(np.asarray(out) - ref).max())
except Exception as e:
    print("sublane gather S=16: FAIL", str(e)[:120])

# --- pack with parametrizable tile rows ---
def pack(tile_rows):
    B = D // 128
    tile = (np.repeat(np.arange(N, dtype=np.int64), K)) // tile_rows
    rl = (np.repeat(np.arange(N, dtype=np.int64), K)) % tile_rows
    bucket = idx.reshape(-1) // 128
    lane = idx.reshape(-1) % 128
    T = -(-N // tile_rows)
    seg = tile * B + bucket
    n_seg = T * B
    counts = np.bincount(seg, minlength=n_seg)
    order = np.argsort(seg, kind="stable")
    seg_s = seg[order]
    starts = np.zeros(n_seg + 1, np.int64); np.cumsum(counts, out=starts[1:])
    pos = np.arange(N * K, dtype=np.int64) - starts[seg_s]
    sp = -(-int(counts.max()) // 1024) * 1024
    spv = sp // 128
    packed = np.zeros((n_seg, sp), np.int32)
    values = np.zeros((n_seg, sp), np.float32)
    packed[seg_s, pos] = (rl[order].astype(np.int32) << 7) | lane[order].astype(np.int32)
    values[seg_s, pos] = val.reshape(-1)[order]
    return (jnp.asarray(packed.reshape(n_seg * spv, 128)),
            jnp.asarray(values.reshape(n_seg * spv, 128)), T, B, spv)

def bcast(row, s):
    return jax.lax.broadcast_in_dim(row[0, :], (s, 128), (1,))

def fwd(pkd, G, RT, spv, T, B, prec, ident_onehot=False, semantics=None):
    tile_rows = RT * 128
    def kern(pk_ref, val_ref, w_ref, z_ref):
        bg = pl.program_id(1)
        zc = jnp.zeros((RT, 128), jnp.float32)
        for gi in range(G):
            pk = pk_ref[pl.ds(gi * spv, spv), :]
            vv = val_ref[pl.ds(gi * spv, spv), :]
            rl = jax.lax.shift_right_logical(pk, 7)
            lane = jax.lax.bitwise_and(pk, 127)
            wb = bcast(w_ref[pl.ds(bg * G + gi, 1), :], spv)
            p = jnp.take_along_axis(wb, lane, axis=1) * vv
            for s in range(spv):
                rl_row = rl[s : s + 1, :]
                rhi = jax.lax.shift_right_logical(rl_row, 7)
                rlo = jax.lax.bitwise_and(rl_row, 127)
                orh = jax.lax.broadcasted_iota(jnp.int32, (RT, 128), 0) == bcast(rhi, RT)
                p1 = jnp.where(orh, bcast(p[s : s + 1, :], RT), 0.0)
                if ident_onehot:
                    eye = jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0) == jax.lax.broadcasted_iota(jnp.int32, (128, 128), 1)
                    orlt = jnp.take_along_axis(eye.astype(jnp.float32), bcast(rlo, 128), axis=1)
                else:
                    orlt = (
                        jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0) == bcast(rlo, 128)
                    ).astype(jnp.float32)
                if prec == "hilo":
                    p_hi = (p1.astype(jnp.bfloat16)).astype(jnp.float32)
                    p_lo = p1 - p_hi
                    zc = zc + jax.lax.dot_general(p_hi, orlt, dimension_numbers=(((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=jax.lax.Precision.DEFAULT)
                    zc = zc + jax.lax.dot_general(p_lo, orlt, dimension_numbers=(((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=jax.lax.Precision.DEFAULT)
                else:
                    zc = zc + jax.lax.dot_general(p1, orlt, dimension_numbers=(((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=prec)
        @pl.when(bg == 0)
        def _():
            z_ref[:] = zc
        @pl.when(bg > 0)
        def _():
            z_ref[:] += zc

    params = {}
    if semantics:
        params["compiler_params"] = pltpu.CompilerParams(dimension_semantics=semantics)
    return pl.pallas_call(
        kern,
        grid=(T, B // G),
        in_specs=[
            pl.BlockSpec((G * spv, 128), lambda t, bg: (t * (B // G) + bg, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((G * spv, 128), lambda t, bg: (t * (B // G) + bg, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((B, 128), lambda t, bg: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((RT, 128), lambda t, bg: (t, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((T * RT, 128), jnp.float32),
        **params,
    )

def bwd(pkd, G, RT, spv, T, B, prec, sub_gather=False, semantics=None):
    def kern(pk_ref, val_ref, u_ref, g_ref):
        bg = pl.program_id(0)
        t = pl.program_id(1)
        u2 = u_ref[:]
        for gi in range(G):
            pk = pk_ref[pl.ds(gi * spv, spv), :]
            vv = val_ref[pl.ds(gi * spv, spv), :]
            rl = jax.lax.shift_right_logical(pk, 7)
            lane = jax.lax.bitwise_and(pk, 127)
            gc = jnp.zeros((1, 128), jnp.float32)
            for s in range(spv):
                rl_row = rl[s : s + 1, :]
                rhi = jax.lax.shift_right_logical(rl_row, 7)
                rlo = jax.lax.bitwise_and(rl_row, 127)
                tu = jnp.take_along_axis(u2, bcast(rlo, RT), axis=1)
                if sub_gather:
                    u_sel = jnp.take_along_axis(tu, bcast(rhi, RT), axis=0)[0:1, :]
                else:
                    orh = jax.lax.broadcasted_iota(jnp.int32, (RT, 128), 0) == bcast(rhi, RT)
                    u_sel = jnp.sum(jnp.where(orh, tu, 0.0), axis=0, keepdims=True)
                a = u_sel * vv[s : s + 1, :]
                olt = (
                    jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0) == bcast(lane[s : s + 1, :], 128)
                ).astype(jnp.float32)
                if prec == "hilo":
                    a_hi = (a.astype(jnp.bfloat16)).astype(jnp.float32)
                    a_lo = a - a_hi
                    gc = gc + jax.lax.dot_general(a_hi, olt, dimension_numbers=(((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=jax.lax.Precision.DEFAULT)
                    gc = gc + jax.lax.dot_general(a_lo, olt, dimension_numbers=(((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=jax.lax.Precision.DEFAULT)
                else:
                    gc = gc + jax.lax.dot_general(a, olt, dimension_numbers=(((1,), (1,)), ((), ())), preferred_element_type=jnp.float32, precision=prec)
            bidx = bg * G + gi
            @pl.when(t == 0)
            def _():
                g_ref[pl.ds(bidx, 1), :] = gc
            @pl.when(t > 0)
            def _():
                g_ref[pl.ds(bidx, 1), :] += gc

    params = {}
    if semantics:
        params["compiler_params"] = pltpu.CompilerParams(dimension_semantics=semantics)
    return pl.pallas_call(
        kern,
        grid=(B // G, T),
        in_specs=[
            pl.BlockSpec((G * spv, 128), lambda bg, t: (t * (B // G) + bg, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((G * spv, 128), lambda bg, t: (t * (B // G) + bg, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((RT, 128), lambda bg, t: (t, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((B, 128), lambda bg, t: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, 128), jnp.float32),
        **params,
    )

z_ref64 = np.einsum("nk,nk->n", w_np[idx].astype(np.float64), val)
g_ref64 = np.zeros(D); np.add.at(g_ref64, idx.reshape(-1), (val.astype(np.float64) * u_np[:, None]).reshape(-1))

def scan_time(name, call, vec, transform, check=None):
    @jax.jit
    def f(pk, v, x):
        def one(c, i):
            return c + jnp.sum(call(pk, v, transform(x * (1.0 + i * 1e-4)))), None
        tot, _ = jax.lax.scan(one, 0.0, jnp.arange(REPS, dtype=jnp.float32))
        return tot
    try:
        float(f(PK, VV, vec))
    except Exception as e:
        print(f"{name}: FAIL {str(e)[:170]}")
        return
    ent = np.random.default_rng()
    ts = []
    for r in range(3):
        xr = vec * (1.0 + float(ent.uniform(1e-4, 1e-2)))
        t0 = time.perf_counter()
        float(f(PK, VV, xr))
        ts.append((time.perf_counter() - t0) / REPS)
    extra = ""
    if check is not None:
        m = 1.0 + float(ent.uniform(1e-4, 1e-2))
        out = np.asarray(jax.jit(lambda pk, v, x: call(pk, v, transform(x)))(PK, VV, vec * m))
        extra = "  " + check(out, m)
    print(f"{name}: {min(ts)*1e3:.1f} ms/eval  (all {[f'{x*1e3:.1f}' for x in ts]}){extra}")

for RT in (8, 16):
    PK, VV, T, B, spv = pack(RT * 128)
    w = jnp.asarray(w_np); u = jnp.asarray(u_np)
    wt = lambda x: x.reshape(B, 128)
    ut = lambda x: jnp.pad(x, (0, T * RT * 128 - N)).reshape(T * RT, 128)
    zchk = lambda out, m: f"err {np.abs(out.reshape(-1)[:N] - z_ref64*m).max()/np.abs(z_ref64).max():.1e}"
    gchk = lambda out, m: f"err {np.abs(out.reshape(-1)[:D] - g_ref64*m).max()/np.abs(g_ref64).max():.1e}"
    print(f"--- RT={RT} spv={spv} T={T}")
    scan_time(f"fwd RT={RT} G=32 default", lambda pk, v, w2: fwd(None, 32, RT, spv, T, B, jax.lax.Precision.DEFAULT)(pk, v, w2), w, wt, zchk)
    scan_time(f"fwd RT={RT} G=32 hilo   ", lambda pk, v, w2: fwd(None, 32, RT, spv, T, B, "hilo")(pk, v, w2), w, wt, zchk)
    scan_time(f"fwd RT={RT} G=32 highest", lambda pk, v, w2: fwd(None, 32, RT, spv, T, B, jax.lax.Precision.HIGHEST)(pk, v, w2), w, wt, zchk)
    scan_time(f"fwd RT={RT} G=32 dflt sem", lambda pk, v, w2: fwd(None, 32, RT, spv, T, B, jax.lax.Precision.DEFAULT, semantics=("parallel", "arbitrary"))(pk, v, w2), w, wt, zchk)
    scan_time(f"bwd RT={RT} G=32 default", lambda pk, v, u2: bwd(None, 32, RT, spv, T, B, jax.lax.Precision.DEFAULT)(pk, v, u2), u, ut, gchk)
    scan_time(f"bwd RT={RT} G=32 subg   ", lambda pk, v, u2: bwd(None, 32, RT, spv, T, B, jax.lax.Precision.DEFAULT, sub_gather=True)(pk, v, u2), u, ut, gchk)
    scan_time(f"bwd RT={RT} G=32 subg hilo", lambda pk, v, u2: bwd(None, 32, RT, spv, T, B, "hilo", sub_gather=True)(pk, v, u2), u, ut, gchk)
print("done")

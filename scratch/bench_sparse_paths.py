"""Fused vs composed sparse kernel timing on the real TPU (honest protocol:
perturbed inputs, jitted combining scalar fetch, rtt-subtracted)."""
import sys
import time

sys.path.insert(0, "/root/repo")
import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.bucketed import pack_bucketed
from photon_ml_tpu.ops import pallas_sparse
from photon_ml_tpu.ops.losses import LOGISTIC

print("backend:", jax.default_backend(), flush=True)
n, k, d = 1 << 19, 32, 16384
rng = np.random.default_rng(11)
rows = np.repeat(np.arange(n, dtype=np.int64), k)
cols = rng.integers(0, d, size=n * k).astype(np.int64)
vals = rng.normal(size=n * k).astype(np.float32)
t0 = time.perf_counter()
bf = pack_bucketed(rows, cols, vals, n, d)
jax.block_until_ready(bf.level1.packed)
print(f"pack(host)+upload: {time.perf_counter()-t0:.1f}s  {bf.density_report()}", flush=True)

y = jnp.asarray((rng.uniform(size=n) > 0.5).astype(np.float32))
off = jnp.zeros(n)
wt = jnp.ones(n)
w0 = jnp.asarray(rng.normal(size=d).astype(np.float32) * 0.05)
zero = jnp.zeros(())


@jax.jit
def force_sum(parts):
    return sum((jnp.sum(p) for p in parts[1:]), jnp.sum(parts[0]))


def force(parts):
    return float(force_sum(tuple(parts)))


force((jnp.ones(2),))
rtt = min(
    (lambda t0: (force((jnp.ones(4) * (i + 1),)), time.perf_counter() - t0)[1])(time.perf_counter())
    for i in range(5)
)
print(f"rtt {rtt*1e3:.0f} ms", flush=True)

entry_bytes = n * k * 8  # packed int32 + f32 value per entry


def bench(label, fn, streams):
    out = fn(w0)
    force(out)
    walls = []
    for i in range(6):
        w = w0 * (1.0 + 1e-4 * (i + 1))
        t0 = time.perf_counter()
        force(fn(w))
        walls.append(time.perf_counter() - t0 - rtt)
    per = min(walls)
    print(f"{label}: {per*1e3:.1f} ms  {streams*entry_bytes/per/1e9:.1f} GB/s "
          f"({streams} entry-stream(s))", flush=True)
    return per


# composed: one matvec (stream 1) ...
bench("matvec           ", lambda w: (pallas_sparse.matvec(bf, w),), 1)
u_fix = jnp.asarray(rng.normal(size=n).astype(np.float32))

bench("rmatvec          ", lambda w: (pallas_sparse.rmatvec(bf, u_fix * w[0]),), 1)

# composed objective eval = matvec + loss + rmatvec (2 streams); bf and the
# label columns must be ARGUMENTS (a closure const-folds them into the
# compile payload, which the remote compile service rejects at this size).
import functools

@functools.partial(jax.jit, static_argnames=())
def composed(bf_, w, y_, off_, wt_):
    z = pallas_sparse.matvec(bf_, w) + off_
    u = wt_ * LOGISTIC.d1(z, y_)
    val = jnp.sum(wt_ * LOGISTIC.loss(z, y_))
    g = pallas_sparse.rmatvec(bf_, u)
    return val, g


bench("composed val+grad", lambda w: composed(bf, w, y, off, wt), 2)

# fused single-stream kernel
if pallas_sparse.fused_feasible(bf):
    bench(
        "fused val+grad   ",
        lambda w: pallas_sparse.fused_value_gradient_sums(
            LOGISTIC, w, zero, bf, y, off, wt
        )[:2],
        1,
    )
else:
    print("fused infeasible:", bf.num_buckets * bf.level1.spv, flush=True)

"""Profile the ingest pipeline stage by stage to find the real bottleneck."""
import os
import sys
import tempfile
import time

import numpy as np

import photon_ml_tpu.io.avro_data as ad
from photon_ml_tpu.io import avro_fast
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.native import avro_reader
from photon_ml_tpu.data.index_map import DELIMITER

n, d, k = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000, 4000, 24
rng = np.random.default_rng(7)
t0 = time.perf_counter()
feats = [
    [(f"f{j}", float(v)) for j, v in zip(
        rng.choice(d, size=k, replace=False), rng.normal(size=k))]
    for _ in range(n)
]
print(f"gen: {time.perf_counter()-t0:.2f}s")

td = tempfile.mkdtemp()
pth = os.path.join(td, "bench.avro")
t0 = time.perf_counter()
ad.write_training_examples(
    pth, feats, (rng.uniform(size=n) > 0.5).astype(float),
    id_tags={"entityId": rng.integers(0, 1000, size=n)},
)
mb = os.path.getsize(pth) / 1e6
print(f"write: {time.perf_counter()-t0:.2f}s  ({mb:.1f} MB)")

cfgs = {"g": ad.FeatureShardConfig(("features",), True)}
cols = ad.InputColumnNames()

# stage 1: read file bytes
t0 = time.perf_counter()
with open(pth, "rb") as f:
    data = f.read()
print(f"read bytes: {time.perf_counter()-t0:.3f}s")

schema, codec, sync, body = avro_io.read_header(data, pth)
print("codec:", codec)
program = avro_reader.compile_program(
    schema, response=cols.response, fallback_label=ad.LABEL,
    offset=cols.offset, weight=cols.weight, uid=cols.uid,
    metadata_map=cols.metadata_map, bag_names=["features"],
    tag_fields=("entityId",),
)
assert program is not None

# stage 2: native decode only
t0 = time.perf_counter()
out = avro_reader.decode_file_native(data, body, codec, sync, program, DELIMITER)
t_dec = time.perf_counter() - t0
assert out is not None
print(f"native decode: {t_dec:.3f}s  ({mb/t_dec:.1f} MB/s)  nnz={len(out.bag_keys[0])}")

# stage 3: full try_read_native (decode + assembly + ELL + device upload)
t0 = time.perf_counter()
r = avro_fast.try_read_native([pth], cfgs, None, ["entityId"], cols, ad.LABEL)
t_full = time.perf_counter() - t0
assert r is not None
print(f"try_read_native total: {t_full:.3f}s  ({mb/t_full:.1f} MB/s)")
print(f"  -> assembly+pack+upload: {t_full - t_dec - 0.05:.3f}s (approx)")

# block structure of the file
cnt = 0
p = body
r2 = data
import photon_ml_tpu.io.avro as A
br = A.BinaryReader(data, p) if hasattr(A, "BinaryReader") else None
# quick manual block walk
def read_long(buf, pos):
    n_ = 0; shift = 0
    while True:
        b = buf[pos]; pos += 1
        n_ |= (b & 0x7F) << shift
        if not (b & 0x80): break
        shift += 7
    return (n_ >> 1) ^ -(n_ & 1), pos

pos = body
sizes = []
while pos < len(data):
    c, pos = read_long(data, pos)
    s, pos = read_long(data, pos)
    sizes.append((c, s))
    pos += s + 16
print(f"blocks: {len(sizes)}, median size {np.median([s for _, s in sizes])/1e3:.0f} KB")

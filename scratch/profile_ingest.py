"""Profile read_game_dataset on a bench-shaped file to locate assembly cost."""
import cProfile
import os
import pstats
import sys
import tempfile
import time

import numpy as np

import photon_ml_tpu.io.avro_data as ad
from photon_ml_tpu.native.avro_writer import write_training_examples_columnar

n_ing = int(sys.argv[1]) if len(sys.argv) > 1 else 200_000
d_ing, k_ing = 4000, 24
rng = np.random.default_rng(7)
indptr = np.arange(n_ing + 1, dtype=np.int64) * k_ing
ids = rng.integers(0, d_ing, size=n_ing * k_ing).astype(np.int32)
vals = rng.normal(size=n_ing * k_ing)
names = [f"f{i}" for i in range(d_ing)]

td = tempfile.mkdtemp()
pth = os.path.join(td, "bench.avro")
write_training_examples_columnar(
    pth,
    (rng.uniform(size=n_ing) > 0.5).astype(np.float64),
    indptr,
    ids,
    vals,
    names,
    tag_key="entityId",
    tag_values=rng.integers(0, 1000, size=n_ing).astype(str),
)
mb = os.path.getsize(pth) / 1e6
print(f"file: {mb:.1f} MB", flush=True)

cfg = {"g": ad.FeatureShardConfig(("features",), True)}

t0 = time.perf_counter()
ad.read_game_dataset(pth, cfg, id_tag_fields=["entityId"])
t1 = time.perf_counter() - t0
print(f"warm full read: {t1:.2f}s -> {mb/t1:.1f} MB/s", flush=True)

prof = cProfile.Profile()
prof.enable()
ad.read_game_dataset(pth, cfg, id_tag_fields=["entityId"])
prof.disable()
st = pstats.Stats(prof)
st.sort_stats("cumulative").print_stats(30)
st.sort_stats("tottime").print_stats(25)

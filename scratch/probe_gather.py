"""Measure tpu.dynamic_gather throughput at various table widths, plus honest
XLA scatter/gather baselines (perturbed inputs inside one jit defeat the axon
execution cache)."""
import functools, time
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

REPS = 16

def bench(name, build):
    try:
        fn, args = build()
        out = jax.block_until_ready(fn(*args))  # compile
        t0 = time.perf_counter()
        out = jax.block_until_ready(fn(*args))
        dt = (time.perf_counter() - t0) / REPS
        print(f"{name}: {dt*1e3:.2f} ms/rep")
        return dt
    except Exception as e:
        print(f"{name}: FAIL {type(e).__name__}: {str(e)[:200]}")
        return None

# ---------------- dynamic_gather lane (axis=1) at width L ----------------
def lane_gather_probe(S, L, n_entries):
    """Gather n_entries total from an L-wide table; entries processed in
    (S, L)-shaped calls => grid = n_entries // (S*L)."""
    rng = np.random.default_rng(0)
    G = n_entries // (S * L)
    idx = jnp.asarray(rng.integers(0, L, size=(G * S, L)).astype(np.int32))
    tab = jnp.asarray(rng.normal(size=(S, L)).astype(np.float32))

    def kernel(idx_ref, tab_ref, out_ref):
        g = jnp.take_along_axis(tab_ref[:], idx_ref[:], axis=1)
        out_ref[0, 0] = jnp.sum(g)

    def call(idx, tab):
        return pl.pallas_call(
            kernel,
            grid=(G,),
            in_specs=[
                pl.BlockSpec((S, L), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((S, L), lambda i: (0, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
        )(idx, tab)

    @jax.jit
    def fn(idx, tab):
        def one(c, i):
            return c + call(idx, tab * (1.0 + i * 1e-6))[0, 0], None
        tot, _ = jax.lax.scan(one, 0.0, jnp.arange(REPS, dtype=jnp.float32))
        return tot

    return fn, (idx, tab)

# ---------------- axis=0 (sublane) gather, table height S ----------------
def sub_gather_probe(S, L, n_entries):
    rng = np.random.default_rng(0)
    G = n_entries // (S * L)
    idx = jnp.asarray(rng.integers(0, S, size=(G * S, L)).astype(np.int32))
    tab = jnp.asarray(rng.normal(size=(S, L)).astype(np.float32))

    def kernel(idx_ref, tab_ref, out_ref):
        g = jnp.take_along_axis(tab_ref[:], idx_ref[:], axis=0)
        out_ref[0, 0] = jnp.sum(g)

    def call(idx, tab):
        return pl.pallas_call(
            kernel,
            grid=(G,),
            in_specs=[
                pl.BlockSpec((S, L), lambda i: (i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((S, L), lambda i: (0, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
        )(idx, tab)

    @jax.jit
    def fn(idx, tab):
        def one(c, i):
            return c + call(idx, tab * (1.0 + i * 1e-6))[0, 0], None
        tot, _ = jax.lax.scan(one, 0.0, jnp.arange(REPS, dtype=jnp.float32))
        return tot

    return fn, (idx, tab)

E = 1 << 23  # 8.4M entries per rep
for S, L in [(8, 128), (8, 2048), (8, 16384), (256, 128), (1024, 128), (8, 65536)]:
    dt = bench(f"lane-gather S={S} L={L}", lambda S=S, L=L: lane_gather_probe(S, L, E))
    if dt:
        print(f"   -> {dt / E * 1e9:.3f} ns/entry, {E/dt/1e9:.1f} G entries/s")

for S, L in [(8, 128), (64, 128), (2048, 128), (16384, 128)]:
    dt = bench(f"sub-gather  S={S} L={L}", lambda S=S, L=L: sub_gather_probe(S, L, E))
    if dt:
        print(f"   -> {dt / E * 1e9:.3f} ns/entry, {E/dt/1e9:.1f} G entries/s")

# ---------------- honest XLA baselines (N=1M, K=64, D=16384) -------------
N, K, D = 1 << 20, 64, 16384
rng = np.random.default_rng(0)
idx = jnp.asarray(rng.integers(0, D, size=(N, K)).astype(np.int32))
val = jnp.asarray(rng.normal(size=(N, K)).astype(np.float32))
u = jnp.asarray(rng.normal(size=(N,)).astype(np.float32))
w = jnp.asarray(rng.normal(size=(D,)).astype(np.float32))

@jax.jit
def xla_fwd(idx, val, w):
    def one(c, i):
        z = jnp.einsum("nk,nk->n", jnp.take(w * (1.0 + i * 1e-6), idx, axis=-1), val)
        return c + jnp.sum(z), None
    tot, _ = jax.lax.scan(one, 0.0, jnp.arange(REPS, dtype=jnp.float32))
    return tot

@jax.jit
def xla_bwd(idx, val, u):
    def one(c, i):
        fv = (val * (u * (1.0 + i * 1e-6))[:, None]).reshape(-1)
        g = jnp.zeros((D,), jnp.float32).at[idx.reshape(-1)].add(fv)
        return c + jnp.sum(g), None
    tot, _ = jax.lax.scan(one, 0.0, jnp.arange(REPS, dtype=jnp.float32))
    return tot

for name, fn, args in [("XLA fwd gather-matvec", xla_fwd, (idx, val, w)),
                       ("XLA bwd scatter-add", xla_bwd, (idx, val, u))]:
    out = jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    jax.block_until_ready(fn(*args))
    dt = (time.perf_counter() - t0) / REPS
    print(f"{name}: {dt*1e3:.1f} ms/eval ({N*K/dt/1e9:.2f} G entries/s)")
print("done")
